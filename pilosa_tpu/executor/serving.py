"""Cross-query dispatch coalescing — the serving path.

The committed TPU record shows the engine's scans are bandwidth-bound
(~88% of v5e HBM peak) while WALL time is dispatch-bound: ~75 ms wall
vs ~0.35 ms device for Count at 954 shards, one device dispatch per
query.  Under concurrent load the per-query path therefore pays one
full dispatch/RTT per request.  This module amortizes that cost the
way TPU inference serving does (continuous batching, cf. Ragged Paged
Attention in PAPERS.md):

- ``QueryBatcher`` — concurrent in-flight queries over the same index
  are admitted for a short window (default 1 ms, or until
  ``max_batch``), their plans fused into ONE jitted program over a
  shared tile-stack upload (stacked.py's "multi" plan kind: leaves are
  deduplicated across queries by the shared ``PlanBuilder``), executed
  as ONE device dispatch and demultiplexed back to the waiting handler
  threads.  The admission lock is held only for queue flips; the
  device runs while the next batch accumulates (continuous batching).

- ``ResultCache`` — a versioned whole-query result cache keyed by the
  plan fingerprint (index, canonical call repr, shard set) and guarded
  by the write-versions of every fragment the query can read: any
  host write bumps its fragment's version (models/fragment.py), so a
  stale entry misses — and an explicit ``sweep()`` after serving-path
  writes evicts exactly the entries whose snapshot no longer matches.
  LRU byte-bounded like ``TileStackCache``.

Consistency bar: a query admitted before a write either executes
against a fragment-version snapshot that is still intact when its
batch completes, or it is re-executed solo (the same consistency the
unbatched path provides).  Anything the batcher cannot express falls
back to ``Executor.execute`` — results are bit-exact by construction
because candidate selection (TopN) and plan building are shared with
the solo path.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import OrderedDict

import numpy as np

from pilosa_tpu.executor.results import Pair, RowResult, ValCount
from pilosa_tpu.executor.stacked import (
    PlanBuilder,
    Unstackable,
    _block,
    _compiled,
    _dispatch_kind,
)
from pilosa_tpu.models.index import EXISTENCE_FIELD
from pilosa_tpu.obs import audit as _audit
from pilosa_tpu.obs import faults as _faults
from pilosa_tpu.obs import flight, metrics
from pilosa_tpu.obs import stats as _stats
from pilosa_tpu.obs.monitor import capture_exception
from pilosa_tpu.obs.tracing import (
    Span,
    capture_context,
    span_into,
    start_span,
)
from pilosa_tpu.ops import kernels
from pilosa_tpu.pql import parse
from pilosa_tpu.pql.ast import Call, Query

# the executor's own write-call table: one source of truth so the
# serving layer's write routing can never drift from dispatch
from pilosa_tpu.executor.executor import _WRITE_CALLS

# bitmap-producing calls the stacked PlanBuilder can express without
# per-query precompute (no Distinct/UnionRows/ConstRow leaves)
_PURE_BITMAP = {"Row", "Range", "Union", "Intersect", "Difference",
                "Xor", "Not", "All", "Shift"}

# read calls whose results depend only on fragment contents (plus
# append-only key translation) — the cacheable dispatch surface of
# Executor._execute_call
_READ_CALLS = _PURE_BITMAP | {
    "Count", "Sum", "Min", "Max", "MinRow", "MaxRow", "Distinct",
    "Rows", "UnionRows", "TopN", "TopK", "GroupBy", "Percentile",
    "Sort", "Extract", "Limit", "IncludesColumn", "FieldValue",
    "ConstRow",
}


class Uncacheable(Exception):
    """Raised when a query's read set cannot be proven version-stable."""


def _fingerprint(key) -> str:
    """Stable short plan fingerprint of a cache key (index, canonical
    call repr, shard set) — correlates flight records across runs,
    unlike the salted builtin hash()."""
    import hashlib
    return hashlib.blake2b(repr(key).encode(),
                           digest_size=8).hexdigest()


# ---------------------------------------------------------------------------
# dependency tracking
# ---------------------------------------------------------------------------

def _dep_fields(idx, call: Call, out: set) -> None:
    """Collect the field names a call tree can read, conservatively
    (over-inclusion only widens invalidation; under-inclusion would be
    a stale-read bug).  Raises Uncacheable for calls whose results
    depend on state outside fragment versions."""
    name = call.name
    if name in _WRITE_CALLS or name not in _READ_CALLS:
        raise Uncacheable(f"not a cacheable call: {name}")
    if name == "Distinct":
        iname = call.arg("index")
        if iname is not None and iname != idx.name:
            raise Uncacheable("cross-index Distinct")
    if name == "ConstRow":
        # keyed columns resolve through the index translator, whose
        # key set can grow without any fragment version bump
        if any(isinstance(c, str) for c in call.arg("columns", []) or []):
            raise Uncacheable("ConstRow with string keys")
    if name in ("Not", "All"):
        out.add(EXISTENCE_FIELD)
    k, cond = call.condition_field()
    if k is not None:
        out.add(k)
        if cond is not None and cond.value is None:
            out.add(EXISTENCE_FIELD)  # null predicates read existence
    for key in ("_field", "field"):
        v = call.args.get(key)
        if isinstance(v, str):
            out.add(v)
    fk, _ = call.field_arg()
    if fk is not None and idx.field(fk) is not None:
        out.add(fk)
    for v in call.args.values():
        if isinstance(v, Call):
            _dep_fields(idx, v, out)
    for c in call.children:
        _dep_fields(idx, c, out)


def _write_targets(idx, q: Query) -> tuple[set | None, set | None]:
    """(fields, shards) a write query touches — the targeted cache
    sweep.  fields None: reach unbounded (Delete removes columns from
    every field; unknown shapes likewise).  shards None: every shard
    of the fields (Store/ClearRow span the whole row; keyed columns
    resolve through the translator).  A point Set/Clear with integer
    columns names exactly the (field, shard) slices its delta
    dirtied — the sweep then compares only those fragments' stamps
    instead of re-walking each entry's whole read set."""
    fields: set = set()
    shards: set | None = set()
    for c in q.calls:
        if c.name not in _WRITE_CALLS or c.name == "Delete":
            return None, None
        fk, _ = c.field_arg()
        if fk is not None:
            fields.add(fk)
        v = c.args.get("_field")
        if isinstance(v, str):
            fields.add(v)
        col = c.args.get("_col")
        if (shards is not None and idx is not None
                and c.name in ("Set", "Clear")
                and isinstance(col, int)
                and not isinstance(col, bool)):
            shards.add(col // idx.width)
        else:
            shards = None
    # Set marks column existence; Store may create the target field —
    # both can stale existence-reading entries
    fields.add(EXISTENCE_FIELD)
    return fields, shards


def _slices_stale(idx, ent_fields: frozenset, snap: tuple,
                  fields: set, shards: set) -> bool:
    """Exact staleness of one cache entry against a POINT write:
    compare only the written (field, shard) fragments' (gen, version)
    stamps with the entry's snapshot — O(written slices), not
    O(entry read set x views x shards).  Sound because the caller
    knows the write touched nothing outside (fields x shards); every
    other write path still hits the full-snapshot comparison at
    get()-time."""
    smap: dict = {}
    absent: set = set()
    for e in snap:
        if len(e) == 2:
            absent.add(e[0])
        else:
            smap[(e[0], e[1], e[2])] = (e[3], e[4])
    for fname in fields & ent_fields:
        f = idx.fields.get(fname)
        if f is None:
            if fname not in absent:
                return True  # field vanished since the snapshot
            continue
        if fname in absent:
            return True  # snapshotted as absent, exists now
        for vname in list(f.views):
            v = f.views.get(vname)
            if v is None:
                continue
            for s in shards:
                fr = v.fragments.get(s)
                cur = None if fr is None else (fr.gen, fr.version)
                if smap.get((fname, vname, s)) != cur:
                    return True
    return False


def query_fields(idx, q: Query) -> frozenset:
    """The field read-set of a whole query (Uncacheable if any call
    escapes version tracking)."""
    out: set = set()
    for c in q.calls:
        _dep_fields(idx, c, out)
    return frozenset(out)


def _shard_set(shards) -> frozenset | None:
    """An explicit-shards query arg as the snapshot restriction; None
    (all shards) stays None."""
    return None if shards is None else frozenset(
        int(s) for s in shards)


def field_snapshot(idx, fields: frozenset, shards=None) -> tuple:
    """Version snapshot of every fragment the fields currently hold:
    ((fname, vname, shard, frag.gen, version), ...).  A write bumps a
    version; a new fragment/view/field changes the tuple's shape; a
    deleted-and-recreated field gets fresh generation stamps (a
    process-global monotonic counter — id() would be unsound, CPython
    reuses freed addresses) — all compare unequal, so comparison-to-
    snapshot is the staleness test.

    ``shards`` (a set) restricts the walk to those shards' fragments:
    a query executed over an explicit shard subset reads nothing
    outside it, so its cache entry must survive writes to OTHER
    shards of the same fields — the (field, shard)-granular
    invalidation bulk imports rely on."""
    snap = []
    for fname in sorted(fields):
        f = idx.fields.get(fname)
        if f is None:
            snap.append((fname, None))
            continue
        for vname in sorted(f.views):
            # .get, skipping None: a concurrent view/field deletion
            # between the key listing and the lookup must produce a
            # (correct) snapshot mismatch, not a KeyError in a read
            v = f.views.get(vname)
            if v is None:
                continue
            for shard in sorted(v.fragments):
                if shards is not None and shard not in shards:
                    continue
                fr = v.fragments.get(shard)
                if fr is None:
                    continue
                snap.append((fname, vname, shard, fr.gen, fr.version))
    return tuple(snap)


def _result_nbytes(r) -> int:
    """Rough byte estimate of one result for LRU accounting.  Every
    container result type gets a size-proportional estimate — a flat
    default would let large Extract/Distinct results slip under the
    byte bound and grow the cache past its budget."""
    from pilosa_tpu.executor.results import (
        DistinctValues,
        ExtractedTable,
        GroupCount,
        SortedRow,
    )
    if isinstance(r, RowResult):
        return 64 + sum(int(w.nbytes) for w in r.segments.values()) + \
            (len(r.keys) * 24 if r.keys else 0)
    if isinstance(r, (list, tuple)):
        return 48 + sum(_result_nbytes(x) for x in r)
    if isinstance(r, dict):
        return 64 + sum(48 + _result_nbytes(v) for v in r.values())
    if isinstance(r, np.ndarray):
        return int(r.nbytes)
    if isinstance(r, DistinctValues):
        return 48 + 24 * len(r.values)
    if isinstance(r, SortedRow):
        return 48 + 16 * (len(r.columns) + len(r.values))
    if isinstance(r, GroupCount):
        return 96 + 64 * len(r.group)
    if isinstance(r, ExtractedTable):
        return 96 + 48 * len(r.fields) + sum(
            64 + 24 * len(c.get("rows", ()))
            if isinstance(c, dict) else 64 for c in r.columns)
    if hasattr(r, "schema") and hasattr(r, "rows"):
        # SQLResult (duck-typed: serving must not import the sql
        # layer) — cached SQL statements size by their row payload
        return 96 + 48 * len(r.schema) + sum(
            48 + 24 * len(row) for row in r.rows)
    return 64


# ---------------------------------------------------------------------------
# versioned result cache
# ---------------------------------------------------------------------------

_MISS = object()


class ResultCache:
    """LRU byte-bounded whole-query result cache, recompute-cost
    aware: entries carry the measured/estimated cost of recomputing
    them (statistics catalog, obs/stats.py), and eviction drops the
    cheapest-to-recompute entry among the LRU window — a hot
    expensive GroupBy survives pressure that flushes point Counts.
    With the catalog disabled every cost is None and eviction is
    pure LRU (the PILOSA_TPU_STATS=0 A/B arm).

    Entry: key -> (fields, snapshot, results, nbytes, cost_ms).  A
    lookup
    recomputes the fields' current snapshot and misses (evicting the
    entry) on any mismatch — so writes invalidate lazily, exactly the
    entries whose read set they touched; ``sweep()`` performs the same
    eviction eagerly after serving-path writes.

    Bytes also account through the process device-memory ledger
    (pilosa_tpu/memory): the local ``max_bytes`` stays as this cache's
    own cap, and under cross-cache pressure the ledger's reclaim
    callback sheds the LRU tail here too — result bytes can no longer
    silently stack on top of a full tile-stack budget."""

    def __init__(self, max_bytes: int = 64 << 20, ledger=None):
        from pilosa_tpu import memory
        self.max_bytes = max_bytes
        self._entries: OrderedDict[tuple, tuple] = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self._client = (memory.ledger() if ledger is None
                        else ledger).register(
            "result_cache", reclaim=self._reclaim)
        self.hits = 0
        self.misses = 0
        # write-through entry keys owned by the standing-query
        # registry (executor/standing.py): maintenance ADVANCES their
        # snapshot in place, so sweeps/eviction must not drop them —
        # a stale get() still misses (no wrong answers) but leaves
        # the entry for the registry's catch_up to advance
        self._standing: set = set()

    # cost-aware eviction scans this many LRU-end entries for the
    # cheapest recompute; small so eviction stays O(1)-ish
    _EVICT_WINDOW = 8

    def _evict_one_locked(self, exclude=None) -> int:
        """Drop one entry (caller holds the lock): the cheapest
        recompute cost among the _EVICT_WINDOW oldest (None cost =
        no evidence = first out; all-None degrades to LRU).
        ``exclude`` protects the entry a put() just inserted — a
        cheap newcomer must not evict ITSELF (it would pin expensive
        entries forever and give the hottest cheap query a 0% hit
        rate).  Returns the freed bytes (0 = nothing evictable)."""
        window = [(k, e) for k, e in itertools.islice(
            self._entries.items(), self._EVICT_WINDOW)
            if k != exclude and k not in self._standing]
        if not window:
            return 0
        best = min(range(len(window)),
                   key=lambda i: (window[i][1][4]
                                  if window[i][1][4] is not None
                                  else -1.0, i))
        key, ent = window[best]
        self._entries.pop(key)
        self._bytes -= ent[3]
        return ent[3]

    def _reclaim(self, need: int) -> int:
        freed = 0
        with self._lock:
            while self._entries and freed < need:
                got = self._evict_one_locked()
                if not got:
                    break  # only standing entries left: not evictable
                freed += got
        if freed:
            self._client.release(freed)
        return freed

    def get(self, idx, key, cur_snap: tuple | None = None):
        """`cur_snap`, when given, must be field_snapshot() of the
        entry's read set taken just now — callers that already walked
        the fragments pass it to avoid a second walk."""
        with self._lock:
            ent = self._entries.get(key)
        if ent is None:
            with self._lock:
                self.misses += 1
            return _MISS
        fields, snap, results, _nb, _cost = ent
        # snapshot outside the lock: touches only holder structures;
        # narrowed to the entry's explicit shard subset (key[2]) so a
        # write to another shard cannot stale it
        if (field_snapshot(idx, fields, _shard_set(key[2]))
                if cur_snap is None else cur_snap) != snap:
            dropped = 0
            with self._lock:
                cur = self._entries.get(key)
                # standing entries stay put on staleness: the
                # registry advances them instead of re-executing
                if cur is ent and key not in self._standing:
                    self._entries.pop(key)
                    self._bytes -= ent[3]
                    dropped = ent[3]
                self.misses += 1
            if dropped:
                self._client.release(dropped)
            return _MISS
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
            self.hits += 1
        return results

    def put(self, key, fields: frozenset, snapshot: tuple, results,
            cost_ms: float | None = None):
        """``cost_ms`` is the entry's recompute cost (fingerprint
        profile estimate, or the duration just measured) — the
        cost-aware eviction's ranking signal; None with the stats
        catalog disabled keeps pure LRU semantics."""
        if _faults.armed("audit-corrupt") and _faults.take(
                "audit-corrupt", f"cache:{key[0]}"):
            # corruption drill (obs/audit.py): the STORED entry gets a
            # flipped bit while the serve in flight stays clean — the
            # injection the cache-audit scrubber must catch
            results = _audit.corrupt_results(results)
        nbytes = _result_nbytes(results)
        if nbytes > self.max_bytes:
            return
        # ledger reservation OUTSIDE our lock (reclaim may call back
        # into _reclaim); denial = serve uncached, exactly like an
        # entry over the local cap
        if not self._client.reserve(nbytes):
            return
        released = 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[3]
                released += old[3]
            self._entries[key] = (fields, snapshot, results, nbytes,
                                  cost_ms)
            self._bytes += nbytes
            while self._bytes > self.max_bytes and self._entries:
                freed = self._evict_one_locked(exclude=key)
                if not freed:  # only the new entry left: it fits
                    break      # (nbytes <= max_bytes guard above)
                released += freed
        if released:
            self._client.release(released)

    def mark_standing(self, key) -> None:
        with self._lock:
            self._standing.add(key)

    def unmark_standing(self, key) -> None:
        """Return a key to normal swept-entry lifecycle (and drop the
        now-unmaintained entry so it cannot serve stale)."""
        dropped = 0
        with self._lock:
            self._standing.discard(key)
            ent = self._entries.pop(key, None)
            if ent is not None:
                self._bytes -= ent[3]
                dropped = ent[3]
        if dropped:
            self._client.release(dropped)

    def advance(self, key, fields: frozenset, snapshot: tuple,
                results, cost_ms: float | None = None) -> None:
        """Write-through maintenance: replace a standing entry's
        snapshot+results in place.  put() already replaces in place
        and its eviction excludes standing keys; a ledger denial just
        drops the entry — the registry's catch_up still serves."""
        self.put(key, fields, snapshot, results, cost_ms)

    def sweep(self, holder, touched: set | None = None,
              shards: set | None = None) -> int:
        """Evict exactly the entries whose snapshot is stale (called
        after serving-path writes).  `touched` narrows the scan to
        entries whose read set intersects the written fields; `shards`
        (a point write's delta naming exactly the (field, shard)
        slices it dirtied) further narrows the staleness test to those
        fragments' stamps — entries a write cannot have staled are not
        re-snapshotted, so per-Set sweep cost tracks relevance, not
        cache occupancy (lazy get-time validation still covers every
        other write path).  Returns the eviction count."""
        with self._lock:
            items = list(self._entries.items())
        evicted = 0
        for key, ent in items:
            if key in self._standing:
                continue  # maintained, not swept
            if touched is not None and not (ent[0] & touched):
                continue
            eshards = shards
            if shards is not None and key[2] is not None:
                # an explicit-shard entry can only be staled by the
                # written shards it actually reads
                eshards = shards & set(key[2])
                if not eshards:
                    continue  # entirely outside the write
            idx = holder.index(key[0])
            if idx is None:
                stale = True
            elif eshards is not None and touched is not None:
                stale = _slices_stale(idx, ent[0], ent[1], touched,
                                      eshards)
            else:
                stale = field_snapshot(idx, ent[0],
                                       _shard_set(key[2])) != ent[1]
            if stale:
                dropped = 0
                with self._lock:
                    cur = self._entries.get(key)
                    if cur is ent:
                        self._entries.pop(key)
                        self._bytes -= ent[3]
                        dropped = ent[3]
                        evicted += 1
                if dropped:
                    self._client.release(dropped)
        return evicted

    def sweep_shards(self, index: str, shards: set[int]) -> int:
        """Online-resharding FENCE/RELEASE sweep: evict exactly the
        entries of ``index`` whose read set can touch the moved
        shards — an explicit-shard entry only when its shard subset
        intersects them, a whole-index entry always (it could have
        read the moved shard).  Unconditional on snapshot equality:
        the donor's fragments are about to leave, and a cached result
        covering the shard would otherwise keep serving answers that
        miss the recipient's new writes.  Entries over OTHER shards
        (and other indexes) survive — a rebalance must never flush
        the whole cache (test-pinned)."""
        with self._lock:
            items = list(self._entries.items())
        evicted = 0
        for key, ent in items:
            if key[0] != index:
                continue
            if key in self._standing:
                continue  # registry fallback re-seeds from the move
            if key[2] is not None and not (set(key[2]) & shards):
                continue
            dropped = 0
            with self._lock:
                cur = self._entries.get(key)
                if cur is ent:
                    self._entries.pop(key)
                    self._bytes -= ent[3]
                    dropped = ent[3]
                    evicted += 1
            if dropped:
                self._client.release(dropped)
        return evicted

    def clear(self):
        with self._lock:
            total = self._bytes
            self._entries.clear()
            self._bytes = 0
        if total:
            self._client.release(total)

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key) -> bool:
        return key in self._entries

    @property
    def nbytes(self) -> int:
        return self._bytes


# ---------------------------------------------------------------------------
# micro-batcher
# ---------------------------------------------------------------------------

class _Req:
    """One in-flight batchable query."""

    __slots__ = ("index", "idx", "q", "call", "kind", "shards", "skey",
                 "fields", "key", "snapshot", "result", "error",
                 "direct", "event", "ctx", "trace_id", "acc",
                 "batch_size")

    def __init__(self, index, idx, q, call, kind, shards, skey,
                 fields, key, snapshot):
        self.index = index
        self.idx = idx
        self.q = q
        self.call = call
        self.kind = kind
        self.shards = shards          # caller's shards arg (may be None)
        self.skey = skey              # resolved shard tuple
        self.fields = fields          # frozenset | None (uncacheable)
        self.key = key
        self.snapshot = snapshot      # admission-time version snapshot
        self.result = None            # list of results when served
        self.error = None
        self.direct = False           # fall back to Executor.execute
        self.event = threading.Event()
        # flight-recorder / tracing plumbing: the follower's captured
        # trace context (obs.tracing.TraceContext — the leader records
        # spans INTO it), its flight trace id, the leader-side phase
        # accumulator merged back at commit, and the batch occupancy
        # the leader stamped
        self.ctx = None
        self.trace_id = None
        self.acc = None
        self.batch_size = 1


class QueryBatcher:
    """Leader/follower continuous batching.

    The first thread to arrive while no leader is active becomes the
    leader: it waits out the admission window (or until ``max_batch``
    requests queue), flips the queue, and executes the fused batch
    while the NEXT batch accumulates behind a new leader.  Followers
    park on a per-request event.
    """

    def __init__(self, serving: "ServingLayer", window_s: float,
                 max_batch: int):
        self.serving = serving
        self.window_s = window_s
        self.max_batch = max_batch
        self._cond = threading.Condition()
        self._pending: list[_Req] = []
        self._leader = False
        self._inflight = 0  # batches currently executing
        # serialize=True (the ragged canonical program, set by
        # ServingLayer): at most ONE batch executes at a time and the
        # next leader waits for it rather than for a wall-clock
        # window.  The canonical program computes every canonical
        # slot per dispatch, so overlapping batches would multiply
        # that fixed cost for ~no extra riders — serializing maximizes
        # occupancy per dispatch, which is the whole amortization.
        self.serialize = False

    def run(self, req: _Req) -> None:
        """Serve one request through the batch path; on return the
        request carries ``result`` or ``error``."""
        with self._cond:
            self._pending.append(req)
            metrics.SERVING_QUEUE_DEPTH.set(len(self._pending))
            if self._leader:
                if len(self._pending) >= self.max_batch:
                    self._cond.notify_all()  # leader stops waiting
                follower = True
            else:
                self._leader = True
                follower = False
        if follower:
            req.event.wait()
            return
        t_lead = time.perf_counter()
        deadline = t_lead + self.window_s
        with self._cond:
            # continuous batching: dispatch IMMEDIATELY when the
            # device is idle (a lone request must not eat the window
            # as pure latency); wait out the admission window only
            # while another batch is executing — that is exactly when
            # requests naturally accumulate
            while (self._inflight > 0
                   and len(self._pending) < self.max_batch):
                if self.serialize:
                    # wait out the in-flight batch itself (notified on
                    # completion), not a wall-clock window — arrivals
                    # during the dispatch become the next full batch
                    self._cond.wait(0.05)
                    continue
                rem = deadline - time.perf_counter()
                if rem <= 0:
                    break
                self._cond.wait(rem)
            batch = self._pending
            self._pending = []
            self._leader = False
            self._inflight += 1
            metrics.SERVING_QUEUE_DEPTH.set(0)
        metrics.SERVING_BATCH_WAIT.observe(time.perf_counter() - t_lead)
        metrics.SERVING_BATCH_SIZE.observe(len(batch))
        # watchdog arming (obs/watchdog.py): the leader is entering
        # the fused dispatch — a dispatch wedged past the deadline is
        # a named stall ("serving-batcher"/"dispatch"), not a silent
        # latency cliff.  begin/end TOKENS, not stamp/idle: under
        # load a full batch dispatches while another is still in
        # flight (the wait loop exits at max_batch even with
        # inflight > 0), and a healthy leader finishing must not
        # disarm or re-stamp away a wedged sibling — staleness is
        # judged against the OLDEST in-flight dispatch.
        wd_tok = self.serving.watch.begin("dispatch")
        try:
            self.serving._run_batch(batch)
        except Exception as e:  # belt-and-braces: never strand a waiter
            # leader-thread failures are otherwise invisible to the
            # followers' own monitoring — capture with the BATCH's
            # trace ids so /debug/errors points at every affected query
            capture_exception(
                e, where="serving.batch", batch=len(batch),
                trace_ids=[r.trace_id for r in batch if r.trace_id])
            # incident trigger (obs/incidents.py): an unhandled batch-
            # leader exception strands no waiter (the loop below fails
            # them typed) but is a serving-plane fault worth a bundle
            from pilosa_tpu.obs import incidents
            incidents.report(
                "batch-leader-exception", detail=type(e).__name__,
                context={"message": str(e)[:300], "batch": len(batch),
                         "trace_ids": [r.trace_id for r in batch
                                       if r.trace_id][:16]})
            for r in batch:
                if r.result is None and r.error is None:
                    r.error = e
        finally:
            self.serving.watch.end(wd_tok)
            with self._cond:
                self._inflight -= 1
                self._cond.notify_all()  # wake a window-waiting leader
            for r in batch:
                r.event.set()


# ---------------------------------------------------------------------------
# serving layer
# ---------------------------------------------------------------------------

class ServingLayer:
    """Front of Executor for the HTTP/gRPC serving path: QoS admission
    first (executor/sched.py), result cache second, micro-batcher
    (per-group or ragged cross-index fused dispatch) third,
    ``Executor.execute`` fallback always."""

    def __init__(self, executor, window_s: float = 0.001,
                 max_batch: int = 32, cache_bytes: int = 64 << 20,
                 batching: bool = True, ragged: bool | None = None,
                 admission: bool | None = None, heavy_slots: int = 2,
                 queue_max: int = 128, tenant_weights=None,
                 default_deadline_ms: float = 0.0):
        import os

        from pilosa_tpu.executor import sched as _sched
        self.executor = executor
        self.batching = batching and max_batch > 1
        self.cache = ResultCache(cache_bytes) if cache_bytes > 0 else None
        self.batcher = QueryBatcher(self, window_s, max_batch)
        self.prefetcher = None
        # ragged cross-index page-table dispatch (executor/ragged.py):
        # one fused device program per batch instead of one per
        # (index, shards) group.  Env-overridable for the bench A/B.
        env_r = os.environ.get("PILOSA_TPU_SERVING_RAGGED")
        if ragged is None:
            ragged = True
        if env_r is not None:
            ragged = env_r != "0"
        self.ragged = ragged
        # QoS admission (executor/sched.py): point reads bypass, heavy
        # reads pass a bounded weighted-fair gate, overflow sheds 503
        env_a = os.environ.get("PILOSA_TPU_SERVING_ADMISSION")
        if admission is None:
            admission = True
        if env_a is not None:
            admission = env_a != "0"
        weights = (tenant_weights
                   if isinstance(tenant_weights, dict)
                   else _sched.parse_weights(tenant_weights))
        self.sched = _sched.AdmissionScheduler(
            heavy_slots=heavy_slots, queue_max=queue_max,
            tenant_weights=weights) if admission else None
        self.default_deadline_ms = float(default_deadline_ms or 0.0)
        # one canonical dispatch at a time (see QueryBatcher)
        self.batcher.serialize = self.ragged
        # stall watchdog on the batch-leader dispatch (obs/watchdog.py;
        # registration is idempotent by name — serving layers are
        # rebuilt freely in-process and the loop identity is the name)
        from pilosa_tpu.obs import watchdog
        self.watch = watchdog.register("serving-batcher")
        # standing-query registry (executor/standing.py): maintained
        # write-through entries over this cache.  Runtime import —
        # standing imports serving's module surface
        from pilosa_tpu.executor.standing import StandingRegistry
        self.standing = StandingRegistry(self)
        # continuous correctness auditing (obs/audit.py): the shadow
        # sampler taps every successful read route; workers spawn
        # lazily on the first sampled serve
        self.audit = _audit.AuditPlane(self)

    def start_prefetcher(self, interval_s: float = 0.5):
        """Warm predicted stack pages off the serving hot path
        (memory/policy.py Prefetcher over the flight recorder's
        per-query stack-outcome records).  Idempotent."""
        if self.prefetcher is None:
            from pilosa_tpu.memory.policy import Prefetcher
            self.prefetcher = Prefetcher(
                self.executor.stacked.cache,
                interval_s=interval_s).start()
        return self.prefetcher

    def stop_prefetcher(self):
        if self.prefetcher is not None:
            self.prefetcher.stop()
            self.prefetcher = None

    # -- entry point ---------------------------------------------------

    def execute(self, index: str, query, shards=None,
                remote: bool = False, qos=None) -> list:
        from pilosa_tpu.executor import sched as _sched
        ex = self.executor
        if remote:
            # node-to-node calls carry the _REMOTE contextvar, which a
            # leader thread would not inherit — serve them solo
            return ex.execute(index, query, shards, remote=True)
        q = parse(query) if isinstance(query, str) else query
        if any(c.name in _WRITE_CALLS for c in q.calls):
            try:
                return ex.execute(index, q, shards)
            finally:
                if self.cache is not None:
                    wf, ws = _write_targets(ex.holder.index(index), q)
                    self.cache.sweep(ex.holder, wf, ws)
                    metrics.RESULT_CACHE.inc(outcome="write")
                    # push the landed delta through the standing
                    # registrations this write can have touched
                    self.standing.on_write(index, wf, ws)
        # default deadline: a [serving] default-deadline-ms applies to
        # every request that carried no deadline of its own — a
        # tenant/priority header must not opt a request out of the
        # operator's configured budget
        if self.default_deadline_ms > 0:
            if qos is None:
                qos = _sched.QoS.make(
                    deadline_ms=self.default_deadline_ms)
            elif qos.deadline_s is None:
                dflt = _sched.QoS.make(
                    deadline_ms=self.default_deadline_ms)
                qos.deadline_ms = dflt.deadline_ms
                qos.deadline_s = dflt.deadline_s
        # cost-based admission (obs/stats.py): classify by the plan
        # fingerprint's MEASURED cost profile when the catalog is warm
        # (query kind stays the cold-start fallback inside classify).
        # An explicit priority override skips the hash — classify
        # returns before reading it, and SQL's inner calls (always
        # explicit point) would otherwise pay a blake2b over a
        # possibly-huge ConstRow repr per call; _execute_read
        # recomputes the key (and commit the fingerprint) when a
        # flight record actually consumes them
        key = None
        fp = None
        if _stats.enabled() and not (
                qos is not None and qos.priority in (
                    _sched.CLASS_POINT, _sched.CLASS_HEAVY)):
            key = (index, repr(q.calls),
                   None if shards is None else tuple(sorted(shards)))
            fp = _fingerprint(key)
        cls = _sched.classify(q, qos, fingerprint=fp)
        # a dead-on-arrival deadline sheds regardless of class — the
        # client stopped waiting, executing would only burn device time
        if (qos is not None and qos.deadline_s is not None
                and time.monotonic() > qos.deadline_s):
            metrics.ADMISSION_TOTAL.inc(**{"class": cls,
                                           "outcome": "expired"})
            raise _sched.ServingDeadlineExceeded(
                "deadline expired before execution")
        # span on the CALLER's thread so the long-query log keeps its
        # executor.Execute root even for fused/cached serves (the
        # direct fallback nests its own copy inside — the root name
        # is what the log consumers pin on)
        if cls == _sched.CLASS_HEAVY and self.sched is not None:
            # bounded heavy concurrency + weighted per-tenant fair
            # queueing: a GroupBy storm can no longer occupy every
            # engine thread, so point reads never queue behind it
            with self.sched.heavy_slot(qos):
                with start_span("executor.Execute", index=index) as root:
                    return self._execute_read(ex, index, q, shards,
                                              root, qos=qos, cls=cls,
                                              key=key, fp=fp)
        metrics.ADMISSION_TOTAL.inc(**{"class": cls,
                                       "outcome": "admitted"})
        with start_span("executor.Execute", index=index) as root:
            return self._execute_read(ex, index, q, shards, root,
                                      qos=qos, cls=cls, key=key,
                                      fp=fp)

    def _execute_read(self, ex, index, q, shards, root=None, qos=None,
                      cls=None, key=None, fp=None):
        t0 = time.perf_counter()
        route = "direct"
        fl = flight.begin(index, q)
        if fl is not None:
            # QoS attribution: every serving-path record names its
            # tenant, admission class, and deadline budget so
            # /debug/queries can answer "whose query, how urgent"
            fl["tenant"] = qos.tenant if qos is not None else "default"
            fl["priority"] = cls or "point"
            if qos is not None and qos.deadline_ms is not None:
                fl["deadline_ms"] = round(float(qos.deadline_ms), 1)
        if fl is not None and root is not None:
            root.set_tag("trace_id", fl["trace_id"])
        req = None
        err = None
        try:
            idx = ex.holder.index(index)
            if idx is None:  # canonical "index not found" error path
                return ex.execute(index, q, shards)
            if key is None:  # stats-off path: execute() skipped it
                key = (index, repr(q.calls),
                       None if shards is None else tuple(sorted(shards)))
            # the read set drives BOTH the cache guard and the
            # batcher's mid-flight consistency re-check, so compute it
            # even with the cache disabled
            fields = None
            tc = time.perf_counter()
            try:
                fields = query_fields(idx, q)
            except Uncacheable:
                if self.cache is not None:
                    metrics.RESULT_CACHE.inc(outcome="bypass")
            # ONE snapshot walk serves the cache guard, batch
            # admission, and the miss-path store protocol (the walk is
            # O(fields x views x shards) Python — at 954 shards it
            # must not run three times per query); explicit-shard
            # queries snapshot only their subset, so writes elsewhere
            # never stale them
            sset = _shard_set(shards)
            snap = (field_snapshot(idx, fields, sset)
                    if fields is not None else None)
            cache_res = _MISS
            if self.cache is not None and fields is not None:
                cache_res = self.cache.get(idx, key, cur_snap=snap)
            flight.note_phase("cache_lookup", time.perf_counter() - tc)
            if cache_res is not _MISS:
                route = "cached"
                metrics.RESULT_CACHE.inc(outcome="hit")
                metrics.QUERY_TOTAL.inc(index=index, status="ok")
                metrics.QUERY_DURATION.observe(
                    time.perf_counter() - t0)
                # audit tap with the hit's OWN guard snapshot: get()
                # verified the entry against `snap`, so the answer is
                # proven to reflect exactly that fragment-version state
                return _audit.tap(self.audit, index, idx, q, shards,
                                  key, fields, snap, "cached",
                                  cache_res, fl)
            if self.cache is not None and fields is not None:
                metrics.RESULT_CACHE.inc(outcome="miss")
            # a registry-owned key pulls maintenance instead of
            # re-executing: the poll pays O(delta), never a restack
            if self.standing.owns(key):
                got = self.standing.catch_up(key)
                if got is not _MISS:
                    route = "standing"
                    metrics.QUERY_TOTAL.inc(index=index, status="ok")
                    metrics.QUERY_DURATION.observe(
                        time.perf_counter() - t0)
                    # the registry's snapshot is the one that provably
                    # covers the maintained result (catch_up may have
                    # advanced past `snap` taken at admission)
                    sq = self.standing._by_key.get(key)
                    return _audit.tap(
                        self.audit, index, idx, q, shards, key,
                        fields,
                        sq.snapshot if sq is not None else None,
                        "standing", got, fl)
            # classification pays a shard-list sort — skip it
            # entirely in cache-only mode
            req = (self._classify(index, idx, q, shards, fields, key,
                                  snap)
                   if self.batching else None)
            if req is not None:
                # cross-thread propagation: the leader records this
                # request's device phases into the captured context
                # (None when nothing traces — zero overhead)
                req.ctx = capture_context()
                if fl is not None:
                    req.trace_id = fl["trace_id"]
                tb = time.perf_counter()
                self.batcher.run(req)
                flight.note_phase("batch", time.perf_counter() - tb)
                if req.error is not None:
                    raise req.error
                if req.result is not None and not req.direct:
                    route = "fused"
                    metrics.QUERY_TOTAL.inc(index=index, status="ok")
                    metrics.QUERY_DURATION.observe(
                        time.perf_counter() - t0)
                    # req.snapshot survived the batch post-pass
                    # re-check, so it provably covers the fused answer
                    return _audit.tap(self.audit, index, idx, q,
                                      shards, key, fields,
                                      req.snapshot, "fused",
                                      req.result, fl)
                # fallback on THIS thread: failed/stale fused serves
                # re-execute in parallel across their callers, not
                # serially on the batch leader.  snap is stale here by
                # definition — _exec_and_cache takes a fresh one.
                snap = None
            return self._exec_and_cache(index, idx, q, shards, fields,
                                        key, snap, fl=fl)
        except Exception as e:
            err = f"{type(e).__name__}: {e}"
            raise
        finally:
            metrics.SERVING_BATCHED.inc(route=route)
            if fl is None:
                # nested under an open record (a SQL statement's
                # inner PQL dispatch): stamp this serve's route into
                # the parent so /debug/queries shows which of a
                # statement's calls rode the fused plane
                flight.note_route(route)
            dur = time.perf_counter() - t0
            metrics.SERVING_LATENCY.observe(dur)
            flight.commit(
                fl, dur, route=route,
                batch=req.batch_size if req is not None else 1,
                error=err,
                # reuse the admission fingerprint (stats path) —
                # repr+hash of the whole key must not be paid twice;
                # with stats off, pay it only when a record is open
                fingerprint=(fp if fp is not None else
                             (_fingerprint(key)
                              if fl is not None and key else None)),
                extra_acc=req.acc if req is not None else None)

    # -- classification ------------------------------------------------

    def _classify(self, index, idx, q: Query, shards, fields, key,
                  snapshot=None):
        """A _Req when the query can ride a fused batch, else None."""
        if len(q.calls) != 1 or not getattr(self.executor,
                                            "use_stacked", False):
            return None
        call = q.calls[0]
        name = call.name
        if name == "Count":
            if len(call.children) != 1:
                return None
            kind, tree_call = "count", call.children[0]
        elif name == "Sum":
            kind = "sum"
            tree_call = call.children[0] if call.children else None
        elif name in ("TopN", "TopK"):
            kind = "topn"
            tree_call = call.children[0] if call.children else None
        elif name == "GroupBy":
            # batchable subset (ISSUE 11): Rows children over plain
            # fields, optional pure filter tree, optional Sum
            # aggregate — the shapes the one-pass "gb_hist" subplan
            # expresses.  previous=/having=/limit= and
            # Min/Max/Count(Distinct) aggregates stay solo.
            if any(call.arg(k) is not None
                   for k in ("previous", "having", "limit")):
                return None
            if not call.children or any(
                    c.name != "Rows" or c.children
                    or set(c.args) - {"_field"}
                    for c in call.children):
                return None
            agg = call.arg("aggregate")
            if agg is not None and (
                    not isinstance(agg, Call) or agg.name != "Sum"
                    or agg.children or agg.arg("_field") is None):
                return None
            kind, tree_call = "groupby", call.arg("filter")
        elif name in _PURE_BITMAP:
            kind, tree_call = "words", call
        else:
            return None
        if tree_call is not None and not _pure_tree(tree_call):
            return None
        skey = tuple(self.executor._shard_list(idx, shards))
        if snapshot is None and fields is not None:
            snapshot = field_snapshot(idx, fields, _shard_set(shards))
        return _Req(index, idx, q, call, kind, shards, skey, fields,
                    key, snapshot)

    # -- batch execution (leader thread) -------------------------------

    def _run_batch(self, batch: list[_Req]) -> None:
        # group by index IDENTITY, not name: two requests straddling a
        # drop-and-recreate of the same index name must not share one
        # PlanBuilder (reqs[0].idx would serve the other's query from
        # the wrong generation's fragments)
        groups: dict[tuple, list[_Req]] = {}
        for r in batch:
            r.batch_size = len(batch)  # flight-record occupancy
            groups.setdefault((id(r.idx), r.skey), []).append(r)
        # ragged cross-index dispatch: ONE fused page-table program
        # serves every group (executor/ragged.py) — a planning failure
        # degrades to the per-group path, a dispatch failure marks the
        # riders direct (both non-fatal, like _run_group's own ladder).
        # Mesh placements keep per-group programs: concatenating
        # differently-sharded operands in one program is not expressible.
        ragged_done = False
        if (self.ragged and groups
                and self.executor.stacked.mesh is None):
            try:
                from pilosa_tpu.executor import ragged as _ragged
                _ragged.run_ragged(self, groups)
                ragged_done = True
            except Exception as e:
                capture_exception(e, where="serving.ragged_plan",
                                  batch=len(batch))
        if not ragged_done:
            for reqs in groups.values():
                self._run_group(reqs)
        # post-pass: snapshot re-check.  Fallbacks are NOT executed
        # here — the leader running every solo re-execution serially
        # would hold all followers hostage; instead the request is
        # marked direct and each CALLER thread re-executes its own
        # query after its event fires (parallel, like batching off).
        for r in batch:
            if (not r.direct and r.error is None and r.result is not None
                    and r.fields is not None
                    and field_snapshot(r.idx, r.fields,
                                       _shard_set(r.shards))
                    != r.snapshot):
                # a write landed while the batch was in flight: the
                # fused result may span versions — re-execute solo
                r.direct = True
                r.result = None
            if r.result is not None and not r.direct and \
                    r.error is None and r.fields is not None and \
                    self.cache is not None:
                self.cache.put(r.key, r.fields, r.snapshot, r.result,
                               cost_ms=self._recompute_cost(r.key,
                                                            r.acc))

    def _run_group(self, reqs: list[_Req]) -> None:
        ex = self.executor
        eng = ex.stacked
        idx = reqs[0].idx
        shards = list(reqs[0].skey)
        b = PlanBuilder(eng, idx, shards, {})
        subs, demuxes, pend = [], [], []
        # canonical build order: leaf indices are assigned during
        # build, so permutations of the same query set must BUILD in
        # one order to share a compiled multi program (sorting only
        # the finished subplans would leave arrival-dependent leaf
        # numbering behind)
        for r in sorted(reqs, key=lambda r: repr(r.call)):
            if r.result is not None or r.error is not None:
                continue
            # per-request attribution ON the leader thread: stack
            # fetches/uploads inside the build accumulate into THIS
            # request's Acc, and spans graft into its TraceContext
            r.acc = acc = flight.Acc()
            prev = flight.push_acc(acc)
            t0 = time.perf_counter()
            try:
                with span_into(r.ctx, "serving.plan",
                               kind=r.kind):
                    built = self._build_sub(b, r, shards)
            except Exception:
                r.direct = True
                continue
            finally:
                flight.pop_acc(prev)
                stack_t = sum(v for k, v in acc.phases.items()
                              if k.startswith("stack_"))
                acc.add_phase("plan_build", max(
                    time.perf_counter() - t0 - stack_t, 0.0))
            if built is None:
                continue  # constant result already set on r
            sub, demux = built
            subs.append(sub)
            demuxes.append(demux)
            pend.append(r)
        if not subs:
            return
        # the SHARED phase: one fused dispatch serves every pending
        # request, timed once and attributed (with a span copy) to
        # each — a recompile of the multi program is tagged distinctly
        # from a cached-executable dispatch
        plan = ("multi", tuple(subs))
        kern = kernels.enabled() and not eng.host_only
        sig = (repr(plan), kern)  # multi-KB at high occupancy: once
        kind = _dispatch_kind(sig, b.leaves, b.params)
        sp = Span("serving.dispatch")
        sp.tags.update(batch=len(pend), subqueries=len(subs),
                       compile=kind == "compile")
        t0 = time.perf_counter()
        try:
            # chaos seam: an armed "serving-dispatch" fault fails the
            # fused program exactly like a device-side error, driving
            # every rider onto the per-caller direct fallback
            from pilosa_tpu.obs import faults
            faults.fire("serving-dispatch")
            fn = _compiled(plan, kern=kern, sig=sig)
            # OOM backstop: RESOURCE_EXHAUSTED on the fused program
            # evicts via the ledger + retries once; a persistent OOM
            # falls through to the per-rider direct path, where each
            # solo dispatch carries its own host-fallback ladder —
            # the batch degrades, no rider's query fails
            from pilosa_tpu.memory import pressure
            outs = pressure.guarded(
                lambda: _block(fn(tuple(b.leaves), tuple(b.params))))
        except Exception as e:
            # the fused program failing is a leader-side event the
            # affected callers never see (they silently fall back) —
            # surface it with every rider's trace id
            capture_exception(
                e, where="serving.fused_dispatch", batch=len(pend),
                trace_ids=[r.trace_id for r in pend if r.trace_id])
            for r in pend:
                r.direct = True
            return
        finally:
            sp.finish()
        metrics.SERVING_DISPATCH.inc(kind="group")
        dt = time.perf_counter() - t0
        for r in pend:
            r.acc.add_phase(kind, dt)
            if r.ctx is not None:
                r.ctx.attach(sp.copy())
        for r, demux, out in zip(pend, demuxes, outs):
            t1 = time.perf_counter()
            try:
                with span_into(r.ctx, "serving.demux"):
                    r.result = demux(out)
            except Exception:
                r.direct = True
                r.result = None
            r.acc.add_phase("demux", time.perf_counter() - t1)

    def _build_sub(self, b: PlanBuilder, r: _Req, shards: list[int]):
        """(subplan, demux) for one request, or None after setting a
        constant result.  Any exception → solo fallback (which also
        reproduces the user-visible error faithfully)."""
        ex = self.executor
        eng = ex.stacked
        idx = r.idx
        red = eng._reduce_in_program(shards)
        call = r.call
        if r.kind == "count":
            tree = b.build(call.children[0])
            if tree == ("zeros",):
                r.result = [0]
                return None

            def demux_count(out):
                c = np.asarray(out, dtype=np.int64)
                return [int(c) if red else int(c.sum())]
            return ("count", tree, red), demux_count
        if r.kind == "words":
            tree = b.build(call)
            if tree == ("zeros",):
                r.result = [self._row_result(idx, shards, None)]
                return None

            def demux_words(out):
                w = np.asarray(out)[: len(shards)]
                return [self._row_result(idx, shards, w)]
            return ("words", tree), demux_words
        if r.kind == "sum":
            fname = call.arg("_field")
            if fname is None:
                raise Unstackable("Sum without field")
            f = ex._bsi_field(idx, fname)
            planes_i = b._planes_leaf(f)
            tree = None
            if call.children:
                tree = b.build(call.children[0])
                if tree == ("zeros",):
                    r.result = [ValCount(value=f.int_to_value(0), count=0)]
                    return None

            def demux_sum(out):
                cnt, pos, neg = out
                total, count = eng.bsi_sum_host(cnt, pos, neg, red)
                return [ValCount(value=f.int_to_value(total),
                                 count=count)]
            return ("bsi_sum", planes_i, tree, red), demux_sum
        if r.kind == "topn":
            n_key = "n" if call.name == "TopN" else "k"
            prep = ex._topnk_prepare(idx, call, r.shards, {}, n_key)
            if prep[0] == "done":
                r.result = [prep[1]]
                return None
            _, f, views, row_ids, filter_call, n, ids = prep
            est = len(row_ids) * max(len(shards), 1) * (idx.width // 8)
            if est > ex._ROWS_STACK_BUDGET:
                raise Unstackable("TopN row stack over batch budget")
            stack = eng.rows_stack_for(idx, f, tuple(views), row_ids,
                                       tuple(shards))
            rows_i = b._add_leaf(stack)
            tree = (b.build(filter_call)
                    if filter_call is not None else None)
            if tree == ("zeros",):
                pairs = ([Pair(id=rr, count=0) for rr in row_ids]
                         if ids is not None else [])
                r.result = [ex._finish_topn(f, pairs, n, ids)]
                return None

            def demux_topn(out):
                c = np.asarray(out, dtype=np.int64)
                if not red:
                    c = c.sum(axis=1)
                pairs = [Pair(id=rr, count=int(cc))
                         for rr, cc in zip(row_ids, c)
                         if cc > 0 or ids is not None]
                return [ex._finish_topn(f, pairs, n, ids)]
            return ("row_counts", rows_i, tree, red), demux_topn
        if r.kind == "groupby":
            return self._build_groupby_sub(b, r, shards)
        raise Unstackable(f"unbatchable kind {r.kind}")

    def _build_groupby_sub(self, b: PlanBuilder, r: _Req,
                           shards: list[int]):
        """One-pass GroupBy as a batched subplan: the group-code stack
        and BSI planes become shared leaves (PageView pages under the
        ragged program) and the histogram evaluates inside the fused
        device program — a GroupBy rider costs the batch ONE
        single-pass tile walk, not its own dispatch (ISSUE 11)."""
        from pilosa_tpu.executor.stacked import (
            _code_space,
            _combo_codes,
            _onepass_arm,
            _onepass_unpack,
        )
        from pilosa_tpu.obs.metrics import GROUPBY_FUSED, GROUPBY_ONEPASS

        ex = self.executor
        eng = ex.stacked
        idx = r.idx
        call = r.call
        if eng.host_only:
            raise Unstackable("groupby batch needs a device program")
        fields, row_lists = [], []
        for rc in call.children:
            fname = rc.arg("_field")
            f = idx.field(fname) if fname else None
            if f is None:
                raise Unstackable("Rows requires a valid field")
            fields.append(f)
            row_lists.append(ex._rows_ids(idx, rc, r.shards))
        if any(not rl for rl in row_lists):
            r.result = [[]]
            return None
        agg_call = call.arg("aggregate")
        agg_field = (ex._bsi_field(idx, agg_call.arg("_field"))
                     if agg_call is not None else None)
        depth = agg_field.bit_depth if agg_field is not None else 0
        fields_rows = list(zip(fields, row_lists))
        combos = np.indices([len(rl) for rl in row_lists]) \
            .reshape(len(row_lists), -1).T.astype(np.int64)
        skey = tuple(shards)
        if not eng._groupby_onepass_ok(
                idx, fields_rows, len(combos), depth,
                agg_field is not None, skey):
            raise Unstackable("groupby shape not one-pass batchable")
        bits, shifts, n_codes = _code_space(fields_rows)
        codes = _combo_codes(shifts, combos)
        arm = _onepass_arm(n_codes, depth)
        if arm != "xla":
            from pilosa_tpu.memory import placement as _placement
            if (eng._n_total_devices() > 1
                    or _placement.mesh_devices() > 1):
                # mirror the solo path's mesh guard: a pallas_call
                # over mesh-sharded leaves inside the fused multi (or
                # shard_map ragged_mesh) program would force a gather
                # (or fail to lower and demote every rider in the
                # batch); the scatter reference shards under GSPMD
                arm = "xla"
        signed = False
        if agg_field is not None:
            frags = eng._frags(idx, agg_field, agg_field.bsi_view,
                               list(skey))
            signed = any(fr is not None and 1 in fr.row_ids
                         for fr in frags)
        filter_call = call.arg("filter")
        tree = None
        if filter_call is not None:
            tree = b.build(filter_call)
            if tree == ("zeros",):
                r.result = [[]]
                return None
        cg_i = b._groupcode_leaf(fields_rows)
        planes_i = (b._planes_leaf(agg_field)
                    if agg_field is not None else None)
        GROUPBY_ONEPASS.inc()
        if arm == "fused":
            GROUPBY_FUSED.inc(path="batched")
        has_planes = agg_field is not None

        def demux_groupby(out):
            counts, nn, pos, neg = _onepass_unpack(
                np.asarray(out), n_codes, depth, has_planes)
            agg_nn = agg_pos = agg_neg = None
            if has_planes:
                agg_nn, agg_pos, agg_neg = nn[codes], pos[codes], \
                    neg[codes]
            return [ex._assemble_groupby(
                fields, row_lists, combos, counts[codes], agg_field,
                "sum", agg_nn, agg_pos, agg_neg, None, None, None,
                None)]
        return (("gb_hist", cg_i, tree, planes_i, n_codes, signed,
                 arm), demux_groupby)

    def _row_result(self, idx, shards: list[int], words) -> RowResult:
        """Mirror Executor._bitmap_result + the translateResults key
        attachment for a fused bitmap query."""
        out = RowResult(idx.width)
        if words is not None:
            for i, shard in enumerate(shards):
                if words[i].any():
                    out.segments[shard] = words[i]
        if idx.keys:
            out.keys = idx.column_translator.translate_ids(out.columns())
        return out

    # -- solo path with cache store ------------------------------------

    def _exec_and_cache(self, index, idx, q, shards, fields, key,
                        snap=None, fl=None):
        """Solo execution with the store protocol: snapshot before,
        execute, store only if the snapshot held.  `snap`, when
        given, must have been taken pre-execution on this path."""
        ex = self.executor
        if self.cache is None or fields is None:
            return ex.execute(index, q, shards)
        sset = _shard_set(shards)
        if snap is None:
            snap = field_snapshot(idx, fields, sset)
        t0 = time.perf_counter()
        results = ex.execute(index, q, shards)
        cost = None
        if _stats.enabled():
            cost = _stats.est_recompute_ms(_fingerprint(key))
            if cost is None:  # cold fingerprint: the run we just paid
                cost = (time.perf_counter() - t0) * 1e3
        # store only if no write raced the execution (a racing write
        # would make the cached value's snapshot provenance unclear)
        if field_snapshot(idx, fields, sset) == snap:
            self.cache.put(key, fields, snap, results, cost_ms=cost)
            # audit tap ONLY on held snapshots: a raced execution has
            # no provable provenance and sampling it could produce a
            # shadow false positive
            results = _audit.tap(self.audit, index, idx, q, shards,
                                 key, fields, snap, "solo", results,
                                 fl)
        return results

    @staticmethod
    def _recompute_cost(key, acc) -> float | None:
        """Recompute-cost hint for a cache entry: the fingerprint
        profile's NON-CACHED estimate (est_recompute_ms — the serve
        EWMA would be talked down to ~0 by the cache's own hits for
        exactly the entries most worth keeping), else the
        leader-attributed phase time of the serve that produced it;
        None (pure LRU) with the statistics catalog disabled."""
        if not _stats.enabled():
            return None
        cost = _stats.est_recompute_ms(_fingerprint(key))
        if cost is None and acc is not None:
            cost = sum(acc.phases.values()) * 1e3
        return cost


def _pure_tree(call: Call) -> bool:
    """True when a bitmap tree uses only calls the PlanBuilder can
    express without per-query precompute or key-dependent leaves."""
    if call.name not in _PURE_BITMAP:
        return False
    if any(isinstance(v, Call) for v in call.args.values()):
        return False
    return all(_pure_tree(c) for c in call.children)
