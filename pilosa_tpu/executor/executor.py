"""Executor — PQL call dispatch and per-shard evaluation.

Behavioral port of the reference executor's read/write call dispatch
(executor.go:634-843) with per-shard hot loops on the device kernels:

- bitmap calls (Row/Union/Intersect/Difference/Xor/Not/Shift/All/
  ConstRow) evaluate to packed word tiles per shard via ops.bitmap;
- BSI condition rows (``Row(x > 5)``) and Sum/Min/Max lower to
  ops.bsi comparator/popcount kernels with plan-time predicate
  scaling (decimal/timestamp → scaled ints, ceil/floor per op) and
  out-of-range short-circuits;
- reductions (Count, Sum, ...) combine per-shard device scalars into
  exact Python ints on the host.

Single-host v0: shards iterate in a Python loop; the mesh executor
(parallel/) stacks shard tiles onto a device mesh instead.
"""

from __future__ import annotations

import contextvars
import datetime as dt
import time
from decimal import Decimal
from fractions import Fraction
from math import ceil, floor

import numpy as np
import jax.numpy as jnp

from pilosa_tpu.executor.results import (
    DistinctValues,
    Pair,
    RowResult,
    ValCount,
)
from pilosa_tpu.models import timeq
from pilosa_tpu.obs import flight, metrics
from pilosa_tpu.obs.tracing import start_span
from pilosa_tpu.models.field import FALSE_ROW, TRUE_ROW, Field
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.models.index import EXISTENCE_FIELD, Index
from pilosa_tpu.models.schema import FieldType
from pilosa_tpu.models.view import VIEW_STANDARD
from pilosa_tpu.ops import bitmap as bm
from pilosa_tpu.ops import bsi as bsi_ops
from pilosa_tpu.ops import kernels
from pilosa_tpu.pql import ast as past
from pilosa_tpu.pql import parse
from pilosa_tpu.pql.ast import Call, Condition, Query


class ExecError(Exception):
    pass


# Calls that write (pql.Call.IsWrite analog).
_WRITE_CALLS = {"Set", "Clear", "Store", "ClearRow", "Delete"}

# True while serving a node-to-node (Remote=true) request whose ids
# were already translated by the coordinator (executor.go opt.Remote)
_REMOTE = contextvars.ContextVar("pilosa_tpu_remote", default=False)


from pilosa_tpu.executor.advanced import AdvancedOps
from pilosa_tpu.executor.stacked import StackedEngine, Unstackable


class Executor(AdvancedOps):
    def __init__(self, holder: Holder):
        self.holder = holder
        # the mesh-integrated stacked engine (executor/stacked.py):
        # bitmap trees run as ONE jitted program over (S, W) shard
        # stacks — the jitted analog of mapReduce (executor.go:6449).
        # The per-shard Python loop below survives only as the
        # fallback for trees the IR can't express.
        self.stacked = StackedEngine(self)
        self.use_stacked = True
        # the serving front (executor/serving.py): cross-query
        # micro-batching + versioned result cache.  None until a
        # server (or bench) opts in via enable_serving().
        self.serving = None

    def enable_serving(self, window_s: float = 0.001,
                       max_batch: int = 32,
                       cache_bytes: int = 64 << 20,
                       batching: bool = True, **qos_kwargs):
        """Attach the serving layer (executor/serving.py): concurrent
        queries coalesce into one device dispatch per admission window
        (ragged cross-index page-table fusion when possible,
        executor/ragged.py) and repeated reads serve from the
        write-version-guarded result cache.  ``qos_kwargs`` forward to
        the admission scheduler (ragged/admission/heavy_slots/
        queue_max/tenant_weights/default_deadline_ms).  Returns the
        layer for introspection."""
        from pilosa_tpu.executor.serving import ServingLayer
        self.serving = ServingLayer(self, window_s=window_s,
                                    max_batch=max_batch,
                                    cache_bytes=cache_bytes,
                                    batching=batching, **qos_kwargs)
        return self.serving

    def execute_serving(self, index_name: str, query: str | Query,
                        shards: list[int] | None = None,
                        remote: bool = False, qos=None) -> list:
        """Serving-path entry: routes through the admission scheduler
        + micro-batcher + result cache when enabled, else plain
        execute().  ``qos`` (executor/sched.py QoS) carries the
        request's tenant/priority/deadline intent."""
        if self.serving is None:
            return self.execute(index_name, query, shards, remote=remote)
        return self.serving.execute(index_name, query, shards,
                                    remote=remote, qos=qos)

    def set_mesh(self, mesh):
        """Place all shard stacks over a jax.sharding.Mesh; cross-
        shard reductions then lower to ICI collectives."""
        self.stacked.set_mesh(mesh)

    # ------------------------------------------------------------------
    # entry point (executor.Execute analog)
    # ------------------------------------------------------------------

    def execute(self, index_name: str, query: str | Query,
                shards: list[int] | None = None,
                remote: bool = False) -> list:
        """remote=True marks a node-to-node call shipping
        pre-translated ids (executor.go opt.Remote): keyed indexes then
        accept raw column ids instead of rejecting them."""
        tok = _REMOTE.set(remote)
        try:
            return self._execute(index_name, query, shards)
        finally:
            _REMOTE.reset(tok)

    def _execute(self, index_name: str, query: str | Query,
                 shards: list[int] | None = None) -> list:
        t0 = time.perf_counter()
        status = "error"
        idx = self.holder.index(index_name)
        # label only with names of real indexes: arbitrary client
        # strings would grow metric cardinality without bound
        known = idx is not None
        # flight record for the SOLO path (no serving layer in front);
        # begin() returns None when one is already open on this thread
        # — the serving layer's direct fallback must not double-record
        fl = flight.begin(index_name, query)
        try:
            if idx is None:
                raise ExecError(f"index not found: {index_name}")
            q = parse(query) if isinstance(query, str) else query
            out = []
            # tracing.StartSpanFromContext analog (executor.go:6450)
            with start_span("executor.Execute", index=index_name) as sp:
                if fl is not None:
                    sp.set_tag("trace_id", fl["trace_id"])
                for c in q.calls:
                    with start_span(f"executor.execute{c.name}"):
                        res = self._execute_call(idx, c, shards)
                    # translateResults analog (executor.go:7519): attach
                    # column keys to row results on keyed indexes
                    if isinstance(res, RowResult) and idx.keys and \
                            getattr(res, "is_row_ids", False) is False:
                        res.keys = idx.column_translator.translate_ids(
                            res.columns())
                    out.append(res)
            status = "ok"
            return out
        finally:
            metrics.QUERY_TOTAL.inc(
                index=index_name if known else "(unknown)", status=status)
            dur = time.perf_counter() - t0
            metrics.QUERY_DURATION.observe(dur)
            flight.commit(fl, dur, route="solo",
                          error=None if status == "ok" else status)

    # ------------------------------------------------------------------
    # dispatch
    # ------------------------------------------------------------------

    def _execute_call(self, idx: Index, call: Call, shards, pre=None):
        name = call.name
        if pre is None:
            pre = self._precompute_nested(idx, call, shards)
        if name == "Options":
            return self._execute_options(idx, call, shards)
        if name in _WRITE_CALLS:
            return self._execute_write(idx, call, pre)
        if name == "Count":
            return self._reduce_count(idx, self._only_child(call), shards, pre)
        if name == "Sum":
            return self._execute_sum(idx, call, shards, pre)
        if name in ("Min", "Max"):
            return self._execute_minmax(idx, call, shards, name == "Min", pre)
        if name in ("MinRow", "MaxRow"):
            return self._execute_minmax_row(idx, call, shards,
                                            name == "MinRow", pre)
        if name == "FieldValue":
            return self._execute_field_value(idx, call)
        if name == "Distinct":
            return self._execute_distinct(idx, call, shards, pre)
        if name == "Rows":
            return self._execute_rows(idx, call, shards)
        if name == "UnionRows":
            return self._execute_union_rows(idx, call, shards)
        if name == "IncludesColumn":
            return self._execute_includes_column(idx, call, shards, pre)
        if name == "Limit":
            return self._execute_limit(idx, call, shards, pre)
        if name == "TopN":
            return self._execute_topnk(idx, call, shards, pre, "n")
        if name == "TopK":
            return self._execute_topnk(idx, call, shards, pre, "k")
        if name == "GroupBy":
            return self._execute_groupby(idx, call, shards, pre)
        if name == "Percentile":
            return self._execute_percentile(idx, call, shards, pre)
        if name == "Sort":
            return self._execute_sort(idx, call, shards, pre)
        if name == "Extract":
            return self._execute_extract(idx, call, shards, pre)
        # bitmap-producing calls
        return self._bitmap_result(idx, call, shards, pre)

    def _only_child(self, call: Call) -> Call:
        if len(call.children) != 1:
            raise ExecError(f"{call.name} requires exactly one subquery")
        return call.children[0]

    def _shard_list(self, idx: Index, shards) -> list[int]:
        if shards is not None:
            return sorted(shards)
        return sorted(idx.available_shards) or [0]

    def _tree_shards(self, idx: Index, shards, pre) -> list[int]:
        """Shard walk for a bitmap tree: the query's shard set plus any
        shards contributed by precomputed cross-shard results (nested
        Distinct row-id bitmaps can land outside the data shards)."""
        out = set(self._shard_list(idx, shards))
        if shards is None:
            for key, res in pre.items():
                if isinstance(key, tuple):
                    if key[0] == "constrow":  # translated column ids
                        out.update(c // idx.width for c in res)
                    continue
                out.update(res.segments)
        return sorted(out)

    def _precompute_nested(self, idx: Index, call: Call, shards) -> dict:
        """Evaluate nested Distinct calls ONCE per query over the
        query's shard set (the reference executes them as separate
        mapReduce passes, executor.go:1820) and cache by call identity
        for the per-shard tree walk."""
        pre: dict[int, RowResult] = {}

        def walk(c: Call, is_root: bool):
            for ch in c.children:
                walk(ch, False)
            for k, v in c.args.items():
                if not isinstance(v, Call):
                    continue
                # a GroupBy aggregate's Count(Distinct(...)) is not a
                # bitmap operand — the aggregate handler consumes that
                # Distinct node itself (executor.go:3918).  Its filter
                # children ARE bitmap operands and still need their
                # own nested precompute.
                if (c.name == "GroupBy" and k == "aggregate"
                        and v.name == "Count" and v.children
                        and v.children[0].name == "Distinct"):
                    for ch in v.children[0].children:
                        walk(ch, False)
                    continue
                walk(v, False)
            if not is_root and c.name == "Distinct":
                # index= redirects the Distinct to ANOTHER index — the
                # cross-index Distinct join (executor.go:1820;
                # defs_join.go distinctjoin PQL:
                # Intersect(Distinct(Row(price > 10), index=orders,
                # field=userid)))
                didx, dshards, dpre = idx, shards, pre
                iname = c.arg("index")
                if iname and iname != idx.name:
                    didx = self.holder.index(iname)
                    if didx is None:
                        raise ExecError(f"index not found: {iname}")
                    # the foreign field's values become COLUMN ids
                    # here, so only an unkeyed int field is coherent
                    # — anything else would silently join garbage
                    # (decimals dropped, keyed row ids mistaken for
                    # columns)
                    df = didx.field(c.arg("_field") or "")
                    if df is None or \
                            df.options.type != FieldType.INT or \
                            df.options.keys:
                        raise ExecError(
                            "cross-index Distinct requires an "
                            "unkeyed int field")
                    dshards, dpre = None, {}
                res = self._execute_distinct(didx, c, dshards, dpre,
                                             raw=True)
                if isinstance(res, DistinctValues):
                    if didx is idx:
                        raise ExecError("BSI Distinct cannot be "
                                        "nested as a bitmap call")
                    # foreign int values are COLUMN ids here
                    res = RowResult.from_columns(
                        [v for v in res.values
                         if isinstance(v, int) and v >= 0],
                        idx.width)
                pre[id(c)] = res
            elif not is_root and c.name == "UnionRows":
                pre[id(c)] = self._execute_union_rows(idx, c, shards)
            elif c.name == "ConstRow":
                # translate string keys ONCE per query, not once per
                # shard in the tree walk (preTranslate analog)
                pre[("constrow", id(c))] = \
                    self._constrow_cols(idx, c)

        walk(call, True)
        return pre

    # ------------------------------------------------------------------
    # bitmap call tree → per-shard tiles (executeBitmapCallShard analog)
    # ------------------------------------------------------------------

    def _bitmap_result(self, idx: Index, call: Call, shards,
                       pre=None) -> RowResult:
        if pre is None:
            pre = self._precompute_nested(idx, call, shards)
        out = RowResult(idx.width)
        tree_shards = self._tree_shards(idx, shards, pre)
        if self.use_stacked:
            try:
                words = self.stacked.words(idx, call, tree_shards, pre)
                metrics.STACKED_QUERIES.inc(path="stacked")
                if words is not None:
                    for i, shard in enumerate(tree_shards):
                        if words[i].any():
                            out.segments[shard] = words[i]
                return out
            except Unstackable:
                metrics.STACKED_QUERIES.inc(path="loop")
        for shard in tree_shards:
            words = np.asarray(self._bitmap_call_shard(idx, call, shard, pre))
            if words.any():
                out.segments[shard] = words
        return out

    def _bitmap_call_shard(self, idx: Index, call: Call, shard: int, pre):
        """Evaluate a bitmap call for one shard → device words (W,)."""
        name = call.name
        if name in ("Row", "Range"):
            return self._row_shard(idx, call, shard)
        if name == "Union":
            return self._nary(idx, call, shard, pre, bm.union,
                              empty_identity=True)
        if name == "Intersect":
            if not call.children:
                raise ExecError("Intersect requires at least one subquery")
            return self._nary(idx, call, shard, pre, bm.intersect)
        if name == "Difference":
            if not call.children:
                raise ExecError("Difference requires at least one subquery")
            return self._nary(idx, call, shard, pre, bm.difference)
        if name == "Xor":
            return self._nary(idx, call, shard, pre, bm.xor,
                              empty_identity=True)
        if name == "Not":
            child = self._only_child(call)
            return bm.difference(
                self._existence_shard(idx, shard),
                self._bitmap_call_shard(idx, child, shard, pre))
        if name == "All":
            return self._existence_shard(idx, shard)
        if name == "Shift":
            child = self._only_child(call)
            n = int(call.arg("n", 1))
            return bm.shift(
                self._bitmap_call_shard(idx, child, shard, pre), n)
        if name == "ConstRow":
            cols = pre.get(("constrow", id(call))) \
                if pre is not None else None
            if cols is None:
                cols = self._constrow_cols(idx, call)
            in_shard = [c % idx.width for c in cols
                        if c // idx.width == shard]
            return jnp.asarray(bm.from_columns(in_shard, idx.width))
        if name in ("Distinct", "UnionRows"):
            # cross-shard calls materialized once per query in
            # _precompute_nested; served per shard from the cache
            return jnp.asarray(pre[id(call)].shard_words(shard))
        raise ExecError(f"unknown or non-bitmap call: {name}")

    def _nary(self, idx, call, shard, pre, op, empty_identity=False):
        if not call.children:
            if empty_identity:
                return jnp.zeros(idx.width // 32, dtype=jnp.uint32)
            raise ExecError(f"{call.name} requires subqueries")
        acc = self._bitmap_call_shard(idx, call.children[0], shard, pre)
        for c in call.children[1:]:
            acc = op(acc, self._bitmap_call_shard(idx, c, shard, pre))
        return acc

    def _existence_shard(self, idx: Index, shard: int):
        if not idx.track_existence:
            raise ExecError(
                "All()/Not() require existence tracking on the index")
        w = idx.existence_row(shard)
        if w is None:
            return jnp.zeros(idx.width // 32, dtype=jnp.uint32)
        return jnp.asarray(w)

    # -- Row in all its forms ------------------------------------------

    def _row_shard(self, idx: Index, call: Call, shard: int):
        fname, cond = call.condition_field()
        if cond is not None:
            return self._bsi_condition_shard(idx, fname, cond, shard)
        fname, row_val = call.field_arg()
        if fname is None:
            raise ExecError("Row() requires a field argument")
        f = idx.field(fname)
        if f is None:
            raise ExecError(f"field not found: {fname}")
        if f.options.type.is_bsi:
            # Row(bsi=5) is equality on the value
            return self._bsi_condition_shard(
                idx, fname, Condition(past.OP_EQ, row_val), shard)
        row_id = self._row_id_for_value(f, row_val)
        if row_id is None:  # unknown row key → empty row
            return jnp.zeros(idx.width // 32, dtype=jnp.uint32)
        views = f.views_for_range(call.arg("from"), call.arg("to"))
        acc = jnp.zeros(idx.width // 32, dtype=jnp.uint32)
        for vn in views:
            v = f.views.get(vn)
            frag = v.fragment(shard) if v else None
            if frag is not None:
                acc = bm.union(acc, frag.device_row(row_id))
        return acc

    def _row_id_for_value(self, f: Field, val, create: bool = False):
        """Resolve a row value to a row id.  String keys go through the
        field's TranslateStore; on the read path a missing key returns
        None (empty row), matching FindKeys semantics."""
        if isinstance(val, bool):
            if f.options.type != FieldType.BOOL:
                raise ExecError(
                    f"bool row value on non-bool field {f.name}")
            return TRUE_ROW if val else FALSE_ROW
        if isinstance(val, str):
            tr = f.row_translator
            if tr is None:
                raise ExecError(
                    f"field {f.name} does not use string keys")
            if create:
                return tr.create_keys(val)[val]
            return tr.find_keys(val).get(val)
        if val is None:
            raise ExecError("null row value")
        if f.options.keys:
            raise ExecError(
                f"field {f.name} uses row keys; got id {val!r}")
        return int(val)

    # -- BSI predicates -------------------------------------------------

    def _bsi_field(self, idx: Index, fname: str) -> Field:
        f = idx.field(fname)
        if f is None:
            raise ExecError(f"field not found: {fname}")
        if not f.options.type.is_bsi:
            raise ExecError(f"field {fname} is not an int-like field")
        return f

    def _scaled_bound(self, f: Field, v, round_up: bool) -> int:
        """Scale a predicate to stored units, rounding the bound
        outward per the comparison op (exact rational arithmetic).
        String bounds coerce by COLUMN type: timestamps for timestamp
        columns, numerics elsewhere ('1.50' on a decimal column is a
        decimal, not a time literal)."""
        if isinstance(v, str):
            if f.options.type == FieldType.TIMESTAMP:
                try:
                    # ns-exact: parse_time would truncate 7-9 digit
                    # fractions to microseconds and shift predicate
                    # boundaries on timeunit-'ns' columns
                    v = timeq.parse_time_ns(v)
                except ValueError as e:
                    raise ExecError(str(e))
            else:
                try:
                    v = Decimal(v)
                except ArithmeticError:
                    raise ExecError(
                        f"cannot parse numeric bound {v!r}")
                if not v.is_finite():
                    raise ExecError(
                        f"numeric bound must be finite: {v!r}")
        if isinstance(v, dt.datetime):
            if f.options.type != FieldType.TIMESTAMP:
                raise ExecError(
                    f"time predicate on {f.options.type.value} field")
            return f.options.timestamp_to_int(v)
        if isinstance(v, bool):
            raise ExecError("bool predicate on int field")
        scale = f.options.scale if f.options.type == FieldType.DECIMAL else 0
        frac = (Fraction(str(v)) if isinstance(v, float)
                else Fraction(v)) * (10 ** scale)
        return ceil(frac) if round_up else floor(frac)

    def _bsi_condition_shard(self, idx: Index, fname: str, cond: Condition,
                             shard: int):
        f = self._bsi_field(idx, fname)
        depth = f.bit_depth
        v = f.views.get(f.bsi_view)
        frag = v.fragment(shard) if v else None
        zeros = jnp.zeros(idx.width // 32, dtype=jnp.uint32)
        if frag is None:
            if cond.value is None and cond.op == past.OP_EQ:
                return self._existence_shard(idx, shard)
            return zeros
        planes = frag.device_planes(depth)

        # null predicates (pql.Call.FieldEquality isNull)
        if cond.value is None:
            if cond.op == past.OP_EQ:    # field == null: no value stored
                return bm.difference(self._existence_shard(idx, shard),
                                     bsi_ops.not_null(planes))
            if cond.op == past.OP_NEQ:   # field != null: not-null
                return bsi_ops.not_null(planes)
            raise ExecError(f"invalid null comparison {cond.op}")

        max_mag = (1 << depth) - 1

        def masks(up):
            return jnp.asarray(bsi_ops.predicate_masks(up, depth))

        if past.is_between(cond):
            lo_raw, hi_raw = cond.value
            lo = self._scaled_bound(f, lo_raw, round_up=True)
            hi = self._scaled_bound(f, hi_raw, round_up=False)
            if cond.op in (past.OP_BTWN_LT_LT, past.OP_BTWN_LT_LTE):
                lo = max(lo, self._scaled_bound(f, lo_raw, round_up=False) + 1)
            if cond.op in (past.OP_BTWN_LT_LT, past.OP_BTWN_LTE_LT):
                hi = min(hi, self._scaled_bound(f, hi_raw, round_up=True) - 1)
            lo, hi = max(lo, -max_mag), min(hi, max_mag)
            if lo > hi:
                return zeros
            return bsi_ops.range_between(
                planes, masks(abs(lo)), masks(abs(hi)),
                jnp.asarray(lo < 0), jnp.asarray(hi < 0))

        op = cond.op
        if op == past.OP_EQ:
            p_lo = self._scaled_bound(f, cond.value, round_up=False)
            p_hi = self._scaled_bound(f, cond.value, round_up=True)
            if p_lo != p_hi or abs(p_lo) > max_mag:
                return zeros
            return bsi_ops.range_eq(planes, masks(abs(p_lo)),
                                    jnp.asarray(p_lo < 0))
        if op == past.OP_NEQ:
            p_lo = self._scaled_bound(f, cond.value, round_up=False)
            p_hi = self._scaled_bound(f, cond.value, round_up=True)
            if p_lo != p_hi or abs(p_lo) > max_mag:
                return bsi_ops.not_null(planes)
            return bsi_ops.range_neq(planes, masks(abs(p_lo)),
                                     jnp.asarray(p_lo < 0))
        if op in (past.OP_LT, past.OP_LTE):
            allow_eq = op == past.OP_LTE
            p = self._scaled_bound(f, cond.value,
                                   round_up=not allow_eq)
            if p > max_mag:
                return bsi_ops.not_null(planes)
            if p < -max_mag:
                return zeros
            return bsi_ops.range_lt(planes, masks(abs(p)),
                                    jnp.asarray(p < 0), allow_eq=allow_eq)
        if op in (past.OP_GT, past.OP_GTE):
            allow_eq = op == past.OP_GTE
            p = self._scaled_bound(f, cond.value,
                                   round_up=allow_eq)
            if p < -max_mag:
                return bsi_ops.not_null(planes)
            if p > max_mag:
                return zeros
            return bsi_ops.range_gt(planes, masks(abs(p)),
                                    jnp.asarray(p < 0), allow_eq=allow_eq)
        raise ExecError(f"unsupported condition op {op}")

    # ------------------------------------------------------------------
    # aggregates
    # ------------------------------------------------------------------

    def _filter_words(self, idx, call, shard, pre):
        """Optional filter child for Sum/Min/Max/Distinct."""
        if call.children:
            return self._bitmap_call_shard(idx, call.children[0], shard, pre)
        return None

    def _reduce_count(self, idx: Index, call: Call, shards, pre) -> int:
        """Count: the whole tree runs as one stacked device program
        with a single (S,) partials fetch; cross-shard totals are
        summed in exact host ints (SURVEY §7 "Exactness")."""
        tree_shards = self._tree_shards(idx, shards, pre)
        if self.use_stacked:
            try:
                n = self.stacked.count(idx, call, tree_shards, pre)
                metrics.STACKED_QUERIES.inc(path="stacked")
                return n
            except Unstackable:
                metrics.STACKED_QUERIES.inc(path="loop")
        words = [self._bitmap_call_shard(idx, call, shard, pre)
                 for shard in tree_shards]
        if not words:
            return 0
        counts = np.asarray(bm.count(jnp.stack(words)), dtype=np.int64)
        return int(counts.sum())

    def _execute_sum(self, idx: Index, call: Call, shards, pre) -> ValCount:
        fname = call.arg("_field")
        if fname is None:
            raise ExecError("Sum requires field=")
        f = self._bsi_field(idx, fname)
        if self.use_stacked:
            try:
                filter_call = call.children[0] if call.children else None
                total, count = self.stacked.bsi_sum(
                    idx, f, filter_call, self._shard_list(idx, shards), pre)
                metrics.STACKED_QUERIES.inc(path="stacked")
                return ValCount(value=f.int_to_value(total), count=count)
            except Unstackable:
                metrics.STACKED_QUERIES.inc(path="loop")
        # queue every shard's device scan, then fetch all per-plane
        # popcounts in one sync (see _reduce_count)
        parts_per_shard = []
        for shard in self._shard_list(idx, shards):
            v = f.views.get(f.bsi_view)
            frag = v.fragment(shard) if v else None
            if frag is None:
                continue
            planes = frag.device_planes(f.bit_depth)
            filt = self._filter_words(idx, call, shard, pre)
            if kernels.enabled():
                # single fused pass over the plane stack (Pallas)
                parts_per_shard.append(kernels.bsi_sum_counts(planes, filt))
            else:
                parts_per_shard.append(bsi_ops.sum_counts(planes, filt))
        total, count = 0, 0
        if parts_per_shard:
            cnt = np.asarray(jnp.stack([p[0] for p in parts_per_shard]))
            pos = np.asarray(jnp.stack([p[1] for p in parts_per_shard]))
            neg = np.asarray(jnp.stack([p[2] for p in parts_per_shard]))
            for i in range(len(parts_per_shard)):
                s, c = bsi_ops.host_sum(cnt[i], pos[i], neg[i])
                total += s
                count += c
        return ValCount(value=f.int_to_value(total), count=count)

    def _execute_minmax(self, idx: Index, call: Call, shards,
                        is_min: bool, pre) -> ValCount:
        fname = call.arg("_field")
        if fname is None:
            raise ExecError(f"{call.name} requires field=")
        f = self._bsi_field(idx, fname)
        if self.use_stacked:
            # fused value-histogram fast path (ISSUE 11 byproduct):
            # one single-pass tile walk over the plane stack instead
            # of a per-shard min/max plane walk each
            try:
                filter_call = (call.children[0] if call.children
                               else None)
                pos, neg = self.stacked.bsi_value_hist(
                    idx, f, filter_call, self._shard_list(idx, shards),
                    pre)
                metrics.STACKED_QUERIES.inc(path="stacked")
                return self._minmax_from_hist(f, pos, neg, is_min)
            except Unstackable:
                metrics.STACKED_QUERIES.inc(path="loop")
        best, count = None, 0
        op = bsi_ops.min_op if is_min else bsi_ops.max_op
        for shard in self._shard_list(idx, shards):
            v = f.views.get(f.bsi_view)
            frag = v.fragment(shard) if v else None
            if frag is None:
                continue
            planes = frag.device_planes(f.bit_depth)
            filt = self._filter_words(idx, call, shard, pre)
            val, c = bsi_ops.host_minmax(*op(planes, filt))
            if c == 0:
                continue
            if best is None or (val < best if is_min else val > best):
                best, count = val, c
            elif val == best:
                count += c
        if best is None:
            return ValCount(value=None, count=0)
        return ValCount(value=f.int_to_value(best), count=count)

    @staticmethod
    def _minmax_from_hist(f, pos, neg, is_min: bool) -> ValCount:
        """Min/Max + attaining count straight out of the fused value
        histogram: the extreme nonzero code, negatives preferred for
        Min / non-negatives for Max (fragment.min/max semantics)."""
        pnz, nnz = np.nonzero(pos)[0], np.nonzero(neg)[0]
        if is_min:
            if nnz.size:
                mag = int(nnz[-1])
                return ValCount(value=f.int_to_value(-mag),
                                count=int(neg[mag]))
            if pnz.size:
                mag = int(pnz[0])
                return ValCount(value=f.int_to_value(mag),
                                count=int(pos[mag]))
        else:
            if pnz.size:
                mag = int(pnz[-1])
                return ValCount(value=f.int_to_value(mag),
                                count=int(pos[mag]))
            if nnz.size:
                mag = int(nnz[0])
                return ValCount(value=f.int_to_value(-mag),
                                count=int(neg[mag]))
        return ValCount(value=None, count=0)

    def _execute_minmax_row(self, idx: Index, call: Call, shards,
                            is_min: bool, pre=None) -> Pair:
        """MinRow/MaxRow (fragment.minRow/maxRow semantics)."""
        fname = call.arg("_field")
        f = idx.field(fname) if fname else None
        if f is None:
            raise ExecError(f"{call.name} requires a field")
        filter_call = call.children[0] if call.children else None
        # per-shard candidate, reduced by row-id preference — counts
        # are NEVER summed across shards (reference reduceFn keeps
        # ONE shard's pair, executor.go:1620), and an UNFILTERED call
        # reports count=1 (a has-value flag, fragment.go:858 minRow:
        # "if filter is nil, it returns minRowID, 1"; defs_keyed.go
        # minrow expects (11, 1) though row 11 spans 3 records).  No
        # stacked fast path: the cross-shard TopN sum would produce
        # the aggregated count the reference never reports.
        best: Pair | None = None
        for shard in self._shard_list(idx, shards):
            v = f.views.get(VIEW_STANDARD)
            frag = v.fragment(shard) if v else None
            if frag is None:
                continue
            rows = sorted(frag.row_ids)
            if not rows:
                continue
            if filter_call is None:
                cand = Pair(id=rows[0] if is_min else rows[-1],
                            count=1)
            else:
                filt = self._bitmap_call_shard(idx, filter_call,
                                               shard, pre)
                cand = None
                for row_id in (rows if is_min else reversed(rows)):
                    c = int(bm.intersection_count(
                        frag.device_row(row_id), filt))
                    if c > 0:
                        cand = Pair(id=row_id, count=c)
                        break
                if cand is None:
                    continue
            if best is None or (cand.id < best.id if is_min
                                else cand.id > best.id):
                best = cand
        return best if best is not None else Pair(id=0, count=0)

    # ------------------------------------------------------------------
    # Distinct / Rows / misc
    # ------------------------------------------------------------------

    def _execute_distinct(self, idx: Index, call: Call, shards,
                          pre=None, raw: bool = False):
        fname = call.arg("_field")
        if fname is None:
            raise ExecError("Distinct requires field=")
        f = idx.field(fname)
        if f is None:
            raise ExecError(f"field not found: {fname}")
        if f.options.type.is_bsi:
            if self.use_stacked and f.bit_depth <= 62:
                try:
                    return self._distinct_bsi_stacked(
                        idx, f, call, shards, pre)
                except Unstackable:
                    pass
            vals: set[int] = set()
            for shard in self._shard_list(idx, shards):
                v = f.views.get(f.bsi_view)
                frag = v.fragment(shard) if v else None
                if frag is None:
                    continue
                filt = self._filter_words(idx, call, shard, pre)
                cols, values = bsi_ops.decode(np.asarray(
                    frag.device_planes(f.bit_depth)))
                if filt is not None:
                    fbits = bsi_ops.unpack_bits_np(np.asarray(filt))
                    values = [val for c, val in zip(cols, values)
                              if fbits[int(c)]]
                vals.update(values)
            return DistinctValues(values=sorted(
                f.int_to_value(v) for v in vals))
        # set-like: distinct row ids with any bit (within filter)
        rows_present: set[int] = set()
        filter_call = call.children[0] if call.children else None
        stacked_done = False
        if self.use_stacked and filter_call is not None:
            # one fused (R, S, W) scan instead of a per-(row, shard)
            # device call each — the TopN candidate machinery reused
            try:
                row_ids = self._all_row_ids(idx, f, shards)
                if row_ids:
                    pairs = self._topnk_stacked(
                        idx, f, row_ids, [VIEW_STANDARD], filter_call,
                        shards, pre, ids=None)
                    rows_present = {p.id for p in pairs}
                stacked_done = True
            except Unstackable:
                pass
        if not stacked_done:
            for shard in self._shard_list(idx, shards):
                v = f.views.get(VIEW_STANDARD)
                frag = v.fragment(shard) if v else None
                if frag is None:
                    continue
                filt = self._filter_words(idx, call, shard, pre)
                for row_id in frag.row_ids:
                    if row_id in rows_present:
                        continue
                    if filt is None:
                        rows_present.add(row_id)
                    elif int(bm.intersection_count(
                            frag.device_row(row_id), filt)) > 0:
                        rows_present.add(row_id)
        res = RowResult.from_columns(rows_present, idx.width)
        res.is_row_ids = True  # row ids, not columns: skip col-key xlate
        if f.options.keys and not raw:
            return DistinctValues(values=sorted(
                k for k in f.row_translator.translate_ids(
                    sorted(rows_present)) if k is not None))
        return res

    def _distinct_bsi_stacked(self, idx: Index, f: Field, call: Call,
                              shards, pre) -> DistinctValues:
        """Distinct over a BSI field on the stacked engine
        (executor.go:2034 re-designed): the fused value histogram
        when the dense value space fits (ISSUE 11 — distinct values
        are the nonzero codes of ONE single-pass tile walk, no
        per-column decode at all), else filter tree as one stacked
        program + the chunked device decode, uniquing in numpy."""
        try:
            pos, neg = self.stacked.bsi_value_hist(
                idx, f, call.children[0] if call.children else None,
                self._shard_list(idx, shards), pre)
            return DistinctValues(values=sorted(
                f.int_to_value(v)
                for v in kernels.distinct_from_hist(pos, neg)))
        except Unstackable:
            pass                      # depth over the dense bound
        skey = tuple(self._shard_list(idx, shards))
        filt_words = None
        if call.children:
            filt_words = self.stacked.words(idx, call.children[0],
                                            list(skey), pre)
            if filt_words is None:      # statically-empty filter
                return DistinctValues(values=[])
        vals: set[int] = set()
        pos = 0
        for chunk_ids, ex, dec in self.stacked.decode_stream(
                idx, f, skey):
            sel = ex
            if filt_words is not None:
                sel = sel & bsi_ops.unpack_bits_np(
                    filt_words[pos:pos + len(chunk_ids)])
            pos += len(chunk_ids)
            if sel.any():
                vals.update(np.unique(dec[sel]).tolist())
        return DistinctValues(values=sorted(
            f.int_to_value(v) for v in vals))

    def _ranged_views(self, f, call: Call) -> list[str]:
        """Views for a Rows/UnionRows call honoring from=/to= time
        bounds (executor.go:4077 executeRowsShard walks the quantum
        views in range)."""
        frm, to = call.arg("from"), call.arg("to")
        try:
            return f.views_for_range(frm, to)
        except ValueError as e:
            raise ExecError(str(e))

    def _rows_ids(self, idx: Index, call: Call, shards) -> list[int]:
        """Rows(field) core returning raw row IDS (executor.
        executeRowsShard basics: column, like, previous, limit)."""
        fname = call.arg("_field")
        f = idx.field(fname) if fname else None
        if f is None:
            raise ExecError("Rows requires a field")
        column = call.arg("column")
        previous = call.arg("previous")
        limit = call.arg("limit")
        if column is not None:
            column = self._col_id(idx, column)
            if column is None:
                return []  # unknown column key matches nothing
        ids: set[int] = set()
        views = self._ranged_views(f, call)  # shard-independent
        for shard in self._shard_list(idx, shards):
            for vn in views:
                v = f.views.get(vn)
                frag = v.fragment(shard) if v else None
                if frag is None:
                    continue
                if column is not None:
                    c = int(column)
                    if c // idx.width != shard:
                        continue
                    ids.update(r for r in frag.row_ids
                               if frag.contains(r, c % idx.width))
                else:
                    ids.update(frag.row_ids)
        like = call.arg("like")
        if like is not None:
            tr = f.row_translator
            if tr is None:
                raise ExecError("Rows(like=) requires a keyed field")
            # PQL Rows(like=) uses the key-filter matcher (like.go);
            # the SQL WHERE planner passes _like_sql for the sql3
            # scalar regex semantics instead
            # (sql3/planner/expression.go:2991)
            from pilosa_tpu.pql.like import like_regex, sql_like_regex
            pat = (sql_like_regex(like) if call.arg("_like_sql")
                   else like_regex(like))
            ids &= set(tr.match(lambda k: pat.match(k) is not None))
        out = sorted(ids)
        if previous is not None:
            prev = previous
            if isinstance(prev, str):
                tr = f.row_translator
                if tr is None:
                    raise ExecError(
                        "string previous= requires a keyed field")
                found = tr.find_keys(prev)
                if prev not in found:
                    raise ExecError(
                        f"previous= key not found: {prev!r}")
                prev = found[prev]
            out = [r for r in out if r > int(prev)]
        if limit is not None:
            out = out[: int(limit)]
        return out

    def _execute_rows(self, idx: Index, call: Call, shards) -> list:
        """Rows(field): row ids, or keys for keyed fields
        (RowIdentifiers.Keys in the reference)."""
        fname = call.arg("_field")
        f = idx.field(fname) if fname else None
        if f is None:
            raise ExecError("Rows requires a field")
        out = self._rows_ids(idx, call, shards)
        if f.options.keys:
            keys = f.row_translator.translate_ids(out)
            return [k if k is not None else r for k, r in zip(keys, out)]
        return out

    def _execute_union_rows(self, idx: Index, call: Call, shards) -> RowResult:
        """UnionRows(Rows(...)): union the row bitmaps named by Rows."""
        out = RowResult(idx.width)
        shard_list = self._shard_list(idx, shards)
        for child in call.children:
            if child.name != "Rows":
                raise ExecError("UnionRows expects Rows() arguments")
            fname = child.arg("_field")
            f = idx.field(fname) if fname else None
            if f is None:
                raise ExecError("Rows requires a field")
            row_ids = self._rows_ids(idx, child, shards)
            views = self._ranged_views(f, child)
            for shard in shard_list:
                acc = jnp.asarray(out.segments.get(
                    shard, bm.empty(idx.width)))
                touched = False
                for vn in views:
                    v = f.views.get(vn)
                    frag = v.fragment(shard) if v else None
                    if frag is None:
                        continue
                    touched = True
                    for r in row_ids:
                        acc = bm.union(acc, frag.device_row(r))
                if not touched:
                    continue
                words = np.asarray(acc)
                if words.any():
                    out.segments[shard] = words
        return out

    def _execute_includes_column(self, idx, call, shards, pre) -> bool:
        col = call.arg("column")
        if col is None:
            raise ExecError("IncludesColumn requires column=")
        col = self._col_id(idx, col)
        if col is None:
            return False
        shard = col // idx.width
        if shards is not None and shard not in set(shards):
            return False
        child = self._only_child(call)
        words = self._bitmap_call_shard(idx, child, shard, pre)
        mask = jnp.asarray(bm.column_bit(col % idx.width, idx.width))
        return bool(bm.any_set(bm.intersect(words, mask)))

    def _execute_limit(self, idx, call, shards, pre) -> RowResult:
        child = self._only_child(call)
        limit = call.arg("limit")
        offset = int(call.arg("offset", 0))
        row = self._bitmap_result(idx, child, shards, pre)
        cols = row.columns()
        end = None if limit is None else offset + int(limit)
        return RowResult.from_columns(cols[offset:end], idx.width)

    def _execute_options(self, idx, call, shards):
        child = self._only_child(call)
        opt_shards = call.arg("shards")
        if opt_shards is not None:
            shards = [int(s) for s in opt_shards]
        return self._execute_call(idx, child, shards)

    # ------------------------------------------------------------------
    # writes (executor.executeSet/executeClear... analogs)
    # ------------------------------------------------------------------

    def _execute_write(self, idx: Index, call: Call, pre=None):
        name = call.name
        if name == "Set":
            return self._execute_set(idx, call)
        if name == "Clear":
            return self._execute_clear(idx, call)
        if name == "Store":
            return self._execute_store(idx, call, pre)
        if name == "ClearRow":
            return self._execute_clear_row(idx, call)
        if name == "Delete":
            return self._execute_delete(idx, call, pre)
        raise ExecError(f"write call not yet supported: {name}")

    def _execute_field_value(self, idx: Index, call: Call) -> ValCount:
        """FieldValue(field=f, column=c): one column's BSI value as
        ValCount(value, 1), count=0 when unset (executor.go:799
        executeFieldValueCall; column keys translate like any read,
        defs_keyed.go fieldvalue)."""
        fname = call.arg("_field") or call.arg("field")
        f = idx.field(fname) if fname else None
        if f is None:
            raise ExecError("FieldValue requires a field")
        if not f.options.type.is_bsi:
            raise ExecError(
                "FieldValue requires an int/decimal/timestamp field")
        col = call.arg("column")
        if col is None:
            raise ExecError("FieldValue requires a column")
        cid = self._col_id(idx, col)
        if cid is None:
            return ValCount(value=None, count=0)
        shard, scol = divmod(int(cid), idx.width)
        v = f.views.get(f.bsi_view)
        frag = v.fragment(shard) if v else None
        if frag is None or not frag.contains(0, scol):
            return ValCount(value=None, count=0)
        mag = sum(1 << i for i in range(f.bit_depth)
                  if frag.contains(2 + i, scol))
        val = f.int_to_value(-mag if frag.contains(1, scol) else mag)
        return ValCount(value=val, count=1)

    def _constrow_cols(self, idx: Index, call: Call) -> list[int]:
        """ConstRow columns with string keys translated (the
        preTranslate analog, executor.go:6814: ConstRow over a keyed
        index takes keys — Extract(ConstRow(columns=['two']), ...),
        defs_keyed.go constrow).  Unknown keys match nothing."""
        out = []
        for c in call.arg("columns", []) or []:
            if isinstance(c, str):
                cid = self._col_id(idx, c)
                if cid is None:
                    continue
                out.append(int(cid))
            else:
                out.append(int(c))
        return out

    def _col_id(self, idx: Index, col, create: bool = False):
        """Resolve a column value (int id or string key) to an id.
        Read path returns None for unknown keys (FindKeys semantics)."""
        if isinstance(col, str):
            tr = idx.column_translator
            if tr is None:
                raise ExecError(
                    f"index {idx.name} does not use column keys")
            if create:
                return tr.create_keys(col)[col]
            return tr.find_keys(col).get(col)
        if idx.keys and not _REMOTE.get():
            raise ExecError(
                f"index {idx.name} uses column keys; got id {col!r}")
        return int(col)

    def _set_col(self, idx: Index, call, create: bool):
        col = call.arg("_col")
        if col is None:
            raise ExecError(f"{call.name} requires a column")
        return self._col_id(idx, col, create)

    def _execute_set(self, idx: Index, call: Call) -> bool:
        col = self._set_col(idx, call, create=True)
        fname, val = call.field_arg()
        if fname is None:
            raise ExecError("Set requires field=value")
        f = idx.field(fname)
        if f is None:
            raise ExecError(f"field not found: {fname}")
        if f.options.type.is_bsi:
            changed = f.set_value(col, val)
        else:
            ts = call.arg("_timestamp")
            changed = f.set_bit(
                self._row_id_for_value(f, val, create=True), col,
                timestamp=timeq.parse_time(ts) if ts else None)
        idx.mark_columns_exist([col])
        return changed

    def _execute_clear(self, idx: Index, call: Call) -> bool:
        col = self._set_col(idx, call, create=False)
        if col is None:
            return False  # unknown column key: nothing to clear
        fname, val = call.field_arg()
        if fname is None:
            raise ExecError("Clear requires field=value")
        f = idx.field(fname)
        if f is None:
            raise ExecError(f"field not found: {fname}")
        if f.options.type.is_bsi:
            return f.clear_value(col)
        row_id = self._row_id_for_value(f, val)
        return False if row_id is None else f.clear_bit(row_id, col)

    def _execute_store(self, idx: Index, call: Call, pre=None) -> bool:
        """Store(Row(...), f=9): write the result bitmap as a row."""
        child = self._only_child(call)
        fname, val = call.field_arg()
        if fname is None:
            raise ExecError("Store requires field=row")
        f = idx.field(fname)
        if f is None:
            f = idx.create_field(fname)
        row_id = self._row_id_for_value(f, val, create=True)
        for shard in self._shard_list(idx, None):
            words = np.asarray(self._bitmap_call_shard(idx, child, shard, pre))
            frag = f.view(VIEW_STANDARD, create=True).fragment(
                shard, create=True)
            frag.set_row_words(row_id, words)
        return True

    def _execute_clear_row(self, idx: Index, call: Call) -> bool:
        fname, val = call.field_arg()
        if fname is None:
            raise ExecError("ClearRow requires field=row")
        f = idx.field(fname)
        if f is None:
            raise ExecError(f"field not found: {fname}")
        row_id = self._row_id_for_value(f, val)
        if row_id is None:
            return False
        changed = False
        for v in f.views.values():
            for frag in v.fragments.values():
                if frag.row_count(row_id):
                    frag.set_row_words(row_id, 0)
                    changed = True
        return changed
