"""Ragged paged dispatch — ONE fused device program for heterogeneous
serving traffic.

The PR 2 batcher fuses only queries over the same (index, shard set):
a mixed batch — point Counts next to TopNs over different indexes and
shard subsets — pays one "multi" dispatch per group, and every group
boundary is a device round trip.  Since PR 5 made device stacks
fixed-size lane-block PAGES, the Ragged Paged Attention trick
(PAPERS.md, arxiv 2604.15464) applies directly: instead of padding
per group, drive one kernel over a *page table* —

- every group's plan is built as usual (the shared ``PlanBuilder``),
  but under ``stacked.raw_pages()`` its stack leaves come back as
  :class:`PageView` handles (the cache's raw page arrays) instead of
  assembled operands;
- pages of every query land in per-(page_lanes, width) *buckets*; a
  flat page-index array per operand (contiguous ``arange`` today —
  the layout survives future page dedup/subsetting) gathers each
  operand out of its bucket INSIDE the fused program (the "ragged"
  plan kind in stacked.py inlines the concat+gather so one
  concatenate is shared per bucket; ``ops.bitmap.concat_gather`` is
  the single-operand reference implementation of the same contract),
  so the per-access assemble dispatch disappears too;
- single-leaf Counts — the dominant point-read shape — skip operand
  materialization entirely: their lanes concatenate into one segment
  family reduced by ``ops.bitmap.segment_count`` (popcount +
  segment-sum, one pass at raw memory bandwidth — the Buddy-RAM
  bound, arxiv 1611.09988);
- every other subplan kind (tree counts, words, bsi_sum, row_counts)
  evaluates exactly as in the "multi" plan over the combined
  virtual+direct leaf space, so results are bit-exact by construction.

Page layout and segment ids ride as runtime *params* while the plan
stays a static int tuple: two batches with the same structural shape
(tree shapes, lane counts, bucket layout) share one compiled
executable even when their page tables differ, and pow2 padding of
page counts, gather arrays, and segment counts keeps the shape space
log-bounded across varying batch compositions.

Consistency is inherited unchanged from the serving layer: the
post-batch snapshot re-check (executor/serving.py ``_run_batch``)
re-executes any rider whose fragment-version snapshot moved while the
fused program ran.
"""

from __future__ import annotations

import time
from collections import OrderedDict

import numpy as np

from pilosa_tpu.executor.stacked import (
    PageView,
    PlanBuilder,
    _block,
    _compiled,
    _dispatch_kind,
    raw_pages,
)
from pilosa_tpu.memory import encode, pressure
from pilosa_tpu.obs import flight, metrics
from pilosa_tpu.obs.monitor import capture_exception
from pilosa_tpu.obs.tracing import Span, span_into
from pilosa_tpu.ops import kernels


class RaggedUnbuildable(Exception):
    """A subplan the ragged program cannot express (falls back to the
    per-group path)."""


def _pow2(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length()


# ---------------------------------------------------------------------------
# IR remapping (group-local leaf/param indices -> fused global space)
# ---------------------------------------------------------------------------

def _remap_tree(node, lmap, poff):
    k = node[0]
    if k == "leaf":
        return ("leaf", lmap[node[1]])
    if k == "zeros":
        return node
    if k == "nary":
        return ("nary", node[1],
                tuple(_remap_tree(c, lmap, poff) for c in node[2]))
    if k == "not":
        return ("not", lmap[node[1]], _remap_tree(node[2], lmap, poff))
    if k == "qcover":
        return ("qcover", tuple(lmap[i] for i in node[1]))
    if k == "shift":
        return ("shift", node[1], _remap_tree(node[2], lmap, poff))
    if k == "bsi_cmp":
        return ("bsi_cmp", lmap[node[1]], node[2],
                node[3] + poff, node[4] + poff)
    if k == "bsi_between":
        return ("bsi_between", lmap[node[1]], node[2] + poff,
                node[3] + poff, node[4] + poff, node[5] + poff)
    if k == "bsi_notnull":
        return ("bsi_notnull", lmap[node[1]])
    if k == "bsi_null":
        return ("bsi_null", lmap[node[1]], lmap[node[2]])
    raise RaggedUnbuildable(f"unknown IR node {k}")


def _remap_sub(sub, lmap, poff):
    kind = sub[0]
    if kind == "count":
        return ("count", _remap_tree(sub[1], lmap, poff), sub[2])
    if kind == "words":
        return ("words", _remap_tree(sub[1], lmap, poff))
    if kind == "bsi_sum":
        tree = None if sub[2] is None else _remap_tree(sub[2], lmap,
                                                       poff)
        return ("bsi_sum", lmap[sub[1]], tree, sub[3])
    if kind == "row_counts":
        tree = None if sub[2] is None else _remap_tree(sub[2], lmap,
                                                       poff)
        return ("row_counts", lmap[sub[1]], tree, sub[3])
    if kind == "gb_hist":
        # one-pass GroupBy histogram rider (ISSUE 11): the group-code
        # stack and BSI plane leaves gather through the same page
        # table as every other operand
        tree = None if sub[2] is None else _remap_tree(sub[2], lmap,
                                                       poff)
        planes = None if sub[3] is None else lmap[sub[3]]
        return ("gb_hist", lmap[sub[1]], tree, planes) + sub[4:]
    raise RaggedUnbuildable(f"unraggable sub kind {kind}")


# ---------------------------------------------------------------------------
# program assembly
# ---------------------------------------------------------------------------

class RaggedProgram:
    """Accumulates per-group (PlanBuilder, subplans) contributions and
    finalizes them into ONE ``("ragged", ...)`` plan + leaf/param
    tuples.  Groups stay what they were (one PlanBuilder per
    (index identity, shard set)); the program is what fuses across
    them."""

    # a segment family below this size gains nothing over a plain
    # count subplan (XLA fuses either way); at >= 2 the family shares
    # one popcount pass and its executable survives composition churn
    _SEG_MIN = 2

    def __init__(self, ndev: int = 1):
        # serving-mesh width (memory/placement.py); > 1 puts the
        # program in MESH mode: pages accumulate per owner device and
        # finalize() emits a ("ragged_mesh", ...) plan whose cross-
        # device combines run inside the compiled shard_map program
        self.ndev = int(ndev)
        self.mesh = self.ndev > 1
        # (page_lanes, width_words) -> accumulated page arrays; in
        # mesh mode, a list of per-device page lists instead (pages
        # stay committed on their placement owner — the pool assembly
        # in _finalize_mesh never moves a byte between devices)
        self.buckets: OrderedDict[tuple, list] = OrderedDict()
        # non-mesh vleaf: (bucket_key, lane_idx, n, shape)
        # mesh vleaf:     (bucket_key, pool_row, lane_dev, n, shape,
        #                  shard_axis, group_i)
        self.vleaves: list = []
        self.direct: list = []
        self.params: list = []
        # (entries, lmap, poff) per group; lmap: local leaf index ->
        # ("v", vleaf_i) | ("d", direct_i); an entry is
        # (riders, subplan, demux, slot_key) — riders may be empty
        # for a canonical slot absent from this batch (the sub still
        # evaluates, keeping the plan composition-stable); slot_key
        # feeds the cross-batch program cache's demux table
        self.groups: list = []
        # mesh bookkeeping: per-group shard owner maps (int32 (S,))
        # and the per-device page-encoding mix (flight/roofline
        # attribution of what each chip actually streams)
        self.group_owners: list = []
        self.dev_mix: list = [dict() for _ in range(self.ndev)]

    def add_group(self, builder: PlanBuilder, entries: list,
                  owners=None):
        """`entries`: [(riders, subplan, demux, slot_key), ...] built
        against `builder` (its leaves may be PageView handles —
        raw_pages).  ``owners``: per-shard serving-mesh owner slots
        (int32, len(builder.shards)) — required in mesh mode.
        Raises :class:`RaggedUnbuildable` when the group can't enter
        the mesh program (whole/host-served operands have no device
        layout); the caller degrades those riders to the solo path."""
        poff = len(self.params)
        self.params.extend(builder.params)
        gidx = len(self.groups)
        lmap: dict = {}
        for i, leaf in enumerate(builder.leaves):
            if isinstance(leaf, PageView):
                if self.mesh:
                    lmap[i] = ("v", self._add_mesh_leaf(leaf, gidx))
                    continue
                key = (leaf.page_lanes, leaf.width_words)
                pages = self.buckets.setdefault(key, [])
                base = len(pages) * leaf.page_lanes
                # per-page decode-to-dense boundary: the fused gather
                # program indexes a homogeneous dense page pool, so
                # container-encoded pages (memory/encode.py) expand
                # here — page identity and lane mapping unchanged
                pages.extend(leaf.dense_pages())
                lane_idx = (base + np.arange(leaf.lanes)).astype(
                    np.int32)
                lmap[i] = ("v", len(self.vleaves))
                self.vleaves.append((key, lane_idx, leaf.lanes,
                                     leaf.shape))
            else:
                if self.mesh:
                    raise RaggedUnbuildable(
                        "direct (whole/host) leaf under mesh")
                lmap[i] = ("d", len(self.direct))
                self.direct.append(leaf)
        self.groups.append((entries, lmap, poff))
        self.group_owners.append(owners)

    def _add_mesh_leaf(self, leaf: PageView, gidx: int) -> int:
        """Accumulate one PageView's pages into per-device bucket
        pools; returns the vleaf index.  ``pool_row[lane]`` is the
        lane's row in its owner device's (pool pages x page_lanes)
        flattened pool — valid after finalize's zero-page padding
        because pad pages append strictly AFTER real ones."""
        if leaf.page_device is None or leaf.shard_axis is None:
            raise RaggedUnbuildable("unplaced PageView under mesh")
        key = (leaf.page_lanes, leaf.width_words)
        per_dev = self.buckets.setdefault(
            key, [[] for _ in range(self.ndev)])
        pages = leaf.dense_pages()   # decode-to-dense ON the owner:
        # jnp ops on device-committed encoded payloads stay committed
        slot = np.empty(len(pages), dtype=np.int64)
        for pi, page in enumerate(pages):
            d = int(leaf.page_device[pi])
            if not 0 <= d < self.ndev:
                raise RaggedUnbuildable("owner slot outside mesh")
            slot[pi] = len(per_dev[d])
            per_dev[d].append(page)
            mk = encode.page_kind(leaf.pages[pi])
            self.dev_mix[d][mk] = self.dev_mix[d].get(mk, 0) + 1
        lane_page = leaf.lane_page.astype(np.int64)
        pool_row = (slot[lane_page] * leaf.page_lanes
                    + leaf.lane_slot.astype(np.int64))
        lane_dev = np.asarray(leaf.page_device,
                              dtype=np.int32)[lane_page]
        self.vleaves.append((key, pool_row, lane_dev, leaf.lanes,
                             leaf.shape, leaf.shard_axis, gidx))
        return len(self.vleaves) - 1

    def _add_mesh_param(self, arr: np.ndarray) -> int:
        """Append one per-device (ndev, X) int32 index param —
        sharded P("dev") into the compiled program, one row per
        device.  X is already pow2-bounded by the callers (local
        shard widths and pool paddings are pow2)."""
        self.params.append(np.ascontiguousarray(arr, dtype=np.int32))
        return len(self.params) - 1

    def _add_param(self, arr: np.ndarray, pad_value) -> int:
        """Append a pow2-padded int32 param array; returns its index."""
        n = arr.shape[0]
        npad = _pow2(max(n, 1))
        if npad != n:
            arr = np.concatenate(
                [arr, np.full(npad - n, pad_value, np.int32)])
        self.params.append(np.ascontiguousarray(arr, dtype=np.int32))
        return len(self.params) - 1

    def finalize(self):
        """(plan, leaves, params, served, table, meshinfo) or None
        when nothing was built.  ``served``: [(req, demux, extract),
        ...] where extract is ("plain", sub_i) or ("seg", sub_i,
        slot); ``table``: slot_key -> (demux, extract) — the cross-
        batch program cache's rider-mapping surface; ``meshinfo``:
        per-device attribution (mesh mode; None otherwise)."""
        if not any(entries for entries, _l, _p in self.groups):
            return None
        # -- segment-count families: single-leaf reduced Counts whose
        # leaf is paged coalesce per bucket into one segment reduce
        families: OrderedDict[tuple, list] = OrderedDict()
        seg_entry: dict = {}      # id(entry tuple) -> (bucket, slot)
        for entries, lmap, _poff in self.groups:
            for ent in entries:
                sub = ent[1]
                if (sub[0] == "count" and sub[2]
                        and sub[1][0] == "leaf"
                        and lmap.get(sub[1][1], ("", 0))[0] == "v"):
                    v = self.vleaves[lmap[sub[1][1]][1]]
                    families.setdefault(v[0], []).append((ent, v))
        for vkey, members in list(families.items()):
            if len(members) < self._SEG_MIN:
                del families[vkey]
                continue
            for slot, (ent, _v) in enumerate(members):
                seg_entry[id(ent)] = (vkey, slot)
        # -- keep only the virtual leaves some surviving (non-segment)
        # subplan actually reads: a leaf consumed solely by a segment
        # family never materializes — its lanes reduce straight out of
        # the bucket gather
        def _refs(sub) -> set:
            """LOCAL leaf indices a subplan reads."""
            out: set = set()

            def walk(node):
                k = node[0]
                if k == "leaf":
                    out.add(node[1])
                elif k == "nary":
                    for c in node[2]:
                        walk(c)
                elif k == "not":
                    out.add(node[1])
                    walk(node[2])
                elif k == "qcover":
                    out.update(node[1])
                elif k == "shift":
                    walk(node[2])
                elif k in ("bsi_cmp", "bsi_between", "bsi_notnull"):
                    out.add(node[1])
                elif k == "bsi_null":
                    out.add(node[1])
                    out.add(node[2])
            if sub[0] in ("bsi_sum", "row_counts"):
                out.add(sub[1])
                if sub[2] is not None:
                    walk(sub[2])
            elif sub[0] == "gb_hist":
                out.add(sub[1])
                if sub[2] is not None:
                    walk(sub[2])
                if sub[3] is not None:
                    out.add(sub[3])
            else:
                walk(sub[1])
            return out

        plain: list = []      # (ent, lmap, poff, group_i) batch order
        kept: set[int] = set()
        for gidx, (entries, lmap, poff) in enumerate(self.groups):
            for ent in entries:
                if id(ent) in seg_entry:
                    continue
                plain.append((ent, lmap, poff, gidx))
                for li in _refs(ent[1]):
                    tag, i = lmap[li]
                    if tag == "v":
                        kept.add(i)
        vkeep = sorted(kept)
        vre = {vi: k for k, vi in enumerate(vkeep)}
        if self.mesh:
            return self._finalize_mesh(families, plain, vkeep, vre)
        # -- leaf layout: bucket pages (pow2-padded) first, direct
        # after.  Only buckets something references survive — a failed
        # subplan build can leave orphan page leaves behind, and an
        # unused bucket would still pay its in-program concatenate.
        used_keys = {self.vleaves[vi][0] for vi in vkeep} \
            | set(families.keys())
        bucket_meta: list = []
        bucket_id: dict = {}
        cur = 0
        leaves: list = []
        for key, pages in self.buckets.items():
            if key not in used_keys:
                continue
            npad = _pow2(max(len(pages), 1))
            padded = pages + [pages[-1]] * (npad - len(pages))
            bucket_id[key] = len(bucket_meta)
            bucket_meta.append((cur, npad))
            leaves.extend(padded)
            cur += npad
        nv = len(vkeep)
        leaves.extend(self.direct)
        # -- virtual-leaf meta + gather params
        vmeta: list = []
        for vi in vkeep:
            key, lane_idx, n, shape = self.vleaves[vi]
            gi = self._add_param(lane_idx, lane_idx[-1])
            vmeta.append((bucket_id[key], gi, int(n), tuple(shape)))
        # -- final lmaps + subs.  Unreferenced virtual leaves map to
        # None: _remap_sub only touches indices a sub actually reads,
        # so a None ever surfacing in a plan is a planner bug that
        # fails loudly at repr/jit time rather than mis-indexing.
        # Identical remapped subplans DEDUPE to one executed sub with
        # several riders: round-robin client mixes put the same query
        # in one batch many times, and without dedupe every
        # multiplicity would be a distinct plan (compile churn) doing
        # duplicate device work.
        subs: list = []
        served: list = []
        table: dict = {}
        sub_ix: dict = {}
        for ent, lmap, poff, _gidx in plain:
            final = {}
            for li, (tag, i) in lmap.items():
                final[li] = vre.get(i) if tag == "v" else nv + i
            riders, sub, demux, slot_key = ent
            rsub = _remap_sub(sub, final, poff)
            i = sub_ix.get(rsub)
            if i is None:
                subs.append(rsub)
                i = sub_ix[rsub] = len(subs) - 1
            if slot_key is not None:
                table[slot_key] = (demux, ("plain", i))
            for r in riders:
                served.append((r, demux, ("plain", i)))
        for vkey, members in families.items():
            # duplicate calls share one leaf (PlanBuilder dedupe), so
            # their lane_idx object is shared — one segment slot
            # serves every rider of that call
            slot_of: dict[int, int] = {}
            uniq: list = []
            member_slots: list = []
            for ent, v in members:
                li = v[1]
                s = slot_of.get(id(li))
                if s is None:
                    s = slot_of[id(li)] = len(uniq)
                    uniq.append(li)
                member_slots.append((ent, s))
            nseg = len(uniq)
            npad_seg = _pow2(nseg + 1)   # +1 dump slot for padding
            lane_cat = np.concatenate(uniq)
            seg_ids = np.concatenate(
                [np.full(li.shape[0], slot, np.int32)
                 for slot, li in enumerate(uniq)])
            # pad lanes to pow2 pointing at the dump segment so the
            # executable shape survives composition churn
            gi = self._add_param(lane_cat, lane_cat[-1])
            si = self._add_param(seg_ids, nseg)
            subs.append(("segcount", bucket_id[vkey], gi, si,
                         npad_seg))
            for ent, slot in member_slots:
                riders, _sub, demux, slot_key = ent
                if slot_key is not None:
                    table[slot_key] = (demux,
                                       ("seg", len(subs) - 1, slot))
                for r in riders:
                    served.append((r, demux,
                                   ("seg", len(subs) - 1, slot)))
        if not subs:
            return None
        plan = ("ragged", tuple(bucket_meta), tuple(vmeta),
                tuple(subs))
        return plan, leaves, self.params, served, table, None

    def _finalize_mesh(self, families, plain, vkeep, vre):
        """Emit the ``("ragged_mesh", ...)`` plan: per-device page
        POOLS as mesh-sharded leaves (assembled zero-copy with
        ``make_array_from_single_device_arrays`` — every page is
        already committed on its placement owner), per-device
        gather/scatter index params, and a combine spec per sub —
        psum trees for reduced outputs, dump-row scatter-adds for
        per-shard outputs — so every cross-device combine happens
        INSIDE the compiled program (no host merge phase).  Padded
        local shard positions gather the pool's guaranteed-zero tail
        page; zero shards are harmless for every reduction we run
        (the place_shards invariant — all BSI range arms mask with
        the exists plane)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from pilosa_tpu.memory import placement

        ndev = self.ndev
        n_base = len(self.params)
        used_keys = ({self.vleaves[vi][0] for vi in vkeep}
                     | set(families.keys()))
        devs = placement.devices()
        if len(devs) < ndev:
            raise RaggedUnbuildable("mesh shrank below plan width")
        smesh = placement.serving_mesh()
        bucket_meta: list = []    # (pool_pages, page_lanes, W)
        bucket_id: dict = {}
        zero_row: dict = {}       # bucket key -> all-zero pool row
        leaves: list = []
        dev_bytes = [0] * ndev
        for key, per_dev in self.buckets.items():
            if key not in used_keys:
                continue
            pl, w = key
            # +1 guarantees >= one zero pad page per device: slot
            # p2-1 is all-zero everywhere, the padding gather target
            p2 = _pow2(max(len(pages) for pages in per_dev) + 1)
            pieces = []
            for d in range(ndev):
                blocks = [jax.device_put(p, devs[d])
                          for p in per_dev[d]]
                dev_bytes[d] += len(blocks) * pl * w * 4
                if len(blocks) < p2:
                    z = jax.device_put(
                        np.zeros((pl, w), dtype=np.uint32), devs[d])
                    blocks.extend([z] * (p2 - len(blocks)))
                pieces.append(jnp.stack(blocks)[None])
            glob = jax.make_array_from_single_device_arrays(
                (ndev, p2, pl, w), NamedSharding(smesh, P("dev")),
                pieces)
            bucket_id[key] = len(bucket_meta)
            bucket_meta.append((p2, pl, w))
            zero_row[key] = (p2 - 1) * pl
            leaves.append(glob)
        # -- per-group geometry: each device's owned shard positions,
        # padded to a common pow2 local width
        geo: dict = {}

        def _geometry(gidx):
            g = geo.get(gidx)
            if g is None:
                owners = self.group_owners[gidx]
                if owners is None:
                    raise RaggedUnbuildable("mesh group w/o owners")
                s = int(owners.shape[0])
                owned = [np.flatnonzero(owners == d)
                         for d in range(ndev)]
                s_p = _pow2(max([o.size for o in owned] + [1]))
                sel = np.full((ndev, s_p), s, dtype=np.int64)
                for d in range(ndev):
                    sel[d, :owned[d].size] = owned[d]
                geo[gidx] = g = (s, s_p, sel)
            return g

        # -- virtual leaves: one per-device gather param each.  The
        # local leaf keeps the global lead shape with the shard axis
        # compressed to s_p; the gather grid extends the shard axis
        # by one sentinel slab pointing at the zero pool row.
        vmeta: list = []
        for vi in vkeep:
            key, pool_row, lane_dev, n, shape, sa, gidx = \
                self.vleaves[vi]
            s, s_p, sel = _geometry(gidx)
            lead = shape[:-1]
            if lead[sa] != s:
                raise RaggedUnbuildable("leaf shard axis mismatch")
            grid = np.arange(n, dtype=np.int64).reshape(lead)
            pad_shape = list(lead)
            pad_shape[sa] = 1
            ext = np.concatenate(
                [grid, np.full(pad_shape, n, dtype=np.int64)],
                axis=sa)
            row_ext = np.concatenate(
                [pool_row, np.array([zero_row[key]], np.int64)])
            dev_ext = np.concatenate(
                [lane_dev, np.array([-1], np.int32)])
            gat = []
            for d in range(ndev):
                flat = np.take(ext, sel[d], axis=sa).reshape(-1)
                fd = dev_ext[flat]
                if np.any((fd != d) & (fd != -1)):
                    raise RaggedUnbuildable("placement drift: lane "
                                            "owner != group owner")
                gat.append(row_ext[flat])
            gi = self._add_mesh_param(np.stack(gat))
            lshape = list(lead)
            lshape[sa] = s_p
            vmeta.append((bucket_id[key], gi,
                          tuple(lshape) + (shape[-1],)))
        # -- subs + per-sub combine specs.  spos params (local shard
        # position -> global shard index, padding -> the S dump row)
        # are per group and shared by every scatter sub of the group.
        spos_param: dict = {}

        def _spos(gidx):
            p = spos_param.get(gidx)
            if p is None:
                _s, _sp, sel = _geometry(gidx)
                p = spos_param[gidx] = self._add_mesh_param(sel)
            return p

        subs: list = []
        combines: list = []
        served: list = []
        table: dict = {}
        sub_ix: dict = {}
        for ent, lmap, poff, gidx in plain:
            # no direct leaves in mesh mode (add_group rejects them)
            final = {li: vre.get(i) for li, (_t, i) in lmap.items()}
            riders, sub, demux, slot_key = ent
            rsub = _remap_sub(sub, final, poff)
            if rsub[0] == "gb_hist":
                # pallas arms can't lower inside the shard_map body;
                # the XLA arm is the same math, bit-exact
                rsub = rsub[:6] + ("xla",)
            i = sub_ix.get(rsub)
            if i is None:
                s, _sp, _sel = _geometry(gidx)
                k = rsub[0]
                if k == "count":
                    comb = (("psum",) if rsub[2]
                            else ("scatter", _spos(gidx), s, 0))
                elif k == "words":
                    comb = ("scatter", _spos(gidx), s, 0)
                elif k == "bsi_sum":
                    comb = (("psum",) if rsub[3]
                            else ("scatter3", _spos(gidx), s))
                elif k == "row_counts":
                    comb = (("psum",) if rsub[3]
                            else ("scatter", _spos(gidx), s, 1))
                elif k == "gb_hist":
                    comb = ("psum",)
                else:
                    raise RaggedUnbuildable(f"unmeshable sub {k}")
                subs.append(rsub)
                combines.append(comb)
                i = sub_ix[rsub] = len(subs) - 1
            if slot_key is not None:
                table[slot_key] = (demux, ("plain", i))
            for r in riders:
                served.append((r, demux, ("plain", i)))
        # -- segment families: per-device lane/segment id arrays over
        # the device pools; padding points at the zero row + the dump
        # segment, partial per-segment counts psum to the exact total
        for vkey, members in families.items():
            slot_of: dict[int, int] = {}
            uniq: list = []
            member_slots: list = []
            for ent, v in members:
                slt = slot_of.get(id(v[1]))
                if slt is None:
                    slt = slot_of[id(v[1])] = len(uniq)
                    uniq.append((v[1], v[2]))
                member_slots.append((ent, slt))
            nseg = len(uniq)
            npad_seg = _pow2(nseg + 1)   # +1 dump slot for padding
            per_rows = [[] for _ in range(ndev)]
            per_segs = [[] for _ in range(ndev)]
            for slt, (pool_row, lane_dev) in enumerate(uniq):
                for d in range(ndev):
                    m = lane_dev == d
                    per_rows[d].append(pool_row[m])
                    per_segs[d].append(
                        np.full(int(m.sum()), slt, dtype=np.int32))
            lens = [int(sum(a.size for a in per_rows[d]))
                    for d in range(ndev)]
            lpad = _pow2(max(lens + [1]))
            rows = np.full((ndev, lpad), zero_row[vkey],
                           dtype=np.int64)
            segs = np.full((ndev, lpad), nseg, dtype=np.int32)
            for d in range(ndev):
                if lens[d]:
                    rows[d, :lens[d]] = np.concatenate(per_rows[d])
                    segs[d, :lens[d]] = np.concatenate(per_segs[d])
            gi = self._add_mesh_param(rows)
            si = self._add_mesh_param(segs)
            subs.append(("segcount", bucket_id[vkey], gi, si,
                         npad_seg))
            combines.append(("psum",))
            for ent, slt in member_slots:
                riders, _sub, demux, slot_key = ent
                if slot_key is not None:
                    table[slot_key] = (demux,
                                       ("seg", len(subs) - 1, slt))
                for r in riders:
                    served.append((r, demux,
                                   ("seg", len(subs) - 1, slt)))
        if not subs:
            return None
        plan = ("ragged_mesh", ndev, placement.epoch(), n_base,
                tuple(bucket_meta), tuple(vmeta), tuple(subs),
                tuple(combines))
        meshinfo = {"ndev": ndev, "dev_bytes": dev_bytes,
                    "dev_pages": [dict(m) for m in self.dev_mix]}
        return plan, leaves, self.params, served, table, meshinfo


# ---------------------------------------------------------------------------
# canonical composition (composition hysteresis)
# ---------------------------------------------------------------------------
# A fused program compiles per batch COMPOSITION, and free-running
# traffic produces endlessly novel compositions: a fast dispatch
# admits a small random batch, that one-off composition compiles for
# hundreds of milliseconds, the backlog forms a full batch, and the
# system oscillates between "warm full batch" and "novel small batch"
# — compile throughput, not serving.  The fix is hysteresis: the
# layer keeps a CANONICAL slot set of RECURRING (index, shards,
# query) items, LRU-bounded, and every batch dispatches the one
# canonical program.  Present riders demux their slots; absent slots
# still evaluate (their operands are resident cache hits and their
# bulk work is bandwidth-trivial) so the plan tuple — and therefore
# the compiled executable — is IDENTICAL from batch to batch.
# Steady state is literally one fused program, the ROADMAP item 1
# shape; composition changes (a hot query joining, an idle slot
# aging out, a dropped index) recompile exactly once.
#
# PROBATION keeps one-off queries out: a key joins the canonical set
# only after appearing in a SECOND batch within the probation window
# (a random ad-hoc query must not force a full canonical recompile).
# Non-canonical riders ride a separate EXTRAS program — a per-batch
# composition fused like the canonical one, whose compile churn is
# confined to exactly the traffic that churns.

_CANON_MAX = 96        # max canonical slots (absent-slot work bound)
_CANON_IDLE = 64       # batches a slot may sit unused before aging out
_CANON_PROBATION = 32  # window (batches) for the second sighting
_SEEN_MAX = 512        # probation bookkeeping bound


class _Slot:
    __slots__ = ("idx", "index_name", "skey", "shards", "kind",
                 "call", "last_used")

    def __init__(self, r, batch_no):
        self.idx = r.idx
        self.index_name = r.index
        self.skey = r.skey
        self.shards = r.shards
        self.kind = r.kind
        self.call = r.call
        self.last_used = batch_no


class _ShimReq:
    """Stand-in for a canonical slot absent from this batch: just
    enough of the _Req surface for ServingLayer._build_sub."""

    __slots__ = ("idx", "call", "kind", "shards", "skey", "result",
                 "error", "direct", "ctx")

    def __init__(self, slot: _Slot):
        self.idx = slot.idx
        self.call = slot.call
        self.kind = slot.kind
        self.shards = slot.shards
        self.skey = slot.skey
        self.result = None
        self.error = None
        self.direct = False
        self.ctx = None


class CanonicalComposition:
    """The layer's slot set + probation bookkeeping + the lock
    guarding them (concurrent batches overlap under continuous
    batching)."""

    def __init__(self):
        self.slots: OrderedDict[tuple, _Slot] = OrderedDict()
        self.seen: OrderedDict[tuple, int] = OrderedDict()
        self.batch_no = 0
        self.lock = __import__("threading").Lock()
        # cross-batch program cache: (slot fingerprint, mutation
        # epoch, plan, leaves, params, table, consts).  Valid while
        # the slot set AND the global mutation epoch
        # (models/fragment.py) are unchanged — a read-heavy steady
        # state then skips plan building entirely and pays ONE
        # dispatch per batch; any write anywhere invalidates
        # conservatively (the per-fragment stamps remain the precise
        # staleness authority via the post-batch snapshot re-check).
        # Holding `leaves` pins the canonical working set's device
        # pages between batches — bounded by _CANON_MAX slots.
        self.cached = None

    def fold(self, layer, groups: dict) -> list:
        """Register the batch's requests (promoting recurring keys
        out of probation), age out idle/dead slots, and return a
        stable-ordered snapshot of the slot list.  Riders whose key
        is still on probation ride the extras program."""
        holder = layer.executor.holder
        with self.lock:
            self.batch_no += 1
            for reqs in groups.values():
                for r in reqs:
                    key = (id(r.idx), r.skey, r.kind, repr(r.call))
                    slot = self.slots.get(key)
                    if slot is not None:
                        slot.last_used = self.batch_no
                        continue
                    last = self.seen.get(key)
                    if (last is not None
                            and 0 < self.batch_no - last
                            <= _CANON_PROBATION):
                        # second sighting in a different recent
                        # batch: promote — it's recurring traffic
                        self.slots[key] = _Slot(r, self.batch_no)
                        self.seen.pop(key, None)
                    else:
                        self.seen[key] = self.batch_no
                        self.seen.move_to_end(key)
                        while len(self.seen) > _SEEN_MAX:
                            self.seen.popitem(last=False)
            for key, slot in list(self.slots.items()):
                if (self.batch_no - slot.last_used > _CANON_IDLE
                        or holder.index(slot.index_name)
                        is not slot.idx):
                    del self.slots[key]
            while len(self.slots) > _CANON_MAX:
                key = min(self.slots,
                          key=lambda k: self.slots[k].last_used)
                del self.slots[key]
            # stable order: groups by (index name, skey), slots by
            # call repr — identical slot sets build identical plans
            slots = sorted(
                self.slots.values(),
                key=lambda s: (s.index_name, s.skey, s.kind,
                               repr(s.call)))
            fp = tuple(sorted(self.slots))
            return slots, fp

    def drop(self, slot_keys):
        with self.lock:
            for key in slot_keys:
                self.slots.pop(key, None)
            self.cached = None


# ---------------------------------------------------------------------------
# batch execution (called by ServingLayer._run_batch on the leader)
# ---------------------------------------------------------------------------

def _mesh_width(eng) -> int:
    """Serving-mesh width for the fused program: > 1 only when the
    serving mesh (memory/placement.py) is configured AND the engine
    runs the plain paged placement — the legacy GSPMD mesh and
    host_only keep whole-array entries, so there is no page table to
    walk per device."""
    from pilosa_tpu import memory as _mem
    from pilosa_tpu.memory import placement
    if eng.mesh is not None or eng.host_only \
            or not _mem.paged_enabled():
        return 1
    return placement.mesh_devices()


def _note_roofline(plan, leaves, dt, meshinfo, served) -> None:
    """Per-dispatch bandwidth attribution for the fused ragged
    program: the aggregate 'ragged' op family plus — under the mesh —
    a per-device series (each chip's resident pool bytes over the
    same program wall time) and the per-device page-encoding mix on
    every rider's flight record."""
    from pilosa_tpu.obs import roofline
    nbytes = sum(int(getattr(a, "nbytes", 0)) for a in leaves)
    roofline.note("ragged", nbytes, dt)
    if not meshinfo:
        return
    for d, b in enumerate(meshinfo.get("dev_bytes", ())):
        roofline.note("ragged", b, dt, device=d)
    mix = {f"d{d}:{k}": v
           for d, m in enumerate(meshinfo.get("dev_pages", ()))
           for k, v in m.items()}
    if mix:
        for r, _d, _e in served:
            r.acc.add_pages(mix)


def run_ragged(layer, groups: dict) -> None:
    """Plan, dispatch, and demux EVERY group of the batch through the
    ONE canonical fused program.  Mirrors the per-group leader
    protocol (serving._run_group): per-request plan/build
    attribution, the serving-dispatch chaos seam, the OOM backstop,
    and the mark-direct-on-failure fallback — a failed fused program
    degrades every rider to its caller-thread solo path, never to an
    error."""
    import pilosa_tpu.models.fragment as _frag
    eng = layer.executor.stacked
    canon = getattr(layer, "_ragged_canon", None)
    if canon is None:
        canon = layer._ragged_canon = CanonicalComposition()
    slots, fp = canon.fold(layer, groups)
    # epoch read BEFORE any build/serve decision: a write landing
    # mid-build leaves a stamp older than the live epoch, so the next
    # batch rebuilds (and this batch's riders are covered by the
    # post-batch snapshot re-check either way)
    epoch = _frag.mutation_epoch()
    # riders by slot key, build order canonical within each group
    by_key: OrderedDict[tuple, list] = OrderedDict()
    for reqs in groups.values():
        for r in reqs:
            if r.result is None and r.error is None:
                by_key.setdefault(
                    (id(r.idx), r.skey, r.kind, repr(r.call)),
                    []).append(r)
    # -- canonical program: serve from the cross-batch cache when the
    # slot set and data are unchanged, else rebuild + re-cache ------
    with canon.lock:
        cached = canon.cached
        if cached is not None and (cached[0] != fp
                                   or cached[1] != epoch):
            cached = None
        if cached is not None and cached[2] is not None \
                and cached[2][0] == "ragged_mesh":
            # mesh plans pin topology + placement epoch at build
            # time: a rebalance or mesh resize must rebuild, never
            # replay pools addressed by a dead placement
            from pilosa_tpu.memory import placement as _pl
            if (cached[2][1] != _mesh_width(eng)
                    or cached[2][2] != _pl.epoch()):
                cached = None
                canon.cached = None
        elif cached is not None and cached[2] is not None \
                and _mesh_width(eng) > 1:
            # single-device plan cached before the mesh came up
            cached = None
            canon.cached = None
    if cached is not None:
        _serve_cached(layer, eng, cached, by_key, len(groups))
    else:
        slot_groups: OrderedDict[tuple, list] = OrderedDict()
        for s in slots:
            slot_groups.setdefault((id(s.idx), s.skey), []).append(s)
        work = []
        for (_gid, skey), gslots in slot_groups.items():
            pairs = [(slot, by_key.pop(
                (id(slot.idx), slot.skey, slot.kind, repr(slot.call)),
                [])) for slot in gslots]
            work.append((gslots[0].idx, skey, pairs))
        if work:
            payload = _plan_and_dispatch(layer, eng, work,
                                         len(groups), canon=canon,
                                         program="canonical")
            if payload is not None:
                with canon.lock:
                    # only cache if no slot died during the build
                    # (drop() cleared cached and changed the set)
                    if tuple(sorted(canon.slots)) == fp:
                        canon.cached = (fp, epoch) + payload
    # -- extras program: probation riders (one-off / not-yet-
    # recurring queries) fuse into their own per-batch composition,
    # so their compile churn never touches the canonical executable
    if by_key:
        ework: OrderedDict[tuple, list] = OrderedDict()
        for key, riders in by_key.items():
            if not riders:
                continue
            r0 = riders[0]
            ework.setdefault((id(r0.idx), r0.skey), []).append(
                (_Slot(r0, 0), riders))
        work2 = [(pairs[0][1][0].idx, skey, pairs)
                 for (_gid, skey), pairs in ework.items()]
        if work2:
            _plan_and_dispatch(layer, eng, work2, len(groups),
                               canon=None, program="extras")


def _plan_and_dispatch(layer, eng, work, n_groups: int,
                       canon=None, program: str = "canonical"):
    """Build ONE ragged program over `work` — [(idx, skey,
    [(slot, riders), ...]), ...] in stable order — dispatch it, and
    demux every rider.  `canon` given: a build failure evicts the
    slot from the canonical set, and a successful build returns the
    (plan, leaves, params, table, consts, meshinfo) payload for the
    cross-batch program cache (None otherwise)."""
    from pilosa_tpu.memory import placement as _placement
    ndev = _mesh_width(eng)
    prog = RaggedProgram(ndev=ndev)
    dead_keys: list = []
    consts: dict = {}
    for idx, skey, pairs in work:
        shards = list(skey)
        owners = (_placement.owners(idx.name, shards)
                  if ndev > 1 else None)
        b = PlanBuilder(eng, idx, shards, {})
        entries = []
        for slot, riders in pairs:
            slot_key = ((id(slot.idx), slot.skey, slot.kind,
                         repr(slot.call))
                        if canon is not None else None)
            target = riders[0] if riders else _ShimReq(slot)
            acc = flight.Acc()
            for r in riders:
                r.acc = flight.Acc()
            if riders:
                riders[0].acc = acc
            prev = flight.push_acc(acc)
            t0 = time.perf_counter()
            try:
                with raw_pages(), span_into(target.ctx,
                                            "serving.plan",
                                            kind=slot.kind):
                    built = layer._build_sub(b, target, shards)
            except Exception:
                # unbuildable now (data/schema drift): the slot
                # leaves the canonical set and its riders fall back
                for r in riders:
                    r.direct = True
                if slot_key is not None:
                    dead_keys.append(slot_key)
                continue
            finally:
                flight.pop_acc(prev)
                stack_t = sum(v for k, v in acc.phases.items()
                              if k.startswith("stack_"))
                acc.add_phase("plan_build", max(
                    time.perf_counter() - t0 - stack_t, 0.0))
            if built is None:
                # constant result: share it across riders (the
                # result cache shares result objects the same way)
                for r in riders[1:]:
                    r.result = target.result
                if slot_key is not None:
                    consts[slot_key] = target.result
                continue
            entries.append((riders, built[0], built[1], slot_key))
        if entries:
            try:
                prog.add_group(b, entries, owners=owners)
            except RaggedUnbuildable:
                # the group can't enter the mesh program (whole/host
                # operand, unplaced pages): its riders degrade to the
                # solo path, everything else stays fused
                for riders, _s, _d, slot_key in entries:
                    for r in riders:
                        r.direct = True
                    if slot_key is not None:
                        dead_keys.append(slot_key)
    if canon is not None and dead_keys:
        canon.drop(dead_keys)
    cacheable = canon is not None and not dead_keys
    try:
        fin = prog.finalize()
    except RaggedUnbuildable as e:
        # finalize-time mesh rejection (placement drift, topology
        # shrink): every rider of the batch degrades, no error
        capture_exception(e, where="serving.ragged_finalize")
        for _idx, _skey, pairs in work:
            for _slot, riders in pairs:
                for r in riders:
                    r.direct = True
        if canon is not None:
            canon.drop([slot_key for _i, _s, pairs in work
                        for slot, _r in pairs
                        for slot_key in [(id(slot.idx), slot.skey,
                                          slot.kind,
                                          repr(slot.call))]])
        return None
    if fin is None:
        # a program of constants alone is still cacheable
        return ((None, None, None, {}, consts, None)
                if cacheable and consts else None)
    plan, leaves, params, served, table, meshinfo = fin
    payload = ((plan, leaves, params, table, consts, meshinfo)
               if cacheable else None)
    if not served:
        # no rider this batch — skip the dispatch but keep the built
        # program for the cache (the next batch serves from it)
        return payload
    kern = (kernels.enabled() and not eng.host_only
            and plan[0] != "ragged_mesh")
    sig = (repr(plan), kern)
    kind = _dispatch_kind(sig, leaves, params)
    nsubs = len(plan[3]) if plan[0] == "ragged" else len(plan[6])
    sp = Span("serving.dispatch")
    sp.tags.update(batch=len(served), subqueries=nsubs,
                   ragged=True, program=program, groups=n_groups,
                   mesh=plan[0] == "ragged_mesh",
                   compile=kind == "compile")
    oom0 = metrics.OOM_TOTAL.total(outcome="caught")
    t0 = time.perf_counter()
    try:
        # same chaos seam + OOM backstop as the per-group dispatch
        from pilosa_tpu.obs import faults
        faults.fire("serving-dispatch")
        fn = _compiled(plan, kern=kern, sig=sig)
        outs = pressure.guarded(
            lambda: _block(fn(tuple(leaves), tuple(params))))
    except Exception as e:
        capture_exception(
            e, where="serving.ragged_dispatch", batch=len(served),
            trace_ids=[r.trace_id for r, _d, _e in served
                       if r.trace_id])
        for r, _d, _e in served:
            r.direct = True
        return
    finally:
        sp.finish()
    metrics.SERVING_DISPATCH.inc(
        kind="ragged_mesh" if plan[0] == "ragged_mesh" else "ragged")
    dt = time.perf_counter() - t0
    if kind == "execute" and \
            metrics.OOM_TOTAL.total(outcome="caught") == oom0:
        _note_roofline(plan, leaves, dt, meshinfo, served)
    for r, _d, _e in served:
        r.acc.add_phase(kind, dt)
        if r.ctx is not None:
            r.ctx.attach(sp.copy())
    for r, demux, ext in served:
        out = outs[ext[1]] if ext[0] == "plain" else \
            outs[ext[1]][ext[2]]
        t1 = time.perf_counter()
        try:
            with span_into(r.ctx, "serving.demux"):
                r.result = demux(out)
        except Exception:
            r.direct = True
            r.result = None
        r.acc.add_phase("demux", time.perf_counter() - t1)
    return payload


def _serve_cached(layer, eng, cached, by_key, n_groups: int) -> None:
    """Serve this batch's canonical riders from the cross-batch
    program cache: no plan building, no leaf fetches — map each rider
    to its slot's demux/extract, run the ONE cached fused program,
    demux.  Keys the cache doesn't know stay in `by_key` for the
    extras program."""
    _fp, _epoch, plan, leaves, params, table, consts, meshinfo = \
        cached
    served: list = []
    for key in list(by_key):
        if key in consts:
            for r in by_key.pop(key):
                r.acc = flight.Acc()
                r.result = consts[key]
        elif table and key in table:
            demux, ext = table[key]
            for r in by_key.pop(key):
                r.acc = flight.Acc()
                served.append((r, demux, ext))
    if not served or plan is None:
        return
    kern = (kernels.enabled() and not eng.host_only
            and plan[0] != "ragged_mesh")
    sig = (repr(plan), kern)
    kind = _dispatch_kind(sig, leaves, params)
    nsubs = len(plan[3]) if plan[0] == "ragged" else len(plan[6])
    sp = Span("serving.dispatch")
    sp.tags.update(batch=len(served), subqueries=nsubs,
                   ragged=True, program="canonical-cached",
                   groups=n_groups, mesh=plan[0] == "ragged_mesh",
                   compile=kind == "compile")
    oom0 = metrics.OOM_TOTAL.total(outcome="caught")
    t0 = time.perf_counter()
    try:
        from pilosa_tpu.obs import faults
        faults.fire("serving-dispatch")
        fn = _compiled(plan, kern=kern, sig=sig)
        outs = pressure.guarded(
            lambda: _block(fn(tuple(leaves), tuple(params))))
    except Exception as e:
        capture_exception(
            e, where="serving.ragged_dispatch", batch=len(served),
            trace_ids=[r.trace_id for r, _d, _e in served
                       if r.trace_id])
        for r, _d, _e in served:
            r.direct = True
        return
    finally:
        sp.finish()
    metrics.SERVING_DISPATCH.inc(
        kind="ragged_mesh" if plan[0] == "ragged_mesh" else "ragged")
    dt = time.perf_counter() - t0
    if kind == "execute" and \
            metrics.OOM_TOTAL.total(outcome="caught") == oom0:
        _note_roofline(plan, leaves, dt, meshinfo, served)
    for r, _d, _e in served:
        r.acc.add_phase(kind, dt)
        if r.ctx is not None:
            r.ctx.attach(sp.copy())
    for r, demux, ext in served:
        out = outs[ext[1]] if ext[0] == "plain" else \
            outs[ext[1]][ext[2]]
        t1 = time.perf_counter()
        try:
            with span_into(r.ctx, "serving.demux"):
                r.result = demux(out)
        except Exception:
            r.direct = True
            r.result = None
        r.acc.add_phase("demux", time.perf_counter() - t1)
