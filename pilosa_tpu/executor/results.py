"""Query result types.

Python analogs of the reference result shapes: Row (row.go:15 —
per-shard segments), ValCount (executor.go:8345), SignedRow-style
distinct values, Pair/PairsField (TopN), GroupCounts (executor.go:3553).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import numpy as np

from pilosa_tpu.ops import bitmap as bm
from pilosa_tpu.shardwidth import SHARD_WIDTH


class RowResult:
    """A set of columns, stored as per-shard packed segments."""

    def __init__(self, width: int = SHARD_WIDTH):
        self.width = width
        self.segments: dict[int, np.ndarray] = {}
        self.keys: list[str] | None = None  # set by key translation

    @classmethod
    def from_segments(cls, segs: dict[int, np.ndarray],
                      width: int = SHARD_WIDTH) -> "RowResult":
        r = cls(width)
        r.segments = {int(s): np.asarray(w, dtype=np.uint32)
                      for s, w in segs.items()}
        return r

    @classmethod
    def from_columns(cls, cols, width: int = SHARD_WIDTH) -> "RowResult":
        r = cls(width)
        cols = np.asarray(sorted(int(c) for c in cols), dtype=np.int64)
        if cols.size:
            shards = cols // width
            for s in np.unique(shards):
                r.segments[int(s)] = bm.from_columns(
                    cols[shards == s] % width, width)
        return r

    def columns(self) -> np.ndarray:
        """Materialize absolute column ids (shard*width + col)."""
        parts = []
        for s in sorted(self.segments):
            cols = bm.to_columns(self.segments[s])
            if cols.size:
                parts.append(cols.astype(np.int64) + s * self.width)
        return np.concatenate(parts) if parts else np.array([], dtype=np.int64)

    def count(self) -> int:
        return int(sum(np.bitwise_count(w).sum()
                       for w in self.segments.values()))

    def any(self) -> bool:
        return any(w.any() for w in self.segments.values())

    def shard_words(self, shard: int) -> np.ndarray:
        w = self.segments.get(shard)
        return w if w is not None else bm.empty(self.width)

    def __eq__(self, other):
        if not isinstance(other, RowResult):
            return NotImplemented
        return np.array_equal(self.columns(), other.columns())

    def __repr__(self):
        cols = self.columns()
        preview = cols[:10].tolist()
        suffix = "..." if cols.size > 10 else ""
        return f"RowResult({preview}{suffix}, n={cols.size})"


@dataclass
class ValCount:
    """Sum/Min/Max/Percentile result (executor.go ValCount): the value
    in field units (int, float for decimal, datetime for timestamp)
    plus the count of columns contributing."""
    value: Any = None
    count: int = 0


@dataclass
class DistinctValues:
    """Distinct over a BSI field (reference SignedRow executor.go:8225):
    the sorted distinct values."""
    values: list = field(default_factory=list)


@dataclass
class Pair:
    """TopN/TopK entry (cache.go:374 Pair): row id + count."""
    id: int = 0
    key: str | None = None
    count: int = 0


@dataclass
class GroupCount:
    """One GroupBy result group (executor.go GroupCount)."""
    group: list[dict]  # [{"field":..., "row_id":... or "value":...}, ...]
    count: int = 0
    agg: Any = None
    agg_count: Any = None  # non-null rows feeding agg (for AVG = agg/agg_count)


@dataclass
class SortedRow:
    """Sort result (executor.go:9540 SortedRow): columns ordered by a
    BSI field's value, with the values carried alongside."""
    columns: list = field(default_factory=list)
    values: list = field(default_factory=list)


@dataclass
class ExtractedTable:
    """Extract result (executor.go:4205 ExtractedTable)."""
    fields: list = field(default_factory=list)
    columns: list = field(default_factory=list)  # [{"column", "rows"}]


def deserialize_result(call, data, width: int = SHARD_WIDTH):
    """Inverse of api.serialize_result for one call's JSON form —
    reconstructs the result OBJECT a remote node serialized, so a
    front end (the DAX queryer's SQL layer) can feed wire results
    back through engine code that expects rich result types
    (dax/queryer/queryer.go:134 wire-decoding role)."""
    name = call.name
    if name in ("Count", "IncludesColumn") or isinstance(data, (int, bool)):
        return data
    if name in ("Sum", "Min", "Max"):
        return ValCount(value=data.get("value"), count=data.get("count", 0))
    if name in ("TopN", "TopK"):
        return [Pair(id=p.get("id", 0), count=p.get("count", 0),
                     key=p.get("key")) for p in data]
    if name == "GroupBy":
        return [GroupCount(group=g.get("group", []),
                           count=g.get("count", 0),
                           agg=g.get("agg"),
                           agg_count=g.get("agg_count"))
                for g in data]
    if name == "Distinct":
        if isinstance(data, dict) and "values" in data:
            return DistinctValues(values=list(data["values"]))
        r = RowResult.from_columns(data.get("columns", []), width)
        r.is_row_ids = True
        return r
    if name == "Rows":
        return list(data)
    if name == "Extract":
        return ExtractedTable(fields=list(data.get("fields", [])),
                              columns=list(data.get("columns", [])))
    if name == "Sort":
        return SortedRow(columns=list(data.get("columns", [])),
                         values=list(data.get("values", [])))
    if isinstance(data, dict) and "columns" in data:
        r = RowResult.from_columns(data["columns"], width)
        if data.get("keys") is not None:
            r.keys = list(data["keys"])
        return r
    return data
