"""QoS admission for the serving path — classes, fairness, shedding.

The ragged batcher (executor/ragged.py) removes the *dispatch* penalty
of heterogeneous traffic; this module removes the *queueing* penalty.
Real mixed load is a few expensive queries (240-combo GroupBys, broad
Extracts) amid a stream of point reads, and FIFO admission lets one
heavy burst occupy every handler thread so point reads wait behind
device-seconds of scan work.  Three mechanisms, all in front of the
batcher:

- **Admission classes** — every read classifies as ``point`` (cheap
  bitmap/aggregate shapes: the batcher can fuse them, and their device
  cost is microseconds) or ``heavy`` (GroupBy/Extract/Sort/TopN/...).
  Point reads are never queued: they go straight to the cache/batcher.
  Heavy reads pass a bounded concurrency gate (``heavy_slots``), so a
  GroupBy storm saturates at most that many engine threads and the
  device stays responsive for point traffic.  An explicit
  ``X-Pilosa-Priority`` header overrides the classifier.

- **Weighted per-tenant fair queueing** — queued heavy requests drain
  by stride scheduling: each tenant advances a virtual pass by
  1/weight per grant, and the gate always grants the tenant with the
  smallest pass (FIFO within a tenant).  A tenant with weight 4 gets
  4x the grant rate of a weight-1 tenant under contention and exactly
  its demand otherwise.  Weights come from ``[serving]
  tenant-weights`` ("analytics:4,adhoc:1"); unknown tenants get 1.

- **Backpressure** — a bounded total queue (``queue_max``).  Overflow
  sheds with :class:`ServingShedError`, a typed 503 carrying
  Retry-After (the PR 6/7 status-carrying dispatch renders it on the
  wire); a request whose deadline (``X-Pilosa-Deadline-Ms``) expires
  while queued — or already arrived dead — sheds with
  :class:`ServingDeadlineExceeded`, a typed 504.  Both count into
  ``pilosa_serving_admission_total{class,outcome}``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass

from pilosa_tpu.obs import metrics
from pilosa_tpu.pql.ast import Query

CLASS_POINT = "point"
CLASS_HEAVY = "heavy"
# the correctness-audit plane's dedicated lowest-priority class
# (obs/audit.py): its own concurrency cap, non-blocking acquisition —
# audits shed when the cap is busy, they never queue against (or
# steal) serving slots
CLASS_AUDIT = "audit"

# calls whose per-query device/host cost is orders beyond a point
# read: combo enumeration (GroupBy), whole-table materialization
# (Extract/Sort), candidate-row scans (TopN/TopK/Rows), cross-shard
# value walks (Distinct/Percentile).  Everything else — Count, Row
# trees, Sum/Min/Max, IncludesColumn — is a point read.
_HEAVY_CALLS = {"GroupBy", "Extract", "Sort", "Percentile", "TopN",
                "TopK", "Rows", "UnionRows", "Distinct", "Limit"}


class ServingShedError(Exception):
    """Admission queue over budget — typed 503 with Retry-After (the
    HTTP/gRPC layers render ``status`` and ``retry_after_s``)."""

    status = 503

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class ServingDeadlineExceeded(Exception):
    """The request's deadline passed before it could be admitted."""

    status = 504


@dataclass
class QoS:
    """Per-request quality-of-service intent, parsed from transport
    headers (server/http.py, server/grpc.py).  ``deadline_ms`` is the
    client's total budget; ``deadline_s`` the derived absolute
    monotonic deadline."""

    tenant: str = "default"
    priority: str | None = None     # explicit class override
    deadline_ms: float | None = None
    deadline_s: float | None = None

    @classmethod
    def make(cls, tenant=None, priority=None, deadline_ms=None):
        dl = None
        if deadline_ms is not None and deadline_ms > 0:
            dl = time.monotonic() + float(deadline_ms) / 1e3
        return cls(tenant=str(tenant) if tenant else "default",
                   priority=priority or None,
                   deadline_ms=float(deadline_ms)
                   if deadline_ms is not None else None,
                   deadline_s=dl)


def classify(q: Query, qos: QoS | None,
             fingerprint: str | None = None) -> str:
    """Admission class of a read query.  Explicit priority wins.
    Next, MEASURED cost: when the statistics catalog (obs/stats.py)
    holds a warm profile for this plan fingerprint, the class is the
    estimated cost against ``[stats] heavy-cost-ms`` — a GroupBy that
    measures cheap (tiny combo space, or always cache-served) rides
    the point lane; a Count that measures expensive gates like the
    heavy query it is.  Query KIND is the cold-start fallback: any
    heavy call in the tree makes the query heavy.  Class choice only
    affects scheduling, never results.

    Known tradeoff: the estimate folds in batches, so after a cache
    invalidation a BURST of a cached-cheap-but-expensive-to-compute
    fingerprint (up to one fold batch, ~32 records, per wave) can
    ride the point lane before the estimate re-adapts — bounded, and
    accepted in exchange for not burning heavy slots on sub-ms
    cache-served queries (the measured misclassification win)."""
    if qos is not None and qos.priority in (CLASS_POINT, CLASS_HEAVY):
        return qos.priority
    if fingerprint is not None:
        from pilosa_tpu.obs import stats
        est = stats.est_cost_ms(fingerprint)
        if est is not None:
            cls = (CLASS_HEAVY if est >= stats.heavy_cost_ms()
                   else CLASS_POINT)
            metrics.STATS_ADMISSION.inc(**{"source": "profile",
                                           "class": cls})
            return cls

    def heavy(call) -> bool:
        if call.name in _HEAVY_CALLS:
            return True
        return any(heavy(c) for c in call.children) or any(
            heavy(v) for v in call.args.values()
            if hasattr(v, "children"))

    cls = CLASS_HEAVY if any(heavy(c) for c in q.calls) \
        else CLASS_POINT
    if fingerprint is not None:
        # the catalog was consulted but had no warm profile — count
        # the fallback so the misclassification A/B is attributable
        metrics.STATS_ADMISSION.inc(**{"source": "static",
                                       "class": cls})
    return cls


def classify_sql(stmt, qos: QoS | None,
                 fingerprint: str | None = None) -> str:
    """Per-statement admission class for the SQL serving path
    (ISSUE 13): explicit priority wins, then the statement
    fingerprint's MEASURED cost from the statistics catalog (same
    ``[stats] heavy-cost-ms`` threshold as PQL classify), and the
    statement SHAPE as the cold-start fallback — joins, GROUP BY,
    aggregates, DISTINCT, and unbounded extracts are heavy; bounded
    single-table projections ride the point lane.  Class choice only
    affects scheduling, never results."""
    if qos is not None and qos.priority in (CLASS_POINT, CLASS_HEAVY):
        return qos.priority
    if fingerprint is not None:
        from pilosa_tpu.obs import stats
        est = stats.est_cost_ms(fingerprint)
        if est is not None:
            cls = (CLASS_HEAVY if est >= stats.heavy_cost_ms()
                   else CLASS_POINT)
            metrics.STATS_ADMISSION.inc(**{"source": "profile",
                                           "class": cls})
            return cls
    from pilosa_tpu.sql import ast as _ast
    point_where = (isinstance(stmt.where, _ast.BinOp)
                   and stmt.where.op == "="
                   and isinstance(stmt.where.left, _ast.Col)
                   and stmt.where.left.name == "_id")
    heavy = bool(
        stmt.joins or stmt.group_by or stmt.having is not None
        or stmt.distinct or stmt.from_select is not None
        or any(isinstance(it.expr, _ast.Agg) for it in stmt.items)
        or (stmt.limit is None and stmt.table and not point_where))
    cls = CLASS_HEAVY if heavy else CLASS_POINT
    if fingerprint is not None:
        metrics.STATS_ADMISSION.inc(**{"source": "static",
                                       "class": cls})
    return cls


class _Ticket:
    __slots__ = ("granted", "abandoned")

    def __init__(self):
        self.granted = False
        self.abandoned = False


def parse_weights(spec: str | None) -> dict[str, float]:
    """"tenantA:4,tenantB:1" -> {"tenantA": 4.0, ...}; malformed
    entries are ignored (an operator typo must not kill serving)."""
    out: dict[str, float] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part or ":" not in part:
            continue
        name, _, w = part.rpartition(":")
        try:
            wf = float(w)
        except ValueError:
            continue
        if name and wf > 0:
            out[name.strip()] = wf
    return out


class AdmissionScheduler:
    """The serving admission plane: class gate + weighted fair queue +
    shed.  One per ServingLayer."""

    def __init__(self, heavy_slots: int = 2, queue_max: int = 128,
                 tenant_weights: dict[str, float] | None = None,
                 audit_slots: int = 1):
        self.heavy_slots = max(1, int(heavy_slots))
        self.queue_max = max(1, int(queue_max))
        self.audit_slots = max(1, int(audit_slots))
        self._audit_running = 0
        self.weights = dict(tenant_weights or {})
        self._cond = threading.Condition()
        # per-tenant state is DROPPED when a tenant's queue drains:
        # X-Pilosa-Tenant is client-controlled, and retaining an
        # entry (plus a stride pass and a metrics label series) per
        # tenant ever seen would leak without bound on a long-lived
        # server — occupancy is therefore bounded by queue_max.  The
        # stride pass resets to the global pass on re-entry, which
        # only forgives a drained tenant its history, never starves.
        self._queues: dict[str, deque[_Ticket]] = {}
        self._passes: dict[str, float] = {}   # stride pass per tenant
        self._global_pass = 0.0
        self._running = 0
        self._queued = 0

    def _gauge_tenant(self, tenant: str) -> str:
        """Metrics label for a tenant: configured tenants get their
        own series, everything else aggregates under "(other)" so a
        client-controlled header can't grow label cardinality."""
        return tenant if tenant in self.weights else "(other)"

    def _drop_if_empty_locked(self, tenant: str):
        q = self._queues.get(tenant)
        if q is not None and not q:
            del self._queues[tenant]
            self._passes.pop(tenant, None)

    # -- introspection --------------------------------------------------

    def queued(self, tenant: str | None = None) -> int:
        with self._cond:
            if tenant is None:
                return self._queued
            return len(self._queues.get(tenant, ()))

    # -- the audit gate -------------------------------------------------

    def audit_slot(self):
        """Non-blocking admission for the correctness-audit class:
        returns a slot handle (call ``release()`` when done) or None
        when the cap is busy — the caller sheds the AUDIT, never a
        serving query.  Audit slots are accounted separately from
        heavy slots by construction, so a saturated audit plane can
        never occupy serving concurrency."""
        with self._cond:
            if self._audit_running >= self.audit_slots:
                metrics.ADMISSION_TOTAL.inc(**{"class": CLASS_AUDIT,
                                               "outcome": "shed"})
                return None
            self._audit_running += 1
        metrics.ADMISSION_TOTAL.inc(**{"class": CLASS_AUDIT,
                                       "outcome": "admitted"})
        return _AuditSlot(self)

    def _audit_release(self):
        with self._cond:
            self._audit_running = max(0, self._audit_running - 1)

    # -- the heavy gate -------------------------------------------------

    def heavy_slot(self, qos: QoS | None):
        """Context manager bounding heavy-class concurrency.  Raises
        ServingShedError / ServingDeadlineExceeded instead of
        entering."""
        return _HeavySlot(self, qos)

    def _retry_after(self) -> float:
        # rough drain estimate: assume ~250 ms per queued heavy query
        # per slot; clamp to a sane Retry-After window
        return round(min(max(
            0.25 * self._queued / self.heavy_slots, 0.5), 30.0), 3)

    def _acquire(self, qos: QoS | None):
        tenant = qos.tenant if qos is not None else "default"
        deadline = qos.deadline_s if qos is not None else None
        with self._cond:
            if deadline is not None and time.monotonic() > deadline:
                metrics.ADMISSION_TOTAL.inc(**{"class": CLASS_HEAVY,
                                            "outcome": "expired"})
                raise ServingDeadlineExceeded(
                    "deadline expired before admission")
            if self._running < self.heavy_slots and self._queued == 0:
                self._running += 1
                metrics.ADMISSION_TOTAL.inc(**{"class": CLASS_HEAVY,
                                            "outcome": "admitted"})
                return
            if self._queued >= self.queue_max:
                metrics.ADMISSION_TOTAL.inc(**{"class": CLASS_HEAVY,
                                            "outcome": "shed"})
                raise ServingShedError(
                    f"serving admission queue full "
                    f"({self._queued} heavy queries waiting)",
                    retry_after_s=self._retry_after())
            tck = _Ticket()
            self._queues.setdefault(tenant, deque()).append(tck)
            self._queued += 1
            metrics.TENANT_QUEUE_DEPTH.set(
                len(self._queues[tenant]),
                tenant=self._gauge_tenant(tenant))
            while not tck.granted:
                if deadline is not None:
                    rem = deadline - time.monotonic()
                    if rem <= 0:
                        tck.abandoned = True
                        self._reap_locked(tenant)
                        metrics.ADMISSION_TOTAL.inc(**{
                            "class": CLASS_HEAVY, "outcome": "expired"})
                        raise ServingDeadlineExceeded(
                            "deadline expired while queued")
                    self._cond.wait(rem)
                else:
                    self._cond.wait()
            metrics.ADMISSION_TOTAL.inc(**{"class": CLASS_HEAVY,
                                        "outcome": "admitted"})

    def _release(self):
        with self._cond:
            self._running -= 1
            self._grant_locked()
            self._cond.notify_all()

    def _reap_locked(self, tenant: str):
        """Drop abandoned tickets from a tenant's queue."""
        q = self._queues.get(tenant)
        if not q:
            self._drop_if_empty_locked(tenant)
            return
        alive = deque(t for t in q if not t.abandoned)
        dropped = len(q) - len(alive)
        if dropped:
            self._queues[tenant] = alive
            self._queued -= dropped
            metrics.TENANT_QUEUE_DEPTH.set(
                len(alive), tenant=self._gauge_tenant(tenant))
        self._drop_if_empty_locked(tenant)

    def _grant_locked(self):
        """Stride scheduling: grant free slots to the tenant with the
        smallest pass value (pass += 1/weight per grant), FIFO within
        a tenant."""
        while self._running < self.heavy_slots and self._queued > 0:
            best = None
            for tenant in list(self._queues):
                q = self._queues[tenant]
                while q and q[0].abandoned:
                    q.popleft()
                    self._queued -= 1
                if not q:
                    self._drop_if_empty_locked(tenant)
                    continue
                p = self._passes.get(tenant, self._global_pass)
                if best is None or p < best[1]:
                    best = (tenant, p)
            if best is None:
                break
            tenant, p = best
            q = self._queues[tenant]
            tck = q.popleft()
            self._queued -= 1
            w = self.weights.get(tenant, 1.0)
            self._passes[tenant] = max(p, self._global_pass) + 1.0 / w
            self._global_pass = max(self._global_pass, p)
            self._running += 1
            tck.granted = True
            metrics.TENANT_QUEUE_DEPTH.set(
                len(q), tenant=self._gauge_tenant(tenant))
            self._drop_if_empty_locked(tenant)
        self._cond.notify_all()


class _AuditSlot:
    __slots__ = ("sched", "_done")

    def __init__(self, sched: AdmissionScheduler):
        self.sched = sched
        self._done = False

    def release(self):
        if not self._done:
            self._done = True
            self.sched._audit_release()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class _HeavySlot:
    def __init__(self, sched: AdmissionScheduler, qos: QoS | None):
        self.sched = sched
        self.qos = qos

    def __enter__(self):
        self.sched._acquire(self.qos)
        return self

    def __exit__(self, *exc):
        self.sched._release()
        return False
