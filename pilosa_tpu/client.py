"""User-facing client library — ORM-style schema + PQL builders.

Reference: client/ (client.go:45 Client, :281 shard-aware import) and
its ORM layer: ``Schema`` -> ``Index`` -> ``Field`` builders whose
methods compose PQL call objects (client/orm.go), executed via
``Client.query``.  HTTP JSON against the server's public routes.

    c = Client("127.0.0.1:10101")
    schema = c.schema()
    idx = schema.index("events")
    f = idx.field("user", keys=True)
    c.sync_schema(schema)
    c.query(idx.count(f.row("alice") & f.row("bob")))
"""

from __future__ import annotations

from pilosa_tpu.cluster.client import InternalClient, RemoteError  # noqa: F401
from pilosa_tpu.shardwidth import SHARD_WIDTH


class PQL:
    """A composable PQL call expression (client/orm.go PQLQuery)."""

    def __init__(self, index: "IndexDef", text: str):
        self.index = index
        self.text = text

    # set algebra composes like the ORM's Union/Intersect/... builders
    def __and__(self, other):
        return PQL(self.index, f"Intersect({self.text}, {other.text})")

    def __or__(self, other):
        return PQL(self.index, f"Union({self.text}, {other.text})")

    def __xor__(self, other):
        return PQL(self.index, f"Xor({self.text}, {other.text})")

    def __sub__(self, other):
        return PQL(self.index, f"Difference({self.text}, {other.text})")

    def __invert__(self):
        return PQL(self.index, f"Not({self.text})")

    def __repr__(self):
        return f"PQL({self.text!r})"


def _lit(v) -> str:
    if isinstance(v, str):
        return '"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"'
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


class FieldDef:
    def __init__(self, index: "IndexDef", name: str, **options):
        self.index = index
        self.name = name
        self.options = options or {"type": "set"}

    # -- row-level builders (client/orm.go PQLRowQuery) ---------------

    def row(self, value) -> PQL:
        return PQL(self.index, f"Row({self.name}={_lit(value)})")

    def set(self, col, value) -> PQL:
        return PQL(self.index,
                   f"Set({_lit(col)}, {self.name}={_lit(value)})")

    def clear(self, col, value) -> PQL:
        return PQL(self.index,
                   f"Clear({_lit(col)}, {self.name}={_lit(value)})")

    def topn(self, n: int, filter: PQL | None = None) -> PQL:
        inner = f", {filter.text}" if filter else ""
        return PQL(self.index, f"TopN({self.name}{inner}, n={n})")

    def rows(self) -> PQL:
        return PQL(self.index, f"Rows({self.name})")

    def sum(self, filter: PQL | None = None) -> PQL:
        inner = f"{filter.text}, " if filter else ""
        return PQL(self.index, f"Sum({inner}field={self.name})")

    def min(self, filter: PQL | None = None) -> PQL:
        inner = f"{filter.text}, " if filter else ""
        return PQL(self.index, f"Min({inner}field={self.name})")

    def max(self, filter: PQL | None = None) -> PQL:
        inner = f"{filter.text}, " if filter else ""
        return PQL(self.index, f"Max({inner}field={self.name})")

    def gt(self, v) -> PQL:
        return PQL(self.index, f"Row({self.name} > {_lit(v)})")

    def lt(self, v) -> PQL:
        return PQL(self.index, f"Row({self.name} < {_lit(v)})")

    def between(self, lo, hi) -> PQL:
        return PQL(self.index,
                   f"Row({self.name} >< [{_lit(lo)},{_lit(hi)}])")


class IndexDef:
    def __init__(self, schema: "Schema", name: str, keys: bool = False):
        self.schema = schema
        self.name = name
        self.keys = keys
        self.fields: dict[str, FieldDef] = {}

    def field(self, name: str, **options) -> FieldDef:
        f = self.fields.get(name)
        if f is None:
            f = self.fields[name] = FieldDef(self, name, **options)
        return f

    def count(self, row: PQL) -> PQL:
        return PQL(self, f"Count({row.text})")

    def group_by(self, *rows_calls: PQL) -> PQL:
        inner = ", ".join(r.text for r in rows_calls)
        return PQL(self, f"GroupBy({inner})")

    def batch_query(self, *calls: PQL) -> PQL:
        return PQL(self, "".join(c.text for c in calls))


class Schema:
    def __init__(self):
        self.indexes: dict[str, IndexDef] = {}

    def index(self, name: str, keys: bool = False) -> IndexDef:
        ix = self.indexes.get(name)
        if ix is None:
            ix = self.indexes[name] = IndexDef(self, name, keys=keys)
        return ix

    def to_dict(self) -> dict:
        return {"indexes": [
            {"name": ix.name, "keys": ix.keys,
             "fields": [{"name": f.name, "options": f.options}
                        for f in ix.fields.values()]}
            for ix in self.indexes.values()]}


class Client:
    """HTTP client (client.go:45)."""

    def __init__(self, host: str = "127.0.0.1:10101",
                 token: str | None = None, timeout: float = 60.0):
        self.host = host
        headers = {"Authorization": f"Bearer {token}"} if token else {}
        self._http = InternalClient(timeout=timeout, headers=headers)

    # -- schema --------------------------------------------------------

    def schema(self) -> Schema:
        """Server schema as builder objects (Client.Schema)."""
        got = self._http._request(self.host, "GET", "/schema")
        s = Schema()
        for ix in got.get("indexes", []):
            opts = ix.get("options", {})
            idef = s.index(ix["name"], keys=opts.get("keys", False))
            for f in ix.get("fields", []):
                idef.field(f["name"], **f.get("options", {}))
        return s

    def sync_schema(self, schema: Schema):
        """Create everything the schema declares (Client.SyncSchema)."""
        self._http._request(self.host, "POST", "/schema",
                            schema.to_dict())

    # -- queries -------------------------------------------------------

    def query(self, q: PQL) -> list:
        resp = self._http._request(
            self.host, "POST", f"/index/{q.index.name}/query",
            {"query": q.text})
        return resp["results"]

    def sql(self, statement: str) -> dict:
        return self._http._request(self.host, "POST", "/sql",
                                   {"sql": statement})

    # -- shard-aware import (client.go:281) ----------------------------

    def import_bits(self, index: str, field: str, bits,
                    batch_size: int = 1 << 16) -> int:
        """bits: iterable of (row, col); batched per request, grouped
        by shard server-side."""
        n = 0
        rows, cols = [], []
        for r, c in bits:
            rows.append(int(r))
            cols.append(int(c))
            if len(rows) >= batch_size:
                n += self._http.import_bits(self.host, index, field,
                                            rows, cols)
                rows, cols = [], []
        if rows:
            n += self._http.import_bits(self.host, index, field,
                                        rows, cols)
        return n

    def import_values(self, index: str, field: str, pairs,
                      batch_size: int = 1 << 16) -> int:
        n = 0
        cols, vals = [], []
        for c, v in pairs:
            cols.append(int(c))
            vals.append(v)
            if len(cols) >= batch_size:
                n += self._http.import_values(self.host, index, field,
                                              cols, vals)
                cols, vals = [], []
        if cols:
            n += self._http.import_values(self.host, index, field,
                                          cols, vals)
        return n

    def import_roaring(self, index: str, field: str, shard: int,
                       rows: dict, clear: bool = False) -> int:
        """rows: {row_id: roaring bytes or base64 str}."""
        import base64
        enc = {str(r): (base64.b64encode(b).decode()
                        if isinstance(b, (bytes, bytearray)) else b)
               for r, b in rows.items()}
        resp = self._http._request(
            self.host, "POST",
            f"/index/{index}/field/{field}/import-roaring/{shard}",
            {"rows": enc, "clear": clear})
        return resp["imported"]

    def status(self) -> dict:
        return self._http._request(self.host, "GET", "/status")
