"""fbsql — interactive SQL shell (cli/cli.go, cmd/fbsql).

Reads statements (``;``-terminated, readline history when on a tty),
POSTs them to a node's /sql endpoint, and renders aligned tables.
Backslash meta-commands follow the reference's psql-style set
(cli/cli.go commands):

    \\d             list tables            (SHOW TABLES)
    \\d <table>     describe a table       (SHOW COLUMNS)
    \\timing        toggle timing output
    \\profile       toggle per-query span trees (Profile=true)
    \\pql <index> <query>   run raw PQL against an index
    \\q             quit
"""

from __future__ import annotations

import sys
import time


def _render(schema, rows, out=sys.stdout):
    if not schema:
        print("OK", file=out)
        return
    names = [f["name"] for f in schema]
    srows = [[("" if v is None else str(v)) for v in row] for row in rows]
    widths = [max(len(n), *(len(r[i]) for r in srows)) if srows else len(n)
              for i, n in enumerate(names)]
    line = " | ".join(n.ljust(w) for n, w in zip(names, widths))
    print(line, file=out)
    print("-+-".join("-" * w for w in widths), file=out)
    for r in srows:
        print(" | ".join(v.ljust(w) for v, w in zip(r, widths)), file=out)
    print(f"({len(srows)} row{'s' if len(srows) != 1 else ''})", file=out)


def _render_spans(spans, out, depth=0):
    """Profile span tree, indented — the CLI face of the flight
    recorder's device-phase attribution."""
    for s in spans:
        tags = s.get("tags", {})
        tag_s = ("  " + " ".join(f"{k}={v}" for k, v in tags.items())
                 if tags else "")
        print(f"{'  ' * depth}{s['name']}: "
              f"{s['duration_us'] / 1e3:.3f} ms{tag_s}", file=out)
        _render_spans(s.get("children", []), out, depth + 1)


class Shell:
    def __init__(self, host: str, client):
        self.host = host
        self.client = client
        self.timing = False
        self.profile = False

    def execute(self, stmt: str, out=sys.stdout) -> bool:
        """Run one statement; returns False to exit the loop."""
        from pilosa_tpu.cluster.client import RemoteError
        stmt = stmt.strip().rstrip(";").strip()
        if not stmt:
            return True
        if stmt.startswith("\\"):
            return self._meta(stmt, out)
        t0 = time.perf_counter()
        try:
            resp = self.client._request(self.host, "POST", "/sql",
                                        {"sql": stmt})
        except RemoteError as e:
            print(f"ERROR: {e}", file=out)
            return True
        _render(resp.get("schema", {}).get("fields", []),
                resp.get("data", []), out)
        if self.timing:
            print(f"Time: {(time.perf_counter() - t0) * 1e3:.1f} ms",
                  file=out)
        return True

    def _meta(self, cmd: str, out) -> bool:
        parts = cmd.split()
        if parts[0] == "\\q":
            return False
        if parts[0] == "\\timing":
            self.timing = not self.timing
            print(f"Timing is {'on' if self.timing else 'off'}.",
                  file=out)
            return True
        if parts[0] == "\\profile":
            self.profile = not self.profile
            print(f"Profiling is {'on' if self.profile else 'off'}.",
                  file=out)
            return True
        if parts[0] == "\\pql":
            if len(parts) < 3:
                print("usage: \\pql <index> <query>", file=out)
                return True
            return self._pql(parts[1], " ".join(parts[2:]), out)
        if parts[0] == "\\d":
            if len(parts) == 1:
                return self.execute("SHOW TABLES", out)
            return self.execute(f"SHOW COLUMNS FROM {parts[1]}", out)
        print(f"unknown command {parts[0]!r}", file=out)
        return True

    def _pql(self, index: str, query: str, out) -> bool:
        """Raw PQL with the shell's profile toggle: Profile=true
        responses include the device-phase span tree."""
        import json as _json

        from pilosa_tpu.cluster.client import RemoteError
        path = f"/index/{index}/query"
        if self.profile:
            path += "?profile=true"
        try:
            resp = self.client._request(self.host, "POST", path,
                                        {"query": query})
        except RemoteError as e:
            print(f"ERROR: {e}", file=out)
            return True
        for r in resp.get("results", []):
            print(_json.dumps(r), file=out)
        if self.profile and resp.get("profile"):
            print("-- profile --", file=out)
            _render_spans(resp["profile"], out)
        return True

    def repl(self):
        try:
            import readline  # noqa: F401 — history + line editing
        except ImportError:
            pass
        buf = ""
        while True:
            try:
                prompt = "fbsql> " if not buf else "  ...> "
                line = input(prompt)
            except (EOFError, KeyboardInterrupt):
                print()
                return 0
            buf += line
            if line.strip().startswith("\\") or buf.rstrip().endswith(";"):
                if not self.execute(buf):
                    return 0
                buf = ""
            else:
                buf += " "


def run_shell(args) -> int:
    from pilosa_tpu.cluster.client import InternalClient
    headers = {}
    if getattr(args, "token", None):
        headers["Authorization"] = f"Bearer {args.token}"
    sh = Shell(args.host, InternalClient(headers=headers))
    if args.command:
        sh.execute(args.command)
        return 0
    return sh.repl()
