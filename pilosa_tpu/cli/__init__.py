"""Operator CLI (cmd/root.go + ctl/ command set, cli/ fbsql shell)."""

from pilosa_tpu.cli.main import main  # noqa: F401
