"""``pilosa-tpu`` command — the operator entry point.

Command set mirrors the reference CLI (cmd/root.go:94-111 subcommand
registration; implementations in ctl/):

    server            run a node                       (ctl/server.go)
    backup            snapshot a live node             (ctl/backup.go:30)
    restore           upload a backup to a node        (ctl/restore.go)
    import            CSV import through the batcher   (ctl/import.go)
    export            dump a field as CSV              (ctl/export.go)
    generate-config   print default config             (cmd generate-config)
    keygen            mint an HS256 auth token         (qa/fakeidp analog)
    rbf               inspect RBF shard files          (ctl/rbf.go)
    sql               fbsql interactive shell          (cli/cli.go)
    dax               controller+queryer+workers       (dax/server/)
    version

argparse instead of cobra; flags keep the reference's names where they
exist (--host, --index, --field, --output-dir, ...).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _client(args):
    from pilosa_tpu.cluster.client import InternalClient
    headers = {}
    if getattr(args, "token", None):
        headers["Authorization"] = f"Bearer {args.token}"
    return InternalClient(timeout=getattr(args, "timeout", 60.0),
                          headers=headers)


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

def cmd_server(args) -> int:
    from pilosa_tpu import config as cfgmod
    from pilosa_tpu.models.holder import Holder
    from pilosa_tpu.obs.logger import StderrLogger
    from pilosa_tpu.server.http import Server

    # flags > env > config file > defaults (server/config.go layering)
    cfg = cfgmod.load(args.config, overrides={
        "data_dir": args.data_dir, "bind": args.bind,
        "port": args.port, "grpc_port": args.grpc_port,
        "auth_secret": args.auth_secret or None,
        "auth_policy": args.auth_policy or None,
        "long_query_time": args.long_query_time,
    })
    cfg.apply_kernel_setting()
    cfg.apply_stack_settings()
    cfg.apply_flight_settings()
    cfg.apply_memory_settings()
    cfg.apply_placement_settings()
    cfg.apply_fault_settings()
    cfg.apply_roofline_settings()
    cfg.apply_slo_settings()
    cfg.apply_watchdog_settings()
    cfg.apply_dax_settings()
    holder = Holder(path=cfg.data_dir) if cfg.data_dir else Holder()
    holder.load_schema()
    auth = None
    if cfg.auth_secret:
        from pilosa_tpu.server.authn import Authenticator
        from pilosa_tpu.server.authz import Authorizer
        authz = (Authorizer.from_yaml(cfg.auth_policy)
                 if cfg.auth_policy else None)
        auth = (Authenticator(cfg.auth_secret.encode()), authz)
    logger = StderrLogger()
    srv = Server(holder=holder, bind=cfg.bind, port=cfg.port,
                 logger=logger, auth=auth, config=cfg)
    srv.api.long_query_time = float(cfg.long_query_time)
    srv.api.logger = logger
    grpc_srv = None
    if cfg.grpc_port >= 0:
        from pilosa_tpu.server.grpc import GRPCServer
        grpc_srv = GRPCServer(srv.api,
                              bind=f"{cfg.bind}:{cfg.grpc_port}",
                              auth=auth).start()
        logger.info("grpc listening on :%d", grpc_srv.port)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        if grpc_srv:
            grpc_srv.stop()
        holder.sync()
        srv.close()
    return 0


# ---------------------------------------------------------------------------
# backup / restore (ctl/backup.go:30,87; ctl/restore.go)
# ---------------------------------------------------------------------------

def _safe_join(base: str, rel: str) -> str:
    """Join a manifest-supplied relative path, refusing traversal out
    of base (zip-slip guard — the server must not steer writes)."""
    if os.path.isabs(rel) or ".." in rel.replace("\\", "/").split("/"):
        raise ValueError(f"unsafe path in manifest: {rel!r}")
    return os.path.join(base, rel)


def cmd_backup(args) -> int:
    cli = _client(args)
    # hold a cluster-exclusive transaction while streaming, so no
    # writer mutates shards mid-backup (ctl/backup.go:87)
    tx = cli._request(args.host, "POST", "/transaction",
                      {"exclusive": True, "timeout": 300.0})
    tid = tx["id"]
    try:
        deadline = time.time() + 30
        while not tx.get("active"):
            if time.time() > deadline:
                print("timed out waiting for exclusive transaction",
                      file=sys.stderr)
                return 1
            time.sleep(0.1)
            tx = cli._request(args.host, "GET", f"/transaction/{tid}")
        man = cli._request(args.host, "GET", "/internal/backup/manifest")
        os.makedirs(args.output_dir, exist_ok=True)
        with open(os.path.join(args.output_dir, "MANIFEST.json"),
                  "w") as f:
            json.dump(man, f, indent=1)
        for rel in man["files"]:
            data = cli.get_raw(args.host,
                               f"/internal/backup/file?path={rel}")
            dst = _safe_join(args.output_dir, rel)
            os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
            with open(dst, "wb") as f:
                f.write(data)
            if not args.quiet:
                print(f"backed up {rel} ({len(data)} bytes)")
    finally:
        cli._request(args.host, "POST", f"/transaction/{tid}/finish")
    print(f"backup complete: {len(man['files'])} files "
          f"-> {args.output_dir}")
    return 0


def cmd_restore(args) -> int:
    cli = _client(args)
    man_path = os.path.join(args.source_dir, "MANIFEST.json")
    with open(man_path) as f:
        man = json.load(f)
    for rel in man["files"]:
        with open(_safe_join(args.source_dir, rel), "rb") as f:
            data = f.read()
        cli.post_raw(args.host,
                     f"/internal/restore/file?path={rel}", data)
        if not args.quiet:
            print(f"restored {rel} ({len(data)} bytes)")
    got = cli._request(args.host, "POST", "/internal/restore/complete")
    print(f"restore complete: indexes {got['indexes']}")
    return 0


# ---------------------------------------------------------------------------
# import / export
# ---------------------------------------------------------------------------

def cmd_import(args) -> int:
    from pilosa_tpu.ingest.importer import HTTPImporter
    from pilosa_tpu.ingest.pipeline import Pipeline
    from pilosa_tpu.ingest.sources import CSVSource

    src = CSVSource(args.file)
    importer = HTTPImporter(args.host, client=_client(args))
    pipe = Pipeline(src, importer, args.index,
                    batch_size=args.batch_size,
                    concurrency=args.concurrency,
                    index_keys=args.keys or None)
    pipe.apply_schema()
    n = pipe.run()
    print(f"imported {n} records into {args.index}")
    return 0


def cmd_export(args) -> int:
    """Dump field bits as row,col CSV (ctl/export.go semantics)."""
    cli = _client(args)
    resp = cli._request(args.host, "POST", f"/index/{args.index}/query",
                        {"query": f"Rows({args.field})"})
    rows = resp["results"][0]
    out = open(args.output, "w") if args.output else sys.stdout
    try:
        for row in rows:
            rid = row if not isinstance(row, dict) else row.get("id", row)
            r = cli._request(
                args.host, "POST", f"/index/{args.index}/query",
                {"query": f"Row({args.field}={rid})"})
            for col in r["results"][0]["columns"]:
                out.write(f"{rid},{col}\n")
    finally:
        if args.output:
            out.close()
    return 0


# ---------------------------------------------------------------------------
# misc
# ---------------------------------------------------------------------------

DEFAULT_CONFIG = """\
# pilosa-tpu configuration (TOML).  Flags override file values;
# environment variables PILOSA_TPU_* override both
# (server/config.go analog).

data-dir = "~/.pilosa-tpu"
bind = "127.0.0.1"
port = 10101
grpc-port = 20101

[cluster]
name = "cluster0"
replicas = 1
# hedged replica reads: a fan-out RPC outlasting this delay fires a
# second attempt at the next live replica, first response wins.
# < 0 disables, 0 auto-derives from flight-recorder attempt records,
# > 0 fixes the delay (milliseconds)
hedge-ms = 0.0
# default end-to-end query deadline (seconds, 0 = none); every RPC
# attempt, hedge, and retry budgets from its remainder
deadline-s = 0.0

[faults]
# fault-injection registry (obs/faults.py): arm named fault points at
# startup for chaos drills — "point[@match][,times=N][,delay=MS]"
# entries joined by ";", e.g. "rpc-delay@10101,delay=200,times=0"
spec = ""

[auth]
# enable by setting a shared HS256 secret
secret = ""
policy = ""      # YAML group->permission file (authz)

[tpu]
# pallas kernel dispatch: "auto" | "on" | "off"
kernels = "auto"

[flight]
# query flight recorder: per-query phase records at /debug/queries
# and /debug/trace (Perfetto).  recorder=false disables record
# keeping; ring bounds how many records are kept.
recorder = true
ring = 512

[memory]
# HBM residency manager: one process-wide device-byte budget shared
# by the tile-stack / jit / result caches.  budget-bytes 0 = auto
# (device memory minus headroom-frac; 8 GiB fallback without device
# stats).  paged = page-granular stack eviction/patching; prefetch
# warms predicted pages from the flight recorder; oom-retry and
# host-fallback are the RESOURCE_EXHAUSTED backstop rungs.
budget-bytes = 0
headroom-frac = 0.1
page-bytes = 4194304
paged = true
prefetch = true
prefetch-interval-s = 0.5
oom-retry = true
host-fallback = true

[incidents]
# incident forensics: anomaly-triggered black-box bundles (SLO burn,
# perf regression, watchdog stall, OOM trip, batch-leader exception,
# ingest crash) persisted under dir (default <data-dir>/incidents),
# rate-limited per trigger and size-bounded per bundle; profile*
# drive the always-on continuous profiler attached to every bundle
enabled = true
dir = ""
min-interval-s = 60.0
max-bundles = 32
max-bundle-bytes = 1048576
slo-burn-threshold = 8.0
profile = true
profile-hz = 7.0
profile-window-s = 10.0
profile-windows = 6
log-ring = 512

[watchdog]
# stall watchdogs: progress-stamped deadlines on the long-running
# loops (serving batcher, ingest window, rebalance controller,
# maintenance ticker, heartbeats); a loop wedged past deadline-s
# fires pilosa_watchdog_stalls_total{loop} + an incident bundle
enabled = true
interval-s = 1.0
deadline-s = 10.0

[audit]
# continuous correctness auditing (obs/audit.py): shadow-execution
# sampling on served reads (re-executed on the independent host
# oracle arm, compared bit-exact), plus maintenance-ticker scrubbers
# for the result cache, standing queries, and replica divergence.
# PILOSA_TPU_AUDIT=0 is the runtime kill-switch; sample-rate is the
# per-serve sampling probability, route-rates overrides it per route
# ("cached=0.05,fused=0.01").  Mismatches fire a rate-limited
# audit-mismatch incident bundle and land in /debug/audit.
enabled = true
sample-rate = 0.01
route-rates = ""
queue-max = 64
concurrency = 1
scrub-cache-n = 4
scrub-standing-n = 2
scrub-replica-n = 2
quarantine = 32

[blob]
# blob shard store (storage/blob.py) — the disaggregated tier's one
# durable home.  backend "" disables the tier; "dir" keeps objects
# under root (default <data-dir>/blob); "mem" is the in-process
# fault-drill arm.  Env twins: PILOSA_TPU_BLOB_BACKEND / _BLOB_ROOT.
backend = ""
root = ""

[dax]
# disaggregated compute tier (dax/worker.py + dax/controller.py).
# blob is the tier switch (PILOSA_TPU_DAX_BLOB=0 kills it at
# runtime); lazy-hydrate materializes shards on first touch;
# worker-budget-bytes bounds each stateless worker's resident set
# through its private HBM ledger (0 = unbounded).  The autoscaler
# scales out past scale-out-burn (SLO burn rate) or pressure-high
# (ledger fill fraction), scales in under scale-in-burn, admits from
# standby warm spares, and never leaves [min-workers, max-workers].
blob = true
lazy-hydrate = true
worker-budget-bytes = 0
prefetch = 2
scale-out-burn = 2.0
scale-in-burn = 0.5
pressure-high = 0.9
min-workers = 1
max-workers = 8
standby = 1
reconcile-interval-s = 5.0
cooldown-s = 30.0
chase-lag = 8
chase-rounds = 12
"""


def cmd_generate_config(args) -> int:
    print(DEFAULT_CONFIG, end="")
    return 0


def cmd_keygen(args) -> int:
    from pilosa_tpu.server.authn import encode_jwt
    claims = {"sub": args.subject,
              "groups": args.groups.split(",") if args.groups else [],
              "exp": time.time() + args.ttl}
    print(encode_jwt(claims, args.secret.encode()))
    return 0


def cmd_rbf(args) -> int:
    """RBF shard file inspection (ctl/rbf.go check/pages)."""
    from pilosa_tpu.storage import rbf
    db = rbf.DB(args.file)
    try:
        with db.begin() as tx:
            names = tx.list_bitmaps()
            print(f"file: {args.file}")
            print(f"bitmaps: {len(names)}")
            for name in names:
                n = sum(1 for _ in tx.items(name))
                print(f"  {name}: {n} containers")
    finally:
        db.close()
    return 0


def cmd_dax(args) -> int:
    """Host the DAX services in one process — controller + queryer +
    N compute workers over a shared storage dir (the reference's
    `featurebase dax` single binary, dax/server/), with the
    queryer's SQL surface on HTTP."""
    import time as _time

    from pilosa_tpu.dax.server import DAXService
    from pilosa_tpu.obs.logger import StderrLogger

    logger = StderrLogger()
    svc = DAXService(args.data_dir, n_workers=args.workers)
    front = svc.serve_queryer(bind=args.bind, port=args.port)
    logger.info("dax queryer listening on %s:%d (%d workers, "
                "storage %s)", args.bind, front.port, args.workers,
                args.data_dir)
    try:
        if svc.blob is not None:
            # disaggregated shape: warm spares + the autoscaler's
            # reconcile loop ([dax] standby / thresholds)
            from pilosa_tpu.dax import settings as dax_settings
            for i in range(dax_settings.standby()):
                svc.add_standby(f"standby{i}")
            svc.start_autoscaler()
            logger.info("dax blob tier active (%d standby)",
                        dax_settings.standby())
        svc.controller.start_poller()
        while True:
            _time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        front.close()
        svc.close()
    return 0


def cmd_version(args) -> int:
    from pilosa_tpu import __version__
    print(__version__)
    return 0


def cmd_sql(args) -> int:
    from pilosa_tpu.cli.fbsql import run_shell
    return run_shell(args)


# ---------------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pilosa-tpu",
        description="TPU-native bitmap index — operator CLI")
    sub = p.add_subparsers(dest="cmd", required=True)

    def host_flags(sp):
        sp.add_argument("--host", default="127.0.0.1:10101",
                        help="host:port of the node")
        sp.add_argument("--token", default=os.environ.get(
            "PILOSA_TPU_TOKEN"), help="bearer token (auth-enabled nodes)")
        sp.add_argument("--timeout", type=float, default=60.0)

    sp = sub.add_parser("server", help="run a node")
    sp.add_argument("--config", "-c", default=None,
                    help="TOML config file (generate-config prints one)")
    sp.add_argument("--data-dir", default=None)
    sp.add_argument("--bind", default=None)
    sp.add_argument("--port", type=int, default=None)
    sp.add_argument("--grpc-port", type=int, default=None,
                    help="-1 disables gRPC")
    sp.add_argument("--auth-secret", default="")
    sp.add_argument("--auth-policy", default="")
    sp.add_argument("--long-query-time", type=float, default=None,
                    help="log queries slower than this many seconds "
                         "(0 disables; server.go:201 analog)")
    sp.set_defaults(fn=cmd_server)

    sp = sub.add_parser(
        "dax", help="run the DAX services (controller + queryer + "
                    "compute workers) in one process")
    sp.add_argument("--data-dir", required=True,
                    help="shared storage dir (write-log, snapshots, "
                         "controller schemar)")
    sp.add_argument("--workers", type=int, default=2)
    sp.add_argument("--bind", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=0,
                    help="queryer HTTP port (0 = ephemeral)")
    sp.set_defaults(fn=cmd_dax)

    sp = sub.add_parser("backup", help="back up a live node")
    host_flags(sp)
    sp.add_argument("--output-dir", required=True)
    sp.add_argument("--quiet", action="store_true")
    sp.set_defaults(fn=cmd_backup)

    sp = sub.add_parser("restore", help="restore a backup to a node")
    host_flags(sp)
    sp.add_argument("--source-dir", required=True)
    sp.add_argument("--quiet", action="store_true")
    sp.set_defaults(fn=cmd_restore)

    sp = sub.add_parser("import", help="import a CSV file")
    host_flags(sp)
    sp.add_argument("--index", "-i", required=True)
    sp.add_argument("--batch-size", type=int, default=65536)
    sp.add_argument("--concurrency", type=int, default=1)
    sp.add_argument("--keys", action="store_true",
                    help="index uses string column keys")
    sp.add_argument("file")
    sp.set_defaults(fn=cmd_import)

    sp = sub.add_parser("export", help="export a field as CSV")
    host_flags(sp)
    sp.add_argument("--index", "-i", required=True)
    sp.add_argument("--field", "-f", required=True)
    sp.add_argument("--output", "-o", default=None)
    sp.set_defaults(fn=cmd_export)

    sp = sub.add_parser("generate-config",
                        help="print the default config file")
    sp.set_defaults(fn=cmd_generate_config)

    sp = sub.add_parser("keygen", help="mint an HS256 bearer token")
    sp.add_argument("--secret", required=True)
    sp.add_argument("--subject", default="admin")
    sp.add_argument("--groups", default="")
    sp.add_argument("--ttl", type=float, default=3600.0)
    sp.set_defaults(fn=cmd_keygen)

    sp = sub.add_parser("rbf", help="inspect an RBF shard file")
    sp.add_argument("file")
    sp.set_defaults(fn=cmd_rbf)

    sp = sub.add_parser("sql", help="interactive SQL shell (fbsql)")
    host_flags(sp)
    sp.add_argument("-c", "--command", default=None,
                    help="run one statement and exit")
    sp.set_defaults(fn=cmd_sql)

    sp = sub.add_parser("version")
    sp.set_defaults(fn=cmd_version)
    return p


def main(argv=None) -> int:
    # honor an explicit JAX_PLATFORMS before any backend init: the
    # axon sitecustomize force-selects the TPU platform via
    # jax.config, overriding the env var, and a down tunnel then
    # hangs the first jit for minutes (bench.py's probe does the
    # same override)
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except Exception as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
