"""Container-adaptive page encodings — Roaring on the paged stack.

The reference's bottom layer keeps three container types per 64Ki-
column chunk (roaring: array / bitmap / run — PAPERS.md arxiv
1402.6407, 1603.06549) because dense bitmaps waste memory and
bandwidth on sparse data.  Our device unit is the stack-cache PAGE
(memory/pages.py): a fixed lane-block of ``(page_lanes, W)`` uint32
words.  This module picks, per page block, between

- **dense**  — the page as-is (today's format; the only arm with a
  word-scatter patch path),
- **packed** — the sorted coordinates of the set bits, one uint32 per
  bit (coordinate = flat bit offset inside the page block), padded to
  a pow2 length with an out-of-range sentinel so the jitted expand /
  count kernels compile O(log) distinct shapes,
- **run**    — word-granular runs of all-ones words (sorted
  ``(start, len)`` int32 pairs over the flat word space) plus the
  residual set bits outside the runs as a packed coordinate tail.

The page keeps its identity: it is still the HBM-ledger/eviction/
patch/prefetch unit, its logical shape and lane range are unchanged,
and ``expand()`` reproduces the dense block bit-exactly (the decode-
to-dense boundary used whenever an op has no packed arm).  Only its
resident *byte size* changes — the TileStackCache accounts encoded
pages at their true size, which is exactly the working-set
multiplier the sparse format exists to buy.

Decision rule (per page block, from host words — no stats required):
the cheapest sparse candidate must undercut the dense page by
``1/dense_frac`` (default: sparse must be <= 0.5x dense bytes) to
enter, and once a page is sparse it re-encodes dense only past a
1.5x-looser leave threshold (hysteresis — drift near the boundary
must not re-encode every patch).  The stats catalog's per-
(index, field) density (obs/stats.py) short-circuits the analysis for
clearly-dense fields; pages of unknown fields always analyze.

Kill switch: ``PILOSA_TPU_SPARSE_FORMAT=0`` (config twin
``[stacked] sparse-format``) restores the all-dense format — the
bench A/B arm.
"""

from __future__ import annotations

import os

import numpy as np

_FULL = np.uint32(0xFFFFFFFF)

# sparse entry threshold: encoded bytes must be <= this fraction of
# the dense page to leave the dense format ([stacked] sparse-dense-
# frac; hysteresis widens it by _LEAVE_RATIO for already-sparse pages)
_DENSE_FRAC = 0.5
_LEAVE_RATIO = 1.5
# stats-catalog density band where analysis is pointless: packed
# can't pay above ~1/64 density and runs only pay near-saturation, so
# a field the catalog pins inside this band skips the per-page scan
_HINT_DENSE_LO = 0.2
_HINT_DENSE_HI = 0.9
# floor for pow2-padded device array lengths: bounds the distinct
# shape count (executable-cache churn) for near-empty pages
_PAD_FLOOR = 8


def enabled() -> bool:
    return os.environ.get("PILOSA_TPU_SPARSE_FORMAT", "1") != "0"


def configure(dense_frac: float | None = None):
    """Apply the [stacked] sparse-format knobs (config.py)."""
    global _DENSE_FRAC
    if dense_frac is not None and dense_frac > 0:
        _DENSE_FRAC = float(dense_frac)


def _pow2(n: int) -> int:
    n = max(int(n), _PAD_FLOOR)
    return 1 << (n - 1).bit_length()


def _positions(flat_words: np.ndarray) -> np.ndarray:
    """Sorted flat bit offsets of the set bits of a flat word array
    (LSB-first inside each word, matching ops/bitmap.py's layout)."""
    bits = np.unpackbits(flat_words.view(np.uint8), bitorder="little")
    return np.flatnonzero(bits).astype(np.uint32)


class EncodedPage:
    """One page's sparse payload.  ``coords`` / ``run_starts`` /
    ``run_lens`` start as host numpy arrays and move to the device
    via :meth:`to_device` (under the OOM backstop — a page that can't
    allocate stays host-resident, like a dense host-fallback block).
    ``lane_counts`` stays on the host: it is the per-lane popcount
    computed for free at encode time, serving the engine's packed
    Count/TopN arms without touching the device at all."""

    __slots__ = ("kind", "page_lanes", "width_words", "coords",
                 "run_starts", "run_lens", "lane_counts", "n_valid",
                 "n_runs", "host_positions", "_nbytes")

    def __init__(self, kind: str, page_lanes: int, width_words: int,
                 coords, run_starts, run_lens,
                 lane_counts: np.ndarray, n_valid: int, n_runs: int):
        self.kind = kind                    # "packed" | "run"
        self.page_lanes = int(page_lanes)
        self.width_words = int(width_words)
        self.coords = coords                # sentinel-padded uint32
        self.run_starts = run_starts        # sentinel-padded int32
        self.run_lens = run_lens            # zero-padded int32
        self.lane_counts = lane_counts      # host (page_lanes,) int64
        self.n_valid = int(n_valid)         # true coordinate count
        self.n_runs = int(n_runs)
        # packed pages keep their sorted positions host-resident (set
        # at encode time, like lane_counts): the engine's packed
        # set-op Count arm does sorted-coordinate algebra without ever
        # fetching coords back from the device
        self.host_positions: np.ndarray | None = None
        self._nbytes: int | None = None

    def positions(self) -> "np.ndarray | None":
        """Sorted unique flat set-bit offsets, host int64 (packed
        pages only); cached on first use."""
        if self.kind != "packed":
            return None
        if self.host_positions is None:
            self.host_positions = np.asarray(
                self.coords, dtype=np.int64)[:self.n_valid]
        return self.host_positions

    @property
    def nbytes(self) -> int:
        """True resident bytes (what the HBM ledger accounts).
        Payload sizes are fixed at construction (``to_device`` moves
        the arrays but never resizes), so the walk over (possibly
        device) array properties runs once."""
        if self._nbytes is None:
            n = int(self.coords.nbytes)
            if self.run_starts is not None:
                n += int(self.run_starts.nbytes)
                n += int(self.run_lens.nbytes)
            self._nbytes = n
        return self._nbytes

    @property
    def shape(self) -> tuple:
        return (self.page_lanes, self.width_words)

    def bit_count(self) -> int:
        return int(self.lane_counts.sum())

    def to_device(self, device=None) -> "EncodedPage":
        """Move the payload arrays onto the device (in place).
        ``device`` commits them to a specific mesh device (the page's
        placement owner) so ``expand()`` decodes to dense ON that
        device; None keeps the default-device behavior."""
        import jax
        import jax.numpy as jnp
        if device is not None:
            put = lambda a: jax.device_put(np.asarray(a), device)  # noqa: E731
        else:
            put = jnp.asarray
        self.coords = put(self.coords)
        if self.run_starts is not None:
            self.run_starts = put(self.run_starts)
            self.run_lens = put(self.run_lens)
        return self

    def expand(self):
        """Dense (page_lanes, W) device block — bit-exact decode (the
        gather-expand at operand boundaries that need dense tiles)."""
        from pilosa_tpu.ops import bitmap as bm
        if self.kind == "packed":
            return bm.expand_coords(self.coords, self.page_lanes,
                                    self.width_words)
        return bm.expand_runs(self.run_starts, self.run_lens,
                              self.coords, self.page_lanes,
                              self.width_words)


def is_encoded(page) -> bool:
    return isinstance(page, EncodedPage)


def page_kind(page) -> str:
    return page.kind if isinstance(page, EncodedPage) else "dense"


def page_nbytes(page) -> int:
    """True byte size of any page payload (dense array or encoded)."""
    return int(page.nbytes)


def to_dense(page):
    """Decode-to-dense boundary: expand an encoded page, pass a dense
    one through untouched."""
    return page.expand() if isinstance(page, EncodedPage) else page


def encode_block(block: np.ndarray, prev_kind: str | None = None,
                 density_hint: float | None = None):
    """Pick an encoding for one host page block.  Returns an
    :class:`EncodedPage` (host arrays — caller commits to device) or
    None to keep the block dense.  ``prev_kind`` is the page's
    current encoding (hysteresis); ``density_hint`` the stats
    catalog's field density, used only to skip the scan for clearly-
    dense fields."""
    if not enabled():
        return None
    pl, w = block.shape
    total_bits = pl * w * 32
    if total_bits >= 1 << 32:
        return None  # coordinate space must fit uint32
    if (density_hint is not None
            and prev_kind in (None, "dense")
            and _HINT_DENSE_LO <= density_hint <= _HINT_DENSE_HI):
        return None
    dense_b = int(block.nbytes)
    lane_counts = np.bitwise_count(block).sum(axis=1, dtype=np.int64)
    nbits = int(lane_counts.sum())
    flat = np.ascontiguousarray(block, dtype=np.uint32).reshape(-1)
    full = flat == _FULL
    n_full = int(np.count_nonzero(full))
    n_resid = nbits - 32 * n_full
    edges = np.flatnonzero(np.diff(
        np.concatenate(([False], full, [False])).astype(np.int8)))
    n_runs = edges.size // 2
    packed_b = 4 * _pow2(nbits)
    run_b = 8 * _pow2(n_runs) + 4 * _pow2(n_resid)
    kind, best_b = (("packed", packed_b) if packed_b <= run_b
                    else ("run", run_b))
    limit = _DENSE_FRAC if prev_kind in (None, "dense") else min(
        _DENSE_FRAC * _LEAVE_RATIO, 0.95)
    if best_b > limit * dense_b:
        return None
    if kind == "packed":
        pos = _positions(flat)
        coords = np.full(_pow2(pos.size), total_bits, dtype=np.uint32)
        coords[:pos.size] = pos
        enc = EncodedPage("packed", pl, w, coords, None, None,
                          lane_counts, pos.size, 0)
        enc.host_positions = pos.astype(np.int64)
        return enc
    starts, ends = edges[0::2], edges[1::2]
    run_starts = np.full(_pow2(starts.size), pl * w, dtype=np.int32)
    run_lens = np.zeros(_pow2(starts.size), dtype=np.int32)
    run_starts[:starts.size] = starts
    run_lens[:starts.size] = ends - starts
    resid = flat.copy()
    resid[full] = 0
    pos = _positions(resid)
    coords = np.full(_pow2(pos.size), total_bits, dtype=np.uint32)
    coords[:pos.size] = pos
    return EncodedPage("run", pl, w, coords, run_starts, run_lens,
                       lane_counts, pos.size, int(starts.size))
