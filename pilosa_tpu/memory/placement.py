"""Shard -> device placement for the mesh-sharded serving plane.

The serving mesh distributes the fused ragged program (executor/
ragged.py) over N local devices: every (index, shard) gets a sticky
owner slot, every paged stack partitions its lanes by that owner
(memory/pages.py grows a device axis), and the compiled program walks
each device's resident page-table slice under one ``shard_map`` with
psum/scatter combines inside the program.  This module is the single
source of placement truth:

- **unit**: the (index, shard) pair.  Every stack kind a ragged group
  touches (row / plane / groupcode / rowchunk) maps lanes to shards,
  so per-shard stickiness colocates ALL of a shard's pages on one
  device — elementwise IR ops stay device-local and only reductions
  cross chips (the pilosa node-per-shard ownership model, folded into
  one process).
- **balance**: a new shard goes to the slot with the fewest live
  device bytes (the per-device ledger occupancy, memory/ledger.py),
  assignment-count as tiebreak — "balance encoded bytes" with the
  container-adaptive format (PR 16) charging true encoded sizes.
- **epoch**: any rebalance/pin change bumps ``epoch()``.  Stack cache
  keys and compiled-plan signatures carry ``(mesh_devices, epoch)``,
  so a device-count flip or rebalance can never false-hit a stale
  stack or executable; superseded entries age out through normal
  eviction (that aging IS the migration mechanism — pages rebuild on
  their new owner on next use).

Knobs: ``[cluster] mesh-devices`` (env twin ``PILOSA_TPU_MESH_DEVICES``)
sets the mesh width (0/1 = off); ``[cluster] placement-pin`` (env twin
``PILOSA_TPU_PLACEMENT_PIN``) force-places shards, syntax
``index/shard=dev`` or ``index/*=dev``, comma-separated.
"""

from __future__ import annotations

import os
import threading

import numpy as np

_lock = threading.RLock()
_configured: int = 0            # [cluster] mesh-devices (0 = off)
_pins: dict = {}                # (index, shard|"*") -> slot
_assign: dict = {}              # (index, shard) -> slot
_counts: dict = {}              # slot -> assignment count
_epoch: int = 0
_mesh_cache: dict = {}          # (n, device ids) -> jax Mesh


def configure(mesh_devices: int | None = None,
              pin: str | None = None):
    """Apply the [cluster] mesh knobs (config.py).  Changing either
    bumps the placement epoch (cached stacks/plans must not be
    reused under a different topology or pin set)."""
    global _configured, _pins
    with _lock:
        changed = False
        if mesh_devices is not None and int(mesh_devices) != _configured:
            _configured = int(mesh_devices)
            changed = True
        if pin is not None:
            pins = _parse_pins(pin)
            if pins != _pins:
                _pins = pins
                changed = True
        if changed:
            _rebalance_locked()


def _parse_pins(spec: str) -> dict:
    pins: dict = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        lhs, _, dev = part.partition("=")
        idx, _, shard = lhs.partition("/")
        try:
            slot = int(dev)
            key = (idx.strip(), "*" if shard.strip() == "*"
                   else int(shard))
        except ValueError:
            continue
        pins[key] = slot
    return pins


def mesh_devices() -> int:
    """Effective serving-mesh width: env twin > config, clamped to
    the local device count once a backend exists.  <= 1 means the
    mesh path is off (the exact single-device behavior)."""
    v = os.environ.get("PILOSA_TPU_MESH_DEVICES")
    if v is not None:
        try:
            n = int(v)
        except ValueError:
            n = 0
    else:
        n = _configured
    if n <= 1:
        return 1
    import jax
    return max(1, min(n, jax.local_device_count()))


def devices() -> list:
    """The mesh's device list (first ``mesh_devices()`` local
    devices, in enumeration order — slot i is always devices()[i])."""
    import jax
    return list(jax.devices()[:mesh_devices()])


def device_of(slot: int):
    import jax
    return jax.devices()[int(slot)]


def serving_mesh():
    """The cached 1-D ("dev",) Mesh the fused serving program is
    shard_map'ped over.  Distinct from StackedEngine.mesh (the legacy
    GSPMD placement arm): the serving mesh keeps paging ON — pages
    are placed per device, not replicated."""
    devs = devices()
    key = (len(devs), tuple(d.id for d in devs))
    with _lock:
        m = _mesh_cache.get(key)
        if m is None:
            from jax.sharding import Mesh
            m = Mesh(np.array(devs), ("dev",))
            _mesh_cache[key] = m
        return m


def epoch() -> int:
    return _epoch


def rebalance():
    """Forget every sticky assignment and bump the epoch.  New stack
    keys / plan signatures rebuild on freshly balanced owners; the
    superseded generation ages out via eviction (live migration =
    rebuild-on-new-owner + evict-old, epoch-fenced by the keys)."""
    with _lock:
        _rebalance_locked()


def _rebalance_locked():
    global _epoch
    _assign.clear()
    _counts.clear()
    _epoch += 1


def reset():
    """Test hook: drop assignments, pins and config; bump epoch."""
    global _configured, _pins
    with _lock:
        _configured = 0
        _pins = {}
        _rebalance_locked()


def _device_bytes() -> list[int]:
    from pilosa_tpu import memory
    try:
        return memory.ledger().device_bytes(mesh_devices())
    except Exception:
        return [0] * mesh_devices()


def place(index_name: str, shard: int) -> int:
    """Sticky owner slot for one (index, shard).  First placement
    balances live per-device ledger bytes (assignment count breaks
    ties); pins override."""
    n = mesh_devices()
    if n <= 1:
        return 0
    key = (str(index_name), int(shard))
    with _lock:
        slot = _assign.get(key)
        if slot is not None:
            return slot
        pin = _pins.get(key, _pins.get((key[0], "*")))
        if pin is not None and 0 <= int(pin) < n:
            slot = int(pin)
        else:
            occ = _device_bytes()
            slot = min(range(n), key=lambda d: (
                occ[d] if d < len(occ) else 0, _counts.get(d, 0), d))
        _assign[key] = slot
        _counts[slot] = _counts.get(slot, 0) + 1
    # keep the ledger's per-device split current (idempotent; outside
    # the placement lock — the ledger has its own)
    from pilosa_tpu import memory
    memory.ledger().set_devices(n)
    return slot


def owners(index_name: str, shards) -> np.ndarray:
    """Owner slot per shard (int32, len(shards)) — the group-level
    owner map every leaf of a ragged group shares."""
    return np.array([place(index_name, s) for s in shards],
                    dtype=np.int32)


def snapshot() -> dict:
    """Placement state for bench/debug surfaces."""
    with _lock:
        per = {d: 0 for d in range(mesh_devices())}
        for slot in _assign.values():
            per[slot] = per.get(slot, 0) + 1
        return {"mesh_devices": mesh_devices(), "epoch": _epoch,
                "assigned_shards": dict(per), "pins": len(_pins)}
