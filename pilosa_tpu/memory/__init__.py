"""HBM residency management — one budget for every device-byte owner.

The reference bounds residency per index with its rank cache
(cache.go:130); on a TPU the scarce resource is per-chip HBM shared by
EVERY cache in the process.  Before this package, three independent
byte-bounded LRUs (``TileStackCache``, the plan jit caches, the
serving ``ResultCache``) each enforced a private ``max_bytes`` and
could collectively over-commit the chip.  Now they register as
*clients* of one process-wide :class:`~pilosa_tpu.memory.ledger.Ledger`
(initialized from the real device memory minus a headroom, config
``[memory]`` fallback otherwise) and reserve/release device bytes
through it — pressure in one cache reclaims cold bytes in another via
the clients' reclaim callbacks.

Pieces:

- ``ledger.py``   — the budget ledger (accounting + cross-client reclaim)
- ``pages.py``    — paged device stacks: fixed-size lane-block pages
  assembled into a query operand by a jitted gather, so eviction and
  delta-patching operate per PAGE, not per whole stack (the Ragged
  Paged Attention trick applied to bitmap tiles)
- ``policy.py``   — cost-aware eviction scoring (rebuild-cost-per-byte
  x recency, not pure LRU) + the flight-recorder-fed prefetcher
- ``pressure.py`` — the OOM backstop: RESOURCE_EXHAUSTED triggers
  ledger-driven eviction and one bounded retry, then a degraded-mode
  host (CPU-backend) re-execution instead of a failed query

Knobs land through config.py ``[memory]`` (``apply_memory_settings``)
or the ``PILOSA_TPU_MEMORY_*`` environment variables read here.
"""

from __future__ import annotations

import os
import threading

from pilosa_tpu.memory.ledger import Ledger

_DEFAULT_PAGE_BYTES = 4 << 20

_lock = threading.Lock()
_global: Ledger | None = None
# module defaults; configure() overrides, env vars override both at
# read time (the same precedence every other knob in this repo uses)
_paged_default = True
_page_bytes_default = _DEFAULT_PAGE_BYTES


def ledger() -> Ledger:
    """The process-wide budget ledger (created on first use)."""
    global _global
    with _lock:
        if _global is None:
            _global = Ledger()
        return _global


def configure(budget_bytes: int | None = None,
              headroom_frac: float | None = None,
              page_bytes: int | None = None,
              paged: bool | None = None,
              oom_retry: bool | None = None,
              host_fallback: bool | None = None) -> Ledger:
    """Apply ``[memory]`` config knobs to the process singletons.
    ``budget_bytes=0`` means auto-detect from the device."""
    global _paged_default, _page_bytes_default
    led = ledger()
    if headroom_frac is not None:
        led.headroom_frac = float(headroom_frac)
    if budget_bytes is not None:
        led.set_budget(int(budget_bytes) if budget_bytes else None)
    if page_bytes is not None and int(page_bytes) > 0:
        _page_bytes_default = int(page_bytes)
    if paged is not None:
        _paged_default = bool(paged)
    if oom_retry is not None or host_fallback is not None:
        from pilosa_tpu.memory import pressure
        if oom_retry is not None:
            pressure.OOM_RETRY = bool(oom_retry)
        if host_fallback is not None:
            pressure.HOST_FALLBACK = bool(host_fallback)
    return led


def paged_enabled() -> bool:
    """Paged stack-cache entries on/off (the bench A/B switch —
    PILOSA_TPU_MEMORY_PAGED=0 restores whole-stack entries)."""
    v = os.environ.get("PILOSA_TPU_MEMORY_PAGED")
    if v is not None:
        return v != "0"
    return _paged_default


def page_bytes() -> int:
    """Fixed device-page size (bytes).  A page spans whole lanes of a
    stack's flattened leading axis — shard-group x row-block."""
    v = os.environ.get("PILOSA_TPU_MEMORY_PAGE_BYTES")
    if v:
        try:
            n = int(v)
            if n > 0:
                return n
        except ValueError:
            pass
    return _page_bytes_default
