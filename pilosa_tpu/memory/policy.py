"""Eviction scoring + the flight-recorder-fed prefetcher.

Eviction is cost-aware, not pure LRU: a page that is cheap to restack
(one fragment row re-read + one H2D page upload) should leave before
an expensive group-code page of equal age (its lanes OR many rows
per word).  Score = age / (rebuild-weight x frequency segment); the
HIGHEST score evicts first.  The frequency term is segmented (probation
vs protected, an SLRU in spirit): pages touched once are fair game,
pages with repeated hits get a bounded boost rather than an unbounded
counter that would pin formerly-hot garbage forever.

The prefetcher closes the loop with the flight recorder (obs/flight.py):
every non-hit stack access stamps its cache-key fingerprint + outcome
into the query's flight record, so "keys that keep getting rebuilt"
is a ring-buffer scan.  A background step warms those keys' missing
pages OFF the serving hot path — but only while the ledger has real
headroom (warming under pressure would evict the very pages queries
are using)."""

from __future__ import annotations

import math
import threading
import time

from pilosa_tpu.obs import metrics

# prefetch only while this fraction of the budget is free: warming is
# strictly speculative work and must never CAUSE eviction pressure
MIN_FREE_FRAC = 0.25
# outcomes that mark a key as "the cache keeps losing this" — the
# prefetch predictor's positive signal
_WARM_OUTCOMES = ("rebuild", "page_rebuild", "patch")


def evict_score(age_s: float, weight: float, hits: int) -> float:
    """Higher = evict sooner.  ``weight`` is rebuild cost per byte
    relative to a plain row stack; ``hits`` feeds the bounded
    frequency segment (log-damped, capped so one hot burst can't pin
    a page forever)."""
    freq = 1.0 + min(math.log1p(hits), 3.0)
    return max(age_s, 1e-9) / (max(weight, 1e-6) * freq)


def victim_order(candidates: list, now: float | None = None) -> list:
    """Sort (last_access, weight, hits, payload) tuples most-evictable
    first."""
    now = time.time() if now is None else now
    return sorted(
        candidates,
        key=lambda c: evict_score(now - c[0], c[1], c[2]),
        reverse=True)


class Prefetcher:
    """Warms predicted stack pages from flight-recorder history.

    ``step()`` is one synchronous pass (what tests drive);
    ``start()`` runs it on a daemon thread every ``interval_s``.  The
    cache side is ``TileStackCache.prewarm(fp)``, which replays the
    recorded build recipe for a key fingerprint iff the entry is
    missing pages — a no-op for fully-resident keys."""

    def __init__(self, cache, recorder=None, ledger=None,
                 interval_s: float = 0.5, max_warm: int = 4,
                 window: int = 256):
        from pilosa_tpu import memory
        from pilosa_tpu.obs import flight
        self.cache = cache
        self.recorder = flight.recorder if recorder is None else recorder
        self.ledger = memory.ledger() if ledger is None else ledger
        self.interval_s = float(interval_s)
        self.max_warm = int(max_warm)
        self.window = int(window)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def step(self) -> int:
        """One prediction + warm pass; returns keys warmed."""
        budget = self.ledger.budget()
        counts: dict[str, int] = {}
        for rec in self.recorder.recent(self.window):
            for fp, outcome in rec.get("stack_keys", ()):
                if outcome in _WARM_OUTCOMES:
                    counts[fp] = counts.get(fp, 0) + 1
        warmed = 0
        for fp, _n in sorted(counts.items(), key=lambda kv: -kv[1]):
            if warmed >= self.max_warm:
                break
            if self.ledger.free_bytes() < MIN_FREE_FRAC * budget:
                metrics.PREFETCH_TOTAL.inc(outcome="skipped_pressure")
                break
            try:
                hit = self.cache.prewarm(fp)
            except Exception:
                metrics.PREFETCH_TOTAL.inc(outcome="error")
                continue
            if hit:
                warmed += 1
                metrics.PREFETCH_TOTAL.inc(outcome="warmed")
            else:
                metrics.PREFETCH_TOTAL.inc(outcome="noop")
        return warmed

    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.step()
                except Exception:
                    pass  # speculative work must never kill the loop

        self._thread = threading.Thread(
            target=loop, name="pilosa-tpu-prefetch", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)
