"""OOM backstop — absorb RESOURCE_EXHAUSTED instead of failing queries.

XLA surfaces HBM exhaustion as an ``XlaRuntimeError`` whose message
leads with ``RESOURCE_EXHAUSTED`` (or ``Out of memory`` on some
backends).  Before this module that exception rode straight up to the
client as a failed query.  :func:`guarded` wraps every device dispatch
on the stacked/serving paths with the recovery ladder:

1. catch an OOM, run a ledger-driven pressure-relief sweep (shed half
   the accounted resident bytes across ALL clients + a gc pass so the
   dropped device buffers actually return to the allocator);
2. ONE bounded retry of the same dispatch;
3. still failing: degraded mode — re-execute the SAME plan on the host
   CPU backend (bit-exact by construction: identical program, the
   leaves fetched to host numpy), so the query answers slowly instead
   of erroring.

``inject_oom(n)`` is the test/CI seam: the next ``n`` guarded
dispatches raise a synthetic RESOURCE_EXHAUSTED before running, which
is how check.sh's memory-pressure smoke proves absorption without a
real 16 GiB working set.  Since ISSUE 6 the seam is a registered
fault point (``device-oom`` in obs/faults.py) — this function is the
backward-compatible wrapper, and the fault can equally be armed via
the registry's config/env spec alongside the rpc/node faults."""

from __future__ import annotations

import gc
import os

from pilosa_tpu.obs import faults, metrics

# config [memory] / PILOSA_TPU_MEMORY_OOM_RETRY / _HOST_FALLBACK
OOM_RETRY = os.environ.get("PILOSA_TPU_MEMORY_OOM_RETRY", "1") != "0"
HOST_FALLBACK = os.environ.get(
    "PILOSA_TPU_MEMORY_HOST_FALLBACK", "1") != "0"

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "Ran out of memory")

_warned_degraded = False


class InjectedOOM(RuntimeError):
    """Synthetic RESOURCE_EXHAUSTED raised by the device-oom fault."""


def inject_oom(n: int = 1):
    """Make the next ``n`` guarded dispatches fail with a synthetic
    RESOURCE_EXHAUSTED (test / smoke hook).  Registry-backed: arms
    the ``device-oom`` fault point, replacing any prior arming (the
    original seam's set-not-add semantics, which the smokes rely on)."""
    faults.clear("device-oom")
    if int(n) > 0:
        faults.inject("device-oom", times=int(n))


def _take_injection() -> bool:
    return faults.take("device-oom")


def is_oom(e: BaseException) -> bool:
    if isinstance(e, InjectedOOM):
        return True
    if type(e).__name__ != "XlaRuntimeError" and not isinstance(
            e, (RuntimeError, MemoryError)):
        return False
    msg = str(e)
    return any(m in msg for m in _OOM_MARKERS)


def relieve(frac: float = 0.5) -> int:
    """Pressure-relief sweep: shed ``frac`` of the ledger-accounted
    resident bytes across every client, then collect so the freed
    device buffers actually return to the allocator."""
    from pilosa_tpu import memory
    need = memory.ledger().reclaim_frac(frac, trigger="oom")
    gc.collect()
    return need


def guarded(run, host_fallback=None):
    """Run a device dispatch under the OOM recovery ladder (see module
    docstring).  ``host_fallback`` is the degraded-mode closure; None
    means re-raise after the bounded retry."""
    def attempt():
        # the injection seam fails attempts AND retries, so tests/CI
        # can drive every rung of the ladder (inject_oom(1) = absorbed
        # by the retry; inject_oom(2) = degraded host fallback)
        if _take_injection():
            raise InjectedOOM(
                "RESOURCE_EXHAUSTED: injected by "
                "pilosa_tpu.memory.pressure.inject_oom")
        return run()
    try:
        return attempt()
    except Exception as e:
        if not is_oom(e):
            raise
        metrics.OOM_TOTAL.inc(outcome="caught")
        # incident trigger (obs/incidents.py): an OOM-ladder trip is
        # exactly the moment whose residency/flight state an operator
        # needs later — capture one rate-limited bundle off this
        # thread before the relief sweep mutates the evidence
        from pilosa_tpu.obs import incidents
        incidents.report("device-oom", detail=type(e).__name__,
                         context={"message": str(e)[:300]})
        relieve()
        if OOM_RETRY:
            try:
                out = attempt()
                metrics.OOM_TOTAL.inc(outcome="retry_ok")
                return out
            except Exception as e2:
                if not is_oom(e2):
                    raise
        if host_fallback is not None and HOST_FALLBACK:
            _warn_degraded()
            metrics.OOM_TOTAL.inc(outcome="host_fallback")
            return host_fallback()
        metrics.OOM_TOTAL.inc(outcome="raised")
        raise


def _warn_degraded():
    global _warned_degraded
    if not _warned_degraded:
        _warned_degraded = True
        import logging
        logging.getLogger("pilosa_tpu.memory").warning(
            "device RESOURCE_EXHAUSTED persisted after eviction + "
            "retry; serving this query from the host engine "
            "(degraded mode)")


def run_host_plan(plan, leaves, params):
    """Degraded-mode execution: the SAME stacked plan, jitted onto the
    host CPU backend with the leaves fetched to numpy.  Bit-exact with
    the device program by construction; Pallas kernels stay off (the
    XLA reference paths serve every plan kind)."""
    import numpy as np
    import jax

    from pilosa_tpu.executor import stacked

    cpu = jax.local_devices(backend="cpu")[0]
    lv = tuple(np.asarray(x) for x in leaves)
    pv = tuple(np.asarray(x) for x in params)
    with jax.default_device(cpu):
        fn = jax.jit(stacked._plan_run(plan, False))
        return jax.block_until_ready(fn(lv, pv))
