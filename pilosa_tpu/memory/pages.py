"""Paged device stacks — sub-stack residency granularity.

A tile-stack cache entry used to be ONE device array: a broad TopN's
(R, S, W) candidate stack evicting meant losing the whole thing, and
a byte-budget squeeze evicted entire hot stacks to fit one new one.
Here an entry becomes a set of fixed-size *pages*: the stack's leading
axes flatten to L lanes (one lane = one (leading-coords, W) row — a
shard-group x row-block slab), and consecutive lanes group into pages
of ``memory.page_bytes()`` each.  Pages are independent device arrays:

- the query operand is assembled by a jitted gather
  (``ops.bitmap.assemble_pages`` — concatenate + trim), so the engine
  sees the same single array it always did;
- eviction drops the COLDEST PAGES (memory/policy.py scoring), not
  whole entries — a 2x-overcommitted working set re-uploads only the
  pages a query actually lost;
- delta patching (PR 3) applies per page: a point write scatters into
  the one page holding its dirty lanes.

This is the ragged-KV-cache paging trick (Ragged Paged Attention,
PAPERS.md) applied to bitmap tiles; the roaring container (64Ki
columns) is the reference's analogous fixed residency unit.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

from pilosa_tpu import memory


def page_lanes_for(width_words: int, itemsize: int = 4) -> int:
    """Lanes per page: the largest whole-lane count fitting the
    configured page size (>= 1 — a lane wider than the page still
    pages lane-by-lane)."""
    lane_bytes = max(int(width_words) * itemsize, 1)
    return max(int(memory.page_bytes()) // lane_bytes, 1)


@dataclass
class StackRecipe:
    """Everything the paged cache path needs to (re)build an entry at
    page granularity, supplied by the stack builders in
    executor/stacked.py:

    - ``logical_lead``: the stack's leading shape (lanes = prod)
    - ``width_words``:  trailing word-axis length
    - ``lane_words(lane)``: the lane's CURRENT full-width host words
      (re-read from live fragments — page rebuilds and patches share
      one source of truth with the whole-stack patcher)
    - ``build_host()``: the full host (lead..., W) array (bulk cold
      builds beat L lane_words calls)
    - ``versions_fn()``: the entry's CURRENT fragment stamp tuple
      (prefetch warms against live versions, never a stale snapshot)
    - ``deltas_fn(old_versions)``: dirty lane map (lane -> [(lo, hi)]
      word runs, None value = whole lane) or None for structural
      changes; absent when delta patching is disabled
    - ``weight``: rebuild cost per byte relative to a plain row stack
      (groupcode stacks OR many rows per lane — evicting their pages
      costs more to restore, so the eviction policy holds them longer)
    - ``alive_fn()``: False once the fields this recipe captured were
      dropped/recreated — the prefetcher must not rebuild (and
      budget-reserve) stacks no live query can ever hit
    - ``lane_device``: serving-mesh owner slot per lane (int32
      (lanes,), from memory/placement.py) or None for the single-
      device layout — gives the PagedStack its device axis
    - ``shard_axis``: which leading axis of ``logical_lead`` indexes
      the group's shards (the axis ``lane_device`` varies along) —
      the mesh program needs it to rebuild per-device local leaves
      with the shard axis compressed to the device's owned shards
    """

    logical_lead: tuple
    width_words: int
    lane_words: object
    build_host: object
    versions_fn: object
    deltas_fn: object = None
    weight: float = 1.0
    alive_fn: object = None
    lane_device: object = None
    shard_axis: int | None = None

    @property
    def lanes(self) -> int:
        n = 1
        for d in self.logical_lead:
            n *= int(d)
        return n


class PagedStack:
    """One cache entry's resident pages + recency/frequency.

    ``pages[i]`` is a device array of shape (page_lanes, W) (the last
    page zero-padded) or None when evicted.  Slots are swapped only
    under the owning cache's lock; readers snapshot the page list so
    a concurrent eviction can never yank an array mid-gather (the
    local reference keeps the buffer alive).  Recency/frequency are
    ENTRY-level scalars: an operand always needs all its pages, so
    per-page stamps would carry no signal (every access touches every
    page) at O(n_pages) bookkeeping cost — eviction concentrates on
    whole entries and drains their pages in index order.

    With ``lane_device`` (the serving mesh, memory/placement.py) the
    stack grows a DEVICE AXIS: lanes partition by owner slot (stable —
    within a device, global lane order is preserved) and each device's
    lane run pages independently, so a page never straddles two
    devices.  ``page_device[pi]`` is the page's owner slot,
    ``page_table[pi]`` its global lane ids, and ``inv[lane]`` the
    lane's row in the padded page concatenation (the permutation the
    single-array assembly fallback applies).  ``lane_device is None``
    keeps the exact legacy layout (contiguous lanes per page,
    ``inv`` identity)."""

    __slots__ = ("shape", "lanes", "page_lanes", "width_words",
                 "weight", "pages", "last_access", "hits",
                 "lane_device", "shard_axis", "page_device",
                 "page_table", "lane_page", "lane_slot")

    def __init__(self, shape: tuple, page_lanes: int,
                 weight: float = 1.0, lane_device=None,
                 shard_axis: int | None = None):
        self.shape = tuple(shape)
        self.width_words = int(shape[-1])
        n = 1
        for d in shape[:-1]:
            n *= int(d)
        self.lanes = n
        self.page_lanes = int(page_lanes)
        self.weight = float(weight)
        self.shard_axis = shard_axis
        if lane_device is None:
            self.lane_device = None
            self.page_device = None
            self.page_table = None
            self.lane_page = None
            self.lane_slot = None
            n_pages = -(-self.lanes // self.page_lanes)
        else:
            ld = np.ascontiguousarray(lane_device, dtype=np.int32)
            if ld.shape != (self.lanes,):
                raise ValueError("lane_device must be (lanes,)")
            self.lane_device = ld
            order = np.argsort(ld, kind="stable")
            self.page_table = []
            self.page_device = []
            pl = self.page_lanes
            for dev in np.unique(ld):
                run = order[ld[order] == dev]
                for k in range(0, run.size, pl):
                    self.page_table.append(run[k:k + pl])
                    self.page_device.append(int(dev))
            self.lane_page = np.empty(self.lanes, dtype=np.int32)
            self.lane_slot = np.empty(self.lanes, dtype=np.int32)
            for pi, ids in enumerate(self.page_table):
                self.lane_page[ids] = pi
                self.lane_slot[ids] = np.arange(ids.size,
                                                dtype=np.int32)
            n_pages = len(self.page_table)
        self.pages: list = [None] * n_pages
        self.last_access = time.time()
        self.hits = 0

    @property
    def n_pages(self) -> int:
        return len(self.pages)

    @property
    def page_nbytes(self) -> int:
        """DENSE byte size of one page — the fixed upper bound.
        Resident accounting uses each page's TRUE byte size instead
        (``resident_bytes``): container-encoded pages
        (memory/encode.py) are smaller, and charging the ledger their
        dense-tile estimate would waste exactly the capacity the
        sparse format buys."""
        return self.page_lanes * self.width_words * 4

    def resident_bytes(self) -> int:
        return sum(int(p.nbytes) for p in self.pages
                   if p is not None)

    def missing(self) -> list[int]:
        return [i for i, p in enumerate(self.pages) if p is None]

    def lane_range(self, pi: int) -> tuple[int, int]:
        """Legacy contiguous page extent (single-device layout only —
        device-partitioned pages hold non-contiguous lane id sets, use
        ``page_lane_ids``)."""
        if self.page_table is not None:
            raise ValueError("lane_range undefined for device-"
                             "partitioned pages")
        lo = pi * self.page_lanes
        return lo, min(lo + self.page_lanes, self.lanes)

    def page_lane_ids(self, pi: int) -> np.ndarray:
        """Global lane ids resident in page ``pi`` (<= page_lanes)."""
        if self.page_table is not None:
            return self.page_table[pi]
        lo, hi = self.lane_range(pi)
        return np.arange(lo, hi, dtype=np.int32)

    def page_of(self, lane: int) -> tuple[int, int]:
        """(page index, row inside the page) holding ``lane``."""
        if self.lane_page is not None:
            return int(self.lane_page[lane]), int(self.lane_slot[lane])
        return divmod(int(lane), self.page_lanes)

    def device_of(self, pi: int) -> int | None:
        """The page's serving-mesh owner slot (None = unplaced)."""
        return (None if self.page_device is None
                else self.page_device[pi])

    def inv_perm(self) -> "np.ndarray | None":
        """lane -> row in the padded page concatenation, or None when
        page order IS lane order (the legacy layout)."""
        if self.lane_page is None:
            return None
        return (self.lane_page.astype(np.int64) * self.page_lanes
                + self.lane_slot)

    def device_resident_bytes(self) -> dict[int, int]:
        """True resident bytes by owner slot (invariant checks +
        bench occupancy)."""
        out: dict[int, int] = {}
        for pi, p in enumerate(self.pages):
            if p is None:
                continue
            d = self.device_of(pi)
            out[-1 if d is None else d] = (
                out.get(-1 if d is None else d, 0) + int(p.nbytes))
        return out

    def build_page_host(self, pi: int, lane_words) -> np.ndarray:
        """Host words for one page (zero-padded past the last lane)."""
        block = np.zeros((self.page_lanes, self.width_words),
                         dtype=np.uint32)
        for k, lane in enumerate(self.page_lane_ids(pi)):
            block[k] = lane_words(int(lane))
        return block

    def touch(self, now: float | None = None):
        self.last_access = time.time() if now is None else now
        self.hits += 1
