"""Paged device stacks — sub-stack residency granularity.

A tile-stack cache entry used to be ONE device array: a broad TopN's
(R, S, W) candidate stack evicting meant losing the whole thing, and
a byte-budget squeeze evicted entire hot stacks to fit one new one.
Here an entry becomes a set of fixed-size *pages*: the stack's leading
axes flatten to L lanes (one lane = one (leading-coords, W) row — a
shard-group x row-block slab), and consecutive lanes group into pages
of ``memory.page_bytes()`` each.  Pages are independent device arrays:

- the query operand is assembled by a jitted gather
  (``ops.bitmap.assemble_pages`` — concatenate + trim), so the engine
  sees the same single array it always did;
- eviction drops the COLDEST PAGES (memory/policy.py scoring), not
  whole entries — a 2x-overcommitted working set re-uploads only the
  pages a query actually lost;
- delta patching (PR 3) applies per page: a point write scatters into
  the one page holding its dirty lanes.

This is the ragged-KV-cache paging trick (Ragged Paged Attention,
PAPERS.md) applied to bitmap tiles; the roaring container (64Ki
columns) is the reference's analogous fixed residency unit.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

from pilosa_tpu import memory


def page_lanes_for(width_words: int, itemsize: int = 4) -> int:
    """Lanes per page: the largest whole-lane count fitting the
    configured page size (>= 1 — a lane wider than the page still
    pages lane-by-lane)."""
    lane_bytes = max(int(width_words) * itemsize, 1)
    return max(int(memory.page_bytes()) // lane_bytes, 1)


@dataclass
class StackRecipe:
    """Everything the paged cache path needs to (re)build an entry at
    page granularity, supplied by the stack builders in
    executor/stacked.py:

    - ``logical_lead``: the stack's leading shape (lanes = prod)
    - ``width_words``:  trailing word-axis length
    - ``lane_words(lane)``: the lane's CURRENT full-width host words
      (re-read from live fragments — page rebuilds and patches share
      one source of truth with the whole-stack patcher)
    - ``build_host()``: the full host (lead..., W) array (bulk cold
      builds beat L lane_words calls)
    - ``versions_fn()``: the entry's CURRENT fragment stamp tuple
      (prefetch warms against live versions, never a stale snapshot)
    - ``deltas_fn(old_versions)``: dirty lane map (lane -> [(lo, hi)]
      word runs, None value = whole lane) or None for structural
      changes; absent when delta patching is disabled
    - ``weight``: rebuild cost per byte relative to a plain row stack
      (groupcode stacks OR many rows per lane — evicting their pages
      costs more to restore, so the eviction policy holds them longer)
    - ``alive_fn()``: False once the fields this recipe captured were
      dropped/recreated — the prefetcher must not rebuild (and
      budget-reserve) stacks no live query can ever hit
    """

    logical_lead: tuple
    width_words: int
    lane_words: object
    build_host: object
    versions_fn: object
    deltas_fn: object = None
    weight: float = 1.0
    alive_fn: object = None

    @property
    def lanes(self) -> int:
        n = 1
        for d in self.logical_lead:
            n *= int(d)
        return n


class PagedStack:
    """One cache entry's resident pages + recency/frequency.

    ``pages[i]`` is a device array of shape (page_lanes, W) (the last
    page zero-padded) or None when evicted.  Slots are swapped only
    under the owning cache's lock; readers snapshot the page list so
    a concurrent eviction can never yank an array mid-gather (the
    local reference keeps the buffer alive).  Recency/frequency are
    ENTRY-level scalars: an operand always needs all its pages, so
    per-page stamps would carry no signal (every access touches every
    page) at O(n_pages) bookkeeping cost — eviction concentrates on
    whole entries and drains their pages in index order."""

    __slots__ = ("shape", "lanes", "page_lanes", "width_words",
                 "weight", "pages", "last_access", "hits")

    def __init__(self, shape: tuple, page_lanes: int,
                 weight: float = 1.0):
        self.shape = tuple(shape)
        self.width_words = int(shape[-1])
        n = 1
        for d in shape[:-1]:
            n *= int(d)
        self.lanes = n
        self.page_lanes = int(page_lanes)
        self.weight = float(weight)
        n_pages = -(-self.lanes // self.page_lanes)
        self.pages: list = [None] * n_pages
        self.last_access = time.time()
        self.hits = 0

    @property
    def n_pages(self) -> int:
        return len(self.pages)

    @property
    def page_nbytes(self) -> int:
        """DENSE byte size of one page — the fixed upper bound.
        Resident accounting uses each page's TRUE byte size instead
        (``resident_bytes``): container-encoded pages
        (memory/encode.py) are smaller, and charging the ledger their
        dense-tile estimate would waste exactly the capacity the
        sparse format buys."""
        return self.page_lanes * self.width_words * 4

    def resident_bytes(self) -> int:
        return sum(int(p.nbytes) for p in self.pages
                   if p is not None)

    def missing(self) -> list[int]:
        return [i for i, p in enumerate(self.pages) if p is None]

    def lane_range(self, pi: int) -> tuple[int, int]:
        lo = pi * self.page_lanes
        return lo, min(lo + self.page_lanes, self.lanes)

    def build_page_host(self, pi: int, lane_words) -> np.ndarray:
        """Host words for one page (zero-padded past the last lane)."""
        lo, hi = self.lane_range(pi)
        block = np.zeros((self.page_lanes, self.width_words),
                         dtype=np.uint32)
        for k, lane in enumerate(range(lo, hi)):
            block[k] = lane_words(lane)
        return block

    def touch(self, now: float | None = None):
        self.last_access = time.time() if now is None else now
        self.hits += 1
