"""Device-memory budget ledger — one accountant for HBM bytes.

Clients (the tile-stack cache, the jit caches, the serving result
cache) register with a *reclaim callback* and account every resident
device allocation through :meth:`Ledger.reserve` / :meth:`release`.
The invariant the ledger maintains — and the concurrency tests pin —
is that the accounted total NEVER exceeds the budget: a reservation
that would cross it first drives reclaim across the OTHER clients
(coldest first, requester last), and is denied outright when not
enough cold bytes exist, in which case the caller serves its array
transiently without retaining it.

The budget resolves lazily on first pressure, in precedence order:
explicit ``configure(budget_bytes=...)`` > the
``PILOSA_TPU_MEMORY_BUDGET_BYTES`` env var > the real device memory
(``jax.local_devices()[0].memory_stats()``) minus a headroom fraction
> an 8 GiB fallback (matching the pre-ledger ``TileStackCache``
bound).  Lazy because eagerly touching ``jax.local_devices()`` at
construction would initialize the backend from every Executor ctor —
including ones that never touch a device.

Clients are held by WEAK reference: a garbage-collected cache (tests
construct thousands of Executors) drops out of the accounting with its
arrays, so the ledger can never leak dead caches or their bytes.
"""

from __future__ import annotations

import os
import threading
import weakref

from pilosa_tpu.obs import metrics

_FALLBACK_BUDGET = 8 << 30
_RECLAIM_ATTEMPTS = 3


class Client:
    """One registered device-byte owner.  ``reserve``/``release`` are
    the only mutators; ``bytes`` is the client's accounted total."""

    __slots__ = ("name", "_bytes", "_reclaim_cb", "_cold_ts_cb",
                 "_ledger", "__weakref__")

    def __init__(self, name: str, ledger: "Ledger", reclaim_cb=None,
                 cold_ts_cb=None):
        self.name = name
        self._bytes = 0
        self._reclaim_cb = reclaim_cb
        self._cold_ts_cb = cold_ts_cb
        self._ledger = ledger

    @property
    def bytes(self) -> int:
        return self._bytes

    def reserve(self, nbytes: int, trigger: str = "reserve") -> bool:
        return self._ledger.reserve(self, nbytes, trigger=trigger)

    def release(self, nbytes: int):
        self._ledger.release(self, nbytes)

    def cold_ts(self) -> float:
        """Timestamp of this client's coldest resident entry (0 =
        unknown, treated as coldest) — the cross-client reclaim
        ordering hint."""
        if self._cold_ts_cb is None:
            return 0.0
        try:
            return float(self._cold_ts_cb())
        except Exception:
            return 0.0


class Ledger:
    def __init__(self, budget_bytes: int | None = None,
                 headroom_frac: float = 0.1):
        # explicit budget (configure/ctor); None = resolve lazily
        self._explicit = (int(budget_bytes)
                          if budget_bytes else None)
        self.headroom_frac = float(headroom_frac)
        self._budget: int | None = None
        self._clients: list[weakref.ref] = []
        self._lock = threading.Lock()

    # -- registration ---------------------------------------------------

    def register(self, name: str, reclaim=None, cold_ts=None) -> Client:
        """Register a client.  ``reclaim(nbytes) -> freed`` evicts the
        client's cold bytes under cross-client pressure (it must call
        ``client.release`` for what it drops and report the total);
        ``cold_ts() -> epoch seconds`` of its coldest entry orders the
        reclaim sweep.  The ledger keeps only a weak reference."""
        c = Client(name, self, reclaim_cb=reclaim, cold_ts_cb=cold_ts)
        with self._lock:
            self._clients.append(weakref.ref(c))
        return c

    def _live_locked(self) -> list[Client]:
        live, refs = [], []
        for r in self._clients:
            c = r()
            if c is not None:
                live.append(c)
                refs.append(r)
        self._clients = refs
        return live

    # -- budget ---------------------------------------------------------

    def set_budget(self, budget_bytes: int | None):
        """Explicit budget (None = auto-detect on next use).  Shrinking
        below the resident total reclaims down to the new bound."""
        with self._lock:
            self._explicit = (int(budget_bytes)
                              if budget_bytes else None)
            self._budget = self._explicit
            total = sum(c._bytes for c in self._live_locked())
            budget = self._budget
        if budget is not None:
            metrics.MEM_BUDGET.set(budget)
            if total > budget:
                self._reclaim(total - budget, requester=None,
                              trigger="shrink")

    def budget(self) -> int:
        b = self._budget
        if b is not None:
            return b
        # resolve OUTSIDE the lock: device init can be slow and must
        # not block concurrent release() calls
        b = self._detect()
        with self._lock:
            if self._budget is None:
                self._budget = b
            b = self._budget
        metrics.MEM_BUDGET.set(b)
        return b

    def _detect(self) -> int:
        if self._explicit:
            return self._explicit
        env = os.environ.get("PILOSA_TPU_MEMORY_BUDGET_BYTES")
        if env:
            try:
                n = int(env)
                if n > 0:
                    return n
            except ValueError:
                pass
        try:
            import jax
            stats = jax.local_devices()[0].memory_stats() or {}
            limit = (stats.get("bytes_limit")
                     or stats.get("bytes_reservable_limit"))
            if limit:
                return max(int(int(limit)
                               * (1.0 - self.headroom_frac)), 1 << 20)
        except Exception:
            pass  # CPU backends report no stats — config fallback
        return _FALLBACK_BUDGET

    # -- accounting -----------------------------------------------------

    @property
    def total_bytes(self) -> int:
        with self._lock:
            return sum(c._bytes for c in self._live_locked())

    def free_bytes(self) -> int:
        return max(self.budget() - self.total_bytes, 0)

    def reserve(self, client: Client, nbytes: int,
                trigger: str = "reserve") -> bool:
        """Account ``nbytes`` to ``client`` iff they fit the budget,
        reclaiming cold bytes across clients first.  False = denied —
        the caller must not retain the allocation."""
        nbytes = int(nbytes)
        if nbytes <= 0:
            return True
        budget = self.budget()  # resolve before taking the lock
        if nbytes > budget:
            metrics.MEM_DENIED.inc(client=client.name)
            return False
        for attempt in range(_RECLAIM_ATTEMPTS):
            with self._lock:
                total = sum(c._bytes for c in self._live_locked())
                if total + nbytes <= budget:
                    client._bytes += nbytes
                    self._export_locked()
                    return True
                need = total + nbytes - budget
            freed = self._reclaim(need, requester=client,
                                  trigger=trigger)
            if freed <= 0:
                break
        metrics.MEM_DENIED.inc(client=client.name)
        return False

    def release(self, client: Client, nbytes: int):
        nbytes = int(nbytes)
        if nbytes <= 0:
            return
        with self._lock:
            client._bytes = max(client._bytes - nbytes, 0)
            self._export_locked()

    # -- reclaim --------------------------------------------------------

    def _reclaim(self, need: int, requester: Client | None,
                 trigger: str) -> int:
        """Ask clients to shed ``need`` bytes: coldest clients first,
        the requester LAST — pressure in one cache evicts cold bytes
        in another before eating its own.  Callbacks run without the
        ledger lock (they call release() as they evict)."""
        metrics.MEM_RECLAIMS.inc(trigger=trigger)
        with self._lock:
            others = [c for c in self._live_locked()
                      if c is not requester and c._reclaim_cb is not None
                      and c._bytes > 0]
            me = (requester if requester is not None
                  and requester._reclaim_cb is not None else None)
        others.sort(key=lambda c: c.cold_ts())
        freed_total = 0
        for c in others + ([me] if me is not None else []):
            if freed_total >= need:
                break
            try:
                freed = int(c._reclaim_cb(need - freed_total) or 0)
            except Exception:
                freed = 0
            if freed > 0:
                freed_total += freed
                metrics.MEM_RECLAIMED.inc(freed, client=c.name)
        return freed_total

    def reclaim_frac(self, frac: float = 0.5,
                     trigger: str = "oom") -> int:
        """Shed a fraction of the resident total (the OOM backstop's
        pressure-relief sweep); returns bytes requested."""
        with self._lock:
            total = sum(c._bytes for c in self._live_locked())
        need = int(total * frac)
        if need > 0:
            self._reclaim(need, requester=None, trigger=trigger)
        return need

    def _export_locked(self):
        per: dict[str, int] = {}
        for c in self._live_locked():
            per[c.name] = per.get(c.name, 0) + c._bytes
        for name, nb in per.items():
            metrics.MEM_RESIDENT.set(nb, client=name)
