"""Device-memory budget ledger — one accountant for HBM bytes.

Clients (the tile-stack cache, the jit caches, the serving result
cache) register with a *reclaim callback* and account every resident
device allocation through :meth:`Ledger.reserve` / :meth:`release`.
The invariant the ledger maintains — and the concurrency tests pin —
is that the accounted total NEVER exceeds the budget: a reservation
that would cross it first drives reclaim across the OTHER clients
(coldest first, requester last), and is denied outright when not
enough cold bytes exist, in which case the caller serves its array
transiently without retaining it.

The budget resolves lazily on first pressure, in precedence order:
explicit ``configure(budget_bytes=...)`` > the
``PILOSA_TPU_MEMORY_BUDGET_BYTES`` env var > the real device memory
(``jax.local_devices()[0].memory_stats()``) minus a headroom fraction
> an 8 GiB fallback (matching the pre-ledger ``TileStackCache``
bound).  Lazy because eagerly touching ``jax.local_devices()`` at
construction would initialize the backend from every Executor ctor —
including ones that never touch a device.

Clients are held by WEAK reference: a garbage-collected cache (tests
construct thousands of Executors) drops out of the accounting with its
arrays, so the ledger can never leak dead caches or their bytes.

Under the serving mesh (memory/placement.py) the one global pool
splits into PER-DEVICE budgets: the global budget divides evenly
across the mesh slots (``device_budget``), reservations carry the
owning slot (``reserve(..., device=slot)``) and are denied when THAT
device's labeled total would cross its share — a hot shard cannot
silently eat a remote chip's HBM.  Reclaim stays a global sweep
(clients shed coldest-first regardless of device; the per-device cap
re-checks after each round), device-less reservations (whole-stack
entries, jit executables, result payloads) stay bounded by the global
budget only, and ``device_bytes()`` feeds both the placer's balance
decision and the bench occupancy cells.
"""

from __future__ import annotations

import os
import threading
import weakref

from pilosa_tpu.obs import metrics

_FALLBACK_BUDGET = 8 << 30
_RECLAIM_ATTEMPTS = 3


class Client:
    """One registered device-byte owner.  ``reserve``/``release`` are
    the only mutators; ``bytes`` is the client's accounted total."""

    __slots__ = ("name", "_bytes", "_dev", "_reclaim_cb",
                 "_cold_ts_cb", "_ledger", "__weakref__")

    def __init__(self, name: str, ledger: "Ledger", reclaim_cb=None,
                 cold_ts_cb=None):
        self.name = name
        self._bytes = 0
        self._dev: dict[int, int] = {}   # mesh slot -> labeled bytes
        self._reclaim_cb = reclaim_cb
        self._cold_ts_cb = cold_ts_cb
        self._ledger = ledger

    @property
    def bytes(self) -> int:
        return self._bytes

    def reserve(self, nbytes: int, trigger: str = "reserve",
                device: int | None = None) -> bool:
        return self._ledger.reserve(self, nbytes, trigger=trigger,
                                    device=device)

    def release(self, nbytes: int, device: int | None = None):
        self._ledger.release(self, nbytes, device=device)

    def cold_ts(self) -> float:
        """Timestamp of this client's coldest resident entry (0 =
        unknown, treated as coldest) — the cross-client reclaim
        ordering hint."""
        if self._cold_ts_cb is None:
            return 0.0
        try:
            return float(self._cold_ts_cb())
        except Exception:
            return 0.0


class Ledger:
    def __init__(self, budget_bytes: int | None = None,
                 headroom_frac: float = 0.1):
        # explicit budget (configure/ctor); None = resolve lazily
        self._explicit = (int(budget_bytes)
                          if budget_bytes else None)
        self.headroom_frac = float(headroom_frac)
        self._budget: int | None = None
        self._clients: list[weakref.ref] = []
        self._lock = threading.Lock()
        # serving-mesh width (memory/placement.py keeps this current);
        # 1 = no per-device split, every device check degenerates to
        # the global one
        self._n_devices = 1

    # -- registration ---------------------------------------------------

    def register(self, name: str, reclaim=None, cold_ts=None) -> Client:
        """Register a client.  ``reclaim(nbytes) -> freed`` evicts the
        client's cold bytes under cross-client pressure (it must call
        ``client.release`` for what it drops and report the total);
        ``cold_ts() -> epoch seconds`` of its coldest entry orders the
        reclaim sweep.  The ledger keeps only a weak reference."""
        c = Client(name, self, reclaim_cb=reclaim, cold_ts_cb=cold_ts)
        with self._lock:
            self._clients.append(weakref.ref(c))
        return c

    def _live_locked(self) -> list[Client]:
        live, refs = [], []
        for r in self._clients:
            c = r()
            if c is not None:
                live.append(c)
                refs.append(r)
        self._clients = refs
        return live

    # -- budget ---------------------------------------------------------

    def set_budget(self, budget_bytes: int | None):
        """Explicit budget (None = auto-detect on next use).  Shrinking
        below the resident total reclaims down to the new bound."""
        with self._lock:
            self._explicit = (int(budget_bytes)
                              if budget_bytes else None)
            self._budget = self._explicit
            total = sum(c._bytes for c in self._live_locked())
            budget = self._budget
        if budget is not None:
            metrics.MEM_BUDGET.set(budget)
            if total > budget:
                self._reclaim(total - budget, requester=None,
                              trigger="shrink")

    def budget(self) -> int:
        b = self._budget
        if b is not None:
            return b
        # resolve OUTSIDE the lock: device init can be slow and must
        # not block concurrent release() calls
        b = self._detect()
        with self._lock:
            if self._budget is None:
                self._budget = b
            b = self._budget
        metrics.MEM_BUDGET.set(b)
        return b

    def _detect(self) -> int:
        if self._explicit:
            return self._explicit
        env = os.environ.get("PILOSA_TPU_MEMORY_BUDGET_BYTES")
        if env:
            try:
                n = int(env)
                if n > 0:
                    return n
            except ValueError:
                pass
        try:
            import jax
            stats = jax.local_devices()[0].memory_stats() or {}
            limit = (stats.get("bytes_limit")
                     or stats.get("bytes_reservable_limit"))
            if limit:
                return max(int(int(limit)
                               * (1.0 - self.headroom_frac)), 1 << 20)
        except Exception:
            pass  # CPU backends report no stats — config fallback
        return _FALLBACK_BUDGET

    # -- devices --------------------------------------------------------

    def set_devices(self, n: int):
        """Serving-mesh width: the global budget splits evenly into
        per-device shares and device-labeled reservations are checked
        against their slot's share."""
        with self._lock:
            self._n_devices = max(int(n), 1)

    def device_budget(self) -> int:
        """One mesh slot's byte share of the global budget."""
        b = self.budget()
        with self._lock:
            return b // max(self._n_devices, 1)

    def device_bytes(self, n: int | None = None) -> list[int]:
        """Device-labeled resident bytes per mesh slot, summed across
        clients (the placer's balance signal + bench occupancy)."""
        with self._lock:
            nd = max(self._n_devices if n is None else int(n), 1)
            out = [0] * nd
            for c in self._live_locked():
                for slot, nb in c._dev.items():
                    if 0 <= slot < nd:
                        out[slot] += nb
            return out

    def _dev_total_locked(self, slot: int) -> int:
        return sum(c._dev.get(slot, 0)
                   for c in self._live_locked())

    # -- accounting -----------------------------------------------------

    @property
    def total_bytes(self) -> int:
        with self._lock:
            return sum(c._bytes for c in self._live_locked())

    def free_bytes(self) -> int:
        return max(self.budget() - self.total_bytes, 0)

    def reserve(self, client: Client, nbytes: int,
                trigger: str = "reserve",
                device: int | None = None) -> bool:
        """Account ``nbytes`` to ``client`` iff they fit the budget,
        reclaiming cold bytes across clients first.  False = denied —
        the caller must not retain the allocation.  ``device`` labels
        the bytes with their mesh slot and additionally enforces that
        slot's per-device share."""
        nbytes = int(nbytes)
        if nbytes <= 0:
            return True
        budget = self.budget()  # resolve before taking the lock
        with self._lock:
            nd = self._n_devices
        dev_budget = budget // nd if (device is not None
                                      and nd > 1) else None
        if nbytes > budget or (dev_budget is not None
                               and nbytes > dev_budget):
            metrics.MEM_DENIED.inc(client=client.name)
            return False
        for attempt in range(_RECLAIM_ATTEMPTS):
            with self._lock:
                total = sum(c._bytes for c in self._live_locked())
                need = max(total + nbytes - budget, 0)
                if need == 0 and dev_budget is not None:
                    dtot = self._dev_total_locked(device)
                    need = max(dtot + nbytes - dev_budget, 0)
                if need == 0:
                    client._bytes += nbytes
                    if device is not None:
                        client._dev[device] = (
                            client._dev.get(device, 0) + nbytes)
                    self._export_locked()
                    return True
            freed = self._reclaim(need, requester=client,
                                  trigger=trigger)
            if freed <= 0:
                break
        metrics.MEM_DENIED.inc(client=client.name)
        return False

    def release(self, client: Client, nbytes: int,
                device: int | None = None):
        nbytes = int(nbytes)
        if nbytes <= 0:
            return
        with self._lock:
            client._bytes = max(client._bytes - nbytes, 0)
            if device is not None:
                left = client._dev.get(device, 0) - nbytes
                if left > 0:
                    client._dev[device] = left
                else:
                    client._dev.pop(device, None)
            self._export_locked()

    # -- reclaim --------------------------------------------------------

    def _reclaim(self, need: int, requester: Client | None,
                 trigger: str) -> int:
        """Ask clients to shed ``need`` bytes: coldest clients first,
        the requester LAST — pressure in one cache evicts cold bytes
        in another before eating its own.  Callbacks run without the
        ledger lock (they call release() as they evict)."""
        metrics.MEM_RECLAIMS.inc(trigger=trigger)
        with self._lock:
            others = [c for c in self._live_locked()
                      if c is not requester and c._reclaim_cb is not None
                      and c._bytes > 0]
            me = (requester if requester is not None
                  and requester._reclaim_cb is not None else None)
        others.sort(key=lambda c: c.cold_ts())
        freed_total = 0
        for c in others + ([me] if me is not None else []):
            if freed_total >= need:
                break
            try:
                freed = int(c._reclaim_cb(need - freed_total) or 0)
            except Exception:
                freed = 0
            if freed > 0:
                freed_total += freed
                metrics.MEM_RECLAIMED.inc(freed, client=c.name)
        return freed_total

    def reclaim_frac(self, frac: float = 0.5,
                     trigger: str = "oom") -> int:
        """Shed a fraction of the resident total (the OOM backstop's
        pressure-relief sweep); returns bytes requested."""
        with self._lock:
            total = sum(c._bytes for c in self._live_locked())
        need = int(total * frac)
        if need > 0:
            self._reclaim(need, requester=None, trigger=trigger)
        return need

    def _export_locked(self):
        per: dict[str, int] = {}
        dev: dict[int, int] = {}
        for c in self._live_locked():
            per[c.name] = per.get(c.name, 0) + c._bytes
            for slot, nb in c._dev.items():
                dev[slot] = dev.get(slot, 0) + nb
        for name, nb in per.items():
            metrics.MEM_RESIDENT.set(nb, client=name)
        for slot, nb in dev.items():
            metrics.MEM_DEVICE_RESIDENT.set(nb, device=f"d{slot}")
