"""DAX — disaggregated serverless deployment (dax/, SURVEY §2.8).

The reference's storage/compute split: stateless *compute* workers own
table-shard "jobs" assigned by a *controller*; all durable state lives
in a shared write-log + snapshot store, so any worker can pick up any
shard by loading its snapshot and replaying the log.  A stateless
*queryer* fans queries out to whichever workers currently own the
touched shards.

This maps directly onto the TPU build's own split (host storage is
the source of truth, device state is a cache): a compute worker is a
controller process driving one TPU slice; elastic recovery is
"replay the log into a fresh worker".

Components (reference files):
    Controller  — dax/controller/, balancer/balancer.go, poller/poller.go
    Directive   — dax/directive.go:8; api_directive.go:19,172,559
    Computer    — dax/computer/
    Queryer     — dax/queryer/queryer.go:34, orchestrator.go:83
    WriteLogger — dax/writelogger/writelogger.go:22
    Snapshotter — dax/snapshotter/snapshotter.go:24
"""

# PEP 562 lazy re-exports: config application touches
# pilosa_tpu.dax.settings on every server boot, and /debug/dax reads
# the light registries — neither should drag the queryer/executor
# stack in.  `from pilosa_tpu.dax import Controller` etc. keep
# working exactly as the eager imports did.
_EXPORTS = {
    "Controller": "pilosa_tpu.dax.controller",
    "ComputeNode": "pilosa_tpu.dax.computer",
    "Directive": "pilosa_tpu.dax.directive",
    "Queryer": "pilosa_tpu.dax.queryer",
    "Snapshotter": "pilosa_tpu.dax.snapshotter",
    "WriteLogger": "pilosa_tpu.dax.writelogger",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}")
    import importlib
    return getattr(importlib.import_module(mod), name)
