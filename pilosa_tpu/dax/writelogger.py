"""WriteLogger — shared append-only log per (table, shard).

Reference: dax/writelogger/writelogger.go:22 — AppendMessage/
LogReader over a shared filesystem; each (table, partition|shard) has
its own log file, truncated when a snapshot supersedes it.

Entries are JSONL: {"op": "bits"|"values", ...import payload...}.
Replay applies them in append order, which reproduces the shard
exactly (imports are idempotent last-write-wins per bit/value).
"""

from __future__ import annotations

import json
import os
import threading


class WriteLogger:
    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        # absolute version per (table, shard), seeded from disk once —
        # O(1) appends instead of re-counting the file every time
        self._versions: dict[tuple[str, int], int] = {}
        os.makedirs(path, exist_ok=True)

    def _log_path(self, table: str, shard: int) -> str:
        return os.path.join(self.path, f"{table}.shard.{shard:04d}.log")

    def _base(self, table: str, shard: int) -> int:
        """Versions are ABSOLUTE across truncations: a snapshot taken
        at version V stays valid after the entries it covers are
        dropped.  base = how many entries have been truncated away."""
        p = self._log_path(table, shard) + ".base"
        if not os.path.exists(p):
            return 0
        with open(p) as f:
            return int(f.read().strip() or 0)

    def _set_base(self, table: str, shard: int, base: int):
        with open(self._log_path(table, shard) + ".base", "w") as f:
            f.write(str(base))

    def _count(self, table: str, shard: int) -> int:
        p = self._log_path(table, shard)
        if not os.path.exists(p):
            return 0
        with open(p) as f:
            return sum(1 for _ in f)

    def _version_locked(self, table: str, shard: int) -> int:
        key = (table, shard)
        v = self._versions.get(key)
        if v is None:
            v = self._base(table, shard) + self._count(table, shard)
            self._versions[key] = v
        return v

    def append(self, table: str, shard: int, entry: dict) -> int:
        """Append one entry; returns the log's absolute version (total
        entries ever appended)."""
        with self._lock:
            v = self._version_locked(table, shard) + 1
            p = self._log_path(table, shard)
            with open(p, "a") as f:
                f.write(json.dumps(entry, separators=(",", ":")) + "\n")
            self._versions[(table, shard)] = v
            return v

    def replay(self, table: str, shard: int,
               from_version: int = 0) -> list[dict]:
        """Entries after absolute version from_version, in append
        order (writelogger.LogReader)."""
        p = self._log_path(table, shard)
        if not os.path.exists(p):
            return []
        skip = max(0, from_version - self._base(table, shard))
        out = []
        with open(p) as f:
            for i, line in enumerate(f):
                if i >= skip and line.strip():
                    out.append(json.loads(line))
        return out

    def version(self, table: str, shard: int) -> int:
        with self._lock:
            return self._version_locked(table, shard)

    def fast_forward(self, table: str, shard: int, version: int):
        """Advance a (possibly fresh) log to an absolute version the
        blob tier already covers, so later appends continue the
        global numbering instead of regressing below it.  Any local
        entries at or below `version` are covered by definition and
        dropped (stateless workers boot with an empty log, so this is
        normally a pure base bump)."""
        with self._lock:
            cur = self._version_locked(table, shard)
            if version <= cur:
                return
            p = self._log_path(table, shard)
            with open(p, "w"):
                pass
            self._set_base(table, shard, version)
            self._versions[(table, shard)] = version

    def truncate_through(self, table: str, shard: int, version: int):
        """Drop entries a snapshot at absolute `version` covers."""
        with self._lock:
            base = self._base(table, shard)
            if version <= base:
                return
            keep = self.replay(table, shard, from_version=version)
            p = self._log_path(table, shard)
            with open(p, "w") as f:
                for e in keep:
                    f.write(json.dumps(e, separators=(",", ":")) + "\n")
            self._set_base(table, shard, version)

    def shards(self, table: str) -> list[int]:
        out = []
        for fn in os.listdir(self.path):
            if fn.startswith(f"{table}.shard.") and fn.endswith(".log"):
                out.append(int(fn.split(".")[-2]))
        return sorted(out)
