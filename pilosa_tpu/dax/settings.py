"""[dax] / [blob] runtime knobs — config-pushed, env-twin-overridable.

``config.apply_dax_settings()`` pushes the loaded stanzas here; the
accessor functions re-check the env twins dynamically so the bench
A/B levers (``PILOSA_TPU_DAX_BLOB=0`` above all) flip live without a
config reload — the same contract every other plane's kill-switch
keeps.  This module stays import-light (no dax package machinery) so
config application never drags the queryer/executor in.
"""

from __future__ import annotations

import os

# config-pushed state (configure()); env twins outrank at read time
_blob = True
_backend = ""            # "" = no blob tier | "dir" | "mem"
_root = ""               # dir-backend root ("" = <data-dir>/blob)
_lazy_hydrate = True
_worker_budget_bytes = 0  # 0 = unbounded (no per-worker ledger bound)
_prefetch = 2            # shards warmed per hydrate tick (0 = off)
_scale_out_burn = 2.0    # SLO burn rate tripping scale-out
_scale_in_burn = 0.5     # burn rate under which scale-in may drain
_pressure_high = 0.9     # worker ledger fill fraction tripping scale-out
_min_workers = 1
_max_workers = 8
_standby = 1             # standby workers cli dax keeps warm
_reconcile_interval_s = 5.0
_cooldown_s = 30.0       # min seconds between scale events
_chase_lag = 8           # hydrate-replay backlog under which FENCE starts
_chase_rounds = 12       # bounded DELTA-CHASE rounds


def configure(blob=None, backend=None, root=None, lazy_hydrate=None,
              worker_budget_bytes=None, prefetch=None,
              scale_out_burn=None, scale_in_burn=None,
              pressure_high=None, min_workers=None, max_workers=None,
              standby=None, reconcile_interval_s=None,
              cooldown_s=None, chase_lag=None, chase_rounds=None):
    """Apply the [dax]/[blob] config stanzas (None = leave as is)."""
    g = globals()
    for name, val in (("_blob", blob), ("_backend", backend),
                      ("_root", root), ("_lazy_hydrate", lazy_hydrate),
                      ("_worker_budget_bytes", worker_budget_bytes),
                      ("_prefetch", prefetch),
                      ("_scale_out_burn", scale_out_burn),
                      ("_scale_in_burn", scale_in_burn),
                      ("_pressure_high", pressure_high),
                      ("_min_workers", min_workers),
                      ("_max_workers", max_workers),
                      ("_standby", standby),
                      ("_reconcile_interval_s", reconcile_interval_s),
                      ("_cooldown_s", cooldown_s),
                      ("_chase_lag", chase_lag),
                      ("_chase_rounds", chase_rounds)):
        if val is not None:
            g[name] = val


def _env_float(name: str, fallback: float) -> float:
    v = os.environ.get(name)
    if v is None:
        return fallback
    try:
        return float(v)
    except ValueError:
        return fallback


def _env_int(name: str, fallback: int) -> int:
    v = os.environ.get(name)
    if v is None:
        return fallback
    try:
        return int(v)
    except ValueError:
        return fallback


def blob_enabled() -> bool:
    """The tier kill-switch: PILOSA_TPU_DAX_BLOB=0 outranks any
    config (the A/B lever) — off, workers fall back to the seed's
    local-disk snapshot+log recovery, bit-exact."""
    v = os.environ.get("PILOSA_TPU_DAX_BLOB")
    if v is not None:
        return v != "0"
    return bool(_blob)


def backend() -> str:
    return os.environ.get("PILOSA_TPU_BLOB_BACKEND", _backend)


def root() -> str:
    return os.environ.get("PILOSA_TPU_BLOB_ROOT", _root)


def lazy_hydrate() -> bool:
    v = os.environ.get("PILOSA_TPU_DAX_LAZY_HYDRATE")
    if v is not None:
        return v != "0"
    return bool(_lazy_hydrate)


def worker_budget_bytes() -> int:
    return _env_int("PILOSA_TPU_DAX_WORKER_BUDGET_BYTES",
                    int(_worker_budget_bytes))


def prefetch() -> int:
    return _env_int("PILOSA_TPU_DAX_PREFETCH", int(_prefetch))


def scale_out_burn() -> float:
    return _env_float("PILOSA_TPU_DAX_SCALE_OUT_BURN",
                      float(_scale_out_burn))


def scale_in_burn() -> float:
    return _env_float("PILOSA_TPU_DAX_SCALE_IN_BURN",
                      float(_scale_in_burn))


def pressure_high() -> float:
    return _env_float("PILOSA_TPU_DAX_PRESSURE_HIGH",
                      float(_pressure_high))


def min_workers() -> int:
    return _env_int("PILOSA_TPU_DAX_MIN_WORKERS", int(_min_workers))


def max_workers() -> int:
    return _env_int("PILOSA_TPU_DAX_MAX_WORKERS", int(_max_workers))


def standby() -> int:
    return _env_int("PILOSA_TPU_DAX_STANDBY", int(_standby))


def reconcile_interval_s() -> float:
    return _env_float("PILOSA_TPU_DAX_RECONCILE_INTERVAL_S",
                      float(_reconcile_interval_s))


def cooldown_s() -> float:
    return _env_float("PILOSA_TPU_DAX_COOLDOWN_S", float(_cooldown_s))


def chase_lag() -> int:
    return _env_int("PILOSA_TPU_DAX_CHASE_LAG", int(_chase_lag))


def chase_rounds() -> int:
    return _env_int("PILOSA_TPU_DAX_CHASE_ROUNDS", int(_chase_rounds))
