"""Directive — the controller's desired-state spec for one worker.

Reference: dax/directive.go:8 — a full statement of what a compute
node should hold (tables + shard jobs + schema), POSTed to the
worker's /directive endpoint; the worker diffs against its current
state and enacts the changes (api_directive.go:19 ApplyDirective,
:172 enactDirective).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class Directive:
    address: str                       # worker this directive targets
    version: int = 0                   # monotonic per worker
    schema: dict = field(default_factory=dict)
    # table -> sorted list of shard ids this worker must serve
    assignments: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"address": self.address, "version": self.version,
                "schema": self.schema,
                "assignments": {t: sorted(s)
                                for t, s in self.assignments.items()}}

    @classmethod
    def from_dict(cls, d: dict) -> "Directive":
        return cls(address=d["address"], version=d.get("version", 0),
                   schema=d.get("schema", {}),
                   assignments={t: list(map(int, s))
                                for t, s in
                                d.get("assignments", {}).items()})
