"""Snapshotter — versioned shard snapshots in shared storage.

Reference: dax/snapshotter/snapshotter.go:24 — WriteSnapshot/
ReadSnapshot keyed (table, shard, writelog-version); recovery loads
the latest snapshot then replays the write-log tail past its version
(api_directive.go:559 loadShard).
"""

from __future__ import annotations

import json
import os
import threading


class Snapshotter:
    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        os.makedirs(path, exist_ok=True)

    def _snap_path(self, table: str, shard: int, version: int) -> str:
        return os.path.join(
            self.path, f"{table}.shard.{shard:04d}.v{version:08d}.snap")

    def write(self, table: str, shard: int, version: int, blob: bytes):
        """Store a snapshot of the shard state as of log `version`."""
        with self._lock:
            p = self._snap_path(table, shard, version)
            tmp = p + ".tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, p)  # atomic: readers never see partials
            # older versions are garbage once a newer one lands
            for fn in os.listdir(self.path):
                if (fn.startswith(f"{table}.shard.{shard:04d}.v")
                        and fn.endswith(".snap")
                        and fn != os.path.basename(p)):
                    os.unlink(os.path.join(self.path, fn))

    def latest(self, table: str, shard: int) -> tuple[int, bytes] | None:
        """(version, blob) of the newest snapshot, or None.  Holds the
        lock so write()'s unlink of superseded versions can't race the
        scan-then-open."""
        with self._lock:
            best = None
            prefix = f"{table}.shard.{shard:04d}.v"
            for fn in os.listdir(self.path):
                if fn.startswith(prefix) and fn.endswith(".snap"):
                    v = int(fn[len(prefix):-5])
                    if best is None or v > best:
                        best = v
            if best is None:
                return None
            with open(self._snap_path(table, shard, best), "rb") as f:
                return best, f.read()


def snapshot_fragment_rows(frag_rows: dict) -> bytes:
    """Serialize {(field, view, row_id): packed-words} row data."""
    out = []
    for (field, view, row), words in frag_rows.items():
        out.append({"f": field, "v": view, "r": int(row),
                    "w": words.tobytes().hex()})
    return json.dumps(out).encode()


def load_fragment_rows(blob: bytes):
    import numpy as np
    out = {}
    for e in json.loads(blob.decode()):
        out[(e["f"], e["v"], e["r"])] = np.frombuffer(
            bytes.fromhex(e["w"]), dtype=np.uint32).copy()
    return out
