"""ComputeNode — a stateless worker serving assigned shard jobs.

Reference: dax/computer/ + api_directive.go.  A worker is an ordinary
engine node (holder + API + HTTP) whose data is entirely reconstructed
from shared storage: on receiving a Directive it diffs desired vs held
shard jobs, loads newly assigned shards from the latest snapshot plus
the write-log tail (api_directive.go:559 loadShard), and drops
revoked ones.  All writes append to the WriteLogger BEFORE applying
locally, so worker loss never loses acknowledged writes.

TPU note: "apply locally" lands the bits in host fragments whose
device tiles refresh lazily — recovery is host-side log replay; the
chip just re-caches.
"""

from __future__ import annotations

import threading

from pilosa_tpu.dax.directive import Directive
from pilosa_tpu.dax.snapshotter import (
    Snapshotter,
    load_fragment_rows,
    snapshot_fragment_rows,
)
from pilosa_tpu.dax.writelogger import WriteLogger


def _strip_keys(schema: dict) -> dict:
    """Worker-local schema with every keys flag cleared (ID-space
    compute; the queryer owns translation)."""
    out = {"indexes": []}
    for ix in schema.get("indexes", []):
        nix = dict(ix, keys=False)
        nix["fields"] = [
            dict(f, options=dict(f.get("options", {}), keys=False))
            for f in ix.get("fields", [])]
        out["indexes"].append(nix)
    return out


class ComputeNode:
    def __init__(self, address: str, writelogger: WriteLogger,
                 snapshotter: Snapshotter, bind: str = "127.0.0.1"):
        from pilosa_tpu.models.holder import Holder
        from pilosa_tpu.server.http import Server
        self.address = address
        self.wl = writelogger
        self.snaps = snapshotter
        self.server = Server(holder=Holder(), bind=bind)
        self.api = self.server.api
        self.directive_version = -1
        # table -> set of shards this worker currently serves
        self.held: dict[str, set[int]] = {}
        self._lock = threading.Lock()
        self.server.add_route("POST", "/directive", self._post_directive)
        self.server.add_route("POST", "/dax/import", self._post_import)
        self.server.add_route("GET", "/dax/held",
                              lambda req: {t: sorted(s) for t, s in
                                           self.held.items()})

    # -- lifecycle -----------------------------------------------------

    def open(self):
        self.server.start()
        self.uri = f"127.0.0.1:{self.server.port}"
        return self

    def close(self):
        self.server.close()

    # -- directive enactment (api_directive.go:19,172) -----------------

    def _post_directive(self, req):
        d = Directive.from_dict(req.json())
        self.apply_directive(d)
        return {"applied": d.version}

    def apply_directive(self, d: Directive):
        with self._lock:
            if d.version <= self.directive_version:
                return  # stale directive (api_directive.go version gate)
            if d.schema:
                # workers run in pure ID space: key translation is a
                # front-end (queryer) concern, exactly like the
                # reference's Remote=true queries shipping
                # pre-translated ids — so strip keys from the local
                # mirror and return raw row ids in results
                self.api.apply_schema(_strip_keys(d.schema))
            for table, want in d.assignments.items():
                want = set(want)
                have = self.held.get(table, set())
                for shard in sorted(want - have):
                    self._load_shard(table, shard)
                for shard in sorted(have - want):
                    self._drop_shard(table, shard)
                self.held[table] = want
            for table in list(self.held):
                if table not in d.assignments:
                    for shard in sorted(self.held[table]):
                        self._drop_shard(table, shard)
                    del self.held[table]
            self.directive_version = d.version

    def _load_shard(self, table: str, shard: int):
        """snapshot + write-log tail -> local fragments
        (api_directive.go:559 loadShard)."""
        idx = self.api.holder.index(table)
        if idx is None:
            return
        version = 0
        snap = self.snaps.latest(table, shard)
        if snap is not None:
            version, blob = snap
            for (fname, view, row), words in load_fragment_rows(
                    blob).items():
                f = idx.field(fname)
                if f is None:
                    continue
                frag = f.view(view, create=True).fragment(
                    shard, create=True)
                # set_row_words keeps the invalidate/touch protocol
                # and re-compresses sparse rows on load
                frag.set_row_words(row, words)
        for e in self.wl.replay(table, shard, from_version=version):
            self._apply_entry(e)

    def _drop_shard(self, table: str, shard: int):
        idx = self.api.holder.index(table)
        if idx is None:
            return
        for f in idx.fields.values():
            for v in f.views.values():
                v.fragments.pop(shard, None)

    # -- writes: log first, then apply ---------------------------------

    def _post_import(self, req):
        e = req.json()
        table, shard = e["table"], int(e["shard"])
        with self._lock:
            if shard not in self.held.get(table, set()):
                from pilosa_tpu.api import ApiError
                raise ApiError(
                    f"worker does not hold {table}/shard {shard}", 409)
            self.wl.append(table, shard, e)
            n = self._apply_entry(e)
        return {"imported": n}

    def _apply_entry(self, e: dict) -> int:
        if e["op"] == "bits":
            return self.api.import_bits(
                e["table"], e["field"], rows=e["rows"], cols=e["cols"],
                timestamps=e.get("timestamps"))
        if e["op"] == "values":
            return self.api.import_values(
                e["table"], e["field"], cols=e["cols"],
                values=e["values"])
        if e["op"] == "clear":
            # record-level field clear (explicit NULL in an INSERT
            # tuple for bool/mutex) — logged like any write so
            # snapshot+tail recovery replays it in order
            return self.api.clear_field_columns(
                e["table"], e["field"], cols=e["cols"])
        raise ValueError(f"unknown write-log op {e['op']!r}")

    # -- snapshotting (dax/snapshotter; checkpoint = snapshot + trunc) --

    def snapshot_shard(self, table: str, shard: int):
        # under _lock vs concurrent _post_import: the recorded log
        # version must match the fragment rows exactly, or recovery
        # replays the wrong tail and drops an acknowledged write
        with self._lock:
            self._snapshot_shard_locked(table, shard)

    def _snapshot_shard_locked(self, table: str, shard: int):
        idx = self.api.holder.index(table)
        if idx is None:
            return
        version = self.wl.version(table, shard)
        rows = {}
        for f in idx.fields.values():
            for v in f.views.values():
                frag = v.fragment(shard)
                if frag is None:
                    continue
                for r in frag.row_ids:
                    rows[(f.name, v.name, r)] = frag.row_words(r)
        self.snaps.write(table, shard, version,
                         snapshot_fragment_rows(rows))
