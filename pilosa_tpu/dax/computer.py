"""ComputeNode — a stateless worker serving assigned shard jobs.

Reference: dax/computer/ + api_directive.go.  A worker is an ordinary
engine node (holder + API + HTTP) whose data is entirely reconstructed
from shared storage: on receiving a Directive it diffs desired vs held
shard jobs, materializes newly assigned shards from the latest
snapshot plus the write-log tail (api_directive.go:559 loadShard), and
drops revoked ones.  All writes append to the WriteLogger BEFORE
applying locally, so worker loss never loses acknowledged writes.

The disaggregated tier generalizes loadShard into the ShardHydrator
(dax/worker.py): with a BlobStore attached the worker boots with an
EMPTY data dir and hydrates assigned shards lazily from blob manifests
on first touch, paging residency through a private HBM-budget ledger;
without one it keeps the seed's eager local-disk semantics bit-exact.

TPU note: "apply locally" lands the bits in host fragments whose
device tiles refresh lazily — recovery is host-side log replay; the
chip just re-caches.
"""

from __future__ import annotations

import threading
import time

from pilosa_tpu.dax.directive import Directive
from pilosa_tpu.dax.snapshotter import (
    Snapshotter,
    snapshot_fragment_rows,
)
from pilosa_tpu.dax.worker import ShardHydrator
from pilosa_tpu.dax.writelogger import WriteLogger
from pilosa_tpu.storage.blob import BlobError


def _strip_keys(schema: dict) -> dict:
    """Worker-local schema with every keys flag cleared (ID-space
    compute; the queryer owns translation)."""
    out = {"indexes": []}
    for ix in schema.get("indexes", []):
        nix = dict(ix, keys=False)
        nix["fields"] = [
            dict(f, options=dict(f.get("options", {}), keys=False))
            for f in ix.get("fields", [])]
        out["indexes"].append(nix)
    return out


class ComputeNode:
    def __init__(self, address: str, writelogger: WriteLogger,
                 snapshotter: Snapshotter, bind: str = "127.0.0.1",
                 blob=None, lazy: bool | None = None,
                 budget_bytes: int | None = None):
        from pilosa_tpu.models.holder import Holder
        from pilosa_tpu.server.http import Server
        self.address = address
        self.wl = writelogger
        self.snaps = snapshotter
        self.server = Server(holder=Holder(), bind=bind)
        self.api = self.server.api
        self.directive_version = -1
        # table -> set of shards this worker currently serves
        self.held: dict[str, set[int]] = {}
        self._lock = threading.Lock()
        # in-flight read registration (the rebalance plane's RELEASE
        # discipline): non-paged queries execute OUTSIDE the node
        # lock, so a directive revoking a shard drains its registered
        # readers before freeing the fragments — an admitted read
        # always completes over intact data instead of racing the
        # release into a 409
        self._shard_readers: dict[tuple[str, int], int] = {}
        self._readers_cv = threading.Condition(self._lock)
        # bumped per directive-driven fragment release; a registered
        # reader seeing the epoch move under it (drain timeout only)
        # refuses its now-torn answer instead of returning it
        self._release_epoch: dict[tuple[str, int], int] = {}
        self.hyd = ShardHydrator(self, blob=blob,
                                 budget_bytes=budget_bytes, lazy=lazy)
        self.server.add_route("POST", "/directive", self._post_directive)
        self.server.add_route("POST", "/dax/import", self._post_import)
        self.server.add_route("GET", "/dax/held",
                              lambda req: {t: sorted(s) for t, s in
                                           self.held.items()})
        # the hydration plane: staged restore (migration COPY/CHASE),
        # tail seal (migration hand-off upload), residency snapshot
        # (autoscaler pressure signal + /debug/dax)
        self.server.add_route("POST", "/dax/hydrate", self._post_hydrate)
        self.server.add_route("POST", "/dax/seal", self._post_seal)
        self.server.add_route("GET", "/dax/residency",
                              lambda req: self.hyd.payload())
        # queries land on lazily-hydrated workers too: materialize the
        # touched shards before the standard handler executes
        self.server.add_route("POST", "/index/{index}/query",
                              self._post_query_hydrated,
                              admin_only=False, override=True)

    # -- lifecycle -----------------------------------------------------

    def open(self):
        self.server.start()
        self.uri = f"127.0.0.1:{self.server.port}"
        return self

    def close(self):
        self.server.close()

    # -- directive enactment (api_directive.go:19,172) -----------------

    def _post_directive(self, req):
        d = Directive.from_dict(req.json())
        self.apply_directive(d)
        return {"applied": d.version}

    def apply_directive(self, d: Directive):
        with self._lock:
            if d.version <= self.directive_version:
                return  # stale directive (api_directive.go version gate)
            if d.schema:
                # workers run in pure ID space: key translation is a
                # front-end (queryer) concern, exactly like the
                # reference's Remote=true queries shipping
                # pre-translated ids — so strip keys from the local
                # mirror and return raw row ids in results
                self.api.apply_schema(_strip_keys(d.schema))
            for table, want in d.assignments.items():
                want = set(want)
                have = self.held.get(table, set())
                self.held[table] = want
                for shard in sorted(want - have):
                    # lazy tier: record the assignment only — the
                    # shard hydrates from its blob manifest on first
                    # touch (or is already staged by a migration)
                    if not self.hyd.lazy:
                        self.hyd.ensure(table, shard, touch=False)
                for shard in sorted(have - want):
                    self._release_locked(table, shard)
            for table in list(self.held):
                if table not in d.assignments:
                    for shard in sorted(self.held[table]):
                        self._release_locked(table, shard)
                    del self.held[table]
            self.directive_version = d.version

    def _release_locked(self, table: str, shard: int):
        self._drain_readers_locked(table, shard)
        key = (table, shard)
        self._release_epoch[key] = self._release_epoch.get(key, 0) + 1
        self.hyd.release(table, shard)

    def _drain_readers_locked(self, table: str, shard: int,
                              timeout: float = 10.0):
        """Wait (bounded) for in-flight reads registered on a shard
        to finish before its fragments are freed.  `held` has already
        dropped the shard, so NEW reads 409 at entry and re-resolve;
        registered ones complete over intact data.  On timeout the
        release proceeds — the straggler's post-execution ownership
        check refuses the stale answer."""
        key = (table, shard)
        deadline = time.monotonic() + timeout
        while self._shard_readers.get(key, 0) > 0:
            left = deadline - time.monotonic()
            if left <= 0:
                break
            self._readers_cv.wait(left)

    def _load_shard(self, table: str, shard: int):
        """snapshot + write-log tail -> local fragments (kept as the
        seed's name for the eager path; the hydrator owns the logic)."""
        self.hyd.ensure(table, shard, touch=False)

    def _drop_shard(self, table: str, shard: int):
        self.hyd.release(table, shard)

    # -- hydration plane -----------------------------------------------

    def _blob_503(self, e: BlobError):
        from pilosa_tpu.api import ApiError
        raise ApiError(f"blob tier unavailable: {e}",
                       getattr(e, "status", 503))

    def _post_hydrate(self, req):
        """Migration COPY/CHASE entry: materialize (or tail-replay) a
        shard, held or merely staged — returns the replay lag the
        controller's DELTA-CHASE loop watches."""
        e = req.json() or {}
        table, shard = e["table"], int(e["shard"])
        try:
            with self._lock:
                replayed = self.hyd.ensure(table, shard, touch=False,
                                           chase=True)
                version = self.wl.version(table, shard)
        except BlobError as err:
            self._blob_503(err)
        return {"replayed": replayed, "version": version,
                "resident": True}

    def _post_seal(self, req):
        e = req.json() or {}
        table, shard = e["table"], int(e["shard"])
        try:
            with self._lock:
                n = self.hyd.seal_tail(table, shard)
        except BlobError as err:
            self._blob_503(err)
        return {"sealed": n}

    def _post_query_hydrated(self, req):
        """Override of the standard PQL endpoint: hydrate the touched
        held shards first, then delegate (the request body is cached
        on the Request, so the standard handler re-reads it safely).
        Budget-bounded workers page instead: hydrating everything up
        front would let the ledger evict early shards while late ones
        load, and the query would execute over missing fragments."""
        table = req.vars.get("index", "")
        body = req.json_lenient() or {}
        shards = body.get("shards")
        held = self.held.get(table, set())
        if shards is not None:
            touch = sorted({int(s) for s in shards})
            missing = [s for s in touch if s not in held]
            if missing:
                # a migration flip can land between the queryer's
                # routing and this execution: answering with the
                # released (empty) fragments would be a silent wrong
                # partial — refuse like the write path does, and the
                # queryer re-resolves ownership and retries
                from pilosa_tpu.api import ApiError
                raise ApiError(
                    f"worker {self.address} does not hold "
                    f"{table}/shards {missing}", 409)
        else:
            touch = sorted(held)
        if not touch:
            return self.server._post_query(req)
        keys = [(table, s) for s in touch]
        try:
            if self.hyd.budget_bytes > 0 and self.hyd.lazy:
                out = self._query_paged(req, table, touch, body)
                self.hyd.kick_warm()
                return out
            with self._lock:
                # re-check under the lock (a directive may have
                # landed since the fast-path check above), then
                # REGISTER the read: apply_directive drains
                # registered readers before freeing fragments, so
                # execution outside the lock still completes over
                # intact data even if ownership flips under it
                held = self.held.get(table, set())
                missing = [s for s in touch if s not in held]
                if missing:
                    from pilosa_tpu.api import ApiError
                    raise ApiError(
                        f"worker {self.address} does not hold "
                        f"{table}/shards {missing}", 409)
                for s in touch:
                    self.hyd.ensure(table, s)
                epochs = {k: self._release_epoch.get(k, 0)
                          for k in keys}
                for k in keys:
                    self._shard_readers[k] = \
                        self._shard_readers.get(k, 0) + 1
        except BlobError as err:
            self._blob_503(err)
        self.hyd.kick_warm()
        try:
            out = self.server._post_query(req)
        finally:
            with self._lock:
                stale = [k for k in keys
                         if self._release_epoch.get(k, 0)
                         != epochs[k]]
                for k in keys:
                    n = self._shard_readers.get(k, 0) - 1
                    if n <= 0:
                        self._shard_readers.pop(k, None)
                    else:
                        self._shard_readers[k] = n
                self._readers_cv.notify_all()
        if stale:
            # drain-timeout backstop: the fragments were freed while
            # this read was still registered — the answer is a torn
            # partial, refuse it so the queryer re-resolves
            from pilosa_tpu.api import ApiError
            gone = sorted(s for _, s in stale)
            raise ApiError(
                f"worker {self.address} does not hold {table}/shards "
                f"{gone} (released mid-query)", 409)
        return out

    def _query_paged(self, req, table: str, touch: list[int],
                     body: dict):
        """Execute the PQL over residency WINDOWS of shards — each
        window hydrated and PINNED (the ledger's reclaim skips pinned
        shards, so filling the window can only evict prior-window
        residue) — then reduce the per-window wire results with the
        same per-call reducers the queryer applies across workers.  A
        corpus 10x over the worker's budget serves bit-exact, just in
        more windows."""
        from pilosa_tpu.cluster.coordinator import (
            _empty_result,
            _reduce,
        )
        from pilosa_tpu.pql import parse
        from pilosa_tpu.server.http import _qos_from_headers
        pql = body.get("query", "")
        remote = bool(body.get("remote"))
        qos = _qos_from_headers(req.headers)
        q = parse(pql)
        partials = []
        i = 0
        while i < len(touch):
            with self._lock:
                batch: list[int] = []
                try:
                    while i < len(touch):
                        s = touch[i]
                        self.hyd.ensure(table, s)
                        r = self.hyd._resident.get((table, s))
                        if batch and r is not None \
                                and r.get("transient"):
                            # s didn't fit alongside the pinned
                            # window: close it; s leads the next one
                            break
                        batch.append(s)
                        self.hyd.pin(table, s)
                        i += 1
                    # execute under the node lock: nothing can evict
                    # a window member mid-query
                    out = self.api.query(table, pql, batch, False,
                                         remote=remote, qos=qos)
                finally:
                    self.hyd.unpin_all()
            partials.append(out["results"])
        if not partials:
            return {"results": [_empty_result(c) for c in q.calls]}
        return {"results": [
            _reduce(q.calls[ci], [p[ci] for p in partials])
            for ci in range(len(q.calls))]}

    # -- writes: log first, then apply ---------------------------------

    def _post_import(self, req):
        e = req.json()
        table, shard = e["table"], int(e["shard"])
        with self._lock:
            if shard not in self.held.get(table, set()):
                from pilosa_tpu.api import ApiError
                raise ApiError(
                    f"worker does not hold {table}/shard {shard}", 409)
            # hydrate BEFORE appending: the restore baseline must not
            # include the entry we are about to apply directly
            try:
                self.hyd.ensure(table, shard)
            except BlobError as err:
                self._blob_503(err)
            v = self.wl.append(table, shard, e)
            n = self._apply_entry(e)
            self.hyd.note_write(table, shard, v)
        return {"imported": n}

    def _apply_entry(self, e: dict) -> int:
        if e["op"] == "bits":
            return self.api.import_bits(
                e["table"], e["field"], rows=e["rows"], cols=e["cols"],
                timestamps=e.get("timestamps"))
        if e["op"] == "values":
            return self.api.import_values(
                e["table"], e["field"], cols=e["cols"],
                values=e["values"])
        if e["op"] == "clear":
            # record-level field clear (explicit NULL in an INSERT
            # tuple for bool/mutex) — logged like any write so
            # snapshot+tail recovery replays it in order
            return self.api.clear_field_columns(
                e["table"], e["field"], cols=e["cols"])
        raise ValueError(f"unknown write-log op {e['op']!r}")

    # -- snapshotting (dax/snapshotter; checkpoint = snapshot + trunc) --

    def snapshot_shard(self, table: str, shard: int):
        # under _lock vs concurrent _post_import: the recorded log
        # version must match the fragment rows exactly, or recovery
        # replays the wrong tail and drops an acknowledged write
        with self._lock:
            self._snapshot_shard_locked(table, shard)

    def _snapshot_shard_locked(self, table: str, shard: int):
        idx = self.api.holder.index(table)
        if idx is None:
            return
        version = self.wl.version(table, shard)
        rows = {}
        for f in idx.fields.values():
            for v in f.views.values():
                frag = v.fragment(shard)
                if frag is None:
                    continue
                for r in frag.row_ids:
                    rows[(f.name, v.name, r)] = frag.row_words(r)
        data = snapshot_fragment_rows(rows)
        self.snaps.write(table, shard, version, data)
        # the blob tier's upload point: the local snapshot + recorded
        # WAL version make this window crash-consistent
        self.hyd.upload_snapshot(table, shard, version, data)
