"""DAX service host — controller + queryer (+ workers) in one process.

Reference: dax/server/ — one binary can host any combination of the
controller, queryer, and computer services; tests and small
deployments run them all in-process (the test.Cluster analog for
DAX).
"""

from __future__ import annotations

import os

from pilosa_tpu.dax.computer import ComputeNode
from pilosa_tpu.dax.controller import Controller
from pilosa_tpu.dax.queryer import Queryer
from pilosa_tpu.dax.schemar import Schemar
from pilosa_tpu.dax.snapshotter import Snapshotter
from pilosa_tpu.dax.writelogger import WriteLogger


class DAXService:
    """All three services over one shared storage directory."""

    def __init__(self, storage_dir: str, n_workers: int = 2,
                 poll_interval: float = 0.5):
        self._storage_dir = storage_dir
        self._poll_interval = poll_interval
        self.wl = WriteLogger(os.path.join(storage_dir, "writelog"))
        self.snaps = Snapshotter(os.path.join(storage_dir, "snapshots"))
        self.controller = Controller(
            poll_interval=poll_interval,
            schemar=Schemar(os.path.join(storage_dir,
                                         "controller.db")))
        self.queryer = Queryer(
            self.controller,
            translate_dir=os.path.join(storage_dir, "queryer"))
        self.workers: list[ComputeNode] = []
        for i in range(n_workers):
            self.add_worker(f"worker{i}")

    def serve_queryer(self, bind: str = "127.0.0.1", port: int = 0):
        """HTTP front for the queryer — the dax/server single-binary
        surface: POST /sql (SQL over the fleet), POST
        /queryer/{table} (PQL), GET /dax/status (workers +
        assignments)."""
        from pilosa_tpu.models.holder import Holder
        from pilosa_tpu.server.http import Server

        front = Server(holder=Holder(), bind=bind, port=port)

        def front_sql(req):
            # both body forms of the standard /sql endpoint: raw SQL
            # text and {"sql": "..."}
            body = req.json_lenient()
            stmt = body.get("sql") if isinstance(body, dict) else None
            return self.queryer.sql(stmt if stmt is not None
                                    else req.text())

        front.add_route("POST", "/sql", front_sql, override=True)
        front.add_route(
            "POST", "/queryer/{table}",
            lambda req: self.queryer.query(
                req.vars["table"], (req.json() or {}).get("query",
                                                          "")))
        front.add_route(
            "GET", "/dax/status",
            lambda req: self.controller.status())
        self.queryer_front = front.start()
        return self.queryer_front

    def restart_controller(self):
        """Kill the controller process-state and boot a fresh one from
        the schemar DB (the reference's controller restart: schema +
        job registry + directive versions survive in the SQL store).
        Workers keep serving throughout."""
        self.controller.stop_poller()
        self.controller._schemar.close()
        self.controller = Controller(
            poll_interval=self._poll_interval,
            schemar=Schemar(os.path.join(self._storage_dir,
                                         "controller.db")))
        self.queryer.controller = self.controller
        return self.controller

    def add_worker(self, address: str) -> ComputeNode:
        w = ComputeNode(address, self.wl, self.snaps).open()
        self.workers.append(w)
        self.controller.register_worker(address, w.uri)
        return w

    def kill_worker(self, address: str):
        """Fault injection: stop the worker WITHOUT deregistering —
        the poller must notice (poller/poller.go behavior)."""
        for w in self.workers:
            if w.address == address:
                w.close()

    def close(self):
        front = getattr(self, "queryer_front", None)
        if front is not None:
            try:
                front.close()
            except Exception:
                pass
            self.queryer_front = None
        self.controller.stop_poller()
        for w in self.workers:
            try:
                w.close()
            except Exception:
                pass
