"""DAX service host — controller + queryer (+ workers) in one process.

Reference: dax/server/ — one binary can host any combination of the
controller, queryer, and computer services; tests and small
deployments run them all in-process (the test.Cluster analog for
DAX).

Two worker shapes coexist:

- ``add_worker``: the seed's shared-storage worker (one WriteLogger +
  Snapshotter directory for the fleet, eager shard loads) — the
  legacy arrangement every pre-tier test runs.
- ``add_blob_worker`` / ``add_standby``: the disaggregated shape — a
  PRIVATE empty data dir per worker, all durable state in the blob
  tier, lazy ledger-paged hydration.  ``start_autoscaler`` runs the
  controller's reconcile loop over them.
"""

from __future__ import annotations

import os

from pilosa_tpu.dax import settings
from pilosa_tpu.dax.computer import ComputeNode
from pilosa_tpu.dax.controller import Controller
from pilosa_tpu.dax.queryer import Queryer
from pilosa_tpu.dax.schemar import Schemar
from pilosa_tpu.dax.snapshotter import Snapshotter
from pilosa_tpu.dax.writelogger import WriteLogger


def blob_from_settings(storage_dir: str):
    """BlobStore per the [blob] stanza (None when no backend is
    configured or the tier kill-switch is off)."""
    from pilosa_tpu.storage.blob import BlobStore, make_backend
    if not settings.blob_enabled():
        return None
    kind = settings.backend()
    if not kind:
        return None
    root = settings.root() or os.path.join(storage_dir, "blob")
    return BlobStore(make_backend(kind, root))


class DAXService:
    """All three services over one shared storage directory."""

    def __init__(self, storage_dir: str, n_workers: int = 2,
                 poll_interval: float = 0.5, blob=None):
        self._storage_dir = storage_dir
        self._poll_interval = poll_interval
        self.blob = blob if blob is not None \
            else blob_from_settings(storage_dir)
        self.wl = WriteLogger(os.path.join(storage_dir, "writelog"))
        self.snaps = Snapshotter(os.path.join(storage_dir, "snapshots"))
        self.controller = Controller(
            poll_interval=poll_interval,
            schemar=Schemar(os.path.join(storage_dir,
                                         "controller.db")))
        self.queryer = Queryer(
            self.controller,
            translate_dir=os.path.join(storage_dir, "queryer"))
        self.workers: list[ComputeNode] = []
        for i in range(n_workers):
            self.add_worker(f"worker{i}")

    def serve_queryer(self, bind: str = "127.0.0.1", port: int = 0):
        """HTTP front for the queryer — the dax/server single-binary
        surface: POST /sql (SQL over the fleet), POST
        /queryer/{table} (PQL), GET /dax/status (workers +
        assignments)."""
        from pilosa_tpu.models.holder import Holder
        from pilosa_tpu.server.http import Server

        front = Server(holder=Holder(), bind=bind, port=port)

        def front_sql(req):
            # both body forms of the standard /sql endpoint: raw SQL
            # text and {"sql": "..."}
            body = req.json_lenient()
            stmt = body.get("sql") if isinstance(body, dict) else None
            return self.queryer.sql(stmt if stmt is not None
                                    else req.text())

        front.add_route("POST", "/sql", front_sql, override=True)
        front.add_route(
            "POST", "/queryer/{table}",
            lambda req: self.queryer.query(
                req.vars["table"], (req.json() or {}).get("query",
                                                          "")))
        front.add_route(
            "GET", "/dax/status",
            lambda req: self.controller.status())
        self.queryer_front = front.start()
        return self.queryer_front

    def restart_controller(self):
        """Kill the controller process-state and boot a fresh one from
        the schemar DB (the reference's controller restart: schema +
        job registry + directive versions survive in the SQL store).
        Workers keep serving throughout."""
        self.controller.stop_reconciler()
        self.controller.stop_poller()
        self.controller._schemar.close()
        self.controller = Controller(
            poll_interval=self._poll_interval,
            schemar=Schemar(os.path.join(self._storage_dir,
                                         "controller.db")))
        self.queryer.controller = self.controller
        return self.controller

    def add_worker(self, address: str) -> ComputeNode:
        """Shared-storage worker (the seed arrangement).  When the
        service has a blob tier, the worker writes through to it on
        snapshot and hydrates lazily from it."""
        w = ComputeNode(address, self.wl, self.snaps,
                        blob=self.blob).open()
        self.workers.append(w)
        self.controller.register_worker(address, w.uri)
        return w

    # -- the disaggregated shape ---------------------------------------

    def _stateless_node(self, address: str,
                        budget_bytes: int | None = None
                        ) -> ComputeNode:
        if self.blob is None:
            raise RuntimeError(
                "stateless workers need a blob tier (configure "
                "[blob] backend or pass blob=)")
        d = os.path.join(self._storage_dir, "workers", address)
        w = ComputeNode(
            address,
            WriteLogger(os.path.join(d, "writelog")),
            Snapshotter(os.path.join(d, "snapshots")),
            blob=self.blob, lazy=True,
            budget_bytes=budget_bytes).open()
        self.workers.append(w)
        return w

    def add_blob_worker(self, address: str,
                        budget_bytes: int | None = None
                        ) -> ComputeNode:
        """A stateless worker: boots with an EMPTY private data dir
        and hydrates assigned shards from blob manifests on first
        touch, paged through its own HBM-budget ledger."""
        w = self._stateless_node(address, budget_bytes)
        self.controller.register_worker(address, w.uri)
        return w

    def add_standby(self, address: str,
                    budget_bytes: int | None = None) -> ComputeNode:
        """A warm spare the autoscaler can admit: boots, health-
        checks, holds nothing until a scale-out."""
        w = self._stateless_node(address, budget_bytes)
        self.controller.register_standby(address, w.uri)
        return w

    def start_autoscaler(self, interval: float | None = None):
        self.controller.start_reconciler(interval)
        return self

    def kill_worker(self, address: str):
        """Fault injection: stop the worker WITHOUT deregistering —
        the poller must notice (poller/poller.go behavior)."""
        for w in self.workers:
            if w.address == address:
                w.close()

    def close(self):
        front = getattr(self, "queryer_front", None)
        if front is not None:
            try:
                front.close()
            except Exception:
                pass
            self.queryer_front = None
        self.controller.stop_reconciler()
        self.controller.stop_poller()
        for w in self.workers:
            try:
                w.close()
            except Exception:
                pass
