"""Controller — worker registry, shard-job balancer, health poller,
and the disaggregated tier's autoscaler.

Reference: dax/controller/ — RegisterNode/DeregisterNode, the
balancer spreading table-shard jobs across workers
(balancer/balancer.go), the schemar (schema store), and the Poller
that health-checks workers and triggers rebalancing when one dies
(poller/poller.go:14-60): dead worker -> its jobs reassign to
survivors -> new Directives pushed -> workers recover the shards from
snapshot + write-log.

The tier additions (this build's dax/worker.py + storage/blob.py):

- **Placement overlay**: a durable (table, shard) -> address map
  layered over jump-hash placement, so admitting or draining a worker
  moves shards ONE AT A TIME through the live-migration state
  machine instead of a big-bang directive flip.
- **Live migration** (``migrate_shard``): snapshot-copy (staged blob
  hydrate on the target, sourcing the blob manifest so a dead donor
  is a non-event) -> delta-chase (seal donor tail, chase on target,
  bounded rounds) -> fence (writers hold at the queryer) -> flip
  (overlay + directives) -> release (donor drops by reference).
- **Reconcile loop** (``reconcile_once``): watches SLO burn rate,
  per-worker ledger pressure (GET /dax/residency), and admission
  shed counts; past the scale-out threshold it admits a standby and
  migrates its jump-hash share live, past the scale-in threshold it
  drains the last-admitted worker back to standby.  Every decision
  leaves an incident-grade audit bundle (obs/incidents.py,
  dax-scale-out / dax-scale-in) with trigger signals, plan, and
  per-shard outcomes.
"""

from __future__ import annotations

import json
import threading
import time
import weakref

from pilosa_tpu.cluster.client import InternalClient
from pilosa_tpu.cluster.hash import jump_hash
from pilosa_tpu.dax import settings
from pilosa_tpu.dax.directive import Directive
from pilosa_tpu.obs import faults, incidents, metrics
from pilosa_tpu.storage.translate import shard_to_shard_partition

# /debug/dax roster: live controllers, weakly held
_controllers: "weakref.WeakSet[Controller]" = weakref.WeakSet()


def controller_payloads() -> list[dict]:
    return [c.debug_payload() for c in list(_controllers)]


class NoWorkersError(Exception):
    pass


def _place(table: str, shard: int, addrs: list[str]) -> str:
    """Stable shard-job placement: fnv partition -> jump hash onto the
    sorted worker list (balancer/balancer.go goal; same scheme as the
    cluster layer, disco/hasher.go:16).  Adding a shard or a worker
    moves only ~1/n of the jobs — no mass snapshot+replay churn."""
    p = shard_to_shard_partition(table, shard)
    return addrs[jump_hash(p, len(addrs))]


class Controller:
    def __init__(self, poll_interval: float = 1.0, schemar=None):
        self.workers: dict[str, str] = {}       # address -> uri
        self.standbys: dict[str, str] = {}      # warm, no assignments
        self.schema: dict = {}
        # bumped on every schema mutation (apply/drop/reload): cheap
        # cache token for schema-derived facts (queryer keyedness)
        self.schema_version = 0
        # table -> sorted shard ids registered for it
        self.tables: dict[str, set[int]] = {}
        self._versions: dict[str, int] = {}     # per-worker directive ver
        # per-worker fingerprint of the last ENACTED directive content
        # (schema + assignments): unchanged workers are skipped — the
        # api_directive.go:172 diff, lifted to the push side so a
        # rebalance only touches the workers whose jobs moved
        self._pushed: dict[str, str] = {}
        # placement overlay: (table, shard) -> address pins outranking
        # jump hash while a scale event migrates shards one at a time
        self.overlay: dict[tuple[str, int], str] = {}
        # autoscaler-admitted workers, admit order (scale-in drains
        # the most recent first)
        self._admitted: list[str] = []
        # worker mid-drain: a partial scale-in resumes THIS drain
        # instead of the generic pin-resume (which would move the
        # already-drained shards straight back)
        self._draining: str | None = None
        # write fences during migration FENCE phase: the queryer's
        # import fan-out holds on fence_wait until the flip lands
        self._fences: dict[tuple[str, int], threading.Event] = {}
        self.last_reconcile: dict = {}
        self._last_scale_ts = 0.0
        self._last_shed: float | None = None
        self._lock = threading.RLock()
        self._poll_interval = poll_interval
        self._poll_stop = threading.Event()
        self._poll_thread: threading.Thread | None = None
        self._recon_stop = threading.Event()
        self._recon_thread: threading.Thread | None = None
        self._client = InternalClient(timeout=5.0)
        # durable state (dax/controller/schemar + Transactor): every
        # registry mutation write-throughs; a restarted controller
        # reloads the world and its next rebalance is a DELTA (the
        # reloaded fingerprints skip workers whose jobs are unchanged)
        self._schemar = schemar
        if schemar is not None:
            st = schemar.load()
            self.workers = st["workers"]
            self.schema = st["schema"]
            self.schema_version += 1
            self.tables = st["tables"]
            self._versions = st["versions"]
            self._pushed = st["pushed"]
            for ix in self.schema.get("indexes", []):
                self.tables.setdefault(ix["name"], set())
            raw = schemar.load_kv("dax_overlay")
            if raw:
                self.overlay = {(t, int(s)): a
                                for t, s, a in json.loads(raw)}
            raw = schemar.load_kv("dax_standbys")
            if raw:
                self.standbys = json.loads(raw)
            raw = schemar.load_kv("dax_admitted")
            if raw:
                self._admitted = json.loads(raw)
            raw = schemar.load_kv("dax_draining")
            if raw:
                self._draining = json.loads(raw)
        _controllers.add(self)

    # -- registry ------------------------------------------------------

    def register_worker(self, address: str, uri: str):
        with self._lock:
            self.workers[address] = uri
            # a worker re-registering at the same address is FRESH
            # (restart): drop the fingerprint so the delta-push does
            # not skip its directive (review r04) — atomically in the
            # schemar too, or a controller restart could reload the
            # stale fingerprint and skip the fresh worker forever
            self._pushed.pop(address, None)
            if self._schemar is not None:
                self._schemar.register_worker(
                    address, uri, self._versions.get(address, 0))
            self._rebalance_locked()

    def register_standby(self, address: str, uri: str):
        """A warm spare: health-polled, schema-less, holding nothing —
        the autoscaler's scale-out admits it into the roster."""
        with self._lock:
            self.standbys[address] = uri
            self._save_scale_state_locked()

    def deregister_worker(self, address: str):
        with self._lock:
            self._drop_worker_locked(address)
            self._rebalance_locked()

    def _drop_worker_locked(self, address: str):
        self.workers.pop(address, None)
        self._versions.pop(address, None)
        self._pushed.pop(address, None)
        if address in self._admitted:
            self._admitted.remove(address)
        # pins to a gone worker are meaningless: placement falls back
        # to jump hash over the survivors
        stale = [k for k, a in self.overlay.items() if a == address]
        for k in stale:
            del self.overlay[k]
        if self._schemar is not None:
            self._schemar.delete_worker(address)
            if stale:
                self._save_overlay_locked()
            self._save_scale_state_locked()

    def _save_overlay_locked(self):
        if self._schemar is not None:
            self._schemar.save_kv("dax_overlay", json.dumps(
                sorted([t, s, a]
                       for (t, s), a in self.overlay.items())))

    def _save_scale_state_locked(self):
        if self._schemar is not None:
            self._schemar.save_kv("dax_standbys",
                                  json.dumps(self.standbys))
            self._schemar.save_kv("dax_admitted",
                                  json.dumps(self._admitted))
            self._schemar.save_kv("dax_draining",
                                  json.dumps(self._draining))

    # -- schema (dax/controller schemar) -------------------------------

    def apply_schema(self, schema: dict):
        with self._lock:
            self.schema = schema
            self.schema_version += 1
            for ix in schema.get("indexes", []):
                self.tables.setdefault(ix["name"], set())
            if self._schemar is not None:
                self._schemar.save_schema(schema)
            self._push_directives_locked()

    def drop_table(self, table: str):
        """Remove a table fleet-wide: schema + shard jobs + fresh
        directives so workers drop their held shards."""
        with self._lock:
            self.tables.pop(table, None)
            self.schema_version += 1
            for k in [k for k in self.overlay if k[0] == table]:
                del self.overlay[k]
            if self.schema:
                self.schema = {
                    "indexes": [ix for ix in
                                self.schema.get("indexes", [])
                                if ix.get("name") != table]}
            if self._schemar is not None:
                self._schemar.drop_table(table)
                self._schemar.save_schema(self.schema)
                self._save_overlay_locked()
            self._push_directives_locked()

    def add_shards(self, table: str, shards):
        """New shards observed (ingest registers them before writing)."""
        with self._lock:
            have = self.tables.setdefault(table, set())
            new = set(map(int, shards)) - have
            if not new:
                return
            have |= new
            if self._schemar is not None:
                self._schemar.add_shards(table, new)
            self._push_directives_locked()

    def status(self) -> dict:
        """Locked snapshot for the queryer front's /dax/status."""
        with self._lock:
            return {
                "workers": sorted(self.workers),
                "standbys": sorted(self.standbys),
                "assignments": self._assignments_locked(),
                "tables": {t: sorted(s)
                           for t, s in self.tables.items()},
            }

    # -- balance (balancer/balancer.go + placement overlay) ------------

    def _owner_locked(self, table: str, shard: int,
                      addrs: list[str] | None = None) -> str:
        a = self.overlay.get((table, shard))
        if a is not None and a in self.workers:
            return a
        if addrs is None:
            addrs = sorted(self.workers)
        return _place(table, shard, addrs)

    def assignments(self) -> dict[str, dict[str, list[int]]]:
        """worker address -> {table: [shards]} under the current
        balance."""
        with self._lock:
            return self._assignments_locked()

    def _assignments_locked(self) -> dict[str, dict[str, list[int]]]:
        addrs = sorted(self.workers)
        out = {a: {} for a in addrs}
        if not addrs:
            return out
        for table, shards in sorted(self.tables.items()):
            for shard in sorted(shards):
                a = self._owner_locked(table, shard, addrs)
                out[a].setdefault(table, []).append(shard)
        return out

    def worker_for(self, table: str, shard: int) -> tuple[str, str]:
        """(address, uri) of the worker owning a shard job."""
        with self._lock:
            if not self.workers:
                raise NoWorkersError("no compute workers registered")
            a = self._owner_locked(table, shard)
            return a, self.workers[a]

    def _rebalance_locked(self):
        self._push_directives_locked()

    def _push_directives_locked(self):
        """Compute the plan under the lock, POST directives OUTSIDE it
        (a hung worker must not stall worker_for/add_shards for its
        whole HTTP timeout), then prune workers that refused."""
        import hashlib
        while True:
            plan = self._assignments_locked()
            targets = []
            for addr, asg in plan.items():
                content = hashlib.sha256(json.dumps(
                    [self.schema, asg],
                    sort_keys=True).encode()).hexdigest()
                if self._pushed.get(addr) == content:
                    continue  # nothing changed for this worker
                self._versions[addr] = self._versions.get(addr, 0) + 1
                targets.append((addr, self.workers[addr], Directive(
                    address=addr, version=self._versions[addr],
                    schema=self.schema, assignments=asg), content))
            self._lock.release()
            dead = []
            ok = []
            try:
                for addr, uri, d, content in targets:
                    try:
                        self._client._request(uri, "POST", "/directive",
                                              d.to_dict())
                        ok.append((addr, content))
                    except Exception:
                        dead.append(addr)
            finally:
                self._lock.acquire()
            for addr, content in ok:
                # the worker may have been deregistered during the
                # unlocked POST window — do not resurrect its entry
                if addr in self.workers:
                    self._pushed[addr] = content
                    if self._schemar is not None:
                        self._schemar.save_worker_state(
                            addr, self._versions.get(addr, 0),
                            content)
            if not dead:
                return
            for addr in dead:
                # a worker that can't take its directive is gone;
                # removing it reassigns its jobs to the survivors
                self._drop_worker_locked(addr)
            if not self.workers:
                return

    # -- live migration (the PR 14 state machine, worker-pool form) ----

    def fence_wait(self, table: str, shard: int,
                   timeout: float = 10.0):
        """Writers hold here while a migration FENCE is up for the
        shard; returns immediately when no fence is set."""
        ev = self._fences.get((table, shard))
        if ev is not None:
            ev.wait(timeout)

    def _chase_round(self, table: str, shard: int, donor_uri,
                     to_uri) -> int:
        """One seal+hydrate round: the donor seals its live tail into
        a blob segment (no-op without a blob tier or a dead donor),
        the target chases it.  Returns entries the target replayed."""
        if donor_uri is not None:
            try:
                self._client._request(
                    donor_uri, "POST", "/dax/seal",
                    {"table": table, "shard": shard})
            except Exception:
                donor_uri = None  # dead donor: blob manifest suffices
        r = self._client._request(to_uri, "POST", "/dax/hydrate",
                                  {"table": table, "shard": shard})
        return int(r.get("replayed", 0))

    def migrate_shard(self, table: str, shard: int,
                      to_addr: str) -> str:
        """Move one shard job live: COPY -> DELTA-CHASE -> FENCE ->
        flip -> RELEASE.  The copy sources the blob manifest, so a
        gone donor degrades to a plain cold restore."""
        key = (table, shard)
        with self._lock:
            to_uri = self.workers.get(to_addr)
            donor = self._owner_locked(table, shard) \
                if self.workers else None
            donor_uri = self.workers.get(donor) if donor else None
        if to_uri is None:
            return "failed:unknown-target"
        if donor == to_addr:
            return "noop"
        detail = f"{table}/{shard}->{to_addr}"
        # COPY: staged hydrate on the target (snapshot + segments +
        # shared-log tail), then bounded DELTA-CHASE until the lag
        # per round is small enough to fence over
        faults.fire("scale-event-interrupted", f"{detail}:copy")
        lag = self._chase_round(table, shard, donor_uri, to_uri)
        for _ in range(settings.chase_rounds()):
            if lag <= settings.chase_lag():
                break
            faults.fire("scale-event-interrupted", f"{detail}:chase")
            lag = self._chase_round(table, shard, donor_uri, to_uri)
        faults.fire("scale-event-interrupted", f"{detail}:fence")
        ev = threading.Event()
        self._fences[key] = ev
        try:
            # pre-flip round bounds the post-flip catch-up; new
            # writers are already holding at the fence
            self._chase_round(table, shard, donor_uri, to_uri)
            faults.fire("scale-event-interrupted", f"{detail}:flip")
            # grant the recipient its post-flip assignment BEFORE the
            # overlay becomes visible to the read plane: worker_for
            # must never name an owner that has not applied the grant
            # yet, or the queryer's 409 retry loop spins against the
            # same address until the directive push lands (and can
            # exhaust its attempts under load).  The grant rides
            # OUTSIDE the lock — a hung recipient must not stall
            # worker_for — while the donor still owns and serves.
            import hashlib
            with self._lock:
                prev = self.overlay.get(key)
                self.overlay[key] = to_addr
                asg = self._assignments_locked().get(to_addr, {})
                if prev is None:
                    self.overlay.pop(key, None)
                else:
                    self.overlay[key] = prev
                content = hashlib.sha256(json.dumps(
                    [self.schema, asg],
                    sort_keys=True).encode()).hexdigest()
                self._versions[to_addr] = \
                    self._versions.get(to_addr, 0) + 1
                grant = Directive(
                    address=to_addr, version=self._versions[to_addr],
                    schema=self.schema, assignments=asg)
            self._client._request(to_uri, "POST", "/directive",
                                  grant.to_dict())
            with self._lock:
                self._pushed[to_addr] = content
                self.overlay[key] = to_addr
                self._save_overlay_locked()
                self._push_directives_locked()
            # post-flip catch-up: any write that raced the fence
            # landed on the donor's log BEFORE its directive applied
            # (after, it 409s) — seal once more and chase it over;
            # the donor has already released the fragments, but
            # sealing reads the log, not the fragments
            self._chase_round(table, shard, donor_uri, to_uri)
        finally:
            self._fences.pop(key, None)
            ev.set()
        return "done"

    def _pending_moves_locked(self) -> list[tuple[tuple[str, int], str]]:
        """Overlay pins that disagree with jump-hash placement — the
        resumable remainder of an interrupted scale event."""
        addrs = sorted(self.workers)
        if not addrs:
            return []
        out = []
        for (t, s), a in sorted(self.overlay.items()):
            if a not in self.workers:
                continue
            want = _place(t, s, addrs)
            if want != a and s in self.tables.get(t, ()):
                out.append(((t, s), want))
        return out

    def _prune_overlay_locked(self):
        addrs = sorted(self.workers)
        done = [k for k, a in self.overlay.items()
                if addrs and _place(k[0], k[1], addrs) == a]
        for k in done:
            del self.overlay[k]
        if done:
            self._save_overlay_locked()

    # -- autoscaler (reconcile loop) -----------------------------------

    def signals(self) -> dict:
        """The reconcile inputs: worst SLO burn rate across windows,
        per-worker ledger pressure, cumulative admission/ingest shed
        count (+ delta since the last reconcile)."""
        burn = 0.0
        try:
            from pilosa_tpu.obs import slo
            payload = slo.get().evaluate()
            for s in payload.get("slos", {}).values():
                for w in s.get("windows", {}).values():
                    burn = max(burn, float(w.get("burn_rate", 0.0)))
        except Exception:
            pass
        pressure = {}
        with self._lock:
            workers = dict(self.workers)
        for addr, uri in workers.items():
            try:
                r = self._client._request(uri, "GET",
                                          "/dax/residency")
                pressure[addr] = round(float(
                    r.get("pressure", 0.0)), 4)
            except Exception:
                pressure[addr] = 0.0
        shed = (metrics.ADMISSION_TOTAL.total(outcome="shed")
                + metrics.INGEST_SHED.total())
        delta = 0.0 if self._last_shed is None \
            else shed - self._last_shed
        self._last_shed = shed
        return {"burn": round(burn, 4), "pressure": pressure,
                "shed": shed, "shed_delta": delta}

    def reconcile_once(self) -> dict:
        """One autoscaler pass: resume any interrupted migration
        first, then weigh the scale thresholds.  Every decision that
        acts files a dax-scale-* incident bundle."""
        sig = self.signals()
        decision: dict = {"signals": sig, "action": "none",
                          "ts": time.time()}
        with self._lock:
            draining = self._draining
            if draining is not None and draining not in self.workers:
                self._draining = draining = None
                self._save_scale_state_locked()
            pending = [] if draining else self._pending_moves_locked()
        if draining:
            decision.update(self._scale_in(sig))
            decision["action"] = "resume-drain"
        elif pending:
            decision["action"] = "resume"
            decision["outcomes"] = self._run_moves(pending)
            with self._lock:
                self._prune_overlay_locked()
        else:
            now = time.monotonic()
            cooled = (now - self._last_scale_ts
                      >= settings.cooldown_s())
            worst_pressure = max(sig["pressure"].values(),
                                 default=0.0)
            with self._lock:
                n_workers = len(self.workers)
                has_standby = bool(self.standbys)
            if cooled and n_workers < settings.max_workers() \
                    and has_standby \
                    and (sig["burn"] >= settings.scale_out_burn()
                         or worst_pressure
                         >= settings.pressure_high()):
                decision.update(self._scale_out(sig))
                self._last_scale_ts = now
            elif cooled and n_workers > settings.min_workers() \
                    and self._admitted \
                    and sig["burn"] <= settings.scale_in_burn() \
                    and worst_pressure < settings.pressure_high():
                decision.update(self._scale_in(sig))
                self._last_scale_ts = now
        self.last_reconcile = decision
        return decision

    def _run_moves(self, moves) -> dict:
        outcomes = {}
        for (t, s), target in moves:
            try:
                outcomes[f"{t}/{s}"] = self.migrate_shard(t, s,
                                                          target)
            except Exception as e:
                outcomes[f"{t}/{s}"] = f"failed:{e}"
        return outcomes

    def _scale_out(self, sig: dict) -> dict:
        with self._lock:
            address = sorted(self.standbys)[0]
            uri = self.standbys.pop(address)
            # pin every placed shard to its current owner FIRST, so
            # admitting the worker moves nothing by itself — the
            # moves then happen one at a time through the fenced
            # state machine
            addrs = sorted(self.workers)
            for t, shards in self.tables.items():
                for s in shards:
                    self.overlay[(t, s)] = self._owner_locked(
                        t, s, addrs)
            self.workers[address] = uri
            self._pushed.pop(address, None)
            self._admitted.append(address)
            self._save_overlay_locked()
            self._save_scale_state_locked()
            if self._schemar is not None:
                self._schemar.register_worker(
                    address, uri, self._versions.get(address, 0))
            # the admitted worker's first directive: schema, no jobs
            self._push_directives_locked()
            plan = self._pending_moves_locked()
        outcomes = self._run_moves(plan)
        with self._lock:
            self._prune_overlay_locked()
        ok = all(v in ("done", "noop") for v in outcomes.values())
        outcome = "done" if ok else "partial"
        metrics.DAX_SCALE_EVENTS.inc(direction="out",
                                     outcome=outcome)
        incidents.report(
            "dax-scale-out", f"admitted {address}",
            context={"signals": sig, "admitted": address,
                     "plan": [f"{t}/{s}" for (t, s), _ in plan],
                     "outcomes": outcomes})
        return {"action": "scale-out", "worker": address,
                "outcome": outcome, "outcomes": outcomes}

    def _scale_in(self, sig: dict) -> dict:
        with self._lock:
            address = self._draining or self._admitted[-1]
            uri = self.workers.get(address)
            if uri is None:
                if address in self._admitted:
                    self._admitted.remove(address)
                self._draining = None
                self._save_scale_state_locked()
                return {"action": "scale-in",
                        "outcome": "skipped:gone"}
            self._draining = address
            self._save_scale_state_locked()
            survivors = sorted(a for a in self.workers
                               if a != address)
            moves = [((t, s), _place(t, s, survivors))
                     for t, shards in sorted(self.tables.items())
                     for s in sorted(shards)
                     if self._owner_locked(t, s) == address]
        outcomes = self._run_moves(moves)
        ok = all(v in ("done", "noop") for v in outcomes.values())
        if ok:
            with self._lock:
                self._drop_worker_locked(address)
                self.standbys[address] = uri   # back to the warm pool
                self._draining = None
                self._prune_overlay_locked()
                self._save_scale_state_locked()
                self._push_directives_locked()
        # a partial drain leaves the worker IN the roster still
        # owning the unmigrated shards — the next reconcile's
        # scale-in pass retries exactly those
        outcome = "done" if ok else "partial"
        metrics.DAX_SCALE_EVENTS.inc(direction="in", outcome=outcome)
        incidents.report(
            "dax-scale-in", f"drained {address}",
            context={"signals": sig, "drained": address,
                     "plan": [f"{t}/{s}" for (t, s), _ in moves],
                     "outcomes": outcomes})
        return {"action": "scale-in", "worker": address,
                "outcome": outcome, "outcomes": outcomes}

    def start_reconciler(self, interval: float | None = None):
        iv = settings.reconcile_interval_s() \
            if interval is None else interval
        self._recon_thread = threading.Thread(
            target=self._recon_loop, args=(iv,), daemon=True)
        self._recon_thread.start()
        return self

    def stop_reconciler(self):
        self._recon_stop.set()
        if self._recon_thread:
            self._recon_thread.join(timeout=7)

    def _recon_loop(self, interval: float):
        while not self._recon_stop.wait(interval):
            try:
                self.reconcile_once()
            except Exception:
                pass  # the reconciler must outlive one bad pass

    def debug_payload(self) -> dict:
        with self._lock:
            return {
                "workers": sorted(self.workers),
                "standbys": sorted(self.standbys),
                "admitted": list(self._admitted),
                "overlay": {f"{t}/{s}": a
                            for (t, s), a in
                            sorted(self.overlay.items())},
                "fenced": [f"{t}/{s}"
                           for t, s in sorted(self._fences)],
                "last_reconcile": self.last_reconcile,
            }

    # -- poller (dax/controller/poller/poller.go) ----------------------

    def start_poller(self):
        self._poll_thread = threading.Thread(target=self._poll_loop,
                                             daemon=True)
        self._poll_thread.start()
        return self

    def stop_poller(self):
        self._poll_stop.set()
        if self._poll_thread:
            # outlast the health-check HTTP timeout (5s): a caller
            # about to close the schemar DB must not race a poll
            # cycle still blocked on a dead worker
            self._poll_thread.join(timeout=7)

    def _poll_loop(self):
        while not self._poll_stop.wait(self._poll_interval):
            self.poll_once()

    def poll_once(self):
        """Health-check every worker (standbys included); rebalance
        away from dead ones."""
        with self._lock:
            workers = dict(self.workers)
            standbys = dict(self.standbys)
        dead = []
        dead_standbys = []
        for addr, uri in workers.items():
            try:
                self._client._request(uri, "GET", "/status")
            except Exception:
                dead.append(addr)
        for addr, uri in standbys.items():
            try:
                self._client._request(uri, "GET", "/status")
            except Exception:
                dead_standbys.append(addr)
        if dead or dead_standbys:
            with self._lock:
                for addr in dead:
                    self._drop_worker_locked(addr)
                for addr in dead_standbys:
                    self.standbys.pop(addr, None)
                if dead_standbys:
                    self._save_scale_state_locked()
                if dead:
                    self._rebalance_locked()
        return dead
