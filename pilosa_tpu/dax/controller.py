"""Controller — worker registry, shard-job balancer, health poller.

Reference: dax/controller/ — RegisterNode/DeregisterNode, the
balancer spreading table-shard jobs across workers
(balancer/balancer.go), the schemar (schema store), and the Poller
that health-checks workers and triggers rebalancing when one dies
(poller/poller.go:14-60): dead worker -> its jobs reassign to
survivors -> new Directives pushed -> workers recover the shards from
snapshot + write-log.
"""

from __future__ import annotations

import threading

from pilosa_tpu.cluster.client import InternalClient
from pilosa_tpu.cluster.hash import jump_hash
from pilosa_tpu.dax.directive import Directive
from pilosa_tpu.storage.translate import shard_to_shard_partition


class NoWorkersError(Exception):
    pass


def _place(table: str, shard: int, addrs: list[str]) -> str:
    """Stable shard-job placement: fnv partition -> jump hash onto the
    sorted worker list (balancer/balancer.go goal; same scheme as the
    cluster layer, disco/hasher.go:16).  Adding a shard or a worker
    moves only ~1/n of the jobs — no mass snapshot+replay churn."""
    p = shard_to_shard_partition(table, shard)
    return addrs[jump_hash(p, len(addrs))]


class Controller:
    def __init__(self, poll_interval: float = 1.0, schemar=None):
        self.workers: dict[str, str] = {}       # address -> uri
        self.schema: dict = {}
        # bumped on every schema mutation (apply/drop/reload): cheap
        # cache token for schema-derived facts (queryer keyedness)
        self.schema_version = 0
        # table -> sorted shard ids registered for it
        self.tables: dict[str, set[int]] = {}
        self._versions: dict[str, int] = {}     # per-worker directive ver
        # per-worker fingerprint of the last ENACTED directive content
        # (schema + assignments): unchanged workers are skipped — the
        # api_directive.go:172 diff, lifted to the push side so a
        # rebalance only touches the workers whose jobs moved
        self._pushed: dict[str, str] = {}
        self._lock = threading.RLock()
        self._poll_interval = poll_interval
        self._poll_stop = threading.Event()
        self._poll_thread: threading.Thread | None = None
        self._client = InternalClient(timeout=5.0)
        # durable state (dax/controller/schemar + Transactor): every
        # registry mutation write-throughs; a restarted controller
        # reloads the world and its next rebalance is a DELTA (the
        # reloaded fingerprints skip workers whose jobs are unchanged)
        self._schemar = schemar
        if schemar is not None:
            st = schemar.load()
            self.workers = st["workers"]
            self.schema = st["schema"]
            self.schema_version += 1
            self.tables = st["tables"]
            self._versions = st["versions"]
            self._pushed = st["pushed"]
            for ix in self.schema.get("indexes", []):
                self.tables.setdefault(ix["name"], set())

    # -- registry ------------------------------------------------------

    def register_worker(self, address: str, uri: str):
        with self._lock:
            self.workers[address] = uri
            # a worker re-registering at the same address is FRESH
            # (restart): drop the fingerprint so the delta-push does
            # not skip its directive (review r04) — atomically in the
            # schemar too, or a controller restart could reload the
            # stale fingerprint and skip the fresh worker forever
            self._pushed.pop(address, None)
            if self._schemar is not None:
                self._schemar.register_worker(
                    address, uri, self._versions.get(address, 0))
            self._rebalance_locked()

    def deregister_worker(self, address: str):
        with self._lock:
            self._drop_worker_locked(address)
            self._rebalance_locked()

    def _drop_worker_locked(self, address: str):
        self.workers.pop(address, None)
        self._versions.pop(address, None)
        self._pushed.pop(address, None)
        if self._schemar is not None:
            self._schemar.delete_worker(address)

    # -- schema (dax/controller schemar) -------------------------------

    def apply_schema(self, schema: dict):
        with self._lock:
            self.schema = schema
            self.schema_version += 1
            for ix in schema.get("indexes", []):
                self.tables.setdefault(ix["name"], set())
            if self._schemar is not None:
                self._schemar.save_schema(schema)
            self._push_directives_locked()

    def drop_table(self, table: str):
        """Remove a table fleet-wide: schema + shard jobs + fresh
        directives so workers drop their held shards."""
        with self._lock:
            self.tables.pop(table, None)
            self.schema_version += 1
            if self.schema:
                self.schema = {
                    "indexes": [ix for ix in
                                self.schema.get("indexes", [])
                                if ix.get("name") != table]}
            if self._schemar is not None:
                self._schemar.drop_table(table)
                self._schemar.save_schema(self.schema)
            self._push_directives_locked()

    def add_shards(self, table: str, shards):
        """New shards observed (ingest registers them before writing)."""
        with self._lock:
            have = self.tables.setdefault(table, set())
            new = set(map(int, shards)) - have
            if not new:
                return
            have |= new
            if self._schemar is not None:
                self._schemar.add_shards(table, new)
            self._push_directives_locked()

    def status(self) -> dict:
        """Locked snapshot for the queryer front's /dax/status."""
        with self._lock:
            return {
                "workers": sorted(self.workers),
                "assignments": self._assignments_locked(),
                "tables": {t: sorted(s)
                           for t, s in self.tables.items()},
            }

    # -- balance (balancer/balancer.go) --------------------------------

    def assignments(self) -> dict[str, dict[str, list[int]]]:
        """worker address -> {table: [shards]} under the current
        balance."""
        with self._lock:
            return self._assignments_locked()

    def _assignments_locked(self) -> dict[str, dict[str, list[int]]]:
        addrs = sorted(self.workers)
        out = {a: {} for a in addrs}
        if not addrs:
            return out
        for table, shards in sorted(self.tables.items()):
            for shard in sorted(shards):
                a = _place(table, shard, addrs)
                out[a].setdefault(table, []).append(shard)
        return out

    def worker_for(self, table: str, shard: int) -> tuple[str, str]:
        """(address, uri) of the worker owning a shard job."""
        with self._lock:
            addrs = sorted(self.workers)
            if not addrs:
                raise NoWorkersError("no compute workers registered")
            a = _place(table, shard, addrs)
            return a, self.workers[a]

    def _rebalance_locked(self):
        self._push_directives_locked()

    def _push_directives_locked(self):
        """Compute the plan under the lock, POST directives OUTSIDE it
        (a hung worker must not stall worker_for/add_shards for its
        whole HTTP timeout), then prune workers that refused."""
        import hashlib
        import json
        while True:
            plan = self._assignments_locked()
            targets = []
            for addr, asg in plan.items():
                content = hashlib.sha256(json.dumps(
                    [self.schema, asg],
                    sort_keys=True).encode()).hexdigest()
                if self._pushed.get(addr) == content:
                    continue  # nothing changed for this worker
                self._versions[addr] = self._versions.get(addr, 0) + 1
                targets.append((addr, self.workers[addr], Directive(
                    address=addr, version=self._versions[addr],
                    schema=self.schema, assignments=asg), content))
            self._lock.release()
            dead = []
            ok = []
            try:
                for addr, uri, d, content in targets:
                    try:
                        self._client._request(uri, "POST", "/directive",
                                              d.to_dict())
                        ok.append((addr, content))
                    except Exception:
                        dead.append(addr)
            finally:
                self._lock.acquire()
            for addr, content in ok:
                # the worker may have been deregistered during the
                # unlocked POST window — do not resurrect its entry
                if addr in self.workers:
                    self._pushed[addr] = content
                    if self._schemar is not None:
                        self._schemar.save_worker_state(
                            addr, self._versions.get(addr, 0),
                            content)
            if not dead:
                return
            for addr in dead:
                # a worker that can't take its directive is gone;
                # removing it reassigns its jobs to the survivors
                self._drop_worker_locked(addr)
            if not self.workers:
                return

    # -- poller (dax/controller/poller/poller.go) ----------------------

    def start_poller(self):
        self._poll_thread = threading.Thread(target=self._poll_loop,
                                             daemon=True)
        self._poll_thread.start()
        return self

    def stop_poller(self):
        self._poll_stop.set()
        if self._poll_thread:
            # outlast the health-check HTTP timeout (5s): a caller
            # about to close the schemar DB must not race a poll
            # cycle still blocked on a dead worker
            self._poll_thread.join(timeout=7)

    def _poll_loop(self):
        while not self._poll_stop.wait(self._poll_interval):
            self.poll_once()

    def poll_once(self):
        """Health-check every worker; rebalance away from dead ones."""
        with self._lock:
            workers = dict(self.workers)
        dead = []
        for addr, uri in workers.items():
            try:
                self._client._request(uri, "GET", "/status")
            except Exception:
                dead.append(addr)
        if dead:
            with self._lock:
                for addr in dead:
                    self._drop_worker_locked(addr)
                self._rebalance_locked()
        return dead
