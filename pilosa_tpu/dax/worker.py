"""Stateless-worker hydration — blob restore, ledger paging, prefetch.

The seed's ComputeNode loaded every assigned shard EAGERLY from the
shared snapshot dir, so a worker's corpus was bounded by what its
directive could afford to materialize.  This module makes the worker
genuinely stateless and genuinely paged:

- **Lazy hydration**: a directive only records the assignment; the
  shard materializes on FIRST TOUCH (query fan-out, routed import, or
  an explicit ``/dax/hydrate`` during migration) — snapshot restore
  from the blob manifest, blob WAL-segment replay, then live
  write-log tail replay past the blob's covered version.  Repeated
  hydrates replay only the new tail (the migration DELTA-CHASE is
  just ``ensure`` in a loop).
- **Ledger paging**: each worker accounts resident shard bytes
  against a PRIVATE HBM-budget ledger (memory/ledger.py — the same
  accountant the serving caches use, one instance per worker so one
  worker's working set can't eat a sibling's budget).  Pressure
  evicts the coldest resident shards BY REFERENCE (fragments drop;
  the blob tier keeps the only durable copy), so a corpus 10x over
  budget serves with eviction instead of OOM.  A single shard larger
  than the whole budget hydrates *transiently*: served, never
  retained, dropped at the next touch of anything else.
- **Prefetch warming**: every query touch bumps a per-shard access
  count; after a demand hydrate, a background warmer pulls the
  hottest still-cold assigned shards in (bounded by [dax] prefetch
  and by the ledger — warming never evicts hotter residents).

``worker-hydrate-crash`` (obs/faults.py) fires inside the hydration
seam — a worker dying mid-hydrate leaves no partial residency (the
shard stays cold and the next touch restarts from the manifest).
"""

from __future__ import annotations

import threading
import time
import weakref

from pilosa_tpu.dax import settings
from pilosa_tpu.dax.snapshotter import load_fragment_rows
from pilosa_tpu.obs import faults, metrics

# process registry for /debug/dax: every live hydrator (weakly held —
# a closed worker drops out with its state)
_hydrators: "weakref.WeakSet[ShardHydrator]" = weakref.WeakSet()


def hydrator_payloads() -> list[dict]:
    return sorted((h.payload() for h in list(_hydrators)),
                  key=lambda p: p.get("worker", ""))


class ShardHydrator:
    """Residency manager for one ComputeNode.  Every method that
    mutates residency runs under the NODE's lock (the node calls in
    with it held; the warmer thread takes it itself) — the ledger's
    reclaim callback re-enters on the same thread and therefore must
    not retake it."""

    def __init__(self, node, blob=None, budget_bytes: int | None = None,
                 lazy: bool | None = None):
        self.node = node
        self.blob = blob
        if lazy is None:
            # default: lazy only for blob-tier workers — the legacy
            # shared-dir DAXService keeps the seed's eager semantics
            lazy = blob is not None and settings.lazy_hydrate()
        self.lazy = bool(lazy)
        budget = (settings.worker_budget_bytes()
                  if budget_bytes is None else int(budget_bytes))
        self.budget_bytes = int(budget or 0)
        self._ledger = self._client = None
        if self.budget_bytes > 0:
            from pilosa_tpu.memory.ledger import Ledger
            self._ledger = Ledger(budget_bytes=self.budget_bytes)
            self._client = self._ledger.register(
                f"dax-worker:{node.address}", reclaim=self._reclaim,
                cold_ts=self._cold_ts)
        # (table, shard) -> {bytes, version, last_touch, transient}
        self._resident: dict[tuple[str, int], dict] = {}
        self._touches: dict[tuple[str, int], int] = {}
        self._hydrating: tuple[str, int] | None = None
        # shards pinned by a paged-query residency window: reclaim
        # must not evict a window member to make room for the next
        # one, or the query would execute over missing fragments
        self._pinned: set[tuple[str, int]] = set()
        self._warm_thread: threading.Thread | None = None
        self.hydrations = 0
        self.evictions = 0
        _hydrators.add(self)

    # -- residency accounting ------------------------------------------

    def _cold_ts(self) -> float:
        tss = [r["last_touch"] for r in self._resident.values()
               if r["bytes"] > 0]
        return min(tss) if tss else 0.0

    def _reclaim(self, need: int) -> int:
        """Ledger pressure: drop the coldest resident shards (the one
        mid-hydrate excepted) until ``need`` bytes freed.  Runs on
        the reserving thread with the node lock already held."""
        freed = 0
        order = sorted(
            (k for k, r in self._resident.items()
             if k != self._hydrating and k not in self._pinned
             and r["bytes"] > 0),
            key=lambda k: self._resident[k]["last_touch"])
        for key in order:
            if freed >= need:
                break
            freed += self._evict_locked(key)
        return freed

    def _evict_locked(self, key: tuple[str, int]) -> int:
        r = self._resident.pop(key, None)
        if r is None:
            return 0
        table, shard = key
        idx = self.node.api.holder.index(table)
        if idx is not None:
            for f in idx.fields.values():
                for v in f.views.values():
                    v.fragments.pop(shard, None)
        if r["bytes"] > 0 and self._client is not None:
            self._client.release(r["bytes"])
        self.evictions += 1
        self._export()
        return r["bytes"]

    def _drop_transients_locked(self, but: tuple[str, int]):
        for key in [k for k, r in self._resident.items()
                    if r.get("transient") and k != but
                    and k not in self._pinned]:
            self._evict_locked(key)

    # -- residency windows (paged query execution) ----------------------

    def pin(self, table: str, shard: int):
        self._pinned.add((table, shard))

    def unpin_all(self):
        self._pinned.clear()

    def _export(self):
        metrics.DAX_RESIDENT_SHARDS.set(
            len(self._resident), worker=self.node.address)
        cold = sum(len(s) for s in self.node.held.values()) \
            - sum(1 for k in self._resident
                  if k[1] in self.node.held.get(k[0], ()))
        metrics.DAX_COLD_SHARDS.set(max(cold, 0),
                                    worker=self.node.address)

    # -- hydration ------------------------------------------------------

    def resident(self, table: str, shard: int) -> bool:
        return (table, shard) in self._resident

    def touch(self, table: str, shard: int):
        key = (table, shard)
        self._touches[key] = self._touches.get(key, 0) + 1

    def ensure(self, table: str, shard: int, touch: bool = True,
               chase: bool = False) -> int:
        """Make (table, shard) serveable; returns the number of
        entries replayed (the DELTA-CHASE lag signal).  Resident
        shards replay only the tail appended since their last applied
        version — from the local write-log always, and from freshly
        sealed blob segments too when ``chase`` is set (the migration
        path; query touches skip the manifest read).  Node lock held
        by the caller."""
        key = (table, shard)
        if touch:
            self.touch(table, shard)
        r = self._resident.get(key)
        if r is not None:
            r["last_touch"] = time.time()
            n = 0
            if chase and self.blob is not None \
                    and settings.blob_enabled():
                n = self._chase_blob_locked(key, r)
                r = self._resident.get(key)
                if r is None:
                    # coverage gap forced a restart from the manifest
                    return n + self._hydrate_locked(table, shard)
            gap = self.node.wl.replay(table, shard,
                                      from_version=r["version"])
            for e in gap:
                self.node._apply_entry(e)
            if gap:
                r["version"] += len(gap)
                metrics.DAX_HYDRATIONS.inc(outcome="replay")
            return n + len(gap)
        return self._hydrate_locked(table, shard)

    def _chase_blob_locked(self, key: tuple[str, int], r: dict) -> int:
        """Apply blob segments sealed past the resident shard's
        applied version (a migration target watching the donor's
        hand-off uploads).  A coverage gap — the donor snapshotted
        past us and retired the segments we need — evicts so the
        caller re-hydrates from the new snapshot."""
        table, shard = key
        covered = self.blob.covered_version(table, shard)
        if covered <= r["version"]:
            return 0
        restored = self.blob.restore(table, shard)
        if restored is None:
            return 0
        _v, _snap, segs = restored
        n, at = 0, r["version"]
        for fv, tv, data in segs:
            if tv <= at:
                continue
            if fv > at:
                self._evict_locked(key)
                return n
            for e in _decode_segment(data)[at - fv:]:
                self.node._apply_entry(e)
                n += 1
            at = tv
        if at == r["version"] and at < covered:
            self._evict_locked(key)  # snapshot-only advance
            return n
        r["version"] = at
        self.node.wl.fast_forward(table, shard, at)
        if n:
            metrics.DAX_HYDRATIONS.inc(outcome="replay")
        return n

    def _hydrate_locked(self, table: str, shard: int) -> int:
        key = (table, shard)
        idx = self.node.api.holder.index(table)
        if idx is None:
            return 0
        faults.fire("worker-hydrate-crash",
                    f"{self.node.address}:{table}/{shard}")
        self._hydrating = key
        try:
            version, est_bytes, applied = 0, 0, 0
            use_blob = (self.blob is not None
                        and settings.blob_enabled())
            restored = self.blob.restore(table, shard) \
                if use_blob else None
            if restored is not None:
                version, snap_data, segs = restored
                if snap_data is not None:
                    est_bytes += self._load_snapshot(idx, shard,
                                                     snap_data)
                for _fv, _tv, data in segs:
                    est_bytes += len(data)
                    for e in _decode_segment(data):
                        self.node._apply_entry(e)
                        applied += 1
                # a fresh private write-log continues the blob's
                # absolute numbering, or the next seal would regress
                self.node.wl.fast_forward(table, shard, version)
            else:
                snap = self.node.snaps.latest(table, shard)
                if snap is not None:
                    version, blob_data = snap
                    est_bytes += self._load_snapshot(idx, shard,
                                                     blob_data)
            tail = self.node.wl.replay(table, shard,
                                       from_version=version)
            for e in tail:
                self.node._apply_entry(e)
            version += len(tail)
            retained = True
            if self._client is not None and est_bytes > 0:
                retained = self._client.reserve(est_bytes,
                                                trigger="hydrate")
            self._resident[key] = {
                "bytes": est_bytes if retained else 0,
                "version": version, "last_touch": time.time(),
                "transient": not retained}
            self.hydrations += 1
            metrics.DAX_HYDRATIONS.inc(
                outcome="full" if retained else "transient")
            self._drop_transients_locked(but=key)
            self._export()
            return applied + len(tail)
        except Exception:
            # no partial residency: a failed hydrate drops whatever
            # fragments it materialized and stays cold
            self._resident.pop(key, None)
            self._evict_fragments_only(table, shard)
            metrics.DAX_HYDRATIONS.inc(outcome="error")
            raise
        finally:
            self._hydrating = None

    def _evict_fragments_only(self, table: str, shard: int):
        idx = self.node.api.holder.index(table)
        if idx is not None:
            for f in idx.fields.values():
                for v in f.views.values():
                    v.fragments.pop(shard, None)

    def _load_snapshot(self, idx, shard: int, blob_data: bytes) -> int:
        nbytes = 0
        for (fname, view, row), words in load_fragment_rows(
                blob_data).items():
            f = idx.field(fname)
            if f is None:
                continue
            frag = f.view(view, create=True).fragment(
                shard, create=True)
            frag.set_row_words(row, words)
            nbytes += int(words.nbytes)
        return nbytes

    def note_write(self, table: str, shard: int, version: int):
        """A routed import landed (already applied by the node):
        advance the applied version so the next ensure doesn't
        re-replay it."""
        r = self._resident.get((table, shard))
        if r is not None and version > r["version"]:
            r["version"] = version

    def release(self, table: str, shard: int):
        """Directive revoked the shard: drop by reference only (the
        blob/write-log tier keeps the data)."""
        key = (table, shard)
        if key in self._resident:
            self._evict_locked(key)
        else:
            self._evict_fragments_only(table, shard)
        self._touches.pop(key, None)

    # -- blob write plane ----------------------------------------------

    def upload_snapshot(self, table: str, shard: int, version: int,
                        data: bytes):
        """Checkpoint upload (called under the node lock right after
        the local snapshot lands, so blob state is crash-consistent
        with the recorded WAL version)."""
        if self.blob is None or not settings.blob_enabled():
            return
        self.blob.put_snapshot(table, shard, version, data)
        r = self._resident.get((table, shard))
        if r is not None and version > r["version"]:
            r["version"] = version

    def seal_tail(self, table: str, shard: int) -> int:
        """Seal the live write-log tail past the blob's covered
        version as one segment object (compaction / migration
        hand-off upload point).  Returns entries sealed."""
        if self.blob is None or not settings.blob_enabled():
            return 0
        covered = self.blob.covered_version(table, shard)
        head = self.node.wl.version(table, shard)
        if head <= covered:
            return 0
        entries = self.node.wl.replay(table, shard,
                                      from_version=covered)
        self.blob.put_segment(table, shard, covered, head,
                              _encode_segment(entries))
        return len(entries)

    # -- prefetch warming ----------------------------------------------

    def kick_warm(self):
        """Start (or no-op if running) the background warmer: hydrate
        the hottest still-cold assigned shards, budget permitting."""
        n = settings.prefetch()
        if n <= 0 or not self.lazy:
            return
        t = self._warm_thread
        if t is not None and t.is_alive():
            return
        t = threading.Thread(target=self._warm_loop, args=(n,),
                             name=f"dax-warm-{self.node.address}",
                             daemon=True)
        self._warm_thread = t
        t.start()

    def _warm_candidates(self) -> list[tuple[str, int]]:
        cold = [(table, shard)
                for table, shards in self.node.held.items()
                for shard in shards
                if (table, shard) not in self._resident]
        cold.sort(key=lambda k: (-self._touches.get(k, 0), k))
        return cold

    def _warm_loop(self, n: int):
        for _ in range(n):
            with self.node._lock:
                cands = self._warm_candidates()
                if not cands:
                    return
                try:
                    self.ensure(*cands[0], touch=False)
                except Exception:
                    return  # warming is best-effort by contract
                if self._resident.get(cands[0], {}).get("transient"):
                    return  # budget full: stop pushing

    # -- surfaces -------------------------------------------------------

    def payload(self) -> dict:
        """One worker's /debug/dax + /dax/residency row."""
        resident_bytes = sum(r["bytes"]
                             for r in self._resident.values())
        return {
            "worker": self.node.address,
            "lazy": self.lazy,
            "blob": self.blob is not None,
            "budget_bytes": self.budget_bytes,
            "resident_bytes": resident_bytes,
            "pressure": (resident_bytes / self.budget_bytes
                         if self.budget_bytes else 0.0),
            "hydrations": self.hydrations,
            "evictions": self.evictions,
            "resident": sorted(
                f"{t}/{s}" for t, s in self._resident),
            "assigned": {t: sorted(s)
                         for t, s in self.node.held.items()},
        }


def _encode_segment(entries: list[dict]) -> bytes:
    import json
    return "\n".join(json.dumps(e, separators=(",", ":"))
                     for e in entries).encode()


def _decode_segment(data: bytes) -> list[dict]:
    import json
    return [json.loads(line) for line in data.decode().splitlines()
            if line.strip()]
