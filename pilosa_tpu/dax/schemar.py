"""Durable controller state — the schemar + Transactor analog.

Reference: dax/controller/schemar/ keeps the schema in a SQL database
and dax/controller's Transactor wraps every registry mutation in a DB
transaction, so a controller restart loses nothing: workers, schema,
table/shard jobs, per-worker directive versions and the fingerprints
of what each worker last enacted all reload from disk.  This module
is the same idea on sqlite (stdlib): one file, one transaction per
mutation, write-through from the controller under its lock.
"""

from __future__ import annotations

import json
import sqlite3
import threading


class Schemar:
    """sqlite-backed controller state store."""

    def __init__(self, path: str):
        self.path = path
        # the controller serializes mutations under its own RLock;
        # the sqlite handle still gets a lock so poller/API threads
        # can read concurrently
        self._lock = threading.Lock()
        self._closed = False
        self._db = sqlite3.connect(path, check_same_thread=False)
        with self._lock, self._db:
            self._db.executescript(
                "CREATE TABLE IF NOT EXISTS workers ("
                " address TEXT PRIMARY KEY, uri TEXT NOT NULL);"
                "CREATE TABLE IF NOT EXISTS kv ("
                " key TEXT PRIMARY KEY, value TEXT NOT NULL);"
                "CREATE TABLE IF NOT EXISTS shard_jobs ("
                " tbl TEXT NOT NULL, shard INTEGER NOT NULL,"
                " PRIMARY KEY (tbl, shard));"
                "CREATE TABLE IF NOT EXISTS worker_state ("
                " address TEXT PRIMARY KEY, version INTEGER NOT NULL,"
                " pushed TEXT);")

    # -- load (controller start) ----------------------------------------

    def load(self) -> dict:
        with self._lock:
            if self._closed:
                raise RuntimeError("schemar is closed")
            cur = self._db.cursor()
            workers = dict(cur.execute(
                "SELECT address, uri FROM workers").fetchall())
            row = cur.execute(
                "SELECT value FROM kv WHERE key='schema'").fetchone()
            schema = json.loads(row[0]) if row else {}
            tables: dict[str, set[int]] = {}
            for tbl, shard in cur.execute(
                    "SELECT tbl, shard FROM shard_jobs"):
                tables.setdefault(tbl, set()).add(int(shard))
            versions = {}
            pushed = {}
            for addr, ver, fp in cur.execute(
                    "SELECT address, version, pushed "
                    "FROM worker_state"):
                versions[addr] = int(ver)
                if fp is not None:
                    pushed[addr] = fp
        return {"workers": workers, "schema": schema,
                "tables": tables, "versions": versions,
                "pushed": pushed}

    # -- mutations (one transaction each) -------------------------------

    def _tx(self, fn) -> None:
        """One locked transaction; a no-op after close() — a poll
        cycle blocked on a dead worker's HTTP timeout can outlive
        restart_controller's stop_poller join, and its late drop must
        not crash on the closed handle (the fresh controller's own
        poll re-detects the dead worker)."""
        with self._lock:
            if self._closed:
                return
            with self._db:
                fn(self._db)

    def save_worker(self, address: str, uri: str):
        self._tx(lambda db: db.execute(
            "INSERT INTO workers (address, uri) VALUES (?, ?) "
            "ON CONFLICT(address) DO UPDATE SET uri=excluded.uri",
            (address, uri)))

    def register_worker(self, address: str, uri: str, version: int):
        """Worker row + fingerprint reset in ONE transaction: a crash
        between them must not strand a re-registered (fresh) worker
        behind a stale persisted fingerprint."""
        def run(db):
            db.execute(
                "INSERT INTO workers (address, uri) VALUES (?, ?) "
                "ON CONFLICT(address) DO UPDATE SET "
                "uri=excluded.uri", (address, uri))
            db.execute(
                "INSERT INTO worker_state (address, version, pushed) "
                "VALUES (?, ?, NULL) ON CONFLICT(address) DO UPDATE "
                "SET pushed=NULL", (address, version))
        self._tx(run)

    def delete_worker(self, address: str):
        def run(db):
            db.execute("DELETE FROM workers WHERE address=?",
                       (address,))
            db.execute("DELETE FROM worker_state WHERE address=?",
                       (address,))
        self._tx(run)

    def save_schema(self, schema: dict):
        self._tx(lambda db: db.execute(
            "INSERT INTO kv (key, value) VALUES ('schema', ?) "
            "ON CONFLICT(key) DO UPDATE SET value=excluded.value",
            (json.dumps(schema),)))

    def add_shards(self, table: str, shards):
        self._tx(lambda db: db.executemany(
            "INSERT OR IGNORE INTO shard_jobs (tbl, shard) "
            "VALUES (?, ?)", [(table, int(s)) for s in shards]))

    def drop_table(self, table: str):
        self._tx(lambda db: db.execute(
            "DELETE FROM shard_jobs WHERE tbl=?", (table,)))

    def save_kv(self, key: str, value: str):
        """Generic durable controller state (placement overlay,
        standby roster, admit order) — same write-through-per-
        mutation contract as the named tables."""
        self._tx(lambda db: db.execute(
            "INSERT INTO kv (key, value) VALUES (?, ?) "
            "ON CONFLICT(key) DO UPDATE SET value=excluded.value",
            (key, value)))

    def load_kv(self, key: str) -> str | None:
        with self._lock:
            if self._closed:
                return None
            row = self._db.execute(
                "SELECT value FROM kv WHERE key=?", (key,)).fetchone()
        return row[0] if row else None

    def save_worker_state(self, address: str, version: int,
                          pushed: str | None):
        self._tx(lambda db: db.execute(
            "INSERT INTO worker_state (address, version, pushed) "
            "VALUES (?, ?, ?) ON CONFLICT(address) DO UPDATE SET "
            "version=excluded.version, pushed=excluded.pushed",
            (address, version, pushed)))

    def close(self):
        with self._lock:
            self._closed = True
            self._db.close()
