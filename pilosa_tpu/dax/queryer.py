"""Queryer — stateless query front end over compute workers.

Reference: dax/queryer/queryer.go:34 + orchestrator.go:83 — a
re-implementation of the executor's mapReduce that asks the
Controller which workers own the touched shards, fans the PQL out per
worker, and reduces the serialized partials (the same cross-node
reducers the cluster layer uses).

Writes route through the queryer too: each (table, shard) import goes
to its owning worker, which write-logs before applying.

SQL fronting (dax/queryer/queryer.go:134 embeds the sql3 planner over
a Controller-backed schema API): :meth:`Queryer.sql` runs the SAME
SQL engine over a schema-only holder whose executor ships each
compiled PQL call to the compute workers and decodes the wire results
back into engine result objects — the single-controller analog of the
reference's orchestrator-backed planner.  DDL and INSERT translate to
controller schema changes and routed imports.
"""

from __future__ import annotations

import time

from pilosa_tpu.cluster.client import InternalClient
from pilosa_tpu.cluster.coordinator import (
    _empty_result,
    _reduce,
    _sort_call_for_shipping,
    extract_of_sort_wire,
)
from pilosa_tpu.dax.controller import Controller
from pilosa_tpu.executor.executor import Executor
from pilosa_tpu.executor.results import deserialize_result
from pilosa_tpu.pql import parse
from pilosa_tpu.shardwidth import SHARD_WIDTH


class _RemoteExecutor(Executor):
    """Executor whose calls execute ON THE COMPUTE WORKERS: every
    dispatched call serializes back to PQL (Call.to_pql) and rides the
    queryer's fan-out; wire results decode into engine result objects.
    The local holder carries SCHEMA ONLY (no fragments), so the SQL
    engine's planning (WHERE compilation, schema checks, key handling)
    works unchanged while the data plane stays remote."""

    def __init__(self, holder, queryer: "Queryer"):
        super().__init__(holder)
        self.queryer = queryer

    def _execute_call(self, idx, call, shards, pre=None):
        # the queryer handles the Sort offset hoist and the
        # Extract(Sort) order-preserving split at the wire level;
        # translate=False: this executor pre-translates the call and
        # key-translates the decoded result OBJECTS itself
        call = self._translate_call(idx, call)
        res = self.queryer.query(idx.name, call.to_pql(),
                                 translate=False)["results"][0]
        return self._translate_result(
            idx, call, deserialize_result(call, res, idx.width))

    # -- front-end key translation (the reference orchestrator's
    # preTranslate/translateResults split: workers run in ID space,
    # string keys exist only here) --------------------------------------

    def _translate_call(self, idx, call):
        return translate_call_keys(idx, call)

    def _translate_result(self, idx, call, res):
        return translate_result_keys(idx, call, res)


def translate_call_keys(idx, call):
    """Ship pre-translated row ids: string row values for keyed
    fields become ids via the queryer-holder translators (an unknown
    key matches nothing, FindKeys semantics).  Handles bare strings,
    lists of strings (Rows(ids=...) shapes), and Condition values —
    keyed-shape raw PQL must never silently match nothing because a
    worker compared a string against an ID-space row."""
    from pilosa_tpu.pql.ast import Call, Condition

    def conv(name, v):
        f = idx.field(name)
        if f is None or not f.options.keys:
            return v
        tr = f.row_translator

        def one(x):
            if not isinstance(x, str):
                return x
            rid = tr.find_keys(x).get(x)
            return -1 if rid is None else int(rid)  # -1: no match

        if isinstance(v, str):
            return one(v)
        if isinstance(v, list):
            nv = [one(x) for x in v]
            return v if all(a is b for a, b in zip(nv, v)) else nv
        if isinstance(v, Condition):
            cv = v.value
            ncv = ([one(x) for x in cv] if isinstance(cv, list)
                   else one(cv))
            if ncv is cv:
                return v
            return Condition(v.op, ncv)
        return v

    def walk(c):
        args = {}
        changed = False
        for k, v in c.args.items():
            nv = conv(k, v) if not isinstance(v, Call) \
                else walk(v)
            changed |= nv is not v
            args[k] = nv
        kids = [walk(ch) for ch in c.children]
        changed |= any(a is not b
                       for a, b in zip(kids, c.children))
        if not changed:
            return c
        return Call(c.name, args=args, children=kids)
    return walk(call)


def translate_result_keys(idx, call, res):
    """ids -> keys on results from the ID-space workers, using
    the queryer-holder translators (translateResults analog,
    executor.go:7519)."""
    from decimal import Decimal

    from pilosa_tpu.executor.results import (
        ExtractedTable,
        Pair,
        ValCount,
    )
    from pilosa_tpu.models.schema import FieldType

    def field_tr(fname):
        f = idx.field(fname) if fname else None
        if f is None or not f.options.keys:
            return None, None
        return f, f.row_translator

    def requantize(f, v):
        # decimals cross the wire as display floats; restore the
        # exact engine type at the front
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return Decimal(str(v)).quantize(
                Decimal(1).scaleb(-f.options.scale))
        return v

    if isinstance(res, ExtractedTable):
        if idx.keys and idx.column_translator is not None:
            # ID-space workers can't attach column keys; the
            # front owns the column translator
            ids = [int(e["column"]) for e in res.columns]
            for e, k in zip(res.columns,
                            idx.column_translator.translate_ids(
                                ids)):
                if k is not None:
                    e["column_key"] = k
        for i, fname in enumerate(res.fields):
            f = idx.field(fname)
            if f is None:
                continue
            if f.options.type == FieldType.DECIMAL:
                for e in res.columns:
                    e["rows"][i] = requantize(f, e["rows"][i])
                continue
            _f, tr = field_tr(fname)
            if tr is None:
                continue
            for e in res.columns:
                v = e["rows"][i]
                if isinstance(v, list):
                    e["rows"][i] = tr.translate_ids(v)
                elif isinstance(v, int) and \
                        f.options.type == FieldType.MUTEX:
                    e["rows"][i] = tr.translate_id(v)
        return res
    from pilosa_tpu.executor.results import DistinctValues
    if isinstance(res, DistinctValues):
        f = idx.field(call.arg("_field") or "")
        if f is not None and \
                f.options.type == FieldType.DECIMAL:
            res.values = [requantize(f, v) for v in res.values]
        return res
    if isinstance(res, ValCount):
        f = idx.field(call.arg("_field") or "")
        if f is not None and \
                f.options.type == FieldType.DECIMAL and \
                call.name != "Count":
            res.value = requantize(f, res.value) \
                if res.value is not None else None
        return res
    if isinstance(res, list) and res and \
            isinstance(res[0], Pair):
        _f, tr = field_tr(call.arg("_field"))
        if tr is not None:
            keys = tr.translate_ids([p.id for p in res])
            for p, k in zip(res, keys):
                p.key = k
        return res
    if isinstance(res, list) and res and \
            hasattr(res[0], "group"):
        for gc in res:
            for entry in gc.group:
                f, tr = field_tr(entry.get("field"))
                if tr is not None and "row_key" not in entry:
                    entry["row_key"] = tr.translate_id(
                        entry["row_id"])
        return res
    return res


class Queryer:
    def __init__(self, controller: Controller,
                 translate_dir: str | None = None):
        # translate_dir persists the front-end key translators (the
        # keyed-field key->id maps workers never see); a restarted
        # queryer over the same dir reloads them.  One active queryer
        # at a time owns the dir (the reference's translation state
        # likewise lives with the control plane, not the workers).
        self.translate_dir = translate_dir
        self.controller = controller
        # generous timeout: a worker's FIRST query jit-compiles the
        # stacked program (~30-60s cold on a busy host) and must not
        # be mistaken for a dead node
        self._client = InternalClient(timeout=180.0)
        self._sql = None  # lazy: schema-only holder + engine
        # table -> (controller schema_version, is_keyed)
        self._keyed_cache: dict[str, tuple[int, bool]] = {}

    # -- schema / ingest ----------------------------------------------

    def apply_schema(self, schema: dict):
        self.controller.apply_schema(schema)

    def _group_by_shard(self, cols, width: int = SHARD_WIDTH):
        groups: dict[int, list[int]] = {}
        for i, c in enumerate(cols):
            groups.setdefault(int(c) // width, []).append(i)
        return groups

    def _import_fanout(self, table: str, field: str, cols,
                       payload) -> int:
        """Shared owner fan-out for every /dax/import write op:
        group cols by shard, register the shards, POST one request
        per owning worker.  payload(idxs) -> op-specific body
        fields."""
        n = 0
        groups = self._group_by_shard(cols)
        self.controller.add_shards(table, groups.keys())
        for shard, idxs in groups.items():
            # a live migration may have this shard FENCED (ownership
            # mid-flip): hold until the flip lands so the write goes
            # to exactly one owner
            self.controller.fence_wait(table, shard)
            _, uri = self.controller.worker_for(table, shard)
            body = {"table": table, "field": field, "shard": shard,
                    "cols": [int(cols[i]) for i in idxs]}
            body.update(payload(idxs))
            r = self._client._request(uri, "POST", "/dax/import", body)
            n += r["imported"]
        return n

    def import_bits(self, table: str, field: str, rows, cols) -> int:
        return self._import_fanout(
            table, field, cols,
            lambda idxs: {"op": "bits",
                          "rows": [int(rows[i]) for i in idxs]})

    def import_values(self, table: str, field: str, cols, values) -> int:
        return self._import_fanout(
            table, field, cols,
            lambda idxs: {"op": "values",
                          "values": [values[i] for i in idxs]})

    def clear_field(self, table: str, field: str, cols) -> int:
        """Record-level field clear on the owning workers (explicit
        NULL for a bool/mutex column — apply_record's clear_field
        shipped over the wire, write-logged like any import)."""
        return self._import_fanout(table, field, cols,
                                   lambda idxs: {"op": "clear"})

    # -- SQL fronting (queryer.go:134 QuerySQL) -------------------------

    def _sql_engine(self):
        if self._sql is None:
            from pilosa_tpu.models.holder import Holder
            from pilosa_tpu.sql import SQLEngine
            holder = Holder(path=self.translate_dir) \
                if self.translate_dir else Holder()
            eng = SQLEngine(holder)
            eng.executor = _RemoteExecutor(holder, self)
            self._sql = eng
        # mirror controller schema into the schema-only holder
        self._apply_schema_local(self._sql.holder,
                                 self.controller.schema)
        return self._sql

    @staticmethod
    def _apply_schema_local(holder, schema: dict):
        from pilosa_tpu.models.schema import FieldOptions
        names = set()
        for ix in schema.get("indexes", []):
            names.add(ix["name"])
            idx = holder.create_index(ix["name"],
                                      keys=ix.get("keys", False),
                                      ok_if_exists=True)
            for f in ix.get("fields", []):
                idx.create_field(
                    f["name"],
                    FieldOptions.from_dict(f.get("options", {})),
                    ok_if_exists=True)
        # mirror is authoritative-FROM-controller: drop local indexes
        # the controller no longer knows (DROP TABLE must not
        # resurrect on the next mirror refresh)
        for n in list(holder.indexes):
            if n not in names:
                holder.delete_index(n)

    def sql(self, statement: str) -> dict:
        """SQL over the compute fleet: reads compile locally (schema-
        only holder) and execute remotely; DDL updates the controller
        schema; INSERT routes through the shard-owner imports.
        Returns the API wire shape {"schema": ..., "data": ...}."""
        from pilosa_tpu.sql import SQLError
        from pilosa_tpu.sql import ast as sqlast
        from pilosa_tpu.sql.parser import parse_sql

        stmts = parse_sql(statement)
        out = None
        for stmt in stmts:
            eng = self._sql_engine()
            if isinstance(stmt, sqlast.CreateTable):
                eng._execute(stmt)  # schema-only holder
                self.apply_schema({"indexes": eng.holder.schema()})
                out = {"schema": {"fields": []}, "data": []}
                continue
            if isinstance(stmt, sqlast.DropTable):
                eng._execute(stmt)  # schema-only holder (404 checks)
                self.controller.drop_table(stmt.name)
                out = {"schema": {"fields": []}, "data": []}
                continue
            if isinstance(stmt, sqlast.Insert):
                out = self._sql_insert(stmt)
                continue
            if isinstance(stmt, sqlast.BulkInsert):
                # materialize the converted CSV rows (shared engine
                # helper), then route through the same shard-owner
                # imports as INSERT — executing it on the schema-only
                # mirror would silently drop the data
                idx = eng.holder.index(stmt.table)
                if idx is None:
                    raise SQLError(f"table not found: {stmt.table}")
                fields, _ = eng._bulk_fields(idx, stmt.columns)
                # same MAP/TRANSFORM analysis as the local engine —
                # count mismatches and type incompatibilities must
                # not silently insert partial records
                eng._bulk_typecheck(stmt, idx, fields)
                rows = list(eng._iter_bulk_rows(stmt, idx, fields))
                out = self._sql_insert(sqlast.Insert(
                    stmt.table, stmt.columns, rows))
                continue
            res = eng._execute(stmt)
            out = {
                "schema": {"fields": [
                    {"name": n, "type": t} for n, t in res.schema]},
                "data": [list(r) for r in res.rows],
            }
        return out

    def _sql_insert(self, stmt) -> dict:
        """INSERT VALUES routed through owner imports (unkeyed ids)."""
        import datetime as _dt
        from decimal import Decimal as _D

        from pilosa_tpu.sql.common import rfc3339 as _rfc3339
        from pilosa_tpu.sql.engine import SQLError

        eng = self._sql_engine()
        idx = eng.holder.index(stmt.table)
        if idx is None:
            raise SQLError(f"table not found: {stmt.table}")
        if "_id" not in stmt.columns:
            raise SQLError("INSERT requires an _id column")
        id_pos = stmt.columns.index("_id")
        # accumulate per-field batches so the fleet sees ONE import
        # fan-out per field, not one RPC per (row, value)
        bit_rows: dict[str, tuple[list, list]] = {}
        val_cols: dict[str, tuple[list, list]] = {}
        # bool/mutex hold ONE value per record: collapse duplicate
        # rows for the same _id to the LAST action (set, or None =
        # explicit-NULL clear) so the batched fan-out preserves
        # apply_record's row-by-row order
        single_last: dict[str, dict[int, object]] = {}
        replace_cols: list[int] = []
        for row in stmt.rows:
            # keyed _id translates at the front like field keys
            col = int(eng._col_id(idx, row[id_pos]))
            if stmt.replace:
                replace_cols.append(col)
            for cname, v in zip(stmt.columns, row):
                if cname == "_id":
                    continue
                f = idx.field(cname)
                if f is None:
                    raise SQLError(f"column not found: {cname}")
                t = f.options.type
                if t.value in ("bool", "mutex"):
                    single_last.setdefault(cname, {})[col] = v
                    continue
                if v is None:
                    # NULL on set/BSI columns is a no-op, matching
                    # apply_record (only bool/mutex state clears)
                    continue
                if t.is_bsi:
                    # ship USER values (JSON-able): the worker's
                    # import does the single value_to_int conversion
                    # — pre-scaling here double-scaled decimals
                    f.value_to_int(v)  # validate/raise front-side
                    wire = (str(v) if isinstance(v, _D)
                            else _rfc3339(v)
                            if isinstance(v, _dt.datetime) else v)
                    cs, vs = val_cols.setdefault(cname, ([], []))
                    cs.append(col)
                    vs.append(wire)
                else:
                    vals = v if isinstance(v, list) else [v]
                    rs, cs = bit_rows.setdefault(cname, ([], []))
                    for item in vals:
                        if isinstance(item, str):
                            # keyed field rows translate at the FRONT
                            # (queryer-holder translators); workers
                            # run in ID space
                            tr = f.row_translator
                            if tr is None:
                                raise SQLError(
                                    f"column {cname} holds ids, got "
                                    f"string {item!r}")
                            item = tr.create_keys(item)[item]
                        rs.append(int(item))
                        cs.append(col)
        if replace_cols:
            # full-record replace: clear old values on the owners
            # first (the engine's clear_columns analog), one fan-out
            cols_pql = ",".join(str(c) for c in replace_cols)
            self.query(stmt.table,
                       f"Delete(ConstRow(columns=[{cols_pql}]))")
        for cname, colvals in single_last.items():
            f = idx.field(cname)
            clears = [c for c, v in colvals.items() if v is None]
            rs, cs = [], []
            for c, v in colvals.items():
                if v is None:
                    continue
                if f.options.type.value == "bool":
                    rs.append(1 if v else 0)
                else:
                    if isinstance(v, list):
                        raise SQLError(
                            f"column {cname} accepts a single value")
                    if isinstance(v, str):
                        tr = f.row_translator
                        if tr is None:
                            raise SQLError(
                                f"column {cname} holds ids, got "
                                f"string {v!r}")
                        v = tr.create_keys(v)[v]
                    rs.append(int(v))
                cs.append(c)
            if clears:
                # an EXPLICIT null clears the record's bool/mutex
                # state on the OWNING worker instead of being
                # silently skipped (defs_bool select-all2: inserting
                # (2, null) over (2, true) must read back NULL), and
                # marks existence so a NULL-only record still
                # inserts — exactly apply_record's local semantics
                self.clear_field(stmt.table, cname, clears)
            if cs:
                self.import_bits(stmt.table, cname, rs, cs)
        for cname, (rs, cs) in bit_rows.items():
            self.import_bits(stmt.table, cname, rs, cs)
        for cname, (cs, vs) in val_cols.items():
            self.import_values(stmt.table, cname, cs, vs)
        return {"schema": {"fields": []}, "data": [[len(stmt.rows)]]}

    # -- reads (orchestrator.go:83 Execute) ----------------------------

    def _keyed_index(self, table: str):
        """The schema-only mirror index for `table` IF any key
        translation applies to it, else None.  Keyedness is memoized
        by controller schema version so the common unkeyed raw-PQL
        fan-out never pays the mirror refresh; keyed tables refresh
        via _sql_engine (same path SQL fronting uses)."""
        ver = self.controller.schema_version
        ent = self._keyed_cache.get(table)
        if ent is None or ent[0] != ver:
            keyed = False
            for ix in self.controller.schema.get("indexes", []):
                if ix.get("name") == table:
                    keyed = bool(ix.get("keys")) or any(
                        f.get("options", {}).get("keys")
                        for f in ix.get("fields", []))
                    break
            ent = (ver, keyed)
            self._keyed_cache[table] = ent
        if not ent[1]:
            return None
        return self._sql_engine().holder.index(table)

    def query(self, table: str, pql: str,
              translate: bool = True) -> dict:
        """Raw-PQL fan-out.  Keyed-shape PQL routes through the same
        translate_call_keys / translate_result_keys pair the SQL front
        uses: string row values become ids BEFORE shipping (workers
        run in pure ID space — an untranslated key would silently
        match nothing) and result ids come back with their keys
        attached.  translate=False is the internal ID-space entry used
        by _RemoteExecutor, which does its own translation on the
        richer result objects."""
        q = parse(pql)
        idx = self._keyed_index(table) if translate else None
        if idx is not None:
            from pilosa_tpu.pql.ast import Query
            q = Query(calls=[translate_call_keys(idx, c)
                             for c in q.calls])
        # order-sensitive calls need call-level handling before the
        # fan-out (same contracts as ClusterExecutor): Extract(Sort)
        # splits; Sort hoists its offset to the merge
        if any((c.name == "Extract" and c.children
                and c.children[0].name == "Sort") for c in q.calls):
            results = []
            for c in q.calls:
                if c.name == "Extract" and c.children \
                        and c.children[0].name == "Sort":
                    results.append(extract_of_sort_wire(
                        c, lambda cc: self.query(
                            table, cc.to_pql(),
                            translate=False)["results"][0]))
                else:
                    results.append(self.query(
                        table, c.to_pql(),
                        translate=False)["results"][0])
            out = {"results": results}
            return (self._translate_wire_results(idx, q, out)
                    if idx is not None else out)
        shipped = [(_sort_call_for_shipping(c) if c.name == "Sort"
                    else c) for c in q.calls]
        pql = "".join(c.to_pql() for c in shipped)
        from pilosa_tpu.cluster.client import RemoteError
        from pilosa_tpu.obs import faults, flight
        from pilosa_tpu.taskpool import Pool

        # a live migration can flip a shard's owner between routing
        # and worker execution; the ex-owner answers a typed 409
        # (never a silent empty partial).  Only the CONFLICTED
        # subset re-resolves ownership and retries — re-running the
        # whole fan-out would re-race every other in-flight flip
        # (a scale event moves many shards back to back), while the
        # conflicted shards' own flip completes in bounded time.
        remaining = sorted(self.controller.tables.get(table, ()))
        partials: list = []
        conflict: Exception | None = None
        for attempt in range(8):
            # group shards by owning worker (ComputeNodes in the
            # reference)
            by_worker: dict[str, list[int]] = {}
            uris: dict[str, str] = {}
            for s in remaining:
                addr, uri = self.controller.worker_for(table, s)
                by_worker.setdefault(addr, []).append(s)
                uris[addr] = uri

            def one(pool, addr):
                with pool.blocked():  # RPC wait
                    faults.fire("dax-rpc", uris[addr])
                    t0 = time.perf_counter()
                    try:
                        out = self._client.query_node(
                            uris[addr], table, pql, by_worker[addr],
                            idempotent=True)
                        flight.note_attempt(
                            addr, time.perf_counter() - t0, "ok")
                        return ("ok", addr, out)
                    except RemoteError as e:
                        flight.note_attempt(
                            addr, time.perf_counter() - t0, "error")
                        if getattr(e, "status", None) == 409:
                            return ("conflict", addr, e)
                        raise
                    except Exception:
                        flight.note_attempt(
                            addr, time.perf_counter() - t0, "error")
                        raise

            # Pool.map settles every sibling RPC before re-raising
            # the first failure (by worker order), so one worker
            # dying fails only THIS query — never the pool or
            # mid-flight siblings
            outs = Pool(size=2).map(one, sorted(by_worker))
            partials.extend(o["results"] for st, _, o in outs
                            if st == "ok")
            conflicted = [(a, e) for st, a, e in outs
                          if st == "conflict"]
            if not conflicted:
                remaining = []
                break
            conflict = conflicted[0][1]
            remaining = sorted(
                s for a, _ in conflicted for s in by_worker[a])
            time.sleep(0.02 * (attempt + 1))
        if remaining:
            routes = {s: self.controller.worker_for(table, s)[0]
                      for s in remaining}
            raise RemoteError(
                getattr(conflict, "status", 409),
                f"ownership retries exhausted for {table}/shards "
                f"{remaining} (now routed {routes}): {conflict}")
        if not partials:
            out = {"results": [_empty_result(c) for c in q.calls]}
        else:
            out = {"results": [
                _reduce(q.calls[ci], [p[ci] for p in partials])
                for ci in range(len(q.calls))]}
        return (self._translate_wire_results(idx, q, out)
                if idx is not None else out)

    def _translate_wire_results(self, idx, q, out: dict) -> dict:
        """ids -> keys on the reduced WIRE results: deserialize each
        call's JSON form into its result object, run the shared
        translate_result_keys pass plus the single-node /query parity
        bits (column keys on Row results, row keys from keyed Rows —
        the ID-space workers can't attach either), re-serialize."""
        from pilosa_tpu.api import serialize_result
        from pilosa_tpu.executor.results import RowResult
        translated = []
        for call, wire in zip(q.calls, out["results"]):
            res = deserialize_result(call, wire, idx.width)
            res = translate_result_keys(idx, call, res)
            if isinstance(res, RowResult):
                if idx.keys and idx.column_translator is not None \
                        and not getattr(res, "is_row_ids", False):
                    res.keys = idx.column_translator.translate_ids(
                        res.columns())
            elif call.name == "Rows" and isinstance(res, list):
                f = idx.field(call.arg("_field") or "")
                if f is not None and f.options.keys \
                        and f.row_translator is not None:
                    keys = f.row_translator.translate_ids(
                        [int(r) for r in res])
                    # keyless ids (raw-id imports) fall back to the
                    # id, matching the single-node _execute_rows
                    res = [k if k is not None else r
                           for k, r in zip(keys, res)]
            translated.append(serialize_result(res))
        return {"results": translated}
