"""Queryer — stateless query front end over compute workers.

Reference: dax/queryer/queryer.go:34 + orchestrator.go:83 — a
re-implementation of the executor's mapReduce that asks the
Controller which workers own the touched shards, fans the PQL out per
worker, and reduces the serialized partials (the same cross-node
reducers the cluster layer uses).

Writes route through the queryer too: each (table, shard) import goes
to its owning worker, which write-logs before applying.  SQL fronting
(the reference embeds the sql3 planner here) rides on the same
orchestration and is deliberately PQL-first in this build; DDL and
ingest are covered via apply_schema/import_*.
"""

from __future__ import annotations

from pilosa_tpu.cluster.client import InternalClient
from pilosa_tpu.cluster.coordinator import _reduce
from pilosa_tpu.dax.controller import Controller
from pilosa_tpu.pql import parse
from pilosa_tpu.shardwidth import SHARD_WIDTH


def _empty_result(call):
    """Zero-value for a call over zero shards — matches what a node
    returns for an empty index (single-node semantics)."""
    name = call.name
    if name == "Count":
        return 0
    if name in ("Sum", "Min", "Max"):
        return {"value": None if name != "Sum" else 0, "count": 0}
    if name in ("TopN", "TopK", "Rows", "GroupBy"):
        return []
    if name == "Distinct":
        return {"values": []}
    return {"columns": []}


class Queryer:
    def __init__(self, controller: Controller):
        self.controller = controller
        self._client = InternalClient()

    # -- schema / ingest ----------------------------------------------

    def apply_schema(self, schema: dict):
        self.controller.apply_schema(schema)

    def _group_by_shard(self, cols, width: int = SHARD_WIDTH):
        groups: dict[int, list[int]] = {}
        for i, c in enumerate(cols):
            groups.setdefault(int(c) // width, []).append(i)
        return groups

    def import_bits(self, table: str, field: str, rows, cols) -> int:
        n = 0
        groups = self._group_by_shard(cols)
        self.controller.add_shards(table, groups.keys())
        for shard, idxs in groups.items():
            _, uri = self.controller.worker_for(table, shard)
            r = self._client._request(uri, "POST", "/dax/import", {
                "op": "bits", "table": table, "field": field,
                "shard": shard,
                "rows": [int(rows[i]) for i in idxs],
                "cols": [int(cols[i]) for i in idxs]})
            n += r["imported"]
        return n

    def import_values(self, table: str, field: str, cols, values) -> int:
        n = 0
        groups = self._group_by_shard(cols)
        self.controller.add_shards(table, groups.keys())
        for shard, idxs in groups.items():
            _, uri = self.controller.worker_for(table, shard)
            r = self._client._request(uri, "POST", "/dax/import", {
                "op": "values", "table": table, "field": field,
                "shard": shard,
                "cols": [int(cols[i]) for i in idxs],
                "values": [values[i] for i in idxs]})
            n += r["imported"]
        return n

    # -- reads (orchestrator.go:83 Execute) ----------------------------

    def query(self, table: str, pql: str) -> dict:
        q = parse(pql)
        shards = sorted(self.controller.tables.get(table, ()))
        # group shards by owning worker (ComputeNodes in the reference)
        by_worker: dict[str, list[int]] = {}
        uris: dict[str, str] = {}
        for s in shards:
            addr, uri = self.controller.worker_for(table, s)
            by_worker.setdefault(addr, []).append(s)
            uris[addr] = uri
        from pilosa_tpu.taskpool import Pool

        def one(pool, addr):
            with pool.blocked():  # RPC wait
                return self._client.query_node(uris[addr], table, pql,
                                               by_worker[addr])

        partials = [r["results"] for r in
                    Pool(size=2).map(one, sorted(by_worker))]
        if not partials:
            return {"results": [_empty_result(c) for c in q.calls]}
        return {"results": [
            _reduce(q.calls[ci], [p[ci] for p in partials])
            for ci in range(len(q.calls))]}
