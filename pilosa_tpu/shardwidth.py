"""Shard-width constants.

The reference fixes shards at 2^20 columns (shardwidth/helper.go:14,
``Exponent = 20``).  We keep the same width so data layouts and query
semantics line up, and derive the packed-word geometry used by the
device kernels: a shard-row is one bit per column packed LSB-first into
``uint32`` words, i.e. ``2^20 / 32 = 32768`` words = 128 KiB — which is
256 TPU lanes x 128 sublanes, a natural VPU tile.
"""

SHARD_WIDTH_EXP = 20
SHARD_WIDTH = 1 << SHARD_WIDTH_EXP  # 1,048,576 columns per shard

BITS_PER_WORD = 32
WORDS_PER_SHARD = SHARD_WIDTH // BITS_PER_WORD  # 32,768 uint32 words

# BSI row layout within a bsiGroup view (fragment.go:34-66): row 0 is the
# not-null/exists bit, row 1 the sign bit, rows 2.. the magnitude bits
# (LSB first).
BSI_EXISTS_BIT = 0
BSI_SIGN_BIT = 1
BSI_OFFSET_BIT = 2

# In-memory hybrid row store threshold (the array/bitmap container
# split of roaring/container_stash.go:46-85 applied per shard-row):
# rows with at most this many set bits are held as sorted int64
# column arrays (8 B/bit); above it they promote to packed uint32
# words.  8192 puts the crossover at 64 KiB array vs 128 KiB dense
# for the full 2^20 width.  Shared by models.fragment (in-memory) and
# storage.shards (compress-on-load).
SPARSE_MAX = 8192
