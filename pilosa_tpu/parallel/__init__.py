"""Mesh parallelism: static shard→device placement + ICI collectives.

Replaces the reference's distribution machinery (SURVEY §2.5): disco
jump-hash shard→node assignment (disco/snapshot.go:64) becomes a
static placement of shard tiles along a mesh axis; executor.mapReduce's
HTTP fan-out/streaming reduce (executor.go:6449-6812) becomes jitted
computation over sharded arrays with XLA collectives (psum/all_gather)
riding ICI.
"""

from pilosa_tpu.parallel.mesh import (
    make_mesh,
    shard_spec,
    place_shards,
)
from pilosa_tpu.parallel.dist import (
    dist_count,
    dist_count_intersect,
    dist_bsi_sum_counts,
    dist_topk_counts,
    host_bsi_sum,
    host_count,
)

__all__ = [
    "make_mesh", "shard_spec", "place_shards",
    "dist_count", "dist_count_intersect", "dist_bsi_sum_counts",
    "dist_topk_counts", "host_bsi_sum", "host_count",
]
