"""Device mesh construction and shard placement.

The shard axis is the data-parallel axis: shard s of an index maps to
device ``s % n_devices`` by stacking per-shard tiles along axis 0 of a
global array sharded with ``PartitionSpec("shards", ...)``.  This is
the static analog of the reference's jump-hash shard→node snapshot
(disco/snapshot.go:54-69, cluster.go:107-230): placement is a pure
function of (shard count, mesh), with no coordination service.

A second mesh axis ("rows") shards batched row scans (TopK/GroupBy row
blocks) — the closest thing a bitmap database has to model parallelism;
there is no sequence-parallel analog (SURVEY §5.7).
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_devices: int | None = None, rows: int = 1) -> Mesh:
    """A (rows, shards) mesh over the first rows*shards devices."""
    devs = jax.devices()
    if n_devices is None:
        n_devices = len(devs)
    assert n_devices % rows == 0
    shape = (rows, n_devices // rows)
    return Mesh(np.array(devs[:n_devices]).reshape(shape), ("rows", "shards"))


def shard_spec(batch_axes: int = 0) -> P:
    """PartitionSpec for a (S, ..., W) stack of shard tiles: axis 0 on
    the 'shards' mesh axis, everything else replicated."""
    return P(*( ("shards",) + (None,) * (batch_axes + 1) ))


def place_shards(mesh: Mesh, tiles, batch_axes: int = 0):
    """Put a stacked (S, ..., W) host array onto the mesh, shard axis 0.

    S must be a multiple of the shards axis size (pad with zero tiles —
    zero shards are harmless for every reduction we run).
    """
    tiles = np.asarray(tiles)
    n = mesh.shape["shards"]
    s = tiles.shape[0]
    if s % n:
        pad = n - s % n
        tiles = np.concatenate(
            [tiles, np.zeros((pad,) + tiles.shape[1:], dtype=tiles.dtype)])
    sharding = NamedSharding(mesh, shard_spec(batch_axes))
    return jax.device_put(tiles, sharding)
