"""Device mesh construction and shard placement.

The shard axis is the data-parallel axis: shard s of an index maps to
device ``s % n_devices`` by stacking per-shard tiles along axis 0 of a
global array sharded with ``PartitionSpec("shards", ...)``.  This is
the static analog of the reference's jump-hash shard→node snapshot
(disco/snapshot.go:54-69, cluster.go:107-230): placement is a pure
function of (shard count, mesh), with no coordination service.

A second mesh axis ("rows") shards batched row scans (TopK/GroupBy row
blocks) — the closest thing a bitmap database has to model parallelism;
there is no sequence-parallel analog (SURVEY §5.7).
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_devices: int | None = None, rows: int = 1) -> Mesh:
    """A (rows, shards) mesh over the first rows*shards devices."""
    devs = jax.devices()
    if n_devices is None:
        n_devices = len(devs)
    assert n_devices % rows == 0
    shape = (rows, n_devices // rows)
    return Mesh(np.array(devs[:n_devices]).reshape(shape), ("rows", "shards"))


def shard_spec(batch_axes: int = 0) -> P:
    """PartitionSpec for a (S, ..., W) stack of shard tiles: axis 0 on
    the 'shards' mesh axis, everything else replicated."""
    return P(*( ("shards",) + (None,) * (batch_axes + 1) ))


def place_shards(mesh: Mesh, tiles, batch_axes: int = 0):
    """Put a stacked (S, ..., W) host array onto the mesh, shard axis 0.

    S must be a multiple of the shards axis size (pad with zero tiles —
    zero shards are harmless for every reduction we run).
    """
    tiles = np.asarray(tiles)
    n = mesh.shape["shards"]
    s = tiles.shape[0]
    if s % n:
        pad = n - s % n
        tiles = np.concatenate(
            [tiles, np.zeros((pad,) + tiles.shape[1:], dtype=tiles.dtype)])
    sharding = NamedSharding(mesh, shard_spec(batch_axes))
    return jax.device_put(tiles, sharding)


def shard_map_nocheck(body, mesh: Mesh, in_specs, out_specs):
    """shard_map with replication checking off, across the JAX API
    rename: new JAX exports jax.shard_map(check_vma=...), 0.4.x has
    jax.experimental.shard_map.shard_map(check_rep=...)."""
    try:
        from jax import shard_map as sm
        kwargs = {"check_vma": False}
    except ImportError:
        from jax.experimental.shard_map import shard_map as sm
        kwargs = {"check_rep": False}
    return sm(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              **kwargs)


def flat_spec(ndim: int, shard_axis: int = 0) -> P:
    """PartitionSpec placing `shard_axis` over ALL mesh devices (both
    mesh axes flattened) — the layout shard_map kernel bodies consume:
    every device holds a contiguous slice of the shard axis and runs
    the same per-shard program, partials psum over the whole mesh."""
    return P(*([None] * shard_axis + [("rows", "shards")]
               + [None] * (ndim - shard_axis - 1)))


def place_flat(mesh: Mesh, tiles, shard_axis: int = 0):
    """device_put with `shard_axis` zero-padded to a multiple of the
    TOTAL device count and sharded over all of them (flat_spec).  Used
    by the fused GroupBy kernel paths, where candidate rows replicate
    and the shard axis is the only data-parallel axis."""
    tiles = np.asarray(tiles)
    n = int(mesh.devices.size)
    s = tiles.shape[shard_axis]
    if s % n:
        widths = [(0, 0)] * tiles.ndim
        widths[shard_axis] = (0, n - s % n)
        tiles = np.pad(tiles, widths)
    return jax.device_put(
        tiles, NamedSharding(mesh, flat_spec(tiles.ndim, shard_axis)))
