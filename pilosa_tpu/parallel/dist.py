"""Distributed reductions over mesh-sharded shard stacks.

Each function takes a stacked global array whose axis 0 is the shard
axis (placed with ``place_shards``) and runs ONE jitted program whose
cross-shard combine lowers to XLA collectives over ICI — the TPU
analog of executor.mapReduce's streaming reduceFn
(executor.go:6449-6530).

Exactness invariant (same as ops.bitmap.count): per-shard popcounts
are < 2^20 and int32-exact; cross-shard totals can exceed 2^31, so
device programs return PER-SHARD partials and the ``host_*`` combiners
sum them in exact Python ints.  Device-side scalar reduces are only
used where the bound is provably safe (see dist_topk_counts).
"""

from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp

from pilosa_tpu.ops import bitmap as bm
from pilosa_tpu.ops import bsi as bsi_ops


@jax.jit
def dist_count(tiles):
    """Per-shard Count over (S, W) sharded tiles → (S,) int32."""
    return bm.count(tiles)


@jax.jit
def dist_count_intersect(a, b):
    """Per-shard Count(Intersect(a, b)) over (S, W) stacks → (S,)."""
    return bm.count(jnp.bitwise_and(a, b))


def host_count(partials) -> int:
    """Exact cross-shard total."""
    return int(np.asarray(partials, dtype=np.int64).sum())


@jax.jit
def dist_bsi_sum_counts(planes, filt):
    """Per-shard BSI Sum partials.

    planes: (S, 2+depth, W); filt: (S, W) filter tiles (all-ones for
    no filter).  Returns (count, pos_pc, neg_pc) each with a leading
    shard axis; combine with host_bsi_sum.
    """
    return jax.vmap(bsi_ops.sum_counts)(planes, filt)


def host_bsi_sum(count, pos_pc, neg_pc) -> tuple[int, int]:
    """Exact (sum, count) from per-shard sum partials."""
    pos = np.asarray(pos_pc, dtype=np.int64).sum(axis=0)
    neg = np.asarray(neg_pc, dtype=np.int64).sum(axis=0)
    total = sum((int(p) - int(n)) << i
                for i, (p, n) in enumerate(zip(pos, neg)))
    return int(total), int(np.asarray(count, dtype=np.int64).sum())


@partial(jax.jit, static_argnames=("k",))
def dist_topk_counts(rows, filt, k: int):
    """Per-row global counts + top-k (row-batched TopN/TopK reduce).

    rows: (R, S, W) — R candidate row bitmaps stacked over S shards;
    filt: (S, W).  Returns (values, indices) of the k largest global
    intersection counts — the reduce half of executor.executeTopKShard
    / mergerator (executor.go:2570-2704) as one XLA top_k over
    ICI-reduced counts.

    Safe range: per-(row, shard) counts are < 2^20, so the int32
    cross-shard accumulation is exact for S < 2^11 shards (2 billion
    columns); above that use per-shard partials + host combine.
    """
    counts = jnp.sum(bm.count(jnp.bitwise_and(rows, filt[None])), axis=1)
    return jax.lax.top_k(counts, k)
