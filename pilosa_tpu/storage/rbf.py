"""ctypes bindings for the native rbf_tpu storage engine.

The engine (native/rbf/rbf.cc) is the host-side storage layer per
SURVEY §2.2: a single-file page store with WAL + checkpoint and
one-writer/N-reader MVCC snapshots, holding roaring-encoded containers
(array/runs/bitmap) that decode into the dense 8KB uint32 tiles the
device kernels consume.  Reference behavior parity: rbf/db.go (DB
lifecycle), rbf/tx.go (bitmap catalog + container get/put/remove),
roaring container encodings (container_stash.go:46).

The shared library builds on demand with g++ (cached by source mtime).
"""

from __future__ import annotations

import ctypes as ct
import os
import subprocess
import threading

import numpy as np

PAGE_SIZE = 8192
TILE_BYTES = 8192
TILE_WORDS = TILE_BYTES // 4       # uint32 words per container tile
TILE_BITS = 1 << 16                # bits per container

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE = os.path.join(_ROOT, "native")
_SO = os.path.join(_NATIVE, "build", "librbf_tpu.so")
_SRC = [os.path.join(_NATIVE, "rbf", "rbf.cc"),
        os.path.join(_NATIVE, "rbf", "rbf.h")]

_build_lock = threading.Lock()
_lib = None


class RBFError(RuntimeError):
    pass


def _build_needed() -> bool:
    if not os.path.exists(_SO):
        return True
    so_m = os.path.getmtime(_SO)
    return any(os.path.getmtime(s) > so_m for s in _SRC)


def _load():
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        if _build_needed():
            subprocess.run(["sh", os.path.join(_NATIVE, "build.sh")],
                           check=True, capture_output=True)
        lib = ct.CDLL(_SO)
        lib.rbf_errmsg.restype = ct.c_char_p
        lib.rbf_open.restype = ct.c_void_p
        lib.rbf_open.argtypes = [ct.c_char_p]
        lib.rbf_close.argtypes = [ct.c_void_p]
        lib.rbf_checkpoint.argtypes = [ct.c_void_p]
        lib.rbf_wal_size.restype = ct.c_int64
        lib.rbf_wal_size.argtypes = [ct.c_void_p]
        lib.rbf_page_count.restype = ct.c_int64
        lib.rbf_page_count.argtypes = [ct.c_void_p]
        lib.rbf_begin.restype = ct.c_void_p
        lib.rbf_begin.argtypes = [ct.c_void_p, ct.c_int]
        lib.rbf_commit.argtypes = [ct.c_void_p]
        lib.rbf_rollback.argtypes = [ct.c_void_p]
        for fn in ("rbf_create_bitmap", "rbf_delete_bitmap",
                   "rbf_has_bitmap"):
            getattr(lib, fn).argtypes = [ct.c_void_p, ct.c_char_p]
        lib.rbf_list_bitmaps.restype = ct.c_int64
        lib.rbf_list_bitmaps.argtypes = [ct.c_void_p, ct.c_char_p,
                                         ct.c_int64]
        lib.rbf_put_container.argtypes = [ct.c_void_p, ct.c_char_p,
                                          ct.c_uint64, ct.c_void_p]
        lib.rbf_get_container.argtypes = [ct.c_void_p, ct.c_char_p,
                                          ct.c_uint64, ct.c_void_p]
        lib.rbf_remove_container.argtypes = [ct.c_void_p, ct.c_char_p,
                                             ct.c_uint64]
        lib.rbf_container_count.restype = ct.c_int64
        lib.rbf_container_count.argtypes = [ct.c_void_p, ct.c_char_p]
        lib.rbf_bitmap_count.restype = ct.c_int64
        lib.rbf_bitmap_count.argtypes = [ct.c_void_p, ct.c_char_p]
        lib.rbf_get_range.argtypes = [ct.c_void_p, ct.c_char_p,
                                      ct.c_uint64, ct.c_int64, ct.c_void_p]
        lib.rbf_iter_open.restype = ct.c_void_p
        lib.rbf_iter_open.argtypes = [ct.c_void_p, ct.c_char_p]
        lib.rbf_iter_next.argtypes = [ct.c_void_p,
                                      ct.POINTER(ct.c_uint64), ct.c_void_p]
        lib.rbf_iter_close.argtypes = [ct.c_void_p]
        lib.rbf_container_encode.restype = ct.c_int32
        lib.rbf_container_encode.argtypes = [ct.c_void_p, ct.c_void_p,
                                             ct.POINTER(ct.c_int32)]
        lib.rbf_container_decode.argtypes = [ct.c_int32, ct.c_void_p,
                                             ct.c_int32, ct.c_void_p]
        _lib = lib
    return _lib


def _err(lib, rc, what):
    raise RBFError(f"{what}: rc={rc} ({lib.rbf_errmsg().decode()})")


NOTFOUND = -2
BUSY = -3


def _as_tile_buf(arr: np.ndarray):
    assert arr.dtype == np.uint32 and arr.flags.c_contiguous
    return arr.ctypes.data_as(ct.c_void_p)


class Tx:
    """One transaction (read snapshot or exclusive writer)."""

    def __init__(self, db: "DB", writable: bool):
        self._lib = db._lib
        ptr = self._lib.rbf_begin(db._ptr, 1 if writable else 0)
        if not ptr:
            _err(self._lib, -1, "begin")
        self._ptr = ptr
        self.writable = writable

    def commit(self):
        if self._ptr:
            rc = self._lib.rbf_commit(self._ptr)
            self._ptr = None
            if rc != 0:
                _err(self._lib, rc, "commit")

    def rollback(self):
        if self._ptr:
            self._lib.rbf_rollback(self._ptr)
            self._ptr = None

    def __enter__(self):
        return self

    def __exit__(self, et, ev, tb):
        if et is None:
            self.commit()
        else:
            self.rollback()

    # -- catalog --

    def create_bitmap(self, name: str):
        rc = self._lib.rbf_create_bitmap(self._ptr, name.encode())
        if rc != 0:
            _err(self._lib, rc, "create_bitmap")

    def delete_bitmap(self, name: str) -> bool:
        rc = self._lib.rbf_delete_bitmap(self._ptr, name.encode())
        if rc == NOTFOUND:
            return False
        if rc != 0:
            _err(self._lib, rc, "delete_bitmap")
        return True

    def has_bitmap(self, name: str) -> bool:
        rc = self._lib.rbf_has_bitmap(self._ptr, name.encode())
        if rc < 0:
            _err(self._lib, rc, "has_bitmap")
        return rc == 1

    def list_bitmaps(self) -> list[str]:
        need = self._lib.rbf_list_bitmaps(self._ptr, None, 0)
        if need < 0:
            _err(self._lib, need, "list_bitmaps")
        if need == 0:
            return []
        buf = ct.create_string_buffer(int(need))
        self._lib.rbf_list_bitmaps(self._ptr, buf, need)
        return buf.raw[:need].decode().rstrip("\n").split("\n")

    # -- containers --

    def put(self, name: str, ckey: int, dense: np.ndarray):
        """Store a dense uint32[2048] tile (all-zeros removes)."""
        rc = self._lib.rbf_put_container(self._ptr, name.encode(),
                                         ckey, _as_tile_buf(dense))
        if rc != 0:
            _err(self._lib, rc, "put")

    def get(self, name: str, ckey: int) -> np.ndarray | None:
        out = np.zeros(TILE_WORDS, dtype=np.uint32)
        rc = self._lib.rbf_get_container(self._ptr, name.encode(),
                                         ckey, _as_tile_buf(out))
        if rc == NOTFOUND:
            return None
        if rc != 0:
            _err(self._lib, rc, "get")
        return out

    def remove(self, name: str, ckey: int) -> bool:
        rc = self._lib.rbf_remove_container(self._ptr, name.encode(), ckey)
        if rc == NOTFOUND:
            return False
        if rc != 0:
            _err(self._lib, rc, "remove")
        return True

    def container_count(self, name: str) -> int:
        n = self._lib.rbf_container_count(self._ptr, name.encode())
        if n < 0:
            _err(self._lib, n, "container_count")
        return int(n)

    def count(self, name: str) -> int:
        n = self._lib.rbf_bitmap_count(self._ptr, name.encode())
        if n < 0:
            _err(self._lib, n, "count")
        return int(n)

    def get_range(self, name: str, base: int, n: int) -> np.ndarray:
        """Read containers [base, base+n) as an (n*2048,) uint32 array
        of dense tiles (the HBM upload path)."""
        out = np.zeros(n * TILE_WORDS, dtype=np.uint32)
        rc = self._lib.rbf_get_range(self._ptr, name.encode(), base, n,
                                     _as_tile_buf(out))
        if rc != 0:
            _err(self._lib, rc, "get_range")
        return out

    def items(self, name: str):
        """Yield (ckey, dense uint32[2048]) in key order."""
        it = self._lib.rbf_iter_open(self._ptr, name.encode())
        if not it:
            _err(self._lib, -1, "iter_open")
        try:
            key = ct.c_uint64()
            while True:
                out = np.zeros(TILE_WORDS, dtype=np.uint32)
                rc = self._lib.rbf_iter_next(it, ct.byref(key),
                                             _as_tile_buf(out))
                if rc == 0:
                    return
                if rc < 0:
                    _err(self._lib, rc, "iter_next")
                yield int(key.value), out
        finally:
            self._lib.rbf_iter_close(it)


class DB:
    """One rbf_tpu database file (+ .wal sidecar)."""

    def __init__(self, path: str):
        self._lib = _load()
        ptr = self._lib.rbf_open(path.encode())
        if not ptr:
            raise RBFError(
                f"open {path}: {self._lib.rbf_errmsg().decode()}")
        self._ptr = ptr
        self.path = path
        from pilosa_tpu.obs import testhook
        testhook.opened("rbf.DB", self, path)

    def begin(self, write: bool = False) -> Tx:
        return Tx(self, write)

    def checkpoint(self) -> bool:
        """Fold the WAL into the main file; False if readers pin it."""
        rc = self._lib.rbf_checkpoint(self._ptr)
        if rc == BUSY:
            return False
        if rc != 0:
            _err(self._lib, rc, "checkpoint")
        return True

    @property
    def wal_size(self) -> int:
        return int(self._lib.rbf_wal_size(self._ptr))

    @property
    def page_count(self) -> int:
        return int(self._lib.rbf_page_count(self._ptr))

    def close(self):
        if self._ptr:
            from pilosa_tpu.obs import testhook
            testhook.closed("rbf.DB", self)
            rc = self._lib.rbf_close(self._ptr)
            self._ptr = None
            if rc != 0:
                _err(self._lib, rc, "close")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def container_encode(dense: np.ndarray) -> tuple[int, bytes]:
    """Encode a dense uint32[2048] tile -> (enc, payload)."""
    lib = _load()
    out = ct.create_string_buffer(TILE_BYTES)
    enc = ct.c_int32()
    n = lib.rbf_container_encode(_as_tile_buf(dense), out, ct.byref(enc))
    if n < 0:
        _err(lib, n, "encode")
    return int(enc.value), out.raw[:n]


def container_decode(enc: int, payload: bytes) -> np.ndarray:
    lib = _load()
    out = np.zeros(TILE_WORDS, dtype=np.uint32)
    rc = lib.rbf_container_decode(enc, payload, len(payload),
                                  _as_tile_buf(out))
    if rc != 0:
        _err(lib, rc, "decode")
    return out
