"""Durable statistics-catalog storage — snapshot + tail log.

The statistics catalog (obs/stats.py) must survive restarts so a
restarted node plans like a warm one (ROADMAP item 3's "the
observability plane becomes the optimizer's statistics catalog").
Persistence reuses the idiom proven in ``storage/translate.py``:

- an append-only JSONL **tail log** of incremental events (data-stats
  ingest notes — low rate, one line per import call, never per query);
- a **snapshot** file (``<path>.snap``) holding the full catalog
  state, written atomically via tmp + fsync + rename — it is either
  absent or complete, never torn;
- on load, a **torn final tail line** (crash mid-append) is dropped
  rather than poisoning the store, and a torn or over-threshold tail
  triggers an immediate recompaction;
- every tail event carries a monotonic sequence (``"q"``) and the
  snapshot records the highest sequence it has folded
  (``"_tail_seq"``) — a crash BETWEEN the snapshot rename and the
  tail truncation leaves the old tail behind, and without the
  watermark a reload would replay events the snapshot already
  contains (data-stats counters are additive, so they would double).

The snapshot writer consults the ``stats-snapshot`` fault point
(obs/faults.py): an armed rule writes half the tmp file and dies
before the rename, proving the catalog never serves a half-written
file — the old snapshot stays intact and the next load serves it.
"""

from __future__ import annotations

import json
import os
import threading

# tail records before the next snapshot compaction (0 disables)
DEFAULT_COMPACT_THRESHOLD = 4096


class StatsStore:
    """One catalog's on-disk state: ``<path>`` tail log +
    ``<path>.snap`` snapshot.  The catalog owns the in-memory state;
    this class only moves dicts to and from disk."""

    def __init__(self, path: str,
                 compact_threshold: int | None = None):
        self.path = path
        self.compact_threshold = (DEFAULT_COMPACT_THRESHOLD
                                  if compact_threshold is None
                                  else compact_threshold)
        self._lock = threading.Lock()
        self._log = None
        self._tail_records = 0
        self._seq = 0  # monotonic tail-event sequence (see "q")
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    @property
    def snap_path(self) -> str:
        return self.path + ".snap"

    @property
    def tail_records(self) -> int:
        return self._tail_records

    # -- load ----------------------------------------------------------

    def load(self) -> tuple[dict | None, list[dict], bool]:
        """Read the persisted state: ``(snapshot_state | None, tail
        events, torn)``.  A torn final tail line is DROPPED (the event
        never acked; replaying a half-record would poison the
        catalog); ``torn`` tells the caller to recompact immediately
        once it has replayed the surviving events.  Opens the tail log
        for appending."""
        from pilosa_tpu.obs import metrics
        state = None
        folded_seq = 0
        if os.path.exists(self.snap_path):
            # tmp+rename: the snapshot is either absent or complete —
            # but FAIL OPEN on external corruption (disk damage, a
            # tool touching the file): stats are advisory telemetry
            # and must never refuse a server boot
            try:
                with open(self.snap_path) as f:
                    state = json.load(f)
                folded_seq = int(state.pop("_tail_seq", 0))
            except (ValueError, OSError):
                state = None
                folded_seq = 0
                metrics.STATS_PERSIST.inc(event="corrupt_drop")
        events: list[dict] = []
        torn = False
        if os.path.exists(self.path):
            with open(self.path) as f:
                lines = f.read().splitlines()
            last = max((i for i, ln in enumerate(lines) if ln.strip()),
                       default=-1)
            for i, line in enumerate(lines):
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    if i == last:
                        # torn tail: the process died mid-append
                        torn = True
                        metrics.STATS_PERSIST.inc(event="torn_drop")
                        break
                    # corrupt NON-final line: fail open — drop the
                    # event, keep the rest, and recompact (torn=True
                    # drives it) so the damage never reloads
                    torn = True
                    metrics.STATS_PERSIST.inc(event="corrupt_drop")
                    continue
                seq = int(ev.pop("q", 0))
                self._seq = max(self._seq, seq)
                if seq and seq <= folded_seq:
                    # already folded into the snapshot: a crash
                    # between the snapshot rename and the tail
                    # truncation left this event behind — replaying
                    # it would double-count additive data stats
                    continue
                events.append(ev)
        with self._lock:
            self._seq = max(self._seq, folded_seq)
            self._log = open(self.path, "a")
            self._tail_records = len(events)
        metrics.STATS_PERSIST.inc(event="load")
        return state, events, torn

    # -- tail append ---------------------------------------------------

    def append(self, event: dict) -> None:
        """Append one incremental event to the tail log (flushed —
        the catalog's ingest notes must survive a crash up to at most
        the torn final line)."""
        from pilosa_tpu.obs import metrics
        with self._lock:
            self._seq += 1
            line = json.dumps({**event, "q": self._seq}) + "\n"
            if self._log is None:
                self._log = open(self.path, "a")
            self._log.write(line)
            self._log.flush()
            self._tail_records += 1
        metrics.STATS_PERSIST.inc(event="tail")

    def tail_over_threshold(self) -> bool:
        return bool(self.compact_threshold) and \
            self._tail_records >= self.compact_threshold

    # -- snapshot compaction -------------------------------------------

    def compact(self, state: dict) -> None:
        """Write the full catalog state atomically and truncate the
        tail log.  The ``stats-snapshot`` fault seam simulates a
        crash mid-snapshot-write: half the tmp file lands, then the
        'process dies' (raise) — the rename never happens, so readers
        keep the previous complete snapshot."""
        from pilosa_tpu.obs import faults, metrics
        tmp = self.snap_path + ".tmp"
        with self._lock:
            # watermark: the snapshot holds everything up to _seq, so
            # a reload can skip stale tail events a crash-between-
            # rename-and-truncate left behind
            payload = json.dumps({**state, "_tail_seq": self._seq})
            if faults.armed("stats-snapshot"):
                with open(tmp, "w") as f:
                    f.write(payload[: max(1, len(payload) // 2)])
                # fire AFTER the half-write so the rule's raise leaves
                # the torn tmp behind, like the real crash would
                faults.fire("stats-snapshot", self.path)
            with open(tmp, "w") as f:
                f.write(payload)
                f.write("\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.snap_path)
            if self._log:
                self._log.close()
            self._log = open(self.path, "w")  # truncate replayed tail
            self._tail_records = 0
        metrics.STATS_PERSIST.inc(event="snapshot")

    def close(self):
        with self._lock:
            if self._log:
                self._log.close()
                self._log = None
