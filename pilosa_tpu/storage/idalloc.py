"""ID allocation with reserve/commit sessions.

Behavioral port of idalloc.go:43,127,238: ingesters reserve a range of
column ids under a (key, session) pair, write records, then commit.
Re-reserving with the same session before commit returns the same
range (exactly-once semantics across ingester retries).  Multiple
sessions may be in flight per key at once — each concurrent ingester
owns its own session (idk/ingest.go:302 per-clone consumers) and they
must not clobber each other's reservations.  Rolling back or
partially committing the LATEST reservation returns its tail to the
pool; earlier ranges are simply abandoned (ids are sparse-friendly,
gaps are harmless).
"""

from __future__ import annotations

import json
import os
import threading


class IDAllocator:
    def __init__(self, path: str | None = None):
        self.path = path
        self._next: dict[str, int] = {}       # key -> next unreserved id
        # key -> session -> (start, count)
        self._reserved: dict[str, dict[bytes, tuple[int, int]]] = {}
        self._lock = threading.RLock()
        if path and os.path.exists(path):
            with open(path) as f:
                state = json.load(f)
            if "next" not in state and "reserved" not in state:
                # legacy flat format: the whole dict is the next-map
                state = {"next": state}
            self._next = {k: int(v) for k, v in state.get("next", {}).items()}
            for k, sessions in state.get("reserved", {}).items():
                if isinstance(sessions, list):
                    # legacy single-session format [sess, start, count]
                    sess, start, count = sessions
                    self._reserved[k] = {
                        bytes.fromhex(sess): (int(start), int(count))}
                else:
                    self._reserved[k] = {
                        bytes.fromhex(s): (int(v[0]), int(v[1]))
                        for s, v in sessions.items()}

    def _persist(self):
        """Both next-ids AND in-flight reservations persist, so an
        ingester retrying the same session after a crash gets the same
        range back (idalloc.go keeps reservations in BoltDB)."""
        if self.path:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            with open(self.path, "w") as f:
                json.dump({
                    "next": self._next,
                    "reserved": {
                        k: {sess.hex(): [start, count]
                            for sess, (start, count) in sessions.items()}
                        for k, sessions in self._reserved.items()},
                }, f)

    def reserve(self, key: str, session: bytes, count: int) -> range:
        """Reserve `count` ids for (key, session).  Matching an
        in-flight session returns the same range (idalloc.go:127);
        other sessions' reservations are left untouched."""
        with self._lock:
            sessions = self._reserved.setdefault(key, {})
            held = sessions.get(session)
            if held is not None:
                start, h_count = held
                return range(start, start + h_count)
            start = self._next.get(key, 0)
            sessions[session] = (start, count)
            self._next[key] = start + count
            self._persist()
            return range(start, start + count)

    def _release_tail(self, key: str, start: int, r_count: int,
                      used: int):
        """Return the unused tail to the pool when this reservation is
        still the newest one (its end == next); abandoned otherwise."""
        if self._next.get(key, 0) == start + r_count:
            self._next[key] = start + used

    def commit(self, key: str, session: bytes, count: int | None = None):
        """Commit the reservation (idalloc.go:238).  count < reserved
        marks the rest unused."""
        with self._lock:
            sessions = self._reserved.get(key, {})
            held = sessions.get(session)
            if held is None:
                raise KeyError("no matching reservation to commit")
            start, r_count = held
            if count is not None and count < r_count:
                self._release_tail(key, start, r_count, count)
            del sessions[session]
            if not sessions:
                del self._reserved[key]
            self._persist()

    def rollback(self, key: str, session: bytes):
        with self._lock:
            sessions = self._reserved.get(key, {})
            held = sessions.pop(session, None)
            if held is not None:
                self._release_tail(key, held[0], held[1], 0)
                if not sessions:
                    del self._reserved[key]
                self._persist()
