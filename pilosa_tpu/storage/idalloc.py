"""ID allocation with reserve/commit sessions.

Behavioral port of idalloc.go:43,127,238: ingesters reserve a range of
column ids under a (key, session) pair, write records, then commit.
Re-reserving with the same session before commit returns the same
range (exactly-once semantics across ingester retries); a new session
rolls the uncommitted range back and allocates fresh.
"""

from __future__ import annotations

import json
import os
import threading


class IDAllocator:
    def __init__(self, path: str | None = None):
        self.path = path
        self._next: dict[str, int] = {}       # key -> next unreserved id
        self._reserved: dict[str, tuple[bytes, int, int]] = {}
        self._lock = threading.RLock()
        if path and os.path.exists(path):
            with open(path) as f:
                state = json.load(f)
            if "next" not in state and "reserved" not in state:
                # legacy flat format: the whole dict is the next-map
                state = {"next": state}
            self._next = {k: int(v) for k, v in state.get("next", {}).items()}
            self._reserved = {
                k: (bytes.fromhex(sess), int(start), int(count))
                for k, (sess, start, count)
                in state.get("reserved", {}).items()}

    def _persist(self):
        """Both next-ids AND in-flight reservations persist, so an
        ingester retrying the same session after a crash gets the same
        range back (idalloc.go keeps reservations in BoltDB)."""
        if self.path:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            with open(self.path, "w") as f:
                json.dump({
                    "next": self._next,
                    "reserved": {
                        k: [sess.hex(), start, count]
                        for k, (sess, start, count)
                        in self._reserved.items()},
                }, f)

    def reserve(self, key: str, session: bytes, count: int) -> range:
        """Reserve `count` ids for (key, session).  Matching an
        in-flight session returns the same range (idalloc.go:127)."""
        with self._lock:
            held = self._reserved.get(key)
            if held is not None:
                h_session, h_start, h_count = held
                if h_session == session:
                    return range(h_start, h_start + h_count)
                # new session: roll back the uncommitted reservation
                self._next[key] = h_start
            start = self._next.get(key, 0)
            self._reserved[key] = (session, start, count)
            self._next[key] = start + count
            self._persist()
            return range(start, start + count)

    def commit(self, key: str, session: bytes, count: int | None = None):
        """Commit the reservation (idalloc.go:238)."""
        with self._lock:
            held = self._reserved.get(key)
            if held is None or held[0] != session:
                raise KeyError("no matching reservation to commit")
            _, start, r_count = held
            if count is not None and count < r_count:
                # partial use: return the tail
                self._next[key] = start + count
            del self._reserved[key]
            self._persist()

    def rollback(self, key: str, session: bytes):
        with self._lock:
            held = self._reserved.get(key)
            if held is not None and held[0] == session:
                self._next[key] = held[1]
                del self._reserved[key]
                self._persist()
