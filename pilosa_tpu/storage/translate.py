"""Key translation: string keys ↔ integer ids.

Behavioral port of the reference's TranslateStore (translate.go:43)
and its partitioned index-key layout:

- Field row keys use a single sequential store (partition -1).
- Index column keys are split over 256 partitions
  (disco/snapshot.go:15 DefaultPartitionN); a key hashes to its
  partition with FNV-64a over index+key (disco/snapshot.go:87), and
  ids are allocated so that the id's SHARD also hashes to the same
  partition (translate.go:103 GenerateNextPartitionedID) — keyed
  columns therefore spread deterministically across the shard space,
  which on the TPU build is what spreads them across the device mesh.

Persistence is an append-only JSONL log per store plus a
snapshot-on-threshold compaction: once ``compact_threshold`` records
accumulate in the tail log, the full state is written atomically to
``<path>.snap`` and the log truncates — restart replays the compact
snapshot + a bounded tail instead of the full append history, and a
torn final log line (crash mid-append) is dropped rather than
poisoning the store.  (Storage layer v0; the native storage library
will replace the file format, not the semantics.)
"""

from __future__ import annotations

import functools
import json
import os
import threading

from pilosa_tpu.shardwidth import SHARD_WIDTH

DEFAULT_PARTITION_N = 256

# tail-log records before the next snapshot compaction (0 disables)
DEFAULT_COMPACT_THRESHOLD = int(os.environ.get(
    "PILOSA_TPU_TRANSLATE_COMPACT_THRESHOLD", "100000"))

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK = 0xFFFFFFFFFFFFFFFF


def _fnv64a(*parts: bytes) -> int:
    h = _FNV_OFFSET
    for p in parts:
        for b in p:
            h = ((h ^ b) * _FNV_PRIME) & _MASK
    return h


def key_to_key_partition(index: str, key: str,
                         partition_n: int = DEFAULT_PARTITION_N) -> int:
    """disco.KeyToKeyPartition: fnv64a(index + key) % N."""
    return _fnv64a(index.encode(), key.encode()) % partition_n


@functools.lru_cache(maxsize=65536)
def shard_to_shard_partition(index: str, shard: int,
                             partition_n: int = DEFAULT_PARTITION_N) -> int:
    """disco.ShardToShardPartition: fnv64a(index + bigendian(shard)) % N.
    Memoized — the hot translate_ids path hits the same few shards for
    millions of ids."""
    return _fnv64a(index.encode(), shard.to_bytes(8, "big")) % partition_n


def next_partitioned_id(index: str, prev: int, partition_id: int,
                        partition_n: int = DEFAULT_PARTITION_N,
                        shard_width: int = SHARD_WIDTH) -> int:
    """translate.GenerateNextPartitionedID: smallest id > prev whose
    shard belongs to partition_id (stepping by shard width)."""
    if partition_id == -1:
        return prev + 1
    candidate = prev + 1
    while True:
        if shard_to_shard_partition(
                index, candidate // shard_width, partition_n) == partition_id:
            return candidate
        candidate += shard_width


class TranslateStore:
    """One translation store (one field, or one index partition)."""

    def __init__(self, path: str | None = None, index: str = "",
                 partition_id: int = -1,
                 partition_n: int = DEFAULT_PARTITION_N,
                 shard_width: int = SHARD_WIDTH,
                 compact_threshold: int | None = None):
        self.path = path
        self.index = index
        self.partition_id = partition_id
        self.partition_n = partition_n
        self.shard_width = shard_width
        self.read_only = False
        self.compact_threshold = (DEFAULT_COMPACT_THRESHOLD
                                  if compact_threshold is None
                                  else compact_threshold)
        self._by_key: dict[str, int] = {}
        self._by_id: dict[int, str] = {}
        self._max_id = 0
        self._lock = threading.RLock()
        self._log = None
        self._tail_records = 0
        if path:
            self._open()

    @property
    def snap_path(self) -> str:
        return self.path + ".snap"

    def _open(self):
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        torn = False
        if os.path.exists(self.snap_path):
            # the compact snapshot is written via tmp+rename, so it is
            # either absent or complete — no torn-snapshot handling
            with open(self.snap_path) as f:
                snap = json.load(f)
            for i, k in snap.get("entries", []):
                self._set(int(i), k)
        if os.path.exists(self.path):
            with open(self.path) as f:
                lines = f.read().splitlines()
            last = max((i for i, ln in enumerate(lines) if ln.strip()),
                       default=-1)
            for i, line in enumerate(lines):
                line = line.strip()
                if not line:
                    continue
                try:
                    entry = json.loads(line)
                except ValueError:
                    if i == last:
                        # torn tail: the process died mid-append; the
                        # record never acked, dropping it is correct
                        torn = True
                        break
                    raise
                self._set(entry["id"], entry["key"])
                self._tail_records += 1
        self._log = open(self.path, "a")
        if torn or (self.compact_threshold
                    and self._tail_records >= self.compact_threshold):
            # compact now: a torn tail must not be appended after, and
            # an over-threshold tail means the last run died between
            # threshold and compaction
            self._compact_locked()

    def _append_locked(self, id_: int, key: str):
        line = json.dumps({"id": id_, "key": key}) + "\n"
        from pilosa_tpu.obs import faults
        if faults.take("torn-write", self.path or ""):
            # chaos seam: a crash mid-append leaves a torn final line —
            # write half the record, then die like the crash would:
            # close the handle (no further appends may land after the
            # torn tail, or the tear stops being the LAST line and
            # restart recovery can no longer absorb it) and raise
            self._log.write(line[: max(1, len(line) // 2)])
            self._log.flush()
            self._log.close()
            raise faults.InjectedFault("torn-write", self.path or "")
        self._log.write(line)
        self._tail_records += 1

    def _maybe_compact_locked(self):
        if self.compact_threshold and \
                self._tail_records >= self.compact_threshold:
            self._compact_locked()

    def _compact_locked(self):
        """Write the full state atomically to the snapshot file and
        truncate the tail log (holding the store lock)."""
        tmp = self.snap_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"index": self.index,
                       "partition": self.partition_id,
                       "entries": [[i, k] for i, k in
                                   sorted(self._by_id.items())]}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.snap_path)
        if self._log:
            self._log.close()
        self._log = open(self.path, "w")  # truncate the replayed tail
        self._tail_records = 0

    def close(self):
        if self._log:
            self._log.close()
            self._log = None

    def _set(self, id_: int, key: str):
        self._by_key[key] = id_
        self._by_id[id_] = key
        self._max_id = max(self._max_id, id_)

    def max_id(self) -> int:
        return self._max_id

    def find_keys(self, *keys: str) -> dict[str, int]:
        """Look up ids; missing keys are absent from the result (not an
        error) — translate.go FindKeys."""
        with self._lock:
            return {k: self._by_key[k] for k in keys if k in self._by_key}

    def create_keys(self, *keys: str) -> dict[str, int]:
        """Map keys to ids, allocating new ids as needed."""
        if self.read_only:
            raise PermissionError("translate store is read-only")
        out = {}
        with self._lock:
            for k in keys:
                id_ = self._by_key.get(k)
                if id_ is None:
                    id_ = next_partitioned_id(
                        self.index, self._max_id, self.partition_id,
                        self.partition_n, self.shard_width)
                    self._set(id_, k)
                    if self._log:
                        self._append_locked(id_, k)
                out[k] = id_
            if self._log:
                self._log.flush()
                self._maybe_compact_locked()
        return out

    def force_set(self, id_: int, key: str):
        """Replication write path (translate.go ForceSet)."""
        with self._lock:
            self._set(id_, key)
            if self._log:
                self._append_locked(id_, key)
                self._log.flush()
                self._maybe_compact_locked()

    def translate_id(self, id_: int) -> str | None:
        return self._by_id.get(id_)

    def translate_ids(self, ids) -> list[str | None]:
        return [self._by_id.get(int(i)) for i in ids]

    def match(self, predicate) -> list[int]:
        """Ids of keys matching a predicate (translate.go Match)."""
        with self._lock:
            return sorted(id_ for k, id_ in self._by_key.items()
                          if predicate(k))

    def keys(self) -> list[str]:
        return sorted(self._by_key)

    # -- replication / sync (holder.go:1488-1715 translation syncer) --

    def entries(self) -> list[tuple[int, str]]:
        """Stable (id, key) listing for snapshot streaming."""
        with self._lock:
            return sorted(self._by_id.items())

    def snapshot(self) -> dict:
        """Serializable full-state snapshot (the analog of the boltdb
        snapshot writer, translate_boltdb.go), streamed to replicas /
        rejoining nodes."""
        with self._lock:
            return {"index": self.index, "partition": self.partition_id,
                    "entries": [[i, k] for i, k in sorted(
                        self._by_id.items())]}

    def restore_snapshot(self, snap: dict):
        """Replace contents from a snapshot taken on the owner."""
        with self._lock:
            self._by_key.clear()
            self._by_id.clear()
            self._max_id = 0
            for i, k in snap.get("entries", []):
                self._set(int(i), k)
            if self._log:
                # persist via the compaction path: the on-disk
                # snapshot + empty tail now reflect exactly the
                # restored state (a stale .snap would otherwise
                # resurrect keys the owner deleted)
                self._compact_locked()


class PartitionedTranslator:
    """Index column-key translation across N partition stores
    (cluster.go:511-826 key-translation routing, single-controller)."""

    def __init__(self, index: str, path: str | None = None,
                 partition_n: int = DEFAULT_PARTITION_N,
                 shard_width: int = SHARD_WIDTH):
        self.index = index
        self.partition_n = partition_n
        self.shard_width = shard_width
        self._stores: dict[int, TranslateStore] = {}
        self._path = path
        self._lock = threading.RLock()

    def _store(self, partition: int) -> TranslateStore:
        with self._lock:
            return self._store_locked(partition)

    def _store_locked(self, partition: int) -> TranslateStore:
        s = self._stores.get(partition)
        if s is None:
            path = (os.path.join(self._path, f"keys.{partition:04d}.jsonl")
                    if self._path else None)
            s = TranslateStore(path, index=self.index,
                               partition_id=partition,
                               partition_n=self.partition_n,
                               shard_width=self.shard_width)
            self._stores[partition] = s
        return s

    def _group(self, keys) -> dict[int, list[str]]:
        groups: dict[int, list[str]] = {}
        for k in keys:
            groups.setdefault(
                key_to_key_partition(self.index, k, self.partition_n),
                []).append(k)
        return groups

    def find_keys(self, *keys: str) -> dict[str, int]:
        out = {}
        for p, ks in self._group(keys).items():
            out.update(self._store(p).find_keys(*ks))
        return out

    def create_keys(self, *keys: str) -> dict[str, int]:
        out = {}
        for p, ks in self._group(keys).items():
            out.update(self._store(p).create_keys(*ks))
        return out

    def translate_ids(self, ids) -> list[str | None]:
        # id → its shard's partition → that partition's store; the
        # memoized shard hash makes this O(1) hashing per id
        out = []
        for i in ids:
            p = shard_to_shard_partition(
                self.index, int(i) // self.shard_width, self.partition_n)
            out.append(self._store(p).translate_id(int(i)))
        return out

    def match(self, predicate) -> list[int]:
        ids: list[int] = []
        for p in list(self._stores):
            ids.extend(self._stores[p].match(predicate))
        # also open on-disk stores not yet loaded
        if self._path and os.path.isdir(self._path):
            for fn in os.listdir(self._path):
                if fn.startswith("keys.") and fn.endswith(".jsonl"):
                    p = int(fn.split(".")[1])
                    if p not in self._stores:
                        ids.extend(self._store(p).match(predicate))
        return sorted(set(ids))

    def partition_snapshot(self, partition: int) -> dict:
        """Snapshot ONE partition store for streaming to a peer."""
        return self._store(partition).snapshot()

    def restore_partition(self, partition: int, snap: dict):
        self._store(partition).restore_snapshot(snap)

    def nonempty_partitions(self) -> list[int]:
        with self._lock:
            out = [p for p, s in self._stores.items() if s.max_id()]
        if self._path and os.path.isdir(self._path):
            for fn in os.listdir(self._path):
                if fn.startswith("keys.") and fn.endswith(".jsonl"):
                    p = int(fn.split(".")[1])
                    if p not in out and self._store(p).max_id():
                        out.append(p)
        return sorted(out)

    def close(self):
        for s in self._stores.values():
            s.close()
