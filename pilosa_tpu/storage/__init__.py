"""Host-side storage: key translation, ID allocation, persistence.

The reference keeps string↔id translation in BoltDB stores
(translate_boltdb.go) and bitmap data in RBF files (rbf/).  Here
translation is a host-side append-log store (the device never sees
strings — SURVEY §7 "Key translation throughput"); bitmap persistence
lives in the snapshot module and will move behind the native RBF-lite
library.
"""

from pilosa_tpu.storage.translate import (
    PartitionedTranslator,
    TranslateStore,
    key_to_key_partition,
    next_partitioned_id,
    shard_to_shard_partition,
    DEFAULT_PARTITION_N,
)
from pilosa_tpu.storage.idalloc import IDAllocator

__all__ = [
    "TranslateStore", "PartitionedTranslator", "IDAllocator",
    "key_to_key_partition", "shard_to_shard_partition",
    "next_partitioned_id", "DEFAULT_PARTITION_N",
]
