"""Official RoaringBitmap serialization — decode + encode.

Reference: the roaring/ package reads and writes both its own pilosa
format and the official interchange format
(roaring/roaring.go:1730 WriteTo, unmarshal_binary.go — cookies
12346/12347 per the RoaringFormatSpec).  This module implements the
official 32-bit format so standard roaring tooling can exchange row
bitmaps with this framework; fragment-level import ships one roaring
blob per row id (shard-relative columns), covering the reference's
importRoaring path (fragment.go:2038) without its 64-bit container
keys.

Decoding is vectorized: array containers are one frombuffer; bitmap
containers unpack via np.unpackbits; run containers expand with
np.repeat arithmetic.  Dense-tile interop: to_words()/from_words()
convert to the packed uint32 lanes the device kernels consume.
"""

from __future__ import annotations

import struct

import numpy as np

SERIAL_COOKIE_NO_RUN = 12346
SERIAL_COOKIE = 12347
NO_OFFSET_THRESHOLD = 4
_ARRAY_MAX = 4096          # cardinality <= this encodes as array
_BITMAP_BYTES = 8192


class RoaringError(ValueError):
    pass


def decode(buf: bytes) -> np.ndarray:
    """Deserialize official-format bytes -> sorted uint32 values."""
    if len(buf) < 4:
        raise RoaringError("short roaring buffer")
    cookie = struct.unpack_from("<I", buf, 0)[0]
    if (cookie & 0xFFFF) == SERIAL_COOKIE:
        n = (cookie >> 16) + 1
        off = 4
        flag_bytes = (n + 7) // 8
        run_flags = np.unpackbits(
            np.frombuffer(buf, np.uint8, flag_bytes, off),
            bitorder="little")[:n].astype(bool)
        off += flag_bytes
        has_offsets = n >= NO_OFFSET_THRESHOLD
    elif cookie == SERIAL_COOKIE_NO_RUN:
        n = struct.unpack_from("<I", buf, 4)[0]
        off = 8
        run_flags = np.zeros(n, dtype=bool)
        has_offsets = True
    else:
        raise RoaringError(f"bad roaring cookie {cookie}")
    keys = np.zeros(n, dtype=np.uint32)
    cards = np.zeros(n, dtype=np.int64)
    for i in range(n):
        k, c = struct.unpack_from("<HH", buf, off + 4 * i)
        keys[i], cards[i] = k, c + 1
    off += 4 * n
    if has_offsets:
        off += 4 * n  # offsets are redundant for sequential decode
    out = []
    for i in range(n):
        base = np.uint32(keys[i]) << np.uint32(16)
        if run_flags[i]:
            n_runs = struct.unpack_from("<H", buf, off)[0]
            off += 2
            pairs = np.frombuffer(buf, np.uint16, 2 * n_runs, off
                                  ).astype(np.int64).reshape(-1, 2)
            off += 4 * n_runs
            lengths = pairs[:, 1] + 1
            starts = np.repeat(pairs[:, 0], lengths)
            steps = np.arange(int(lengths.sum())) - np.repeat(
                np.concatenate(([0], np.cumsum(lengths)[:-1])), lengths)
            vals = (starts + steps).astype(np.uint32)
        elif cards[i] <= _ARRAY_MAX:
            vals = np.frombuffer(buf, np.uint16, int(cards[i]), off
                                 ).astype(np.uint32)
            off += 2 * int(cards[i])
        else:
            bits = np.unpackbits(
                np.frombuffer(buf, np.uint8, _BITMAP_BYTES, off),
                bitorder="little")
            off += _BITMAP_BYTES
            vals = np.nonzero(bits)[0].astype(np.uint32)
        out.append(base | vals)
    return (np.concatenate(out) if out
            else np.array([], dtype=np.uint32))


def encode(values) -> bytes:
    """Serialize sorted-able uint32 values in the no-run official
    format (cookie 12346 — every reader supports it; the reference
    likewise writes without optimizing to runs unless asked).
    Values must fit uint32 — the official interop format is 32-bit;
    silently truncating would corrupt round-trips."""
    raw = np.asarray(values, dtype=np.uint64)
    if raw.size and int(raw.max()) > 0xFFFFFFFF:
        raise RoaringError(
            "official roaring format holds 32-bit values only")
    vals = np.unique(raw.astype(np.uint32))
    keys = (vals >> np.uint32(16)).astype(np.uint16)
    uniq_keys, starts = np.unique(keys, return_index=True)
    bounds = list(starts) + [len(vals)]
    n = len(uniq_keys)
    head = struct.pack("<II", SERIAL_COOKIE_NO_RUN, n)
    desc = b"".join(
        struct.pack("<HH", int(k), int(bounds[i + 1] - bounds[i] - 1))
        for i, k in enumerate(uniq_keys))
    bodies = []
    for i in range(n):
        lows = (vals[bounds[i]:bounds[i + 1]] & np.uint32(0xFFFF)
                ).astype(np.uint16)
        if lows.size <= _ARRAY_MAX:
            bodies.append(lows.tobytes())
        else:
            bits = np.zeros(1 << 16, dtype=np.uint8)
            bits[lows] = 1
            bodies.append(np.packbits(bits, bitorder="little").tobytes())
    offsets = []
    pos = len(head) + len(desc) + 4 * n
    for b in bodies:
        offsets.append(struct.pack("<I", pos))
        pos += len(b)
    return head + desc + b"".join(offsets) + b"".join(bodies)


def to_words(values, width: int) -> np.ndarray:
    """Roaring values -> packed uint32 lanes (device tile layout)."""
    from pilosa_tpu.ops import bitmap as bm
    vals = np.asarray(values, dtype=np.int64)
    if vals.size and vals.max() >= width:
        raise RoaringError(
            f"value {int(vals.max())} exceeds shard width {width}")
    return bm.from_columns(vals, width)


def from_words(words) -> np.ndarray:
    from pilosa_tpu.ops import bitmap as bm
    return bm.to_columns(words).astype(np.uint32)
