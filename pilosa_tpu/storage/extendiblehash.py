"""On-disk extendible hash table over the buffer pool.

Reference: extendiblehash/extendiblehash.go:1 — a directory of bucket
page ids indexed by the low ``global_depth`` bits of the key hash;
buckets split (doubling the directory when a bucket at full global
depth overflows).  The sql3 layer spills large DISTINCT sets here
(opdistinct) instead of holding them in memory — this build's SQL
engine does the same above a size threshold.

Bucket page layout (8 KiB): [u16 n_entries][u16 local_depth] then
n_entries of [u16 klen][u16 vlen][key][value].
"""

from __future__ import annotations

import hashlib
import struct

from pilosa_tpu.storage.bufferpool import BufferPool, PAGE_SIZE

_HDR = struct.Struct("<HH")
_ENT = struct.Struct("<HH")


def _hash(key: bytes) -> int:
    return int.from_bytes(hashlib.blake2b(key, digest_size=8).digest(),
                          "little")


class _Bucket:
    def __init__(self, page):
        self.page = page
        n, depth = _HDR.unpack_from(page.data, 0)
        self.local_depth = depth
        self.entries: list[tuple[bytes, bytes]] = []
        off = _HDR.size
        for _ in range(n):
            klen, vlen = _ENT.unpack_from(page.data, off)
            off += _ENT.size
            k = bytes(page.data[off:off + klen]); off += klen
            v = bytes(page.data[off:off + vlen]); off += vlen
            self.entries.append((k, v))

    def bytes_used(self) -> int:
        return _HDR.size + sum(_ENT.size + len(k) + len(v)
                               for k, v in self.entries)

    def write(self):
        d = self.page.data
        _HDR.pack_into(d, 0, len(self.entries), self.local_depth)
        off = _HDR.size
        for k, v in self.entries:
            _ENT.pack_into(d, off, len(k), len(v))
            off += _ENT.size
            d[off:off + len(k)] = k; off += len(k)
            d[off:off + len(v)] = v; off += len(v)


class ExtendibleHash:
    def __init__(self, pool: BufferPool):
        self.pool = pool
        self.global_depth = 0
        first = pool.new_page()
        _HDR.pack_into(first.data, 0, 0, 0)
        pool.unpin(first, dirty=True)
        self.directory = [first.page_no]
        self.n_keys = 0

    # -- public --------------------------------------------------------

    def put(self, key: bytes, value: bytes = b""):
        assert len(key) + len(value) + _ENT.size + _HDR.size <= PAGE_SIZE, \
            "entry larger than a page"
        while not self._try_put(key, value):
            pass

    def get(self, key: bytes) -> bytes | None:
        page = self.pool.fetch(self._dir_page(key))
        try:
            b = _Bucket(page)
            for k, v in b.entries:
                if k == key:
                    return v
            return None
        finally:
            self.pool.unpin(page)

    def __contains__(self, key: bytes) -> bool:
        return self.get(key) is not None

    def __len__(self) -> int:
        return self.n_keys

    def keys(self):
        """All keys (dedup across directory aliases of each bucket)."""
        seen_pages = set()
        for pno in self.directory:
            if pno in seen_pages:
                continue
            seen_pages.add(pno)
            page = self.pool.fetch(pno)
            try:
                for k, _ in _Bucket(page).entries:
                    yield k
            finally:
                self.pool.unpin(page)

    # -- internals -----------------------------------------------------

    def _dir_index(self, key: bytes) -> int:
        return _hash(key) & ((1 << self.global_depth) - 1)

    def _dir_page(self, key: bytes) -> int:
        return self.directory[self._dir_index(key)]

    def _try_put(self, key: bytes, value: bytes) -> bool:
        page = self.pool.fetch(self._dir_page(key))
        b = _Bucket(page)
        try:
            for i, (k, _) in enumerate(b.entries):
                if k == key:
                    b.entries[i] = (key, value)
                    b.write()
                    self.pool.unpin(page, dirty=True)
                    return True
            need = _ENT.size + len(key) + len(value)
            if b.bytes_used() + need <= PAGE_SIZE:
                b.entries.append((key, value))
                b.write()
                self.pool.unpin(page, dirty=True)
                self.n_keys += 1
                return True
        except Exception:
            self.pool.unpin(page)
            raise
        # overflow: split (extendiblehash.go split/grow)
        self._split(page, b)
        return False

    def _split(self, page, b: _Bucket):
        if b.local_depth == self.global_depth:
            # double the directory
            self.directory = self.directory + list(self.directory)
            self.global_depth += 1
        new_page = self.pool.new_page()
        new_depth = b.local_depth + 1
        old_entries = b.entries
        bit = 1 << b.local_depth
        # rehome directory slots whose index has the new bit set and
        # pointed at the old page
        mask = (1 << self.global_depth) - 1
        for i in range(len(self.directory)):
            if self.directory[i] == page.page_no and (i & bit):
                self.directory[i] = new_page.page_no
        keep, move = [], []
        for k, v in old_entries:
            (move if (_hash(k) & bit) else keep).append((k, v))
        b.entries = keep
        b.local_depth = new_depth
        b.write()
        nb = _Bucket(new_page)
        nb.entries = move
        nb.local_depth = new_depth
        nb.write()
        self.pool.unpin(page, dirty=True)
        self.pool.unpin(new_page, dirty=True)


class SpillSet:
    """DISTINCT spill set: in-memory until `threshold` keys, then an
    on-disk extendible hash (sql3 opdistinct behavior)."""

    def __init__(self, path: str, threshold: int = 1 << 16,
                 frames: int = 64):
        from pilosa_tpu.storage.bufferpool import DiskManager
        self.path = path
        self.threshold = threshold
        self.frames = frames
        self._mem: set[bytes] | None = set()
        self._disk: ExtendibleHash | None = None
        self._pool = None
        from pilosa_tpu.obs import testhook
        testhook.opened("spill.SpillSet", self, path)

    # keys longer than this store as a 32-byte blake2b digest so no
    # entry can outgrow a bucket page (collision odds ~2^-128)
    _MAX_INLINE_KEY = 4096

    def add(self, key: bytes) -> bool:
        """Add; True if newly added."""
        if len(key) > self._MAX_INLINE_KEY:
            key = b"#" + hashlib.blake2b(key, digest_size=32).digest()
        if self._mem is not None:
            if key in self._mem:
                return False
            self._mem.add(key)
            if len(self._mem) > self.threshold:
                self._spill()
            return True
        if key in self._disk:
            return False
        self._disk.put(key)
        return True

    def _spill(self):
        from pilosa_tpu.storage.bufferpool import DiskManager
        self._pool = BufferPool(DiskManager(self.path),
                                max_frames=self.frames)
        self._disk = ExtendibleHash(self._pool)
        for k in self._mem:
            self._disk.put(k)
        self._mem = None

    def __len__(self):
        return len(self._mem) if self._mem is not None else len(self._disk)

    def __iter__(self):
        if self._mem is not None:
            return iter(self._mem)
        return self._disk.keys()

    def close(self):
        from pilosa_tpu.obs import testhook
        testhook.closed("spill.SpillSet", self)
        if self._pool is not None:
            self._pool.close()
            self._pool.disk.destroy()
