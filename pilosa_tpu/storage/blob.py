"""Blob shard store — the disaggregated tier's one durable home.

Reference: the DAX deployment parks cold shard data in an external
object store (S3-shaped WriteLogger/Snapshotter services) so compute
workers stay stateless; this build re-expresses that as an in-process
object-store-SHAPED interface: opaque keys, put/get/list/delete, no
rename, no partial reads — everything a real S3 client offers, and
nothing it doesn't.  Two backends ship: ``LocalDirBackend`` (keys are
relative paths under a root, written tmp+fsync+rename so a crashed
put never leaves a half object) and ``MemBackend`` (a dict — the
fault-injection arm of every drill).

Layout — per (table, shard), a *versioned manifest* names the current
snapshot object plus the WAL segment objects layered over it::

    {table}/{shard:05d}/manifest.json
        {"manifest_version": N, "table": t, "shard": s,
         "snapshot": {"key", "version", "sha256", "bytes"} | None,
         "segments": [{"key", "from_version", "to_version",
                       "sha256", "bytes"}, ...]}
    {table}/{shard:05d}/snap.v{version:08d}.{sha8}
    {table}/{shard:05d}/seg.v{from:08d}-{to:08d}.{sha8}

Torn-upload invisibility is structural: data objects upload FIRST
under content-hashed keys, the manifest flips LAST, and a reader
always resolves through the manifest — an upload that dies anywhere
before the manifest flip leaves at most an orphan object no manifest
names (the ``blob-torn-upload`` fault point drills exactly that
window).  Every get re-verifies the manifest's sha256 before
returning; a checksum mismatch is a typed :class:`BlobError`, never
silently-served corruption.  ``blob-unavailable`` turns any backend
op into a :class:`BlobUnavailableError` (workers surface it as a
typed 503 — degraded, never a silent partial result).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading

from pilosa_tpu.obs import faults, metrics


class BlobError(Exception):
    """Typed blob-tier failure (checksum mismatch, malformed
    manifest).  Carries an HTTP status so serving surfaces map it
    without string-matching."""

    status = 500


class BlobUnavailableError(BlobError):
    """The blob tier is unreachable — the outage shape.  503: the
    condition is transient and retryable, exactly like an admission
    shed."""

    status = 503


def _check(op: str, key: str):
    """The ``blob-unavailable`` fault seam, consulted by every
    backend op (detail: ``op:key``)."""
    try:
        faults.fire("blob-unavailable", f"{op}:{key}")
    except faults.InjectedFault as e:
        raise BlobUnavailableError(
            f"blob tier unavailable ({op} {key!r})") from e


class MemBackend:
    """Dict-backed object store — the default test/drill arm."""

    def __init__(self):
        self._objects: dict[str, bytes] = {}
        self._lock = threading.Lock()

    def put(self, key: str, data: bytes):
        _check("put", key)
        with self._lock:
            self._objects[key] = bytes(data)

    def get(self, key: str) -> bytes:
        _check("get", key)
        with self._lock:
            try:
                return self._objects[key]
            except KeyError:
                raise BlobError(f"no such object: {key}") from None

    def exists(self, key: str) -> bool:
        _check("head", key)
        with self._lock:
            return key in self._objects

    def list(self, prefix: str = "") -> list[str]:
        _check("list", prefix)
        with self._lock:
            return sorted(k for k in self._objects
                          if k.startswith(prefix))

    def delete(self, key: str):
        _check("delete", key)
        with self._lock:
            self._objects.pop(key, None)


class LocalDirBackend:
    """Keys are relative paths under ``root``; puts land
    tmp+fsync+rename so a crash mid-put never leaves a half object
    (the same atomicity contract every store in this repo keeps)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        # opaque keys stay INSIDE the root: reject traversal shapes
        # rather than normalizing them away
        if key.startswith(("/", "~")) or ".." in key.split("/"):
            raise BlobError(f"invalid object key: {key!r}")
        return os.path.join(self.root, *key.split("/"))

    def put(self, key: str, data: bytes):
        _check("put", key)
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def get(self, key: str) -> bytes:
        _check("get", key)
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise BlobError(f"no such object: {key}") from None

    def exists(self, key: str) -> bool:
        _check("head", key)
        return os.path.isfile(self._path(key))

    def list(self, prefix: str = "") -> list[str]:
        _check("list", prefix)
        out = []
        for dirpath, _dirs, files in os.walk(self.root):
            rel = os.path.relpath(dirpath, self.root)
            rel = "" if rel == "." else rel.replace(os.sep, "/") + "/"
            for fname in files:
                if fname.endswith(".tmp"):
                    continue  # torn-put debris is never listable
                key = rel + fname
                if key.startswith(prefix):
                    out.append(key)
        return sorted(out)

    def delete(self, key: str):
        _check("delete", key)
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass


def make_backend(kind: str, root: str | None = None):
    """Config-string backend factory ([blob] backend = "dir"|"mem")."""
    if kind == "mem":
        return MemBackend()
    if kind == "dir":
        if not root:
            raise BlobError("[blob] backend='dir' needs [blob] root")
        return LocalDirBackend(root)
    raise BlobError(f"unknown blob backend {kind!r}")


def _sha(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


class BlobStore:
    """Versioned per-shard manifests over a backend.

    The writer protocol (one writer per shard at a time — the shard's
    owning worker, serialized by the controller's placement):
    ``put_snapshot`` on checkpoint, ``put_segment`` for the WAL tail
    sealed at hand-off; both upload data first and flip the manifest
    last.  Readers call ``restore`` and get a checksum-verified
    (version, snapshot bytes, ordered segment list).
    """

    def __init__(self, backend):
        self.backend = backend
        self._lock = threading.Lock()

    # -- keys ----------------------------------------------------------

    @staticmethod
    def _prefix(table: str, shard: int) -> str:
        return f"{table}/{int(shard):05d}/"

    def _manifest_key(self, table: str, shard: int) -> str:
        return self._prefix(table, shard) + "manifest.json"

    # -- manifest read -------------------------------------------------

    def manifest(self, table: str, shard: int) -> dict | None:
        """The current manifest, or None when the shard has never
        been uploaded.  Unavailability propagates typed; a manifest
        that exists but doesn't parse is corruption, not absence."""
        key = self._manifest_key(table, shard)
        if not self.backend.exists(key):
            return None
        raw = self.backend.get(key)
        metrics.DAX_BLOB_BYTES.inc(len(raw), op="get")
        try:
            m = json.loads(raw)
        except ValueError as e:
            raise BlobError(f"corrupt manifest {key}: {e}") from None
        if not isinstance(m, dict) or "manifest_version" not in m:
            raise BlobError(f"malformed manifest {key}")
        return m

    def shards(self) -> list[tuple[str, int]]:
        """Every (table, shard) with a manifest — the cold catalog a
        booting worker or a donor-less copy phase enumerates."""
        out = []
        for key in self.backend.list():
            if not key.endswith("/manifest.json"):
                continue
            parts = key.split("/")
            if len(parts) != 3:
                continue
            try:
                out.append((parts[0], int(parts[1])))
            except ValueError:
                continue
        return sorted(out)

    def covered_version(self, table: str, shard: int) -> int:
        """Highest WAL version the blob tier holds for a shard (0 =
        nothing uploaded): the worker's seal/snapshot planes upload
        only past this, and hydration replays the live WAL from it."""
        m = self.manifest(table, shard)
        if m is None:
            return 0
        v = int((m.get("snapshot") or {}).get("version", 0))
        for seg in m.get("segments", ()):
            v = max(v, int(seg.get("to_version", 0)))
        return v

    # -- writes (data first, manifest flip LAST) -----------------------

    def _flip_manifest(self, table: str, shard: int, m: dict):
        m["manifest_version"] = int(m.get("manifest_version", 0)) + 1
        raw = json.dumps(m, sort_keys=True).encode()
        self.backend.put(self._manifest_key(table, shard), raw)
        metrics.DAX_BLOB_BYTES.inc(len(raw), op="put")

    def _put_object(self, key: str, data: bytes):
        """One data-object upload, with the ``blob-torn-upload``
        crash seam: when armed, HALF the object lands under the key
        and the 'process dies' before the manifest flip — the reader
        contract is that this must be invisible (the old manifest
        still resolves the old, complete objects)."""
        if faults.armed("blob-torn-upload"):
            self.backend.put(key, data[: max(1, len(data) // 2)])
            faults.fire("blob-torn-upload", key)
        self.backend.put(key, data)
        metrics.DAX_BLOB_BYTES.inc(len(data), op="put")

    def put_snapshot(self, table: str, shard: int, version: int,
                     data: bytes) -> str:
        """Upload a shard snapshot at WAL ``version`` and flip the
        manifest to it, retiring the segments (and prior snapshot) it
        supersedes.  Retired objects delete AFTER the flip — a crash
        between leaves unreferenced garbage, never a dangling
        reference."""
        with self._lock:
            m = self.manifest(table, shard) or {
                "manifest_version": 0, "table": table,
                "shard": int(shard), "snapshot": None, "segments": []}
            if version < int((m.get("snapshot") or {})
                             .get("version", 0)):
                raise BlobError(
                    f"stale snapshot upload v{version} for "
                    f"{table}/{shard}")
            digest = _sha(data)
            key = (self._prefix(table, shard)
                   + f"snap.v{int(version):08d}.{digest[:8]}")
            self._put_object(key, data)
            old_snap = m.get("snapshot")
            keep, retired = [], []
            for seg in m.get("segments", ()):
                if int(seg.get("to_version", 0)) <= int(version):
                    retired.append(seg["key"])
                else:
                    keep.append(seg)
            m["snapshot"] = {"key": key, "version": int(version),
                             "sha256": digest, "bytes": len(data)}
            m["segments"] = keep
            self._flip_manifest(table, shard, m)
            if old_snap and old_snap.get("key") != key:
                retired.append(old_snap["key"])
            for k in retired:
                try:
                    self.backend.delete(k)
                except BlobError:
                    pass  # garbage, swept on a later pass
            return key

    def put_segment(self, table: str, shard: int, from_version: int,
                    to_version: int, data: bytes) -> str:
        """Upload one sealed WAL segment covering
        ``(from_version, to_version]`` and append it to the
        manifest."""
        if to_version <= from_version:
            raise BlobError(
                f"empty segment v{from_version}-{to_version}")
        with self._lock:
            m = self.manifest(table, shard) or {
                "manifest_version": 0, "table": table,
                "shard": int(shard), "snapshot": None, "segments": []}
            covered = int((m.get("snapshot") or {}).get("version", 0))
            for seg in m.get("segments", ()):
                covered = max(covered, int(seg["to_version"]))
            if from_version != covered:
                raise BlobError(
                    f"segment gap for {table}/{shard}: have v"
                    f"{covered}, got v{from_version}-{to_version}")
            digest = _sha(data)
            key = (self._prefix(table, shard)
                   + f"seg.v{int(from_version):08d}-"
                     f"{int(to_version):08d}.{digest[:8]}")
            self._put_object(key, data)
            m.setdefault("segments", []).append(
                {"key": key, "from_version": int(from_version),
                 "to_version": int(to_version), "sha256": digest,
                 "bytes": len(data)})
            self._flip_manifest(table, shard, m)
            return key

    def delete_shard(self, table: str, shard: int):
        """Drop a shard from the blob tier (table drop): manifest
        first — readers lose the reference before the data goes."""
        with self._lock:
            self.backend.delete(self._manifest_key(table, shard))
            for key in self.backend.list(self._prefix(table, shard)):
                self.backend.delete(key)

    # -- restore (checksum-verified) -----------------------------------

    def _get_verified(self, ref: dict) -> bytes:
        data = self.backend.get(ref["key"])
        metrics.DAX_BLOB_BYTES.inc(len(data), op="get")
        if _sha(data) != ref.get("sha256"):
            raise BlobError(
                f"checksum mismatch on {ref['key']} "
                f"({len(data)} bytes)")
        return data

    def restore(self, table: str, shard: int):
        """(covered_version, snapshot bytes | None, [(from, to,
        segment bytes), ...]) — everything a hydrating worker
        replays, each object verified against its manifest sha256.
        None when the shard has never been uploaded."""
        m = self.manifest(table, shard)
        if m is None:
            return None
        snap = m.get("snapshot")
        snap_data = self._get_verified(snap) if snap else None
        version = int(snap.get("version", 0)) if snap else 0
        segs = []
        for seg in sorted(m.get("segments", ()),
                          key=lambda s: int(s["from_version"])):
            segs.append((int(seg["from_version"]),
                         int(seg["to_version"]),
                         self._get_verified(seg)))
            version = max(version, int(seg["to_version"]))
        return version, snap_data, segs
