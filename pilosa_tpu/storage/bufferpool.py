"""Page buffer pool with clock replacement over a disk file.

Reference: bufferpool/ (bufferpool.go BufferPool, clockreplacer.go
ClockReplacer, diskmanager.go) — fixed-size page frames cached in
memory over an on-disk page file; victims chosen by the clock
algorithm; used by the sql3 layer's spill-to-disk structures
(extendiblehash/ for DISTINCT).

Pages are 8 KiB like the RBF engine's (rbf/rbf.go PageSize).
"""

from __future__ import annotations

import os
import threading

PAGE_SIZE = 8192


class Page:
    __slots__ = ("page_no", "data", "dirty", "pin_count", "ref")

    def __init__(self, page_no: int):
        self.page_no = page_no
        self.data = bytearray(PAGE_SIZE)
        self.dirty = False
        self.pin_count = 0
        self.ref = False  # clock reference bit


class DiskManager:
    """Page-granular file IO (bufferpool diskmanager)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if not os.path.exists(path):
            open(path, "wb").close()
        # r+b, NOT a+b: append mode ignores seek() on write, which
        # would scatter every in-place page write to the file tail
        self._f = open(path, "r+b")
        self._f.seek(0, os.SEEK_END)
        self._n_pages = self._f.tell() // PAGE_SIZE

    def allocate(self) -> int:
        no = self._n_pages
        self._n_pages += 1
        self._f.seek(no * PAGE_SIZE)
        self._f.write(b"\0" * PAGE_SIZE)
        return no

    def read(self, page_no: int, buf: bytearray):
        self._f.seek(page_no * PAGE_SIZE)
        got = self._f.read(PAGE_SIZE)
        buf[: len(got)] = got
        buf[len(got):] = b"\0" * (PAGE_SIZE - len(got))

    def write(self, page_no: int, data):
        self._f.seek(page_no * PAGE_SIZE)
        self._f.write(bytes(data))

    @property
    def n_pages(self) -> int:
        return self._n_pages

    def close(self):
        self._f.close()

    def destroy(self):
        self.close()
        if os.path.exists(self.path):
            os.unlink(self.path)


class ClockReplacer:
    """Second-chance eviction (clockreplacer.go)."""

    def __init__(self):
        self._frames: list[Page] = []
        self._hand = 0

    def track(self, page: Page):
        self._frames.append(page)

    def untrack(self, page: Page):
        self._frames.remove(page)
        self._hand = 0

    def victim(self) -> Page | None:
        if not self._frames:
            return None
        spins = 0
        while spins < 2 * len(self._frames):
            p = self._frames[self._hand % len(self._frames)]
            self._hand = (self._hand + 1) % len(self._frames)
            spins += 1
            if p.pin_count > 0:
                continue
            if p.ref:
                p.ref = False  # second chance
                continue
            return p
        return None


class BufferPool:
    """Fixed-frame page cache (bufferpool.go BufferPool)."""

    def __init__(self, disk: DiskManager, max_frames: int = 128):
        self.disk = disk
        self.max_frames = max_frames
        self._pages: dict[int, Page] = {}
        self._clock = ClockReplacer()
        self._lock = threading.RLock()

    def new_page(self) -> Page:
        with self._lock:
            no = self.disk.allocate()
            return self._admit(Page(no), fresh=True)

    def fetch(self, page_no: int) -> Page:
        """Pinned page; callers must unpin()."""
        with self._lock:
            p = self._pages.get(page_no)
            if p is None:
                p = Page(page_no)
                self.disk.read(page_no, p.data)
                p = self._admit(p, fresh=False)
            else:
                p.pin_count += 1
                p.ref = True
            return p

    def _admit(self, p: Page, fresh: bool) -> Page:
        while len(self._pages) >= self.max_frames:
            v = self._clock.victim()
            if v is None:
                raise RuntimeError(
                    "buffer pool exhausted: all pages pinned")
            if v.dirty:
                self.disk.write(v.page_no, v.data)
            self._clock.untrack(v)
            del self._pages[v.page_no]
        p.pin_count = 1
        p.ref = True
        if fresh:
            p.dirty = True
        self._pages[p.page_no] = p
        self._clock.track(p)
        return p

    def unpin(self, page: Page, dirty: bool = False):
        with self._lock:
            page.pin_count = max(0, page.pin_count - 1)
            page.dirty = page.dirty or dirty

    def flush_all(self):
        with self._lock:
            for p in self._pages.values():
                if p.dirty:
                    self.disk.write(p.page_no, p.data)
                    p.dirty = False

    def close(self):
        self.flush_all()
        self.disk.close()
