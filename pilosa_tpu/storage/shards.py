"""Per-index shard storage — DB-file-per-shard layout.

Mirrors the reference's dbshard scheme (dbshard.go:1-30: one RBF DB
file per (index, shard) under ``backends/``), with bitmap names
``<field>/<view>`` inside each shard file and container keys
``row * tiles_per_row + tile`` (fragment.go:84 keying collapsed onto
dense 2^16-bit tiles).

The in-memory Fragment remains the query-plane source (dense rows +
device tile cache); this layer is durability: ``sync()`` persists
dirty rows inside one write transaction per shard file, and fragments
reload from here on holder open.  WALs are checkpointed once they pass
a size threshold (rbf/db.go checkpoint-on-size behavior).
"""

from __future__ import annotations

import os
import re
import shutil
import threading

import numpy as np

from pilosa_tpu.storage import rbf

BACKENDS_DIR = "backends"
_SHARD_FILE = re.compile(r"^shard\.(\d+)\.rbf$")
CHECKPOINT_WAL_BYTES = 64 << 20


def bitmap_name(field: str, view: str) -> str:
    return f"{field}/{view}"


class IndexStorage:
    """Owns the per-shard RBF DB handles of one index."""

    def __init__(self, path: str):
        self.path = path  # index directory
        self._dbs: dict[int, rbf.DB] = {}
        self._lock = threading.Lock()

    def _dir(self) -> str:
        return os.path.join(self.path, BACKENDS_DIR)

    def _shard_path(self, shard: int) -> str:
        return os.path.join(self._dir(), f"shard.{shard:04d}.rbf")

    def db(self, shard: int) -> rbf.DB:
        # one handle per shard file, ever: a second handle would replay
        # (and truncate) a WAL the first is still appending to
        with self._lock:
            d = self._dbs.get(shard)
            if d is None:
                os.makedirs(self._dir(), exist_ok=True)
                d = rbf.DB(self._shard_path(shard))
                self._dbs[shard] = d
            return d

    def shards_on_disk(self) -> list[int]:
        if not os.path.isdir(self._dir()):
            return []
        out = []
        for fn in os.listdir(self._dir()):
            m = _SHARD_FILE.match(fn)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def discover(self) -> list[tuple[str, str, int]]:
        """All (field, view, shard) triples present on disk."""
        out = []
        for shard in self.shards_on_disk():
            with self.db(shard).begin() as tx:
                for name in tx.list_bitmaps():
                    field, _, view = name.partition("/")
                    out.append((field, view, shard))
        return out

    # -- fragment IO -----------------------------------------------------

    @staticmethod
    def _tiles_per_row(width: int) -> int:
        return max(1, width >> 16)

    def load_rows(self, field: str, view: str, shard: int,
                  width: int) -> dict[int, np.ndarray]:
        """Read every row of a fragment, compressing AS rows complete:
        a returned int64 array is sorted column ids (rows with at most
        SPARSE_MAX bits), a uint32 array is packed words.  Peak dense
        memory is ONE row, so a million near-empty persisted rows load
        in megabytes — the restore-path half of the hybrid row store
        (models/fragment.py)."""
        from pilosa_tpu.ops import bitmap as bm
        from pilosa_tpu.shardwidth import SPARSE_MAX

        nw = width // 32
        tpr = self._tiles_per_row(width)
        rows: dict[int, np.ndarray] = {}
        name = bitmap_name(field, view)

        def finalize(row: int, w: np.ndarray):
            if int(np.bitwise_count(w).sum()) <= SPARSE_MAX:
                rows[row] = bm.to_columns(w).astype(np.int64)
            else:
                rows[row] = w

        cur_row, cur_w = None, None
        with self.db(shard).begin() as tx:
            if not tx.has_bitmap(name):
                return rows
            for ckey, tile in tx.items(name):
                row, t = divmod(ckey, tpr)
                if row != cur_row:
                    if cur_row is not None:
                        finalize(cur_row, cur_w)
                    prev = rows.pop(row, None)  # defensive: reopened row
                    if prev is None:
                        cur_w = np.zeros(nw, dtype=np.uint32)
                    elif prev.dtype == np.int64:
                        cur_w = bm.from_columns(prev, width)
                    else:
                        cur_w = prev
                    cur_row = row
                if tpr == 1 and nw < rbf.TILE_WORDS:
                    cur_w[:] = tile[:nw]
                else:
                    cur_w[t * rbf.TILE_WORDS:
                          (t + 1) * rbf.TILE_WORDS] = tile
        if cur_row is not None:
            finalize(cur_row, cur_w)
        return rows

    def write_fragments(self, frags) -> None:
        """Persist dirty rows of fragments belonging to ONE shard in a
        single write transaction.

        Crash seams (obs/faults.py), compiled into the production
        sync path exactly where real crashes strike:

        - ``wal-torn``: the process dies while the commit's WAL frames
          are only partially on disk — enacted by committing, then
          truncating the shard WAL's tail mid-frame and dropping the
          handle (the dying process's state).  Native recovery
          (rbf.cc wal_replay) detects the torn frame on reopen, drops
          the whole uncommitted transaction, and truncates to the last
          complete commit — the fragment reloads its pre-sync state
          instead of garbage, and the stream replay re-syncs it.
        - ``crash-pre-checkpoint``: the process dies after the WAL
          fsync but before the checkpoint folds it into the main
          file — the window IS durable (WAL replay recovers it) even
          though it never acked; replay must therefore be idempotent.

        Both raise, so ``dirty_rows`` stays set and the failed window
        never acks."""
        if not frags:
            return
        shard = frags[0].shard
        db = self.db(shard)
        path = self._shard_path(shard)
        with db.begin(write=True) as tx:
            for frag in frags:
                assert frag.shard == shard
                name = bitmap_name(frag.field_name, frag.view_name)
                tx.create_bitmap(name)
                tpr = self._tiles_per_row(frag.width)
                nw = frag.width // 32
                for row in sorted(frag.dirty_rows):
                    words = frag.row_words(row)
                    if tpr == 1 and nw < rbf.TILE_WORDS:
                        tile = np.zeros(rbf.TILE_WORDS, dtype=np.uint32)
                        tile[:nw] = words
                        tx.put(name, row, tile)
                    else:
                        for t in range(tpr):
                            tile = np.ascontiguousarray(
                                words[t * rbf.TILE_WORDS:
                                      (t + 1) * rbf.TILE_WORDS])
                            tx.put(name, row * tpr + t, tile)
        from pilosa_tpu.obs import faults
        if faults.take("wal-torn", path):
            self._tear_wal(shard)
            raise faults.InjectedFault("wal-torn", path)
        faults.fire("crash-pre-checkpoint", path)
        for frag in frags:
            frag.dirty_rows.clear()
        if db.wal_size > CHECKPOINT_WAL_BYTES:
            db.checkpoint()  # best-effort; skipped if readers pinned

    def _tear_wal(self, shard: int) -> None:
        """Enact the wal-torn fault: close the shard's DB handle (the
        dying process's file-descriptor state) and truncate the WAL
        mid-frame so the final commit frame can never replay.  4 KiB
        is half a page — always inside the last frame's meta image."""
        with self._lock:
            d = self._dbs.pop(shard, None)
        if d is not None:
            d.close()
        wal = self._shard_path(shard) + ".wal"
        if os.path.exists(wal):
            sz = os.path.getsize(wal)
            if sz:
                os.truncate(wal, max(0, sz - 4096))

    def delete_field_bitmaps(self, field: str) -> None:
        prefix = field + "/"
        for shard in self.shards_on_disk():
            with self.db(shard).begin(write=True) as tx:
                for name in tx.list_bitmaps():
                    if name.startswith(prefix):
                        tx.delete_bitmap(name)

    def delete_view_bitmaps(self, field: str, view: str) -> None:
        """Remove ONE view's bitmap from every shard file (TTL view
        expiry; the per-field analog of delete_field_bitmaps)."""
        name = bitmap_name(field, view)
        for shard in self.shards_on_disk():
            with self.db(shard).begin(write=True) as tx:
                if tx.has_bitmap(name):
                    tx.delete_bitmap(name)

    def drop_shard(self, shard: int) -> None:
        """Delete ONE shard's persisted file + WAL (online-resharding
        RELEASE: the donor no longer owns the shard, so keeping the
        file would resurrect a stale copy on restart).  A later write
        to the shard simply recreates a fresh file."""
        with self._lock:
            d = self._dbs.pop(shard, None)
        if d is not None:
            d.close()
        p = self._shard_path(shard)
        for f in (p, p + ".wal"):
            if os.path.exists(f):
                os.remove(f)

    # -- lifecycle -------------------------------------------------------

    def checkpoint_all(self) -> None:
        for d in self._dbs.values():
            d.checkpoint()

    def close(self) -> None:
        for d in self._dbs.values():
            d.close()
        self._dbs.clear()

    def destroy(self) -> None:
        """Close and delete all storage (index deletion)."""
        self.close()
        if os.path.isdir(self._dir()):
            shutil.rmtree(self._dir())
