"""ctypes bindings for the native ingest scatter kernels
(native/ingest/scatter.cc) with numpy fallbacks.

The columnar import hot loops — bit scatter (np.bitwise_or.at) and
the per-plane BSI fill — are word-at-a-time scatters that numpy
cannot fuse; the C versions run ~10-20x faster.  Build is on demand
like the RBF library (same build.sh, cached by mtime).
"""

from __future__ import annotations

import ctypes as ct
import os
import subprocess
import threading

import numpy as np

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE = os.path.join(_ROOT, "native")
_SO = os.path.join(_NATIVE, "build", "libingest_tpu.so")
_SRC = os.path.join(_NATIVE, "ingest", "scatter.cc")

_build_lock = threading.Lock()
_lib = None
_lib_failed = False

_I64 = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_U32 = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")


def _load():
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _build_lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            if not os.path.exists(_SO) or \
                    os.path.getmtime(_SRC) > os.path.getmtime(_SO):
                subprocess.run(
                    ["sh", os.path.join(_NATIVE, "build.sh")],
                    check=True, capture_output=True)
            lib = ct.CDLL(_SO)
            lib.pt_or_bits.argtypes = [_U32, _I64, ct.c_int64]
            lib.pt_bsi_fill_t.argtypes = [_U32, ct.c_int64, _I64,
                                          _I64, ct.c_int64]
            lib.pt_mutex_fill.argtypes = [_U32, _U32, ct.c_int64,
                                          _I64, _I64, ct.c_int64]
            lib.pt_groupcode_hist.argtypes = [
                _U32, ct.c_int64, _U32, ct.c_void_p, ct.c_int64,
                ct.c_int64, ct.c_int64, ct.c_int64,
                _I64, _I64, _I64, _I64]
            _lib = lib
        except Exception:
            _lib_failed = True  # no toolchain: numpy fallbacks
    return _lib


def available() -> bool:
    return _load() is not None


def or_bits(words: np.ndarray, cols: np.ndarray) -> None:
    """words[c>>5] |= 1 << (c&31) for every c (bitwise_or.at)."""
    lib = _load()
    cols = np.ascontiguousarray(cols, dtype=np.int64)
    if lib is not None:
        lib.pt_or_bits(words, cols, cols.size)
        return
    np.bitwise_or.at(words, cols >> 5,
                     np.uint32(1) << (cols & 31).astype(np.uint32))


def bsi_fill(scratch: np.ndarray, cols: np.ndarray,
             vals: np.ndarray, depth: int) -> None:
    """Fill a zeroed (2+depth, plane_words) scratch: plane 0 exists,
    1 sign, 2+i magnitude bit i — one reverse pass over the values
    with built-in last-write-wins per column."""
    lib = _load()
    cols = np.ascontiguousarray(cols, dtype=np.int64)
    vals = np.ascontiguousarray(vals, dtype=np.int64)
    if lib is not None:
        n_planes, plane_words = scratch.shape
        # interleaved fill (one cache line per value) + one
        # vectorized transpose back to plane-major
        scratch_t = np.zeros((plane_words, n_planes), np.uint32)
        lib.pt_bsi_fill_t(scratch_t, n_planes, cols, vals,
                          cols.size)
        scratch[:] = scratch_t.T
        return
    # numpy fallback dedups explicitly (the kernel's reverse scan)
    if cols.size > 1:
        _, rev_first = np.unique(cols[::-1], return_index=True)
        keep = cols.size - 1 - rev_first
        cols, vals = cols[keep], vals[keep]
    neg = vals < 0
    mags = np.where(neg, -vals, vals).view(np.uint64)
    or_bits(scratch[0], cols)
    or_bits(scratch[1], cols[neg])
    for i in range(depth):
        sel = (mags >> np.uint64(i)) & np.uint64(1) == 1
        or_bits(scratch[2 + i], cols[sel])


def groupcode_hist(code_planes: np.ndarray, valid: np.ndarray,
                   bsi: np.ndarray | None, n_codes: int,
                   signed: bool,
                   counts: np.ndarray, nn: np.ndarray,
                   pos: np.ndarray, neg: np.ndarray) -> None:
    """One shard of the one-pass GroupBy histogram: accumulate counts
    (n_codes,), nn (n_codes,) and sign-split per-plane partials
    pos/neg (n_codes, depth) int64 in place.  code_planes (CB, W)
    packed group-code bit-planes, valid (W,), bsi (2+depth, W) or
    None.  Host twin of ops/kernels.groupby_onehot."""
    code_planes = np.ascontiguousarray(code_planes, dtype=np.uint32)
    valid = np.ascontiguousarray(valid, dtype=np.uint32)
    depth = 0 if bsi is None else bsi.shape[0] - 2
    lib = _load()
    if lib is not None:
        if bsi is not None:
            bsi = np.ascontiguousarray(bsi, dtype=np.uint32)
        lib.pt_groupcode_hist(
            code_planes, code_planes.shape[0], valid,
            None if bsi is None else bsi.ctypes.data, depth,
            int(signed), valid.shape[0], int(n_codes),
            counts, nn, pos, neg)
        return
    # numpy fallback: unpack + bincount per payload row
    from pilosa_tpu.ops import bitmap as bmops
    from pilosa_tpu.ops import bsi as bsi_ops
    code = bmops.code_from_planes_np(code_planes)     # (W*32,)
    va = bsi_ops.unpack_bits_np(valid)
    counts += np.bincount(code[va], minlength=n_codes)[:n_codes]
    if bsi is None:
        return
    ex = bsi_ops.unpack_bits_np(bsi[0]) & va
    sg = bsi_ops.unpack_bits_np(bsi[1])
    nn += np.bincount(code[ex], minlength=n_codes)[:n_codes]
    posm = ex & ~sg if signed else ex
    negm = ex & sg
    for p in range(depth):
        mb = bsi_ops.unpack_bits_np(bsi[2 + p])
        pos[:, p] += np.bincount(code[mb & posm],
                                 minlength=n_codes)[:n_codes]
        if signed:
            neg[:, p] += np.bincount(code[mb & negm],
                                     minlength=n_codes)[:n_codes]


def groupcode_minmax(code_planes: np.ndarray, valid: np.ndarray,
                     bsi: np.ndarray, n_codes: int, signed: bool,
                     mm: np.ndarray) -> None:
    """One shard of the per-group Min/Max magnitude table: accumulate
    mm (4, n_codes) int64 rows [max_mag_pos, min_mag_pos, max_mag_neg,
    min_mag_neg] in place (caller pre-fills identities -1 / 1<<depth).
    Host numpy twin of the fused kernel's presence-walk Min/Max
    (ops/kernels.groupby_fused(minmax=True) / minmax_from_table)."""
    from pilosa_tpu.ops import bitmap as bmops
    from pilosa_tpu.ops import bsi as bsi_ops
    depth = bsi.shape[0] - 2
    code = bmops.code_from_planes_np(
        np.ascontiguousarray(code_planes, dtype=np.uint32))
    va = bsi_ops.unpack_bits_np(
        np.ascontiguousarray(valid, dtype=np.uint32))
    ex = bsi_ops.unpack_bits_np(bsi[0]) & va
    sg = bsi_ops.unpack_bits_np(bsi[1])
    mag = np.zeros(code.shape, np.int64)
    for p in range(depth):
        mag |= bsi_ops.unpack_bits_np(bsi[2 + p]).astype(np.int64) << p
    posm = (ex & ~sg if signed else ex).astype(bool)
    negm = (ex & sg).astype(bool) if signed else np.zeros_like(posm)
    inb = code < n_codes
    for row, op, mask in ((0, np.maximum, posm), (1, np.minimum, posm),
                          (2, np.maximum, negm), (3, np.minimum, negm)):
        sel = mask & inb
        if sel.any():
            op.at(mm[row], code[sel], mag[sel])


def mutex_fill(written: np.ndarray, scratch: np.ndarray,
               rowidx: np.ndarray, cols: np.ndarray) -> None:
    """Fill a zeroed (n_rows, plane_words) scratch with one bit per
    (dense row index, column), last write per column winning;
    `written` collects every touched column (the clear-then-set
    mask)."""
    lib = _load()
    rowidx = np.ascontiguousarray(rowidx, dtype=np.int64)
    cols = np.ascontiguousarray(cols, dtype=np.int64)
    if lib is not None:
        lib.pt_mutex_fill(written, scratch.reshape(-1),
                          scratch.shape[1], rowidx, cols, cols.size)
        return
    if cols.size > 1:
        _, rev_first = np.unique(cols[::-1], return_index=True)
        keep = cols.size - 1 - rev_first
        cols, rowidx = cols[keep], rowidx[keep]
    or_bits(written, cols)
    for r in np.unique(rowidx):
        or_bits(scratch[int(r)], cols[rowidx == r])
