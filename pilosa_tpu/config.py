"""Layered configuration: defaults < TOML file < env < flags.

Reference: server/config.go — one Config struct populated from a TOML
file, PILOSA_* environment variables, and cobra flags, in that
precedence order; ``featurebase generate-config`` prints the default
file (cmd generate-config).  Env prefix here: ``PILOSA_TPU_``;
nested TOML tables flatten with ``_`` (``[auth] secret`` ->
``PILOSA_TPU_AUTH_SECRET``).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field, fields

try:
    import tomllib  # Python >= 3.11
except ModuleNotFoundError:  # 3.10: only needed when a file is given
    tomllib = None


@dataclass
class Config:
    data_dir: str = ""
    bind: str = "127.0.0.1"
    port: int = 10101
    grpc_port: int = 20101
    cluster_name: str = "cluster0"
    replicas: int = 1
    auth_secret: str = ""
    auth_policy: str = ""
    tpu_kernels: str = "auto"   # auto | on | off -> PILOSA_TPU_PALLAS
    # queries slower than this (seconds) go to the long-query log;
    # 0 disables (server.go:201 OptServerLongQueryTime)
    long_query_time: float = 0.0
    # serving path (executor/serving.py): concurrent queries coalesce
    # into one device dispatch per admission window, and repeated
    # reads serve from a write-version-guarded result cache.
    # Env-overridable like every knob (PILOSA_TPU_SERVING_BATCHING=0,
    # PILOSA_TPU_SERVING_CACHE_MB=0, ...).
    serving_batching: bool = True
    serving_batch_window_ms: float = 1.0
    serving_batch_max: int = 32
    serving_cache_mb: int = 64
    # ragged paged dispatch + QoS admission (executor/ragged.py,
    # executor/sched.py): ragged fuses a whole mixed batch — different
    # indexes and shard subsets — into ONE page-table device program;
    # admission classes keep point reads ahead of heavy analytics
    # (heavy-slots bounds concurrent heavy queries, queue-max bounds
    # the wait queue, overflow sheds typed 503 + Retry-After).
    # tenant-weights ("analytics:4,adhoc:1") weight the per-tenant
    # fair queue; default-deadline-ms applies to requests that carried
    # no X-Pilosa-Deadline-Ms of their own (0 = none).
    serving_ragged: bool = True
    serving_admission: bool = True
    serving_heavy_slots: int = 2
    serving_queue_max: int = 128
    serving_tenant_weights: str = ""
    serving_default_deadline_ms: float = 0.0
    # incremental stack maintenance (executor/stacked.py delta
    # patching + models/fragment.py delta log): patch device-resident
    # stacks on write instead of rebuilding them.  delta-log-max
    # bounds the per-fragment mutation log (older snapshots fall back
    # to slice rebuilds); patch-max-frac is the dirty fraction past
    # which one dense rebuild upload beats scattering runs.
    stack_patch: bool = True
    stack_delta_log_max: int = 256
    stack_patch_max_frac: float = 0.5
    # container-adaptive device format (memory/encode.py): per page
    # block pick dense / packed-array / run encoding.  sparse-format
    # false = all-dense (the A/B arm, env twin
    # PILOSA_TPU_SPARSE_FORMAT); sparse-dense-frac is the entry
    # threshold — a sparse candidate must be <= this fraction of the
    # dense page's bytes to leave the dense format.
    stack_sparse_format: bool = True
    stack_sparse_dense_frac: float = 0.5
    # HBM residency manager (pilosa_tpu/memory): one process-wide
    # device-byte budget shared by the tile-stack/jit/result caches.
    # budget-bytes 0 = auto (device memory_stats minus headroom-frac,
    # 8 GiB fallback on backends without stats).  paged turns stack
    # cache entries into fixed page-bytes device pages (sub-stack
    # eviction + patching); prefetch warms predicted pages from the
    # flight recorder off the hot path; oom-retry / host-fallback are
    # the RESOURCE_EXHAUSTED backstop rungs.
    memory_budget_bytes: int = 0
    memory_headroom_frac: float = 0.1
    memory_page_bytes: int = 4 << 20
    memory_paged: bool = True
    memory_prefetch: bool = True
    memory_prefetch_interval_s: float = 0.5
    memory_oom_retry: bool = True
    memory_host_fallback: bool = True
    # streaming write plane (ingest/stream.py): concurrent mutations
    # coalesce per (field, shard) into one bulk apply + ONE durable
    # WAL-synced storage write per admission window; a submit acks
    # only after the window landed.  queue / tenant-queue bound the
    # admission backlog (shed = typed 503 + Retry-After); sync=false
    # turns off the per-window durability barrier (ack = applied).
    ingest_stream: bool = True
    ingest_window_ms: float = 2.0
    ingest_max_batch: int = 4096
    ingest_queue: int = 8192
    ingest_tenant_queue: int = 4096
    ingest_sync: bool = True
    # failure-tolerance plane (obs/faults.py + cluster hedging):
    # fault-spec arms named fault points at startup
    # ("point[@match][,times=N][,delay=MS];..." — obs/faults.py);
    # hedge-ms < 0 disables hedged replica reads, 0 auto-derives the
    # hedge delay from flight-recorder p99 records, > 0 fixes it;
    # deadline-s is the default end-to-end cluster query deadline
    # (0 = none; every RPC attempt/hedge/retry budgets from it).
    fault_spec: str = ""
    cluster_hedge_ms: float = 0.0
    cluster_deadline_s: float = 0.0
    # mesh-sharded serving (memory/placement.py): mesh-devices > 1
    # splits the paged working set over the first N local devices —
    # every (index, shard) gets a sticky owner balanced by live
    # per-device ledger bytes, and the fused ragged program runs as
    # ONE shard_map with in-program psum/scatter combines.  0/1 = off
    # (the exact single-device behavior).  The env twin
    # PILOSA_TPU_MESH_DEVICES outranks the config (bench A/B lever).
    # placement-pin force-places shards ("idx/3=1,idx/*=0"; env twin
    # PILOSA_TPU_PLACEMENT_PIN) — pins override the balancer.
    cluster_mesh_devices: int = 0
    cluster_placement_pin: str = ""
    # online resharding (cluster/rebalance.py): chase-lag is the
    # delta-span backlog under which DELTA-CHASE hands off to the
    # FENCE (smaller = shorter write-blocked window, more chase
    # rounds); max-rounds bounds chase/copy retry loops;
    # fence-timeout-s bounds the drain + blocked-writer wait.
    cluster_rebalance_chase_lag: int = 8
    cluster_rebalance_max_rounds: int = 12
    cluster_rebalance_fence_timeout_s: float = 10.0
    # query flight recorder (obs/flight.py): always-on per-query ring
    # of phase-attributed records feeding /debug/queries and
    # /debug/trace.  recorder=false disables record keeping (the
    # tracing-overhead A/B switch; also PILOSA_TPU_FLIGHT=0);
    # ring bounds how many records are kept.
    flight_recorder: bool = True
    flight_ring: int = 512
    # roofline attribution (obs/roofline.py): join bytes-touched with
    # device execute time per op family into achieved-GB/s and
    # fraction-of-peak gauges.  peak-gbps 0 = measure a STREAM-style
    # probe at startup (PILOSA_TPU_PEAK_GBPS also overrides);
    # attribution=false drops the per-dispatch note entirely (the
    # overhead-smoke A/B switch, also PILOSA_TPU_ROOFLINE=0).
    roofline_attribution: bool = True
    roofline_peak_gbps: float = 0.0
    # statistics catalog (obs/stats.py + storage/stats_store.py):
    # persisted flight/roofline telemetry driving the engine's cost
    # decisions (cost gates, admission classing, cache eviction,
    # hedge derivation) plus the per-fingerprint regression sentinel.
    # enabled=false (or PILOSA_TPU_STATS=0 — the bench A/B lever)
    # reverts every consumer to its static heuristic, bit-exact.
    # The runtime plane samples FLIGHT RECORDS: disabling the flight
    # recorder ([flight] recorder=false) stops profile/sentinel/
    # hedge accumulation (the ingest-fed data plane keeps working).
    # persist=false keeps the catalog memory-only; snapshot-interval-s
    # is the tmp+rename snapshot cadence; heavy-cost-ms is the
    # measured-cost admission threshold; regression-ratio /
    # regression-min-samples arm the sentinel.
    stats_enabled: bool = True
    stats_persist: bool = True
    stats_snapshot_interval_s: float = 60.0
    stats_heavy_cost_ms: float = 5.0
    stats_regression_ratio: float = 3.0
    stats_regression_min_samples: int = 6
    # SQL serving plane (sql/costplan.py + sql/engine.py): pushdown
    # routes SELECT plan operators through the fused serving plane
    # (batcher, ragged dispatch, QoS admission, result cache) with
    # the catalog-fed cost-based planner; false — or the
    # PILOSA_TPU_SQL_PUSHDOWN=0 env kill-switch, the bench A/B
    # lever — reverts SQL to the solo host path, bit-exact.
    sql_pushdown: bool = True
    # incident forensics plane (obs/incidents.py + obs/watchdog.py +
    # obs/profiler.py continuous ring + obs/logger.py log ring):
    # anomaly triggers (SLO burn over slo-burn-threshold, the perf
    # sentinel, watchdog stalls, OOM-ladder trips, batch-leader
    # exceptions, ingest crashes) each capture ONE rate-limited
    # (min-interval-s), size-bounded (max-bundle-bytes) black-box
    # bundle persisted tmp+fsync+rename under dir (default
    # <data-dir>/incidents; empty + no data dir = memory-only ring).
    # enabled=false — or PILOSA_TPU_INCIDENTS=0 — kills the plane.
    # profile* drive the always-on continuous profiler whose window
    # ring rides in every bundle; log-ring sizes the log tail.
    incidents_enabled: bool = True
    incidents_dir: str = ""
    incidents_min_interval_s: float = 60.0
    incidents_max_bundles: int = 32
    incidents_max_bundle_bytes: int = 1 << 20
    incidents_slo_burn_threshold: float = 8.0
    incidents_profile: bool = True
    incidents_profile_hz: float = 7.0
    incidents_profile_window_s: float = 10.0
    incidents_profile_windows: int = 6
    incidents_log_ring: int = 512
    # stall watchdogs (obs/watchdog.py): progress-stamped deadlines
    # on the serving batch leader, ingest window drain, rebalance
    # controller, maintenance ticker, and heartbeat loops.  A loop
    # armed past deadline-s fires pilosa_watchdog_stalls_total{loop}
    # + a watchdog-stall incident naming the stuck phase; interval-s
    # paces the monitor.  enabled=false (or PILOSA_TPU_WATCHDOG=0)
    # disarms detection; the stamps themselves stay (~sub-us).
    watchdog_enabled: bool = True
    watchdog_interval_s: float = 1.0
    watchdog_deadline_s: float = 10.0
    # SLO burn-rate plane (obs/slo.py): latency-ms + latency-objective
    # define the latency SLO ("latency-objective of queries answer
    # under latency-ms"); availability-objective bounds the typed-
    # error fraction (503 sheds, 504 deadlines, partial results).
    # windows is the multi-window burn-rate set ("5m,1h,6h" or bare
    # seconds), evaluated at /debug/slo and exported as
    # pilosa_slo_burn_rate{slo,window}.
    slo_latency_ms: float = 250.0
    slo_latency_objective: float = 0.99
    slo_availability_objective: float = 0.999
    slo_windows: str = "5m,1h,6h"
    # temporal analytics ([timeq], models/timeq.py): write-finest
    # lands TIME writes in standard + the finest quantum unit only
    # (coarse views compact on the rollup tick instead of fanning out
    # per write); rollup arms the HTTP ticker's quantum-rollup sweep;
    # qcover plans multi-view range covers as per-view fused leaves
    # (one restack per cover shift instead of a whole-cover rebuild;
    # env twin PILOSA_TPU_QCOVER is the bench A/B lever).
    timeq_write_finest: bool = False
    timeq_rollup: bool = False
    timeq_qcover: bool = True
    # standing queries ([standing], executor/standing.py): registered
    # Count/TopN/GroupBy/SQL results are delta-maintained on write —
    # the serving ResultCache entry is ADVANCED by maintenance
    # instead of swept.  PILOSA_TPU_STANDING=0 is the kill-switch /
    # bench A/B lever and outranks a default-True config; max bounds
    # live registrations (register past it -> typed error).
    standing_enabled: bool = True
    standing_max: int = 256
    # continuous correctness auditing ([audit], obs/audit.py): the
    # shadow-execution sampler + ticker scrubbers.  PILOSA_TPU_AUDIT=0
    # is the runtime kill-switch and outranks a default-True config;
    # sample-rate is the per-served-read sampling fraction,
    # route-rates overrides it per serve route
    # ("cached=0.05,fused=0.01"), queue-max/concurrency bound the
    # shadow worker, scrub-*-n budget each ticker scrubber, and
    # quarantine caps the mismatch evidence ring.
    audit_enabled: bool = True
    audit_sample_rate: float = 0.01
    audit_route_rates: str = ""
    audit_queue_max: int = 64
    audit_concurrency: int = 1
    audit_scrub_cache_n: int = 4
    audit_scrub_standing_n: int = 2
    audit_scrub_replica_n: int = 2
    audit_quarantine: int = 32

    # -- disaggregated DAX tier ([dax] + [blob], dax/settings.py) --
    # blob names the tier kill-switch (PILOSA_TPU_DAX_BLOB=0 outranks
    # it); backend/root pick the blob store; worker-budget-bytes
    # bounds each stateless worker's resident set (0 = unbounded);
    # the scale-* thresholds drive the autoscaler's reconcile loop.
    blob_backend: str = ""
    blob_root: str = ""
    dax_blob: bool = True
    dax_lazy_hydrate: bool = True
    dax_worker_budget_bytes: int = 0
    dax_prefetch: int = 2
    dax_scale_out_burn: float = 2.0
    dax_scale_in_burn: float = 0.5
    dax_pressure_high: float = 0.9
    dax_min_workers: int = 1
    dax_max_workers: int = 8
    dax_standby: int = 1
    dax_reconcile_interval_s: float = 5.0
    dax_cooldown_s: float = 30.0
    dax_chase_lag: int = 8
    dax_chase_rounds: int = 12

    def apply_kernel_setting(self):
        """Translate tpu_kernels into the Pallas dispatch env flag.
        'auto' (the default) leaves PILOSA_TPU_PALLAS untouched — a
        user-exported override must survive config loading."""
        if self.tpu_kernels == "on":
            os.environ["PILOSA_TPU_PALLAS"] = "1"
        elif self.tpu_kernels == "off":
            os.environ["PILOSA_TPU_PALLAS"] = "0"

    def apply_stack_settings(self):
        """Push the [stacked] knobs into the runtime modules (the env
        flag for the A/B toggle, module globals for the numeric
        bounds — both read dynamically by the hot paths)."""
        os.environ["PILOSA_TPU_STACK_PATCH"] = \
            "1" if self.stack_patch else "0"
        os.environ["PILOSA_TPU_SPARSE_FORMAT"] = \
            "1" if self.stack_sparse_format else "0"
        from pilosa_tpu.executor import stacked
        from pilosa_tpu.memory import encode
        from pilosa_tpu.models import fragment
        fragment.DELTA_LOG_MAX = int(self.stack_delta_log_max)
        stacked._PATCH_MAX_FRAC = float(self.stack_patch_max_frac)
        encode.configure(dense_frac=self.stack_sparse_dense_frac)

    def apply_flight_settings(self):
        """Configure the process-global flight recorder ([flight])."""
        from pilosa_tpu.obs import flight
        flight.recorder.configure(enabled=self.flight_recorder,
                                  keep=self.flight_ring)

    def apply_fault_settings(self):
        """Arm config-specified fault points and publish the cluster
        hedge/deadline knobs (read dynamically per fan-out by
        cluster/coordinator.py, so a reconfigure applies live).
        Test-armed faults (faults.inject) are never touched."""
        from pilosa_tpu.obs import faults
        # config.load already folds PILOSA_TPU_FAULT_SPEC into
        # fault_spec: drop the import-time env arming before re-arming
        # as config, or every env rule's budget doubles.  A Config
        # carrying NO spec of its own (directly constructed, not
        # load()-built) must leave the operator's env arming alone —
        # clearing it here would silently disarm the chaos drill
        if self.fault_spec:
            faults.clear(source="env")
        faults.configure(self.fault_spec)
        # publish the knobs only when this Config actually carries a
        # non-default value (config.load folds the env var in, so a
        # loaded Config always does) — a directly-built default
        # Config must not clobber an operator-set env override
        for env, val, default in (
                ("PILOSA_TPU_CLUSTER_HEDGE_MS",
                 self.cluster_hedge_ms, 0.0),
                ("PILOSA_TPU_CLUSTER_DEADLINE_S",
                 self.cluster_deadline_s, 0.0),
                ("PILOSA_TPU_REBALANCE_CHASE_LAG",
                 self.cluster_rebalance_chase_lag, 8),
                ("PILOSA_TPU_REBALANCE_MAX_ROUNDS",
                 self.cluster_rebalance_max_rounds, 12),
                ("PILOSA_TPU_REBALANCE_FENCE_TIMEOUT_S",
                 self.cluster_rebalance_fence_timeout_s, 10.0)):
            if val != default or env not in os.environ:
                os.environ[env] = str(val)

    def apply_roofline_settings(self):
        """Configure roofline attribution ([roofline]) and kick the
        peak-bandwidth probe on a background thread (startup must not
        block ~50 ms on a STREAM probe).  A default-True config must
        not override an operator's PILOSA_TPU_ROOFLINE env
        kill-switch — leave the module resolving from env in that
        case (same contract as the hedge/deadline knobs in
        apply_fault_settings)."""
        from pilosa_tpu.obs import roofline
        enabled = self.roofline_attribution
        if enabled and "PILOSA_TPU_ROOFLINE" in os.environ:
            enabled = None  # env kill-switch stays in charge
        roofline.configure(enabled=enabled,
                           peak_gbps=self.roofline_peak_gbps or None)
        if roofline.enabled():
            roofline.ensure_peak(block=False)

    def apply_stats_settings(self, data_dir: str | None = None):
        """Configure the process statistics catalog ([stats]).  An
        operator's PILOSA_TPU_STATS env kill-switch outranks a
        default-True config (same contract as apply_roofline_settings);
        persistence lands under ``data_dir`` (the holder's path) when
        one exists — memory-only otherwise."""
        from pilosa_tpu.obs import stats
        enabled = self.stats_enabled
        if enabled and "PILOSA_TPU_STATS" in os.environ:
            enabled = None  # env kill-switch stays in charge
        base = data_dir if data_dir is not None else (self.data_dir
                                                      or None)
        path = (os.path.join(base, "stats.jsonl")
                if (self.stats_persist and base) else None)
        stats.configure(
            enabled=enabled, path=path,
            heavy_cost_ms=self.stats_heavy_cost_ms,
            regression_ratio=self.stats_regression_ratio,
            regression_min_samples=self.stats_regression_min_samples,
            snapshot_interval_s=self.stats_snapshot_interval_s)

    def apply_sql_settings(self):
        """Configure the SQL serving plane ([sql]).  The default-True
        config leaves the PILOSA_TPU_SQL_PUSHDOWN env kill-switch in
        charge (it is the bench A/B lever and may flip at runtime);
        an explicit pushdown=false pins the host path."""
        from pilosa_tpu.sql import costplan
        costplan.configure(
            enabled=None if self.sql_pushdown else False)

    def apply_watchdog_settings(self):
        """Configure the stall-watchdog monitor ([watchdog]).  The
        PILOSA_TPU_WATCHDOG env kill-switch outranks a default-True
        config (same contract as apply_roofline_settings)."""
        from pilosa_tpu.obs import watchdog
        enabled = self.watchdog_enabled
        if enabled and "PILOSA_TPU_WATCHDOG" in os.environ:
            enabled = None  # env kill-switch stays in charge
        watchdog.configure(enabled=enabled,
                           interval_s=self.watchdog_interval_s,
                           deadline_s=self.watchdog_deadline_s)

    def apply_incident_settings(self, data_dir: str | None = None):
        """Configure the incident forensics plane ([incidents]):
        bundle manager (persistence under ``data_dir``/incidents when
        one exists — memory-only otherwise), the continuous profiler,
        and the log-ring size.  The PILOSA_TPU_INCIDENTS env
        kill-switch outranks a default-True config."""
        from pilosa_tpu.obs import incidents, logger, profiler
        enabled = self.incidents_enabled
        if enabled and "PILOSA_TPU_INCIDENTS" in os.environ:
            enabled = None  # env kill-switch stays in charge
        base = data_dir if data_dir is not None else (self.data_dir
                                                     or None)
        dir = self.incidents_dir or (
            os.path.join(base, "incidents") if base else None)
        snap = {f.name: getattr(self, f.name)
                for f in fields(Config)
                if "secret" not in f.name}  # bundles must not leak auth
        # dir=None leaves the manager's current dir alone (a
        # path-less embedded server must not detach a data-dir'd
        # sibling's persistence — same contract as stats paths)
        incidents.configure(
            enabled=enabled, dir=dir,
            min_interval_s=self.incidents_min_interval_s,
            max_bundles=self.incidents_max_bundles,
            max_bundle_bytes=self.incidents_max_bundle_bytes,
            slo_burn_threshold=self.incidents_slo_burn_threshold,
            config_snapshot=snap)
        on = (incidents.enabled() if enabled is None
              else bool(enabled)) and self.incidents_profile
        profiler.configure_continuous(
            enabled=on, hz=self.incidents_profile_hz,
            window_s=self.incidents_profile_window_s,
            keep=self.incidents_profile_windows)
        logger.ring.configure(int(self.incidents_log_ring))

    def apply_slo_settings(self):
        """Build the process SLO tracker from the [slo] knobs."""
        from pilosa_tpu.obs import slo
        slo.configure(
            latency_ms=self.slo_latency_ms,
            latency_objective=self.slo_latency_objective,
            availability_objective=self.slo_availability_objective,
            windows=self.slo_windows)

    def apply_timeq_settings(self):
        """Push the [timeq] knobs into models/timeq.py.  Env twins
        (PILOSA_TPU_TIMEQ_WRITE_FINEST / PILOSA_TPU_TIMEQ_ROLLUP /
        PILOSA_TPU_QCOVER) are read dynamically by the module and
        outrank these values (bench A/B levers)."""
        from pilosa_tpu.models import timeq
        qc = self.timeq_qcover
        if qc and "PILOSA_TPU_QCOVER" in os.environ:
            qc = None  # env kill-switch stays in charge
        timeq.configure(write_finest=self.timeq_write_finest,
                        rollup=self.timeq_rollup, qcover=qc)

    def apply_standing_settings(self):
        """Configure the standing-query registry ([standing]).  The
        PILOSA_TPU_STANDING env kill-switch outranks a default-True
        config (same contract as apply_roofline_settings)."""
        from pilosa_tpu.executor import standing
        enabled = self.standing_enabled
        if enabled and "PILOSA_TPU_STANDING" in os.environ:
            enabled = None  # env kill-switch stays in charge
        standing.configure(enabled=enabled,
                           max_registrations=self.standing_max)

    def apply_audit_settings(self):
        """Configure the correctness-auditing plane ([audit]).  The
        PILOSA_TPU_AUDIT env kill-switch outranks a default-True
        config (same contract as apply_standing_settings)."""
        from pilosa_tpu.obs import audit
        enabled = self.audit_enabled
        if enabled and "PILOSA_TPU_AUDIT" in os.environ:
            enabled = None  # env kill-switch stays in charge
        audit.configure(
            enabled=enabled,
            sample_rate=self.audit_sample_rate,
            route_rates=self.audit_route_rates,
            queue_max=self.audit_queue_max,
            concurrency=self.audit_concurrency,
            scrub_cache_n=self.audit_scrub_cache_n,
            scrub_standing_n=self.audit_scrub_standing_n,
            scrub_replica_n=self.audit_scrub_replica_n,
            quarantine=self.audit_quarantine)

    def apply_dax_settings(self):
        """Push the [dax]/[blob] stanzas into dax/settings.py.  The
        PILOSA_TPU_DAX_BLOB env kill-switch outranks a default-True
        config (same contract as apply_standing_settings); the other
        knobs' env twins are re-read dynamically by the accessors."""
        from pilosa_tpu.dax import settings as dax_settings
        blob = self.dax_blob
        if blob and "PILOSA_TPU_DAX_BLOB" in os.environ:
            blob = None  # env kill-switch stays in charge
        dax_settings.configure(
            blob=blob,
            backend=self.blob_backend,
            root=self.blob_root,
            lazy_hydrate=self.dax_lazy_hydrate,
            worker_budget_bytes=self.dax_worker_budget_bytes,
            prefetch=self.dax_prefetch,
            scale_out_burn=self.dax_scale_out_burn,
            scale_in_burn=self.dax_scale_in_burn,
            pressure_high=self.dax_pressure_high,
            min_workers=self.dax_min_workers,
            max_workers=self.dax_max_workers,
            standby=self.dax_standby,
            reconcile_interval_s=self.dax_reconcile_interval_s,
            cooldown_s=self.dax_cooldown_s,
            chase_lag=self.dax_chase_lag,
            chase_rounds=self.dax_chase_rounds)

    def apply_placement_settings(self):
        """Push the [cluster] serving-mesh knobs into the placement
        module (memory/placement.py).  Env twins
        (PILOSA_TPU_MESH_DEVICES / PILOSA_TPU_PLACEMENT_PIN) are read
        dynamically by the module and outrank these values; configure
        bumps the placement epoch only when something changed."""
        from pilosa_tpu.memory import placement
        placement.configure(
            mesh_devices=self.cluster_mesh_devices,
            pin=self.cluster_placement_pin)

    def apply_memory_settings(self):
        """Push the [memory] knobs into the process residency manager
        (pilosa_tpu/memory: budget ledger, paged stacks, OOM
        backstop)."""
        from pilosa_tpu import memory
        memory.configure(budget_bytes=self.memory_budget_bytes,
                         headroom_frac=self.memory_headroom_frac,
                         page_bytes=self.memory_page_bytes,
                         paged=self.memory_paged,
                         oom_retry=self.memory_oom_retry,
                         host_fallback=self.memory_host_fallback)


# TOML key (possibly [table] key) -> Config attribute
_TOML_KEYS = {
    "data-dir": "data_dir",
    "bind": "bind",
    "port": "port",
    "grpc-port": "grpc_port",
    "cluster.name": "cluster_name",
    "cluster.replicas": "replicas",
    "auth.secret": "auth_secret",
    "auth.policy": "auth_policy",
    "tpu.kernels": "tpu_kernels",
    "long-query-time": "long_query_time",
    "serving.batching": "serving_batching",
    "serving.batch-window-ms": "serving_batch_window_ms",
    "serving.batch-max": "serving_batch_max",
    "serving.cache-mb": "serving_cache_mb",
    "serving.ragged": "serving_ragged",
    "serving.admission": "serving_admission",
    "serving.heavy-slots": "serving_heavy_slots",
    "serving.queue-max": "serving_queue_max",
    "serving.tenant-weights": "serving_tenant_weights",
    "serving.default-deadline-ms": "serving_default_deadline_ms",
    "stacked.patch": "stack_patch",
    "stacked.delta-log-max": "stack_delta_log_max",
    "stacked.patch-max-frac": "stack_patch_max_frac",
    "stacked.sparse-format": "stack_sparse_format",
    "stacked.sparse-dense-frac": "stack_sparse_dense_frac",
    "flight.recorder": "flight_recorder",
    "flight.ring": "flight_ring",
    "roofline.attribution": "roofline_attribution",
    "roofline.peak-gbps": "roofline_peak_gbps",
    "stats.enabled": "stats_enabled",
    "stats.persist": "stats_persist",
    "stats.snapshot-interval-s": "stats_snapshot_interval_s",
    "stats.heavy-cost-ms": "stats_heavy_cost_ms",
    "stats.regression-ratio": "stats_regression_ratio",
    "stats.regression-min-samples": "stats_regression_min_samples",
    "sql.pushdown": "sql_pushdown",
    "incidents.enabled": "incidents_enabled",
    "incidents.dir": "incidents_dir",
    "incidents.min-interval-s": "incidents_min_interval_s",
    "incidents.max-bundles": "incidents_max_bundles",
    "incidents.max-bundle-bytes": "incidents_max_bundle_bytes",
    "incidents.slo-burn-threshold": "incidents_slo_burn_threshold",
    "incidents.profile": "incidents_profile",
    "incidents.profile-hz": "incidents_profile_hz",
    "incidents.profile-window-s": "incidents_profile_window_s",
    "incidents.profile-windows": "incidents_profile_windows",
    "incidents.log-ring": "incidents_log_ring",
    "watchdog.enabled": "watchdog_enabled",
    "watchdog.interval-s": "watchdog_interval_s",
    "watchdog.deadline-s": "watchdog_deadline_s",
    "slo.latency-ms": "slo_latency_ms",
    "slo.latency-objective": "slo_latency_objective",
    "slo.availability-objective": "slo_availability_objective",
    "slo.windows": "slo_windows",
    "ingest.stream": "ingest_stream",
    "ingest.window-ms": "ingest_window_ms",
    "ingest.max-batch": "ingest_max_batch",
    "ingest.queue": "ingest_queue",
    "ingest.tenant-queue": "ingest_tenant_queue",
    "ingest.sync": "ingest_sync",
    "faults.spec": "fault_spec",
    "cluster.mesh-devices": "cluster_mesh_devices",
    "cluster.placement-pin": "cluster_placement_pin",
    "cluster.hedge-ms": "cluster_hedge_ms",
    "cluster.deadline-s": "cluster_deadline_s",
    "cluster.rebalance-chase-lag": "cluster_rebalance_chase_lag",
    "cluster.rebalance-max-rounds": "cluster_rebalance_max_rounds",
    "cluster.rebalance-fence-timeout-s":
        "cluster_rebalance_fence_timeout_s",
    "memory.budget-bytes": "memory_budget_bytes",
    "memory.headroom-frac": "memory_headroom_frac",
    "memory.page-bytes": "memory_page_bytes",
    "memory.paged": "memory_paged",
    "memory.prefetch": "memory_prefetch",
    "memory.prefetch-interval-s": "memory_prefetch_interval_s",
    "memory.oom-retry": "memory_oom_retry",
    "memory.host-fallback": "memory_host_fallback",
    "timeq.write-finest": "timeq_write_finest",
    "timeq.rollup": "timeq_rollup",
    "timeq.qcover": "timeq_qcover",
    "standing.enabled": "standing_enabled",
    "standing.max": "standing_max",
    "audit.enabled": "audit_enabled",
    "audit.sample-rate": "audit_sample_rate",
    "audit.route-rates": "audit_route_rates",
    "audit.queue-max": "audit_queue_max",
    "audit.concurrency": "audit_concurrency",
    "audit.scrub-cache-n": "audit_scrub_cache_n",
    "audit.scrub-standing-n": "audit_scrub_standing_n",
    "audit.scrub-replica-n": "audit_scrub_replica_n",
    "audit.quarantine": "audit_quarantine",
    "blob.backend": "blob_backend",
    "blob.root": "blob_root",
    "dax.blob": "dax_blob",
    "dax.lazy-hydrate": "dax_lazy_hydrate",
    "dax.worker-budget-bytes": "dax_worker_budget_bytes",
    "dax.prefetch": "dax_prefetch",
    "dax.scale-out-burn": "dax_scale_out_burn",
    "dax.scale-in-burn": "dax_scale_in_burn",
    "dax.pressure-high": "dax_pressure_high",
    "dax.min-workers": "dax_min_workers",
    "dax.max-workers": "dax_max_workers",
    "dax.standby": "dax_standby",
    "dax.reconcile-interval-s": "dax_reconcile_interval_s",
    "dax.cooldown-s": "dax_cooldown_s",
    "dax.chase-lag": "dax_chase_lag",
    "dax.chase-rounds": "dax_chase_rounds",
}

ENV_PREFIX = "PILOSA_TPU_"


def _parse_toml_minimal(text: str) -> dict:
    """Fallback TOML subset parser for Python 3.10 (no stdlib
    tomllib): ``[table]`` headers and scalar ``key = value`` pairs
    (quoted strings, booleans, ints, floats) — exactly the shape of
    this server's config files.  Anything fancier raises."""
    doc: dict = {}
    table = doc
    for ln, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("[") and line.endswith("]"):
            table = doc
            for part in line[1:-1].strip().split("."):
                table = table.setdefault(part.strip(), {})
            continue
        if "=" not in line:
            raise ValueError(f"config line {ln}: not key = value")
        key, _, val = line.partition("=")
        key, val = key.strip(), val.strip()
        if not val or val.startswith("#"):
            raise ValueError(f"config line {ln}: missing value")
        if val[:1] in "\"'":
            # quoted string: close at the MATCHING quote so '#' (and
            # anything else) inside the value survives; a trailing
            # comment after the close quote is dropped
            end = val.find(val[0], 1)
            if end < 0:
                raise ValueError(f"config line {ln}: unclosed string")
            table[key] = val[1:end]
            continue
        val = val.split("#", 1)[0].strip()
        if val in ("true", "false"):
            table[key] = val == "true"
        else:
            try:
                table[key] = int(val)
            except ValueError:
                table[key] = float(val)  # raises on junk — good
    return doc


def _flatten(doc: dict, prefix: str = "") -> dict:
    out = {}
    for k, v in doc.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "."))
        else:
            out[key] = v
    return out


def load(path: str | None = None, env: dict | None = None,
         overrides: dict | None = None) -> Config:
    """Build a Config with flag > env > file > default precedence
    (server/config.go's viper layering)."""
    cfg = Config()
    names = {f.name for f in fields(Config)}
    if path:
        if tomllib is not None:
            with open(path, "rb") as f:
                doc = tomllib.load(f)
        else:
            with open(path, "r", encoding="utf-8") as f:
                doc = _parse_toml_minimal(f.read())
        flat = _flatten(doc)
        for tk, attr in _TOML_KEYS.items():
            if tk in flat:
                setattr(cfg, attr, _coerce(cfg, attr, flat[tk]))
    env = os.environ if env is None else env
    for attr in names:
        ev = env.get(ENV_PREFIX + attr.upper())
        if ev is not None:
            setattr(cfg, attr, _coerce(cfg, attr, ev))
    for k, v in (overrides or {}).items():
        if v is not None and k in names:
            setattr(cfg, k, _coerce(cfg, k, v))
    return cfg


def _coerce(cfg: Config, attr: str, value):
    cur = getattr(cfg, attr)
    if isinstance(cur, bool):
        return str(value).lower() in ("1", "true", "yes", "on")
    if isinstance(cur, int):
        return int(value)
    if isinstance(cur, float):
        return float(value)
    return str(value)
