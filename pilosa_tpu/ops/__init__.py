"""Device-side kernels: packed-bitmap set algebra, BSI plane math.

These are the TPU equivalents of the reference's "kernel-grade" Go code:
roaring container pairwise ops (roaring/roaring.go:927-1663), BSI plane
walks (fragment.go:724-1305), and popcount loops.  Container
polymorphism (array/run/bitmap) collapses on-device to dense packed
``uint32`` lanes; sparse encodings live host-side in the storage layer.
"""

from pilosa_tpu.ops import bitmap, bsi

__all__ = ["bitmap", "bsi"]
