"""Bit-sliced integer (BSI) kernels.

Encoding matches the reference (fragment.go:34-66, fragment.go:579-718):
an integer field's shard is a stack of packed bit-planes —

- plane 0: exists (not-null) bit        (bsiExistsBit)
- plane 1: sign bit (set => negative)   (bsiSignBit)
- plane 2+i: magnitude bit i, LSB first (bsiOffsetBit)

i.e. sign-magnitude, NOT two's complement.  ``planes`` arrays have shape
``(2 + depth, W)`` uint32 with W packed words per shard-row.

The reference computes Range/Min/Max with data-dependent bitmap walks
(fragment.go:937-1305).  Here the same semantics are expressed as
fixed-shape bit-serial comparator chains over all 2^20 columns at once:
one pass over the magnitude planes yields per-column LT/EQ masks against
a predicate, and all six comparison ops plus BETWEEN are cheap boolean
combinations of those masks with the sign/exists planes.  Predicates
enter as per-plane broadcast masks (a ``(depth,)`` uint32 input array),
so changing the predicate does NOT trigger recompilation and 64-bit
predicates never need 64-bit scalars on device.

Exactness: Sum returns per-plane popcounts; the host combines them as
``sum(+/- pc[i] << i)`` in exact Python ints, so >2^53 totals are exact
without enabling x64 on device (SURVEY §7 "Exactness").
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from pilosa_tpu.ops import bitmap as bm
from pilosa_tpu.shardwidth import (
    BSI_EXISTS_BIT,
    BSI_OFFSET_BIT,
    BSI_SIGN_BIT,
    SHARD_WIDTH,
)

_ONES = np.uint32(0xFFFFFFFF)


# ---------------------------------------------------------------------------
# Host-side encode/decode + predicate prep (numpy / exact Python ints)
# ---------------------------------------------------------------------------

def depth_for_range(lo: int, hi: int) -> int:
    """Bit depth needed to store magnitudes in [lo, hi] (>=1)."""
    m = max(abs(int(lo)), abs(int(hi)), 1)
    return max(1, m.bit_length())


def encode(columns, values, depth: int | None = None,
           width: int = SHARD_WIDTH) -> np.ndarray:
    """Pack (column, value) pairs into sign-magnitude planes.

    Mirrors fragment.setValueBase (fragment.go:662-718): exists bit set,
    sign bit iff value < 0, magnitude bits of abs(value) LSB-first.
    Values must fit int64; magnitudes must fit in `depth` bits.
    """
    columns = np.asarray(columns, dtype=np.int64)
    vals = np.asarray(values, dtype=np.int64).reshape(-1)
    assert vals.shape == columns.shape
    if columns.size:
        # last-write-wins on duplicate columns (setValueBase overwrites)
        _, rev_first = np.unique(columns[::-1], return_index=True)
        keep = columns.size - 1 - rev_first
        columns, vals = columns[keep], vals[keep]
    neg = vals < 0
    # two's-complement wrap of -int64min yields magnitude 2^63 in uint64
    mags = np.where(neg, np.negative(vals), vals).view(np.uint64)
    need = depth_for_range(0, int(mags.max())) if vals.size else 1
    if depth is None:
        depth = need
    elif need > depth:
        raise ValueError(
            f"value magnitude needs {need} bits, field depth is {depth}")
    planes = np.zeros((2 + depth, width // 32), dtype=np.uint32)
    planes[BSI_EXISTS_BIT] = bm.from_columns(columns, width)
    planes[BSI_SIGN_BIT] = bm.from_columns(columns[neg], width)
    for i in range(depth):
        planes[BSI_OFFSET_BIT + i] = bm.from_columns(
            columns[(mags >> np.uint64(i)) & np.uint64(1) == 1], width)
    return planes


def decode(planes) -> tuple[np.ndarray, list[int]]:
    """Inverse of encode: -> (columns, values) with exact Python ints.

    Vectorized per plane: one numpy gather+shift per magnitude bit
    (depth passes over the set columns), with an object-int fallback
    only for magnitudes beyond int64 (depth > 62).
    """
    planes = np.asarray(planes)
    depth = planes.shape[0] - 2
    cols = bm.to_columns(planes[BSI_EXISTS_BIT])
    if cols.size == 0:
        return cols, []
    w = (cols >> np.uint64(5)).astype(np.int64)
    b = (cols & np.uint64(31)).astype(np.uint32)

    def bits(plane):
        return ((plane[w] >> b) & 1).astype(np.int64)

    if depth <= 62:
        mags = np.zeros(cols.size, dtype=np.int64)
        for i in range(depth):
            mags |= bits(planes[BSI_OFFSET_BIT + i]) << np.int64(i)
        sign = bits(planes[BSI_SIGN_BIT]).astype(bool)
        values = np.where(sign, -mags, mags).tolist()
    else:
        mags = np.zeros(cols.size, dtype=object)
        for i in range(depth):
            mags += bits(planes[BSI_OFFSET_BIT + i]).astype(object) << i
        sign = bits(planes[BSI_SIGN_BIT]).astype(bool)
        values = [-m if s else m for m, s in zip(mags.tolist(), sign)]
    return cols, values


def unpack_bits(words):
    """Device bit-unpack: (..., W) uint32 words -> (..., W*32) int32
    0/1 per column (column c = word c>>5, bit c&31)."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = (words[..., None] >> shifts) & jnp.uint32(1)
    return bits.reshape(*words.shape[:-1],
                        words.shape[-1] * 32).astype(jnp.int32)


def decode_device(planes):
    """Device-side BSI decode: planes (..., 2+depth, W) ->
    (exists, sign, lo, hi), each (..., W*32) int32.

    The value of column c is  (-1)^sign * (lo + (hi << 31)); the split
    keeps every device word in int32 (no x64) for depths up to 62.
    This is the fixed-shape replacement for the reference's per-column
    value materialization (executor.go:4758 Extract, 9321 Sort,
    2034 Distinct-BSI): one pass over the plane stack unpacks ALL 2^20
    columns at once, so Sort/Extract/Distinct issue O(shard-chunks)
    device calls instead of O(columns) host work.
    """
    depth = planes.shape[-2] - 2
    assert depth <= 62, "decode_device supports depth <= 62"
    exists = unpack_bits(planes[..., BSI_EXISTS_BIT, :])
    sign = unpack_bits(planes[..., BSI_SIGN_BIT, :])
    lo = jnp.zeros_like(exists)
    hi = jnp.zeros_like(exists)
    for i in range(depth):
        bit = unpack_bits(planes[..., BSI_OFFSET_BIT + i, :])
        if i < 31:
            lo = lo | (bit << i)
        else:
            hi = hi | (bit << (i - 31))
    return exists, sign, lo, hi


def host_combine_decoded(exists, sign, lo, hi):
    """Numpy combine of decode_device outputs -> (exists bool array,
    values int64 array over ALL columns; value 0 where not exists)."""
    ex = np.asarray(exists).astype(bool)
    vals = (np.asarray(lo).astype(np.int64)
            | (np.asarray(hi).astype(np.int64) << 31))
    neg = np.asarray(sign).astype(bool)
    vals = np.where(neg, -vals, vals)
    return ex, np.where(ex, vals, 0)


def unpack_bits_np(words: np.ndarray) -> np.ndarray:
    """Host bit-unpack mirroring unpack_bits: (..., W) uint32 ->
    (..., W*32) bool."""
    words = np.asarray(words, dtype=np.uint32)
    bits = (words[..., None] >> np.arange(32, dtype=np.uint32)) & 1
    return bits.reshape(*words.shape[:-1],
                        words.shape[-1] * 32).astype(bool)


def predicate_masks(upredicate: int, depth: int) -> np.ndarray:
    """Per-plane broadcast masks for an unsigned predicate.

    mask[i] is 0xFFFFFFFF iff bit i of upredicate is set.  upredicate
    must fit in `depth` bits — the executor clamps/short-circuits
    out-of-range predicates at plan time (see range_* docstrings).
    """
    assert 0 <= upredicate < (1 << depth), (upredicate, depth)
    return np.array(
        [_ONES if (upredicate >> i) & 1 else np.uint32(0) for i in range(depth)],
        dtype=np.uint32,
    )


# ---------------------------------------------------------------------------
# Device-side kernels (pure jnp; compose under one jit)
# ---------------------------------------------------------------------------

def _mag(planes):
    return planes[BSI_OFFSET_BIT:]


def cmp_unsigned(mag_planes, pbits):
    """Bit-serial compare of per-column magnitudes against a predicate.

    mag_planes: (depth, W) uint32, LSB-first.  pbits: (depth,) uint32
    broadcast masks from predicate_masks().  Returns packed masks
    (lt, eq): per-column magnitude <, == the predicate.

    This one pass replaces the reference's rangeLTUnsigned /
    rangeGTUnsigned / rangeEQ bit walks (fragment.go:1044-1100,
    1158-1213, 968-1005): each of depth steps is 4 VPU ops on 32768
    lanes, with no data-dependent control flow.
    """
    depth = mag_planes.shape[0]
    w = mag_planes.shape[-1]
    lt = jnp.zeros_like(mag_planes[0])
    eq = jnp.full_like(mag_planes[0], _ONES)
    for i in range(depth - 1, -1, -1):
        m = mag_planes[i]
        p = pbits[i]  # scalar word mask, broadcasts over (W,)
        lt = lt | (eq & ~m & p)
        eq = eq & ~(m ^ p)
    return lt, eq


def range_eq(planes, pbits, pred_is_neg):
    """Columns whose value == predicate (fragment.rangeEQ semantics).

    pred_is_neg: traced bool scalar — predicate sign chooses the sign
    plane filter (negatives-only vs positives-only).
    """
    exists, sign = planes[BSI_EXISTS_BIT], planes[BSI_SIGN_BIT]
    _, eq = cmp_unsigned(_mag(planes), pbits)
    sign_sel = jnp.where(pred_is_neg, exists & sign, exists & ~sign)
    return sign_sel & eq


def range_neq(planes, pbits, pred_is_neg):
    """exists AND NOT eq (fragment.rangeNEQ)."""
    exists = planes[BSI_EXISTS_BIT]
    return exists & ~range_eq(planes, pbits, pred_is_neg)


def range_lt(planes, pbits, pred_is_neg, allow_eq: bool):
    """Columns with value < (or <=) predicate.

    Equivalent to fragment.rangeLT (fragment.go:1007-1042) without its
    dynamic special cases: for predicate p with magnitude masks pbits,
      p >= 0: negatives ∪ (positives with mag <(=) p)
      p <  0: negatives with mag >(=) |p|
    """
    exists, sign = planes[BSI_EXISTS_BIT], planes[BSI_SIGN_BIT]
    lt, eq = cmp_unsigned(_mag(planes), pbits)
    if allow_eq:
        ltu, gtu = lt | eq, ~lt
    else:
        ltu, gtu = lt, ~(lt | eq)
    pos_case = (exists & sign) | (exists & ~sign & ltu)
    neg_case = exists & sign & gtu
    return jnp.where(pred_is_neg, neg_case, pos_case)


def range_gt(planes, pbits, pred_is_neg, allow_eq: bool):
    """Columns with value > (or >=) predicate (fragment.rangeGT).

      p >= 0: positives with mag >(=) p
      p <  0: positives ∪ (negatives with mag <(=) |p|)
    """
    exists, sign = planes[BSI_EXISTS_BIT], planes[BSI_SIGN_BIT]
    lt, eq = cmp_unsigned(_mag(planes), pbits)
    if allow_eq:
        ltu, gtu = lt | eq, ~lt
    else:
        ltu, gtu = lt, ~(lt | eq)
    pos_case = exists & ~sign & gtu
    neg_case = (exists & ~sign) | (exists & sign & ltu)
    return jnp.where(pred_is_neg, neg_case, pos_case)


def range_between(planes, abits, bbits, a_is_neg, b_is_neg):
    """Columns with a <= value <= b (fragment.rangeBetween semantics).

    abits/bbits are magnitude masks of |a| and |b|.  Regimes selected
    by the (traced) predicate signs:
      0 <= a <= b      : positives with a <= mag <= b
      a <= b < 0       : negatives with |b| <= mag <= |a|
      a < 0 <= b       : (negatives with mag <= |a|) ∪ (positives with mag <= b)
      a >= 0 > b       : inverted range — empty
    """
    exists, sign = planes[BSI_EXISTS_BIT], planes[BSI_SIGN_BIT]
    lt_a, eq_a = cmp_unsigned(_mag(planes), abits)
    lt_b, eq_b = cmp_unsigned(_mag(planes), bbits)
    gte_a, lte_a = ~lt_a, lt_a | eq_a
    gte_b, lte_b = ~lt_b, lt_b | eq_b
    pos_case = exists & ~sign & gte_a & lte_b
    neg_case = exists & sign & gte_b & lte_a
    cross_case = (exists & sign & lte_a) | (exists & ~sign & lte_b)
    empty = jnp.zeros_like(exists)
    return jnp.where(
        a_is_neg,
        jnp.where(b_is_neg, neg_case, cross_case),
        jnp.where(b_is_neg, empty, pos_case),
    )


def not_null(planes):
    """The exists row (fragment.notNull)."""
    return planes[BSI_EXISTS_BIT]


def sum_counts(planes, filter_words=None):
    """Per-plane popcounts for exact host-side Sum.

    Returns (count, pos_pc, neg_pc): count of non-null (filtered)
    columns, and per-magnitude-plane popcounts split by sign, each
    (depth,) int32.  Host computes  sum = Σ (pos[i]-neg[i]) << i  in
    exact Python ints — the analog of roaring.BitmapBSICountFilter
    (fragment.sum, fragment.go:718-746) with int64-exactness preserved.
    """
    exists, sign = planes[BSI_EXISTS_BIT], planes[BSI_SIGN_BIT]
    consider = exists if filter_words is None else exists & filter_words
    pos = consider & ~sign
    neg = consider & sign
    mag = _mag(planes)
    pos_pc = bm.count(mag & pos[None, :])
    neg_pc = bm.count(mag & neg[None, :])
    return bm.count(consider), pos_pc, neg_pc


def host_sum(count, pos_pc, neg_pc) -> tuple[int, int]:
    """Combine sum_counts() outputs into (sum, count) exact ints."""
    pos_pc = np.asarray(pos_pc).tolist()
    neg_pc = np.asarray(neg_pc).tolist()
    total = sum((p - n) << i for i, (p, n) in enumerate(zip(pos_pc, neg_pc)))
    return int(total), int(np.asarray(count))


def _max_unsigned_walk(mag_planes, filter_words):
    """fragment.maxUnsigned (fragment.go:836-857) as a fixed-shape scan.

    Returns (bits, count): bits (depth,) bool MSB-walk decisions
    (bit i of the max), count int32 of columns attaining the max.
    """
    depth = mag_planes.shape[0]
    filt = filter_words
    bits = []
    for i in range(depth - 1, -1, -1):
        ones = filt & mag_planes[i]
        took = bm.any_set(ones)
        filt = jnp.where(took, ones, filt)
        bits.append(took)
    bits = jnp.stack(bits[::-1])  # LSB-first
    return bits, bm.count(filt)


def _min_unsigned_walk(mag_planes, filter_words):
    """fragment.minUnsigned (fragment.go:783-803): prefer zero bits."""
    depth = mag_planes.shape[0]
    filt = filter_words
    bits = []
    for i in range(depth - 1, -1, -1):
        zeroes = filt & ~mag_planes[i]
        nonempty = bm.any_set(zeroes)
        filt = jnp.where(nonempty, zeroes, filt)
        bits.append(~nonempty)  # forced 1-bit when no zero survives
    bits = jnp.stack(bits[::-1])
    return bits, bm.count(filt)


def min_op(planes, filter_words=None):
    """fragment.min (fragment.go:745-781) both branches + selector.

    Returns (is_neg, bits, count, nonempty).  If any negative value is
    in scope the min is -(max unsigned over negatives); otherwise the
    min unsigned over positives.  Host assembles value = (+/-) Σ bits<<i.
    """
    exists, sign = planes[BSI_EXISTS_BIT], planes[BSI_SIGN_BIT]
    consider = exists if filter_words is None else exists & filter_words
    negs = consider & sign
    pos = consider & ~sign
    any_neg = bm.any_set(negs)
    nb, ncount = _max_unsigned_walk(_mag(planes), negs)
    pb, pcount = _min_unsigned_walk(_mag(planes), pos)
    bits = jnp.where(any_neg, nb, pb)
    count = jnp.where(any_neg, ncount, pcount)
    return any_neg, bits, count, bm.any_set(consider)


def max_op(planes, filter_words=None):
    """fragment.max (fragment.go:805-834): positives preferred, else
    -(min unsigned over negatives)."""
    exists, sign = planes[BSI_EXISTS_BIT], planes[BSI_SIGN_BIT]
    consider = exists if filter_words is None else exists & filter_words
    pos = consider & ~sign
    negs = consider & sign
    any_pos = bm.any_set(pos)
    pb, pcount = _max_unsigned_walk(_mag(planes), pos)
    nb, ncount = _min_unsigned_walk(_mag(planes), negs)
    bits = jnp.where(any_pos, pb, nb)
    count = jnp.where(any_pos, pcount, ncount)
    return ~any_pos, bits, count, bm.any_set(consider)


def host_minmax(is_neg, bits, count, nonempty) -> tuple[int, int]:
    """Assemble (value, count) from min_op/max_op outputs; exact ints.

    Matches reference behavior of returning (0, 0) on empty scope.
    """
    if not bool(np.asarray(nonempty)):
        return 0, 0
    bits = np.asarray(bits).tolist()
    mag = sum(1 << i for i, b in enumerate(bits) if b)
    val = -mag if bool(np.asarray(is_neg)) else mag
    return int(val), int(np.asarray(count))
