"""Pallas TPU kernels for the per-shard hot loops.

SURVEY §3.2 names four hot loops in the reference; the three that are
device-side here get hand-scheduled Pallas kernels (the fourth — RBF
leaf-cell iteration — is the native C++ storage layer):

- pairwise container ops + popcount  (roaring/roaring.go:927-1663, 542)
  -> :func:`pair_popcount` — one fused AND+popcount+reduce pass.
- BSI plane walks                    (fragment.go:724-1305)
  -> :func:`bsi_sum_counts` — one pass over the plane stack computing
  the filtered per-plane sign-split popcounts.
- TopK candidate-row counting        (executor.go:2570-2777)
  -> :func:`masked_popcount` — batched rows AND one filter, popcounts.

Why Pallas instead of plain jnp: these ops are pure HBM-bandwidth
streams (popcount is 1 VPU op/word).  The jnp forms are already good —
XLA fuses AND into the popcount-reduce — so the kernels' win is
schedule control: one grid walk per operand stream, explicit VMEM
blocks sized to double-buffer, and accumulation in int32 without
intermediate materialization.  Everything is wrapped so the jnp path
(`ops.bitmap`/`ops.bsi`) stays the reference implementation; tests
cross-check the two.

Measured guidance (v5e-1, 954 shards x 2^20 cols): standalone these
kernels match XLA within noise (~760 GB/s scan, ~93% of HBM peak —
the op is bandwidth-bound, there is nothing left to schedule).  BUT a
pallas_call is a fusion barrier: when the operand is produced by an
upstream elementwise op (e.g. the bench's per-iteration perturbation),
XLA fuses producer+scan into one pass while the kernel forces the
intermediate through HBM (measured 6x slower).  Hence the dispatch
rule in enabled(): kernels serve executor paths whose inputs are
device-RESIDENT tiles (no producer to fuse); whole-pipeline jnp
expressions stay with XLA.

The exception is :func:`groupby_sum`, where the kernel is the DEFAULT
on TPU: the XLA GroupBy scan must materialize gathered (C, S, W)
combo masks and re-read them once per BSI plane, while the kernel's
scalar-prefetch gather + plane-block reuse reads each operand stream
approximately once (measured 4x faster at design scale, r03 — the
schedule, not the arithmetic, is what XLA cannot reproduce).

All kernels run in interpreter mode automatically off-TPU, so the same
code path is exercised by the CPU test mesh (conftest.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from pilosa_tpu.ops import bitmap as bm

_LANES = 128          # TPU lane width (last-dim tile)
_ROW_BLOCK = 8        # rows per grid step in batched kernels
_WORD_BLOCK = 4096    # words per grid step in plane-stack kernels


def _interpret() -> bool:
    """Pallas interpret mode off-TPU (trace-time decision)."""
    return jax.default_backend() != "tpu"


def enabled() -> bool:
    """Whether the executor should route hot ops through these kernels.

    Default OFF: measured head-to-head on a real v5e chip at design
    scale (954 shards, r03 A/B through the full engine), the XLA jnp
    path matched or beat the Pallas route on every stacked plan shape
    — the ops are pure HBM-bandwidth streams XLA already schedules
    optimally, and the pallas_call boundary only adds dispatch
    overhead (count_intersect net p50: 2.35 ms XLA vs 3.45 ms Pallas;
    table in BENCH_TPU_NOTES.md).  The kernels stay as a measured,
    env-selectable alternative: PILOSA_TPU_PALLAS=1 routes resident-
    leaf plans through them (and exercises the interpret path in CPU
    tests); off-TPU the interpreter would be far slower than XLA, so
    callers fall back regardless unless forced.
    """
    import os
    return os.environ.get("PILOSA_TPU_PALLAS") == "1"


def _pc(x):
    return jax.lax.population_count(x).astype(jnp.int32)


def _pad_rows(x, block):
    n = x.shape[0]
    pad = (-n) % block
    if pad:
        x = jnp.pad(x, ((0, pad),) + ((0, 0),) * (x.ndim - 1))
    return x, n


def _pad_axis(x, axis, block):
    """Zero-pad `axis` of x up to a multiple of block (zeros are
    popcount-neutral, so all kernels here tolerate the padding)."""
    n = x.shape[axis]
    pad = (-n) % block
    if pad:
        widths = [(0, 0)] * x.ndim
        widths[axis] = (0, pad)
        x = jnp.pad(x, widths)
    return x


# ---------------------------------------------------------------------------
# popcount over rows: (N, W) -> (N,)
# ---------------------------------------------------------------------------

def _popcount_rows_kernel(x_ref, o_ref):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)

    o_ref[...] += jnp.sum(_pc(x_ref[...]), axis=-1, keepdims=True)


def _row_word_grid(w: int) -> int:
    """Word-axis block: whole row when small, 8K-word chunks when a
    row would not fit VMEM (arbitrarily wide flattened rows)."""
    return min(_WORD_BLOCK * 2, w)


def popcount_rows(x):
    """Per-row popcount: x (N, W) uint32 -> (N,) int32."""
    x, n = _pad_rows(x, _ROW_BLOCK)
    bw = _row_word_grid(x.shape[1])
    x = _pad_axis(x, 1, bw)
    npad, w = x.shape
    out = pl.pallas_call(
        _popcount_rows_kernel,
        grid=(npad // _ROW_BLOCK, w // bw),
        in_specs=[pl.BlockSpec((_ROW_BLOCK, bw), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((_ROW_BLOCK, 1), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((npad, 1), jnp.int32),
        interpret=_interpret(),
    )(x)
    return out[:n, 0]


# ---------------------------------------------------------------------------
# fused pairwise AND + popcount: (N, W), (N, W) -> (N,)
# ---------------------------------------------------------------------------

def _pair_popcount_kernel(a_ref, b_ref, o_ref):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)

    o_ref[...] += jnp.sum(
        _pc(a_ref[...] & b_ref[...]), axis=-1, keepdims=True)


def pair_popcount(a, b):
    """popcount(a & b) per row — the Count(Intersect) hot loop.

    a, b: (N, W) uint32 -> (N,) int32.  One pass over each operand
    stream; the intersection is never materialized in HBM (the analog
    of roaring.IntersectionCount, roaring/roaring.go:711).
    """
    assert a.shape == b.shape, (a.shape, b.shape)
    a, n = _pad_rows(a, _ROW_BLOCK)
    b, _ = _pad_rows(b, _ROW_BLOCK)
    bw = _row_word_grid(a.shape[1])
    a = _pad_axis(a, 1, bw)
    b = _pad_axis(b, 1, bw)
    npad, w = a.shape
    spec = pl.BlockSpec((_ROW_BLOCK, bw), lambda i, j: (i, j))
    out = pl.pallas_call(
        _pair_popcount_kernel,
        grid=(npad // _ROW_BLOCK, w // bw),
        in_specs=[spec, spec],
        out_specs=pl.BlockSpec((_ROW_BLOCK, 1), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((npad, 1), jnp.int32),
        interpret=_interpret(),
    )(a, b)
    return out[:n, 0]


# ---------------------------------------------------------------------------
# masked popcount: rows (N, W) AND one filter (W,) -> (N,)
# ---------------------------------------------------------------------------

def _masked_popcount_kernel(x_ref, m_ref, o_ref):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)

    o_ref[...] += jnp.sum(
        _pc(x_ref[...] & m_ref[...]), axis=-1, keepdims=True)


def masked_popcount(x, mask):
    """popcount(x[i] & mask) for every row — TopK candidate counting.

    x: (N, W) uint32, mask: (W,) uint32 -> (N,) int32.  The filter
    block is loaded once per grid step and broadcast over the row
    block (executor.go:2750 topKFilter semantics).
    """
    x, n = _pad_rows(x, _ROW_BLOCK)
    bw = _row_word_grid(x.shape[1])
    x = _pad_axis(x, 1, bw)
    mask = _pad_axis(mask, 0, bw)
    npad, w = x.shape
    out = pl.pallas_call(
        _masked_popcount_kernel,
        grid=(npad // _ROW_BLOCK, w // bw),
        in_specs=[
            pl.BlockSpec((_ROW_BLOCK, bw), lambda i, j: (i, j)),
            pl.BlockSpec((1, bw), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((_ROW_BLOCK, 1), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((npad, 1), jnp.int32),
        interpret=_interpret(),
    )(x, mask.reshape(1, w))
    return out[:n, 0]


# ---------------------------------------------------------------------------
# BSI sum: one pass over the plane stack
# ---------------------------------------------------------------------------

def _bsi_sum_kernel(planes_ref, filt_ref, cnt_ref, pos_ref, neg_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        cnt_ref[...] = jnp.zeros_like(cnt_ref)
        pos_ref[...] = jnp.zeros_like(pos_ref)
        neg_ref[...] = jnp.zeros_like(neg_ref)

    exists = planes_ref[0, :]
    sign = planes_ref[1, :]
    consider = exists & filt_ref[0, :]
    pos = consider & ~sign
    neg = consider & sign
    mag = planes_ref[2:, :]                      # (depth, BW)
    cnt_ref[...] += jnp.sum(_pc(consider)).reshape(1, 1)
    pos_ref[...] += jnp.sum(_pc(mag & pos[None, :]), axis=-1, keepdims=True)
    neg_ref[...] += jnp.sum(_pc(mag & neg[None, :]), axis=-1, keepdims=True)


def bsi_sum_counts(planes, filter_words=None):
    """Fused BSI Sum scan (fragment.sum, fragment.go:718-746).

    planes: (2+depth, W) uint32, filter_words: (W,) uint32 or None.
    Returns (count, pos_pc, neg_pc) matching ops.bsi.sum_counts — the
    whole plane stack is streamed through VMEM exactly once, with the
    sign/exists masking fused into the same pass.  Combine on host
    with ops.bsi.host_sum for exact >2^53 totals.
    """
    p, w = planes.shape
    depth = p - 2
    assert depth >= 1
    if filter_words is None:
        filter_words = jnp.full((w,), np.uint32(0xFFFFFFFF), dtype=jnp.uint32)
    bw = min(_WORD_BLOCK, w)
    planes = _pad_axis(planes, 1, bw)
    filter_words = _pad_axis(filter_words, 0, bw)
    w = planes.shape[1]
    cnt, pos, neg = pl.pallas_call(
        _bsi_sum_kernel,
        grid=(w // bw,),
        in_specs=[
            pl.BlockSpec((p, bw), lambda i: (0, i)),
            pl.BlockSpec((1, bw), lambda i: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((depth, 1), lambda i: (0, 0)),
            pl.BlockSpec((depth, 1), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
            jax.ShapeDtypeStruct((depth, 1), jnp.int32),
            jax.ShapeDtypeStruct((depth, 1), jnp.int32),
        ],
        interpret=_interpret(),
    )(planes, filter_words.reshape(1, w))
    return cnt[0, 0], pos[:, 0], neg[:, 0]


# ---------------------------------------------------------------------------
# Fused flagship query step (bench.py / __graft_entry__ workload)
# ---------------------------------------------------------------------------

def _rows_filter_kernel(rows_ref, filt_ref, rc_ref):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init_rc():
        rc_ref[...] = jnp.zeros_like(rc_ref)

    # rows block: (R, BS, BW) & filt (BS, BW) -> counts (BS, R)
    rc_ref[...] += jnp.sum(
        _pc(rows_ref[...] & filt_ref[...][None]), axis=-1).T


_ROWS_CHUNK = 16


def rows_filter_counts(rows, filt):
    """Per-(row, shard) filtered popcounts — the TopK candidate scan.

    rows: (R, S, W), filt: (S, W) -> (R, S) int32.  The R axis is
    processed in chunks of <= 16 candidate rows per pallas_call so the
    VMEM block stays ~4 MB no matter how many candidates a query has
    (Mosaic requires the output lane dim to equal the full array dim,
    so R is chunked on the host rather than in the grid).
    """
    r_dim = rows.shape[0]
    if r_dim == 0:
        return jnp.zeros((0, filt.shape[0]), dtype=jnp.int32)
    bs = _ROW_BLOCK
    filt, s_dim = _pad_rows(filt, bs)
    pad = filt.shape[0] - s_dim
    if pad:
        rows = jnp.pad(rows, ((0, 0), (0, pad), (0, 0)))
    bw = min(8192, filt.shape[1])
    filt = _pad_axis(filt, 1, bw)
    rows = _pad_axis(rows, 2, bw)
    spad, w = filt.shape
    out = []
    for lo in range(0, r_dim, _ROWS_CHUNK):
        chunk = rows[lo:lo + _ROWS_CHUNK]
        r = chunk.shape[0]
        rc = pl.pallas_call(
            _rows_filter_kernel,
            grid=(spad // bs, w // bw),
            in_specs=[
                pl.BlockSpec((r, bs, bw), lambda s, j: (0, s, j)),
                pl.BlockSpec((bs, bw), lambda s, j: (s, j)),
            ],
            out_specs=pl.BlockSpec((bs, r), lambda s, j: (s, 0)),
            out_shape=jax.ShapeDtypeStruct((spad, r), jnp.int32),
            interpret=_interpret(),
        )(chunk, filt)
        out.append(rc[:s_dim].T)
    return jnp.concatenate(out, axis=0)


# ---------------------------------------------------------------------------
# fused GroupBy + Sum: the whole combo space in one pass
# ---------------------------------------------------------------------------

def _groupby_kernel(nf: int, depth: int, signed: bool, c_dim: int):
    """Kernel body factory: nf field stacks, BSI depth (0 = no
    aggregate), sign-split on/off, c_dim combos.  Outputs are whole
    (·, C) blocks resident in VMEM for the entire grid; each step
    accumulates into its combo's lane via a one-hot (dynamic lane
    stores don't lower on TPU)."""

    def kernel(sel_ref, *refs):
        # refs: nf stack refs [+ planes_ref], then outputs
        # (cnt_ref [, nn_ref, pos_ref, neg_ref])
        stacks = refs[:nf]
        i = nf
        planes_ref = refs[i] if depth else None
        i += 1 if depth else 0
        cnt_ref = refs[i]
        s, w, c = (pl.program_id(0), pl.program_id(1),
                   pl.program_id(2))

        @pl.when((s == 0) & (w == 0) & (c == 0))
        def _init():
            for r in refs[i:]:
                r[...] = jnp.zeros_like(r)

        onehot = (jax.lax.broadcasted_iota(
            jnp.int32, (1, c_dim), 1) == c).astype(jnp.int32)
        m = stacks[0][0]
        for f in range(1, nf):
            m = m & stacks[f][0]                   # (BS, BW)
        cnt_ref[...] += jnp.sum(_pc(m)) * onehot
        if depth:
            nn_ref, pos_ref = refs[i + 1], refs[i + 2]
            exists = planes_ref[:, 0, :]
            em = m & exists
            nn_ref[...] += jnp.sum(_pc(em)) * onehot
            mag = planes_ref[:, 2:, :]             # (BS, depth, BW)
            if signed:
                neg_ref = refs[i + 3]
                sign = planes_ref[:, 1, :]
                pos = em & ~sign
                neg = em & sign
                pos_pc = jnp.sum(_pc(mag & pos[:, None, :]),
                                 axis=(0, 2))      # (depth,)
                neg_pc = jnp.sum(_pc(mag & neg[:, None, :]),
                                 axis=(0, 2))
                pos_ref[...] += pos_pc[:, None] * onehot
                neg_ref[...] += neg_pc[:, None] * onehot
            else:
                pos_pc = jnp.sum(_pc(mag & em[:, None, :]),
                                 axis=(0, 2))
                pos_ref[...] += pos_pc[:, None] * onehot
    return kernel


_GB_SHARD_BLOCK = 8
_GB_WORD_BLOCK = 4096


def groupby_sum(stacks, sel, planes=None, signed=True):
    """Fused GroupBy: every combo's count (+ BSI Sum partials) in ONE
    pass over the field stacks (executor.go:3918 + 8617, collapsed).

    stacks: list of (R_f, S, W) uint32 per GroupBy field;
    sel: (C, nf) int32 combo row indices; planes: (S, P+2, W) or None;
    signed: compute the negative sign-split (skippable when the sign
    plane is empty).  Returns (counts (C,), nn (C,), pos (C, depth),
    neg (C, depth)) int32 — nn/pos/neg None without planes.

    Schedule: grid (S/BS, W/BW, C) with combos INNERMOST and the combo
    row chosen via scalar-prefetched `sel` (the embedding-gather
    pattern) — the plane block loads once per (shard, word) tile and
    is reused by all C combos, so total HBM traffic is ~one read of
    each stack row per referencing combo plus ONE read of the planes,
    instead of the XLA path's per-chunk re-materialization (measured
    r03: 273 ms -> see BENCH_TPU_NOTES for the kernel number).
    Per-combo totals accumulate across shard tiles in int32 (exact
    below ~2k shards; callers above that use the unreduced XLA path).
    """
    from jax.experimental.pallas import tpu as pltpu

    nf = len(stacks)
    c_dim, nf2 = sel.shape
    assert nf2 == nf and nf >= 1
    s_dim, w_dim = stacks[0].shape[1:]
    bs = min(_GB_SHARD_BLOCK, s_dim)
    bw = min(_GB_WORD_BLOCK, w_dim)
    stacks = [_pad_axis(_pad_axis(x, 1, bs), 2, bw) for x in stacks]
    depth = 0
    if planes is not None:
        planes = _pad_axis(_pad_axis(planes, 0, bs), 2, bw)
        depth = planes.shape[1] - 2
    spad, wpad = stacks[0].shape[1:]
    grid = (spad // bs, wpad // bw, c_dim)
    sel = jnp.asarray(sel, dtype=jnp.int32)

    def stack_spec(f):
        return pl.BlockSpec(
            (1, bs, bw), lambda s, w, c, sel_ref: (sel_ref[c, f], s, w))

    in_specs = [stack_spec(f) for f in range(nf)]
    arrays = list(stacks)
    if planes is not None:
        in_specs.append(pl.BlockSpec(
            (bs, 2 + depth, bw), lambda s, w, c, sel_ref: (s, 0, w)))
        arrays.append(planes)
    # outputs live as whole (·, C) VMEM-resident blocks (index_map
    # constant across the grid)
    fixed = lambda s, w, c, sel_ref: (0, 0)
    out_specs = [pl.BlockSpec((1, c_dim), fixed)]
    out_shape = [jax.ShapeDtypeStruct((1, c_dim), jnp.int32)]
    if planes is not None:
        out_specs.append(pl.BlockSpec((1, c_dim), fixed))
        out_shape.append(jax.ShapeDtypeStruct((1, c_dim), jnp.int32))
        n_agg = 2 if signed else 1
        for _ in range(n_agg):
            out_specs.append(pl.BlockSpec((depth, c_dim), fixed))
            out_shape.append(
                jax.ShapeDtypeStruct((depth, c_dim), jnp.int32))
    out = pl.pallas_call(
        _groupby_kernel(nf, depth, signed, c_dim),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
        ),
        out_shape=out_shape,
        interpret=_interpret(),
    )(sel, *arrays)
    if planes is None:
        return out[0][0], None, None, None
    counts, nn = out[0][0], out[1][0]
    pos = out[2].T                                 # (C, depth)
    neg = out[3].T if signed else jnp.zeros_like(pos)
    return counts, nn, pos, neg


# ---------------------------------------------------------------------------
# one-pass GroupBy: combo-independent group-code histogram
# ---------------------------------------------------------------------------
#
# The fused per-combo kernel above re-reads every referenced stack row
# and re-popcounts every BSI plane once PER COMBO — O(C*S*W) traffic.
# The histogram formulation reads every word exactly once regardless of
# combo count: per column, compose a dense group code from packed digit
# planes (ops.bitmap.digit_planes — one digit per disjoint GroupBy
# field), then accumulate counts and BSI sign-split plane partials into
# a (K, G) table indexed by code.  groupby_onehot does the
# accumulation with MXU matmuls (one-hot.T @ payload-bits);
# groupby_codes_xla is the scatter-add XLA reference the kernel is
# cross-checked against (and the mesh shard_map body).
#
# Output layout (shared): rows [counts, nn, pos_plane_0..d-1,
# neg_plane_0..d-1] — identical per-plane sign-split partials to
# groupby_sum / bsi.sum_counts, so host combination (exact Python-int
# shift-add) is byte-for-byte the same across all GroupBy paths.


def _gc_payload_rows(va, ex, sg, mag_bits, depth: int, signed: bool):
    """Per-column 0/1 payload rows [count, nn, pos*depth, neg*depth]
    from unpacked bit vectors (any common shape)."""
    rows = [va]
    if depth:
        rows.append(ex)
        posm = ex * (1 - sg) if signed else ex
        for p in range(depth):
            rows.append(mag_bits[p] * posm)
        if signed:
            negm = ex * sg
            for p in range(depth):
                rows.append(mag_bits[p] * negm)
    return rows


def groupby_codes_xla(code_planes, valid, planes=None, n_codes: int = 1,
                      signed: bool = True, minmax: bool = False):
    """XLA reference for the one-pass GroupBy histogram.

    code_planes: (S, CB, W) uint32 packed group-code bit-planes
    (bitmap.digit_planes of each field, stride-concatenated);
    valid: (S, W) uint32 mask of columns belonging to some combo
    (AND of field unions, AND the filter); planes: (S, 2+depth, W)
    BSI stack or None.  Returns (counts (G,), nn (G,), pos (G, depth),
    neg (G, depth)) int32 over the FULL dense code space G = n_codes —
    every input word is read exactly once, independent of combo count.
    With ``minmax=True`` (requires planes) additionally returns the
    (4, G) [max_mag_pos, min_mag_pos, max_mag_neg, min_mag_neg] table
    via scatter-max/min — the oracle for groupby_fused's presence-walk
    Min/Max (identities -1 / 1<<depth; see minmax_from_table).
    """
    depth = 0 if planes is None else planes.shape[1] - 2
    assert not (minmax and depth == 0), "minmax requires BSI planes"
    k = 1 if depth == 0 else 2 + (2 if signed else 1) * depth
    big = 1 << depth

    def one_shard(acc, args):
        cp, va_w = args[0], args[1]
        pl_w = args[2] if depth else None
        code = bm.code_from_planes(cp)                # (N,) int32
        va = bm.unpack_bits(va_w)                     # (N,) 0/1
        # invalid columns route to an overflow bucket sliced off below
        seg = jnp.where(va == 1, code, n_codes)
        ex = sg = None
        mag = []
        if depth:
            ex = bm.unpack_bits(pl_w[0]) * va
            sg = bm.unpack_bits(pl_w[1])
            mag = [bm.unpack_bits(pl_w[2 + p]) for p in range(depth)]
        rows = _gc_payload_rows(va, ex, sg, mag, depth, signed)
        outs = [jnp.zeros(n_codes + 1, jnp.int32).at[seg].add(r)
                for r in rows]
        hist_acc = acc[0] if minmax else acc
        hist_acc = hist_acc + jnp.stack(outs)[:, :n_codes]
        if not minmax:
            return hist_acc, None
        mag_val = jnp.zeros_like(code)
        for p in range(depth):
            mag_val = mag_val | (mag[p] << p)
        posm = ex * (1 - sg) if signed else ex
        negm = ex * sg if signed else jnp.zeros_like(ex)

        def side(mask):
            sm = jnp.where(mask == 1, seg, n_codes)
            mx = jnp.full(n_codes + 1, -1, jnp.int32
                          ).at[sm].max(mag_val)[:n_codes]
            mn = jnp.full(n_codes + 1, big, jnp.int32
                          ).at[sm].min(mag_val)[:n_codes]
            return mx, mn

        mxp, mnp_ = side(posm)
        mxn, mnn = side(negm)
        mm = jnp.stack([jnp.maximum(acc[1][0], mxp),
                        jnp.minimum(acc[1][1], mnp_),
                        jnp.maximum(acc[1][2], mxn),
                        jnp.minimum(acc[1][3], mnn)])
        return (hist_acc, mm), None

    init = jnp.zeros((k, n_codes), jnp.int32)
    if minmax:
        mm0 = jnp.stack([jnp.full(n_codes, -1, jnp.int32),
                         jnp.full(n_codes, big, jnp.int32),
                         jnp.full(n_codes, -1, jnp.int32),
                         jnp.full(n_codes, big, jnp.int32)])
        init = (init, mm0)
    args = (code_planes, valid) + ((planes,) if depth else ())
    acc, _ = jax.lax.scan(one_shard, init, args)
    acc, mm = acc if minmax else (acc, None)
    counts = acc[0]
    if depth == 0:
        return counts, None, None, None
    nn = acc[1]
    pos = acc[2:2 + depth].T                          # (G, depth)
    neg = acc[2 + depth:].T if signed else jnp.zeros_like(pos)
    if not minmax:
        return counts, nn, pos, neg
    return counts, nn, pos, neg, mm


def _gc_onehot_kernel(cb: int, depth: int, signed: bool, k: int,
                      g_pad: int):
    """Kernel body factory for groupby_onehot: per (shard, word-block)
    grid step, decode the 32 bit positions of the block and accumulate
    payload.T @ one-hot MXU matmuls into the VMEM-resident (K, G)
    table.  Per-step partial sums are <= 32 * BW < 2^24 so the f32
    MXU accumulator is exact; cross-step accumulation is int32."""

    def kernel(cp_ref, va_ref, *refs):
        pl_ref = refs[0] if depth else None
        out_ref = refs[-1]
        s, wi = pl.program_id(0), pl.program_id(1)

        @pl.when((s == 0) & (wi == 0))
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)

        iota_g = jax.lax.broadcasted_iota(jnp.int32, (1, g_pad), 1)
        acc = jnp.zeros_like(out_ref)
        for j in range(32):
            sh = jnp.uint32(j)
            va = ((va_ref[0, :] >> sh) & 1).astype(jnp.int32)
            code = jnp.zeros_like(va)
            for b in range(cb):
                code = code | (
                    ((cp_ref[0, b, :] >> sh) & 1).astype(jnp.int32) << b)
            ex = sg = None
            mag = []
            if depth:
                ex = ((pl_ref[0, 0, :] >> sh) & 1).astype(jnp.int32) * va
                sg = ((pl_ref[0, 1, :] >> sh) & 1).astype(jnp.int32)
                mag = [((pl_ref[0, 2 + p, :] >> sh) & 1).astype(jnp.int32)
                       for p in range(depth)]
            rows = _gc_payload_rows(va, ex, sg, mag, depth, signed)
            payload = jnp.stack(rows).astype(jnp.float32)      # (K, BW)
            # invalid columns carry all-zero payload (every row has a
            # `va` factor), so their arbitrary code contributes nothing
            onehot = (code[:, None] == iota_g).astype(jnp.float32)
            acc += jnp.dot(payload, onehot,
                           preferred_element_type=jnp.float32
                           ).astype(jnp.int32)
        out_ref[...] += acc
    return kernel


def groupby_onehot(code_planes, valid, planes=None, n_codes: int = 1,
                   signed: bool = True):
    """One-pass GroupBy histogram with f32 MXU accumulation (the
    first-generation one-pass kernel; superseded by the int8
    :func:`groupby_fused` path but kept as a measured alternative and
    A/B arm).

    Same contract as :func:`groupby_codes_xla` (bit-exact against it
    and against groupby_sum over the same data — tests cross-check all
    three).  Schedule: grid (S, W/BW) with NO combo axis — each stack
    word, valid word, and plane word streams through VMEM exactly once
    and the (K, G) histogram table stays VMEM-resident for the whole
    grid, so HBM traffic is O(S*W) for ANY combo count.  The combo
    dimension only exists inside a grid step as the one-hot lane axis
    of a (K, BW) @ (BW, G) matmul — work the MXU does for free next to
    the bandwidth-bound stream.
    """
    s_dim, cb, w_dim = code_planes.shape
    if cb == 0:                        # all fields single-row: code 0
        code_planes = jnp.zeros((s_dim, 1, w_dim), dtype=jnp.uint32)
        cb = 1
    depth = 0 if planes is None else planes.shape[1] - 2
    k = 1 if depth == 0 else 2 + (2 if signed else 1) * depth
    g_pad = max(-(-int(n_codes) // 128) * 128, 128)
    # word block sized so the per-step (BW, G) one-hot stays ~2 MB f32
    bw = min(w_dim, max(128, (1 << 19) // g_pad))
    code_planes = _pad_axis(code_planes, 2, bw)
    valid = _pad_axis(valid, 1, bw)
    arrays = [code_planes, valid]
    in_specs = [
        pl.BlockSpec((1, cb, bw), lambda s, w: (s, 0, w)),
        pl.BlockSpec((1, bw), lambda s, w: (s, w)),
    ]
    if depth:
        planes = _pad_axis(planes, 2, bw)
        arrays.append(planes)
        in_specs.append(
            pl.BlockSpec((1, 2 + depth, bw), lambda s, w: (s, 0, w)))
    wpad = code_planes.shape[2]
    out = pl.pallas_call(
        _gc_onehot_kernel(cb, depth, signed, k, g_pad),
        grid=(s_dim, wpad // bw),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((k, g_pad), lambda s, w: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((k, g_pad), jnp.int32),
        interpret=_interpret(),
    )(*arrays)
    counts = out[0, :n_codes]
    if depth == 0:
        return counts, None, None, None
    nn = out[1, :n_codes]
    pos = out[2:2 + depth, :n_codes].T                 # (G, depth)
    neg = (out[2 + depth:, :n_codes].T if signed
           else jnp.zeros_like(pos))
    return counts, nn, pos, neg


# ---------------------------------------------------------------------------
# fused single-pass GroupBy: int8 MXU popcount-accumulate
# ---------------------------------------------------------------------------
#
# Second-generation one-pass kernel (ISSUE 11).  groupby_onehot above
# unrolls the 32 bit positions of each word block and pays one f32
# (K, BW) @ (BW, G) matmul PER BIT — 32 MXU launches per tile, with
# f32 one-hot operands 4x the bytes they need.  groupby_fused flattens
# bit-position chunks into the contraction axis and accumulates the
# whole (K, G) histogram with int8 @ int8 -> int32 MXU dots — a
# popcount computed by the matrix unit (the dot of two 0/1 int8
# vectors IS popcount(a & b)), 4x the MXU throughput of the f32 path
# and a handful of launches per tile instead of 32.  Each (lanes,
# words) stack tile crosses VMEM exactly once and simultaneously
# yields:
#
#   - the group-code histogram (counts),
#   - validity counts (nn) and per-group BSI Sum sign-split plane
#     partials (pos/neg) — identical layout to groupby_codes_xla,
#   - optionally per-group Min/Max, via per-group plane-PRESENCE
#     masks: an MSB->LSB candidate walk where "does any candidate in
#     group g have magnitude bit p" is one int8 mat-vec against the
#     same one-hot, and the per-column candidate narrowing gathers the
#     presence bit back through the transposed one-hot,
#   - and (as a byproduct of the same tile walk) fused Range/Distinct
#     over BSI planes: bsi_value_hist() below runs THIS kernel with
#     the magnitude+sign planes as the code planes, so the dense
#     per-value histogram — distinct values, min/max, and arbitrary
#     range counts — falls out of one single-pass walk.
#
# Exactness: per-chunk partial sums are <= bc*BW*32 < 2^24 terms of
# {0, 1} products accumulated in int32 — exact; cross-tile
# accumulation is int32 (callers bound shards like the other paths).


def _gb_fused_kernel(cb: int, depth: int, signed: bool, k: int,
                     g_pad: int, bw: int, bc: int, minmax: bool):
    """Kernel body factory.  Per (shard, word-block) grid step the 32
    bit positions are processed in chunks of `bc`; each chunk is one
    flattened (bc*bw,) column axis shared by the int8 payload matmul
    and (when requested) the Min/Max presence walks."""

    def kernel(cp_ref, va_ref, *refs):
        pl_ref = refs[0] if depth else None
        i = 1 if depth else 0
        out_ref = refs[i]
        mm_ref = refs[i + 1] if minmax else None
        s, wi = pl.program_id(0), pl.program_id(1)

        @pl.when((s == 0) & (wi == 0))
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)
            if minmax:
                big = jnp.int32(1 << depth)
                ident = jnp.stack([
                    jnp.full((g_pad,), -1, jnp.int32),
                    jnp.full((g_pad,), big, jnp.int32),
                    jnp.full((g_pad,), -1, jnp.int32),
                    jnp.full((g_pad,), big, jnp.int32)])
                mm_ref[...] = ident

        iota_g = jax.lax.broadcasted_iota(jnp.int32, (1, g_pad), 1)
        acc = jnp.zeros((k, g_pad), jnp.int32)
        big = 1 << depth
        mxp = jnp.full((g_pad,), -1, jnp.int32)
        mnp_ = jnp.full((g_pad,), big, jnp.int32)
        mxn = jnp.full((g_pad,), -1, jnp.int32)
        mnn = jnp.full((g_pad,), big, jnp.int32)
        for c in range(0, 32, bc):
            sh = (jax.lax.broadcasted_iota(jnp.uint32, (bc, 1), 0)
                  + jnp.uint32(c))

            def bits(w, sh=sh):
                # (bw,) uint32 -> (bc*bw,) 0/1 int32 — positions
                # [c, c+bc) of every word, flattened bit-major
                return ((w[None, :] >> sh)
                        & jnp.uint32(1)).astype(jnp.int32).reshape(-1)

            va = bits(va_ref[0])
            code = jnp.zeros_like(va)
            for b in range(cb):
                code = code | (bits(cp_ref[0, b]) << b)
            ex = sg = None
            mag = []
            if depth:
                ex = bits(pl_ref[0, 0]) * va
                sg = bits(pl_ref[0, 1])
                mag = [bits(pl_ref[0, 2 + p]) for p in range(depth)]
            rows = _gc_payload_rows(va, ex, sg, mag, depth, signed)
            payload = jnp.stack(rows).astype(jnp.int8)   # (K, bc*bw)
            # invalid columns carry all-zero payload (every row has a
            # `va` factor), so their arbitrary code contributes 0
            onehot = (code[:, None] == iota_g).astype(jnp.int8)
            acc += jnp.dot(payload, onehot,
                           preferred_element_type=jnp.int32)
            if minmax:
                posm = ex * (1 - sg) if signed else ex
                negm = ex * sg if signed else None

                def gdot(col_vec):
                    # per-group popcount of a 0/1 column mask: one
                    # int8 mat-vec against the shared one-hot
                    return jnp.dot(
                        col_vec.astype(jnp.int8).reshape(1, -1),
                        onehot,
                        preferred_element_type=jnp.int32)[0]

                def cdot(g_vec):
                    # presence bit gathered back per column through
                    # the transposed one-hot
                    return jnp.dot(
                        onehot, g_vec.astype(jnp.int8).reshape(-1, 1),
                        preferred_element_type=jnp.int32)[:, 0]

                def walk_max(candm):
                    alive = gdot(candm)
                    out = jnp.zeros((g_pad,), jnp.int32)
                    cand = candm
                    for p in range(depth - 1, -1, -1):
                        pres = (gdot(cand * mag[p]) > 0)
                        out = out | (pres.astype(jnp.int32) << p)
                        pres_c = cdot(pres.astype(jnp.int32)) > 0
                        cand = cand * jnp.where(pres_c, mag[p], 1)
                    return jnp.where(alive > 0, out, -1)

                def walk_min(candm):
                    alive = gdot(candm)
                    out = jnp.zeros((g_pad,), jnp.int32)
                    cand = candm
                    for p in range(depth - 1, -1, -1):
                        cnt_all = gdot(cand)
                        cnt_with = gdot(cand * mag[p])
                        zpres = (cnt_all - cnt_with) > 0
                        forced1 = jnp.logical_and(
                            jnp.logical_not(zpres), cnt_all > 0)
                        out = out | (forced1.astype(jnp.int32) << p)
                        zp_c = cdot(zpres.astype(jnp.int32)) > 0
                        cand = cand * jnp.where(zp_c, 1 - mag[p], 1)
                    return jnp.where(alive > 0, out, big)

                mxp = jnp.maximum(mxp, walk_max(posm))
                mnp_ = jnp.minimum(mnp_, walk_min(posm))
                if signed:
                    mxn = jnp.maximum(mxn, walk_max(negm))
                    mnn = jnp.minimum(mnn, walk_min(negm))
        out_ref[...] += acc
        if minmax:
            cur = mm_ref[...]
            mm_ref[...] = jnp.stack([
                jnp.maximum(cur[0], mxp), jnp.minimum(cur[1], mnp_),
                jnp.maximum(cur[2], mxn), jnp.minimum(cur[3], mnn)])
    return kernel


def groupby_fused(code_planes, valid, planes=None, n_codes: int = 1,
                  signed: bool = True, minmax: bool = False):
    """Fused single-pass GroupBy histogram — int8 MXU
    popcount-accumulate (the ISSUE 11 tentpole kernel).

    Same contract as :func:`groupby_codes_xla` (bit-exact against it,
    against groupby_onehot, and against the host twins — the property
    suite cross-checks all of them).  Returns (counts, nn, pos, neg)
    and, with ``minmax=True`` (requires planes), additionally a
    (4, G) int32 table [max_mag_pos, min_mag_pos, max_mag_neg,
    min_mag_neg] with identities (-1 / 1<<depth) marking empty sides —
    combine with :func:`minmax_from_table`.

    Schedule: grid (S, W/BW) with NO combo axis — every code plane,
    valid word, and BSI plane word streams through VMEM exactly once
    and the (K, G) table (+ (4, G) Min/Max table) stays VMEM-resident
    for the whole walk.  The combo dimension exists only inside a grid
    step as the one-hot axis of int8 matmuls the MXU does for free
    next to the bandwidth-bound stream.
    """
    s_dim, cb, w_dim = code_planes.shape
    if cb == 0:                        # all fields single-row: code 0
        code_planes = jnp.zeros((s_dim, 1, w_dim), dtype=jnp.uint32)
        cb = 1
    depth = 0 if planes is None else planes.shape[1] - 2
    assert not (minmax and depth == 0), "minmax requires BSI planes"
    k = 1 if depth == 0 else 2 + (2 if signed else 1) * depth
    g_pad = max(-(-int(n_codes) // 128) * 128, 128)
    # word block + bit-chunk sized so the per-chunk int8 one-hot
    # (bc*bw, G) stays ~2 MB; bc divides 32 so chunks tile the word
    bw = max(128, min(2048, w_dim))
    bc = max(1, min(32, (1 << 21) // (bw * g_pad)))
    while 32 % bc:
        bc -= 1
    code_planes = _pad_axis(code_planes, 2, bw)
    valid = _pad_axis(valid, 1, bw)
    arrays = [code_planes, valid]
    in_specs = [
        pl.BlockSpec((1, cb, bw), lambda s, w: (s, 0, w)),
        pl.BlockSpec((1, bw), lambda s, w: (s, w)),
    ]
    if depth:
        planes = _pad_axis(planes, 2, bw)
        arrays.append(planes)
        in_specs.append(
            pl.BlockSpec((1, 2 + depth, bw), lambda s, w: (s, 0, w)))
    wpad = code_planes.shape[2]
    fixed = lambda s, w: (0, 0)
    out_specs = [pl.BlockSpec((k, g_pad), fixed)]
    out_shape = [jax.ShapeDtypeStruct((k, g_pad), jnp.int32)]
    if minmax:
        out_specs.append(pl.BlockSpec((4, g_pad), fixed))
        out_shape.append(jax.ShapeDtypeStruct((4, g_pad), jnp.int32))
    out = pl.pallas_call(
        _gb_fused_kernel(cb, depth, signed, k, g_pad, bw, bc, minmax),
        grid=(s_dim, wpad // bw),
        in_specs=in_specs,
        out_specs=out_specs if minmax else out_specs[0],
        out_shape=out_shape if minmax else out_shape[0],
        interpret=_interpret(),
    )(*arrays)
    hist = out[0] if minmax else out
    counts = hist[0, :n_codes]
    if depth == 0:
        return counts, None, None, None
    nn = hist[1, :n_codes]
    pos = hist[2:2 + depth, :n_codes].T                # (G, depth)
    neg = (hist[2 + depth:, :n_codes].T if signed
           else jnp.zeros_like(pos))
    if not minmax:
        return counts, nn, pos, neg
    return counts, nn, pos, neg, out[1][:, :n_codes]


def minmax_from_table(mm, depth: int, op: str):
    """Host combiner for the (4, G) Min/Max magnitude table (fused
    kernel or XLA reference): per group, ``max = max_mag_pos`` when
    any non-negative member exists else ``-min_mag_neg``; ``min =
    -max_mag_neg`` when any negative member exists else
    ``min_mag_pos``.  Returns (values (G,) int64, has (G,) bool)."""
    mm = np.asarray(mm, dtype=np.int64)
    big = 1 << depth
    mxp, mnp_, mxn, mnn = mm[0], mm[1], mm[2], mm[3]
    if op == "max":
        vals = np.where(mxp >= 0, mxp, -mnn)
        has = (mxp >= 0) | (mnn < big)
    else:
        vals = np.where(mxn >= 0, -mxn, mnp_)
        has = (mxn >= 0) | (mnp_ < big)
    return vals, has


def bsi_value_hist(planes, filter_words=None, signed: bool = True,
                   use_kernel: bool = True, gb=None):
    """Fused per-VALUE histogram over a BSI plane stack — the
    Range/Distinct byproduct of the single-pass tile walk.

    planes: (S, 2+depth, W) uint32, filter_words: (S, W) or None.
    Treats the magnitude planes plus the SIGN plane as a group code
    (sign is the top code bit), so one run of the fused GroupBy kernel
    yields counts per signed value: returns (pos (2^depth,) int32,
    neg (2^depth,) int32) — pos[v] = columns with value +v, neg[v] =
    columns with value -v.  Derive Distinct (codes with count > 0),
    Min/Max (extreme nonzero codes), and Range counts
    (:func:`range_count_from_hist`) without decoding a single column.

    This function is the ONE owner of the planes-to-code layout
    (sign plane as the top code bit, exists AND filter as validity);
    `gb` overrides the histogram arm (any groupby_* callable) so the
    executor's arm selection reuses the same transform.  The host
    twin (executor/stacked.py's native arm) mirrors this layout —
    keep them in lockstep.
    """
    depth = planes.shape[1] - 2
    ex = planes[:, 0]
    valid = (ex if filter_words is None
             else jnp.bitwise_and(ex, filter_words))
    cp = jnp.concatenate(
        [planes[:, 2:], planes[:, 1:2]], axis=1)     # (S, depth+1, W)
    n_codes = 1 << (depth + 1)
    if gb is None:
        gb = groupby_fused if use_kernel else groupby_codes_xla
    counts, _, _, _ = gb(cp, valid, None, n_codes, signed)
    return counts[: 1 << depth], counts[1 << depth:]


def range_count_from_hist(pos, neg, lo: int, hi: int) -> int:
    """Columns whose value lies in [lo, hi] — exact, from the fused
    value histogram (pos/neg magnitude counts)."""
    pos = np.asarray(pos, dtype=np.int64)
    neg = np.asarray(neg, dtype=np.int64)
    total = 0
    if hi >= 0:
        total += int(pos[max(lo, 0):hi + 1].sum())
    if lo < 0:
        nlo, nhi = max(-hi, 1), -lo           # magnitudes of negatives
        if nlo <= nhi:
            total += int(neg[nlo:nhi + 1].sum())
    return total


def distinct_from_hist(pos, neg) -> list[int]:
    """Sorted distinct signed values present in the fused value
    histogram.  A -0 cannot occur (the encoder signs only v < 0)."""
    pos = np.asarray(pos)
    neg = np.asarray(neg)
    vals = [-int(v) for v in np.nonzero(neg)[0][::-1] if v > 0]
    vals += [int(v) for v in np.nonzero(pos)[0]]
    return vals


# ---------------------------------------------------------------------------
# HBM traffic models — the roofline plane's bytes-touched source
# ---------------------------------------------------------------------------
#
# pilosa_device_bandwidth_fraction{op=groupby} is only honest if each
# dispatch notes the bytes ITS schedule actually streams: the fused
# single-pass kernel reads every tile once, while the per-combo arms
# re-read stack rows per referencing combo and the XLA scan
# re-materializes gathered combo masks per payload pass.  Crediting
# the one-pass path with the per-combo arms' re-read traffic (or vice
# versa) would inflate (deflate) the fraction.  These models are the
# single source the executor arms note from (ISSUE 11 satellite).


def groupby_onepass_hbm_bytes(n_shards: int, width_words: int,
                              code_bits: int, depth: int = 0,
                              has_filter: bool = False) -> int:
    """Single-pass tile walk: (code planes + valid plane) + BSI stack
    + filter words each cross VMEM exactly once — independent of combo
    count, and counted WITHOUT mesh padding rows."""
    per_shard = (code_bits + 1) + ((2 + depth) if depth else 0) \
        + (1 if has_filter else 0)
    return 4 * n_shards * width_words * per_shard


def groupby_percombo_hbm_bytes(n_shards: int, width_words: int,
                               n_combos: int, nf: int,
                               depth: int = 0) -> int:
    """groupby_sum kernel schedule: each referenced stack row is read
    once per referencing combo (combos innermost in the grid), the
    plane block once per (shard, word) tile — i.e. ONCE total."""
    return 4 * n_shards * width_words * (
        n_combos * nf + ((2 + depth) if depth else 0))


def groupby_scan_hbm_bytes(n_shards: int, width_words: int,
                           n_combos: int, nf: int, depth: int = 0,
                           signed: bool = True,
                           has_filter: bool = False) -> int:
    """XLA per-combo scan traffic: gathered (C, S, W) combo masks
    materialize and are re-read once per payload pass (exists mask +
    one sign-split mask read per magnitude plane) — the multi-pass
    traffic the one-pass kernels exist to remove."""
    w = 4 * n_shards * width_words
    b = n_combos * nf * w + (w if has_filter else 0)
    if depth:
        b += (2 + depth) * w
        b += n_combos * w * (1 + (2 if signed else 1) * depth)
    return b


def fused_query_counts(a, b, filt, rows):
    """Per-shard Count(Intersect) + TopK candidate counts.

    a, b, filt: (S, W); rows: (R, S, W).  Returns (per-shard intersect
    counts (S,) int32, row_counts (R, S) int32).  Cross-shard totals
    must be combined on the host in int64/Python ints (the per-shard
    count is < 2^20 so int32 is exact; a grand total may not be — see
    ops.bitmap.count).  Each operand stream is read exactly once.
    """
    return pair_popcount(a, b), rows_filter_counts(rows, filt)


__all__ = [
    "popcount_rows",
    "pair_popcount",
    "masked_popcount",
    "bsi_sum_counts",
    "groupby_sum",
    "groupby_codes_xla",
    "groupby_onehot",
    "groupby_fused",
    "minmax_from_table",
    "bsi_value_hist",
    "range_count_from_hist",
    "distinct_from_hist",
    "groupby_onepass_hbm_bytes",
    "groupby_percombo_hbm_bytes",
    "groupby_scan_hbm_bytes",
    "fused_query_counts",
]
