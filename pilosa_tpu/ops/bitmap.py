"""Dense packed-bitmap kernels.

A shard-row is one bit per column, packed LSB-first into ``uint32``
words: column ``c`` lives at word ``c >> 5``, bit ``c & 31``.  A full
2^20-column shard-row is ``uint32[32768]`` (128 KiB).  All ops are pure
``jnp`` functions of arrays whose *last* axis is the word axis, so they
vmap/broadcast over arbitrary leading batch axes (rows, shards) and jit
cleanly onto the TPU VPU.

Reference semantics covered here (behavior, not code):
- pairwise set ops — roaring/roaring.go:927-1663 (intersect/union/
  difference/xor for all container-type pairs collapse to single
  bitwise ops on dense lanes);
- Count/Any — roaring popcount paths (roaring/roaring.go:542);
- CountRange / column-range masks — roaring/roaring.go:573;
- Shift — roaring shift-by-1 used by PQL Shift() (executor.go Shift).

Host-side packing helpers (numpy) mirror what the storage layer's
container decoder produces for HBM upload.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from pilosa_tpu.shardwidth import BITS_PER_WORD, SHARD_WIDTH

_WORD_DTYPE = jnp.uint32
_NP_WORD_DTYPE = np.uint32


# ---------------------------------------------------------------------------
# Host-side packing helpers (numpy — used by storage/ingest/tests)
# ---------------------------------------------------------------------------

def empty(width: int = SHARD_WIDTH) -> np.ndarray:
    """An all-zeros packed shard-row of `width` bits (width % 32 == 0)."""
    assert width % BITS_PER_WORD == 0
    return np.zeros(width // BITS_PER_WORD, dtype=_NP_WORD_DTYPE)


def from_columns(cols, width: int = SHARD_WIDTH) -> np.ndarray:
    """Pack a list/array of set column ids (< width) into words."""
    words = empty(width)
    cols = np.asarray(cols, dtype=np.int64)
    if cols.size:
        assert cols.min() >= 0 and cols.max() < width, "column id out of range"
        # native or-scatter (~20x numpy's bitwise_or.at; falls back
        # to it without a toolchain)
        from pilosa_tpu.storage import native_ingest as ni
        ni.or_bits(words, cols)
    return words


def to_columns(words) -> np.ndarray:
    """Unpack a packed row back into a sorted array of set column ids."""
    words = np.asarray(words, dtype=_NP_WORD_DTYPE)
    # uint32 little-endian byte view -> unpackbits(bitorder little) gives
    # bit i of word w at flat index w*32 + i, matching our LSB-first layout.
    bits = np.unpackbits(words.view(np.uint8), bitorder="little")
    return np.nonzero(bits)[0].astype(np.uint64)


def range_mask(start: int, end: int, width: int = SHARD_WIDTH) -> np.ndarray:
    """Packed mask with bits set for columns in [start, end)."""
    start = max(0, min(start, width))
    end = max(start, min(end, width))
    mask = empty(width)
    sw, sb = start >> 5, start & 31
    ew, eb = end >> 5, end & 31
    if sw == ew:
        if sb != eb:
            mask[sw] = ((_NP_WORD_DTYPE(1) << (eb - sb)) - 1) << sb
        return mask
    mask[sw] = _NP_WORD_DTYPE(0xFFFFFFFF) << sb
    mask[sw + 1 : ew] = 0xFFFFFFFF
    if eb:
        mask[ew] = (_NP_WORD_DTYPE(1) << eb) - 1
    return mask


# ---------------------------------------------------------------------------
# Device-side ops (jnp — jit/vmap/shard_map friendly)
# ---------------------------------------------------------------------------

def intersect(a, b):
    return jnp.bitwise_and(a, b)


def union(a, b):
    return jnp.bitwise_or(a, b)


def difference(a, b):
    """a AND NOT b."""
    return jnp.bitwise_and(a, jnp.bitwise_not(b))


def xor(a, b):
    return jnp.bitwise_xor(a, b)


def complement(a):
    """Bitwise NOT over the full shard width.

    PQL ``Not()`` is existence-relative (executor.go executeNotShard);
    the executor composes this with the existence row via difference().
    """
    return jnp.bitwise_not(a)


def popcount_words(words):
    """Per-word popcount (uint32 -> int32 counts 0..32)."""
    return jax.lax.population_count(words).astype(jnp.int32)


def count(words):
    """Number of set bits, reduced over the last (word) axis -> int32.

    Per-shard counts are < 2^20 so int32 is exact; cross-shard totals
    are combined in int64/Python on the host (SURVEY §7 "Exactness").
    """
    return jnp.sum(popcount_words(words), axis=-1)


def any_set(words):
    """True if any bit is set (last axis)."""
    return jnp.any(words != 0, axis=-1)


def intersection_count(a, b):
    """popcount(a & b) without materializing the intersection separately.

    Mirrors roaring.IntersectionCount (roaring/roaring.go:711); XLA fuses
    the AND into the popcount-reduce so this is one pass over HBM.
    """
    return count(jnp.bitwise_and(a, b))


def shift(words, n: int = 1):
    """Shift all bits toward higher column ids by static n (zero fill).

    Column c becomes column c+n; bits shifted past the end are dropped.
    Reference: PQL Shift() -> executor.executeShiftShard -> Row.Shift.
    """
    if n == 0:
        return words
    assert n > 0
    q, r = divmod(n, BITS_PER_WORD)
    w = words.shape[-1]
    zeros_shape = words.shape[:-1] + (min(q + 1, w),)
    zpad = jnp.zeros(zeros_shape, dtype=words.dtype)
    if q:
        if q >= w:
            return jnp.zeros_like(words)
        words_q = jnp.concatenate(
            [zpad[..., : q], words[..., : w - q]], axis=-1)
    else:
        words_q = words
    if r == 0:
        return words_q
    # carry bits across word boundaries
    prev = jnp.concatenate([zpad[..., :1], words_q[..., : w - 1]], axis=-1)
    return (words_q << np.uint32(r)) | (prev >> np.uint32(BITS_PER_WORD - r))


def count_range(words, start: int, end: int, width: int | None = None):
    """Count of set bits with column id in [start, end) (static bounds).

    Mirrors roaring CountRange (roaring/roaring.go:573).  The mask is a
    host-built constant captured by jit, so on device this is a fused
    AND + popcount-reduce.
    """
    if width is None:
        width = words.shape[-1] * BITS_PER_WORD
    mask = jnp.asarray(range_mask(start, end, width))
    return count(jnp.bitwise_and(words, mask))


def column_bit(col: int, width: int = SHARD_WIDTH) -> np.ndarray:
    """Packed row with exactly one column set (host helper)."""
    return from_columns([col], width)


# Multi-row folds -----------------------------------------------------------

def union_rows(rows):
    """OR-fold over axis 0: rows (R, W) -> (W,). Used by Rows/GroupBy paths."""
    return jnp.bitwise_or.reduce(rows, axis=0)


def intersect_rows(rows):
    """AND-fold over axis 0.

    Explicit fold: jnp.bitwise_and.reduce seeds its reduction with
    np.array(-1, dtype) — an OverflowError on unsigned dtypes under
    numpy 2's strict conversion rules.
    """
    acc = rows[0]
    for i in range(1, rows.shape[0]):
        acc = jnp.bitwise_and(acc, rows[i])
    return acc


# Stack patching (incremental device-stack maintenance) ---------------------
#
# Device-resident shard stacks are PATCHED on write instead of rebuilt
# (executor/stacked.py TileStackCache): a write's delta log names the
# dirty (lane, word-range) runs, and these ops scatter the replacement
# word runs into the resident array — O(delta) upload instead of an
# O(S*W) host restack + transfer.

def patch_rows(stack2d, idxs, starts, data):
    """Scatter word runs into a (L, W) stack: run k replaces
    ``stack2d[idxs[k], starts[k]:starts[k]+P]`` with ``data[k]``
    (data is (N, P); every run must lie within one lane).  A
    ``lax.scan`` of ``dynamic_update_slice`` so one jitted program
    serves any run count of one padded width — duplicate runs are
    safe (sequential, identical content)."""
    def body(st, seg):
        i, s, d = seg
        return jax.lax.dynamic_update_slice(st, d[None, :], (i, s)), None
    out, _ = jax.lax.scan(body, stack2d, (idxs, starts, data))
    return out


def patch_rows_np(stack2d: np.ndarray, idxs, starts,
                  data: np.ndarray, out=None) -> np.ndarray:
    """Host twin of patch_rows.  Copies by default (resident host
    stacks are shared read-only with concurrent queries); pass a
    scratch `out` to chain width buckets over one copy."""
    if out is None:
        out = stack2d.copy()
    p = data.shape[1]
    for k in range(len(idxs)):
        out[int(idxs[k]), int(starts[k]):int(starts[k]) + p] = data[k]
    return out


# Paged stack assembly (HBM residency manager) -------------------------------
#
# Stack cache entries live as fixed-size device PAGES (memory/pages.py)
# so eviction under budget pressure drops cold page-granular slabs
# instead of whole stacks; a query's operand is gathered back into one
# array here.  jitted per (page count, page shape, logical shape) —
# the shape space is tiny (pages are fixed-size, logical shapes are
# the handful of stack layouts the engine builds).

from functools import partial as _partial


@_partial(jax.jit, static_argnums=(1,))
def _assemble_pages_jit(pages, shape: tuple):
    n_lanes = 1
    for d in shape[:-1]:
        n_lanes *= int(d)
    flat = jnp.concatenate(pages, axis=0) if len(pages) > 1 else pages[0]
    return flat[:n_lanes].reshape(shape)


def assemble_pages(pages, shape: tuple):
    """Concatenate page blocks (each (page_lanes, W)) along the lane
    axis, trim the final page's padding, and restore the stack's
    logical shape.  On device this is one fused copy; XLA drops the
    slice when the lane count is already exact.  The page tuple pads
    to a pow2 count by repeating the last page (the lane trim drops
    the extras) so jax's per-shape executable cache grows log-, not
    linearly, in page count across varying stack sizes."""
    pages = tuple(pages)
    n = len(pages)
    npad = 1 << max(n - 1, 0).bit_length()
    if npad != n:
        pages = pages + (pages[-1],) * (npad - n)
    return _assemble_pages_jit(pages, tuple(shape))


# Ragged segment reductions (page-table dispatch) ---------------------------
#
# The ragged serving plane (executor/ragged.py) drives ONE device
# program over a page table assembled from many queries' PagedStack
# pages: a flat page array is gathered into per-query lane segments
# and reduced per segment.  These are the segment primitives; they are
# plain jnp functions so the ragged plan kind composes them inside one
# jitted program (the Ragged Paged Attention shape from PAPERS.md —
# ragged per-query page lists + segment ids instead of per-group
# padding).

def concat_gather(pages, lane_idx):
    """Page-table gather: concatenate page blocks (each (page_lanes,
    W)) into the flat bucket lane space and gather ``lane_idx`` rows
    out of it — the materialization of one ragged operand.  The
    caller pow2-pads both the page tuple (repeating the last page)
    and ``lane_idx`` (repeating the last index) so the executable
    cache grows log-, not linearly, in batch composition.  This is
    the REFERENCE implementation of the contract (pinned by
    tests/test_ragged.py); the fused "ragged" plan kind
    (executor/stacked.py _plan_run) inlines the same graph so that
    operands of one bucket share a single concatenate."""
    pages = tuple(pages)
    flat = jnp.concatenate(pages, axis=0) if len(pages) > 1 else pages[0]
    return flat[jnp.asarray(lane_idx)]


def segment_count(lanes, seg_ids, num_segments: int):
    """Per-segment popcount totals of a flat (L, W) lane block:
    popcount each lane, then segment-sum by ``seg_ids`` — N point
    Counts over different indexes/shard subsets reduce in ONE pass.
    int32-exact while a segment spans < 2^11 full shards (counts
    < 2^20 per lane), the same bound as the in-program cross-shard
    reduce (executor/stacked.py _REDUCE_MAX_SHARDS)."""
    pc = count(lanes)                                  # (L,) int32
    return jax.ops.segment_sum(pc, jnp.asarray(seg_ids),
                               num_segments=num_segments)


def segment_count_np(lanes: np.ndarray, seg_ids, num_segments: int):
    """Host twin of segment_count (numpy, exact int64)."""
    pc = np.bitwise_count(np.asarray(lanes, dtype=np.uint32)).sum(
        axis=-1).astype(np.int64)
    out = np.zeros(num_segments, dtype=np.int64)
    np.add.at(out, np.asarray(seg_ids), pc)
    return out


# Sparse page encodings (container-adaptive device format) ------------------
#
# memory/encode.py stores sparse stack-cache pages as sorted set-bit
# COORDINATES (packed) or word-granular all-ones RUNS + a residual
# coordinate tail (run) — the roaring array/run containers mapped onto
# the fixed page unit.  These are the device arms: a jitted gather-
# expand back to the dense (page_lanes, W) block for operand
# boundaries that need dense tiles, and count kernels that consume
# the coordinates natively (no expand).  All inputs are pow2-padded
# with out-of-range sentinels (coordinate >= page bits, run start >=
# page words), which the scatter/gather arms drop by construction —
# so the executable cache grows log-, not linearly, in payload size.

@_partial(jax.jit, static_argnums=(1, 2))
def _expand_coords_jit(coords, page_lanes: int, width_words: int):
    n_words = page_lanes * width_words
    flat = jnp.zeros((n_words,), dtype=jnp.uint32)
    word_idx = (coords >> jnp.uint32(5)).astype(jnp.int32)
    vals = jnp.uint32(1) << (coords & jnp.uint32(31))
    # coordinates are unique set bits, so add == or; sentinel pads
    # index past n_words and mode="drop" discards them exactly
    flat = flat.at[word_idx].add(vals, mode="drop")
    return flat.reshape(page_lanes, width_words)


def expand_coords(coords, page_lanes: int, width_words: int):
    """Packed coordinate page -> dense (page_lanes, W) uint32 block."""
    return _expand_coords_jit(jnp.asarray(coords), int(page_lanes),
                              int(width_words))


@_partial(jax.jit, static_argnums=(3, 4))
def _expand_runs_jit(starts, lens, coords, page_lanes: int,
                     width_words: int):
    n_words = page_lanes * width_words
    base = _expand_coords_jit(coords, page_lanes,
                              width_words).reshape(-1)
    w = jnp.arange(n_words, dtype=jnp.int32)
    # runs are sorted and disjoint: the covering candidate is the last
    # run starting at or before w (sentinel starts sort past every w)
    j = jnp.clip(jnp.searchsorted(starts, w, side="right") - 1,
                 0, starts.shape[0] - 1)
    inside = (w >= starts[j]) & (w < starts[j] + lens[j])
    flat = jnp.where(inside, jnp.uint32(0xFFFFFFFF), base)
    return flat.reshape(page_lanes, width_words)


def expand_runs(starts, lens, coords, page_lanes: int,
                width_words: int):
    """Run page (all-ones word runs + residual coordinates) -> dense
    (page_lanes, W) uint32 block."""
    return _expand_runs_jit(jnp.asarray(starts), jnp.asarray(lens),
                            jnp.asarray(coords), int(page_lanes),
                            int(width_words))


def packed_count(coords, total_bits: int):
    """Set-bit count of a packed coordinate page (sentinel-aware)."""
    return jnp.sum((jnp.asarray(coords)
                    < jnp.uint32(total_bits)).astype(jnp.int32))


def packed_segment_count(coords, lane_bits: int, num_lanes: int):
    """Per-lane set-bit counts of a packed page: each coordinate's
    lane is coord // lane_bits; sentinel coordinates land past
    num_lanes and drop.  The packed twin of segment_count."""
    lane = (jnp.asarray(coords) // jnp.uint32(lane_bits)).astype(
        jnp.int32)
    return jnp.zeros((num_lanes,), jnp.int32).at[lane].add(
        1, mode="drop")


def packed_intersect_count(coords, dense_words, total_bits: int):
    """popcount(expand(coords) & dense) WITHOUT expanding: gather the
    dense operand word under each coordinate and test its bit — the
    packed intersect-count arm (roaring array-vs-bitmap galloping
    intersection, collapsed to a gather)."""
    coords = jnp.asarray(coords)
    flat = jnp.asarray(dense_words).reshape(-1)
    wi = jnp.minimum((coords >> jnp.uint32(5)).astype(jnp.int32),
                     flat.shape[0] - 1)
    bits = (flat[wi] >> (coords & jnp.uint32(31))) & jnp.uint32(1)
    valid = coords < jnp.uint32(total_bits)
    return jnp.sum(jnp.where(valid, bits,
                             jnp.uint32(0)).astype(jnp.int32))


# Group-code planes (one-pass GroupBy) --------------------------------------
#
# A stack of R DISJOINT packed rows (no column in two rows) is exactly a
# base-R digit per column; these helpers re-encode that digit as
# ceil(log2 R) packed BIT-PLANES so the one-pass GroupBy histogram can
# compose a dense group code per column without ever unpacking the row
# stacks.  Work entirely with | and & so the same code serves numpy host
# arrays and jnp device arrays.

def digit_bits(n_rows: int) -> int:
    """Bit-planes needed to encode a digit in [0, n_rows)."""
    return max(int(n_rows) - 1, 0).bit_length()


def digit_planes(rows):
    """Disjoint row stack (R, ..., W) -> (digit_bits(R), ..., W) packed
    digit planes: plane b = OR of rows whose index has bit b set, so a
    column in row r carries the bits of r.  Caller guarantees
    disjointness (overlap would OR two digits together)."""
    import numpy as _np
    r = rows.shape[0]
    nbits = digit_bits(r)
    xp = _np if isinstance(rows, _np.ndarray) else jnp
    planes = []
    for b in range(nbits):
        acc = None
        for i in range(r):
            if (i >> b) & 1:
                acc = rows[i] if acc is None else acc | rows[i]
        planes.append(acc)
    if not planes:
        return xp.zeros((0,) + rows.shape[1:], dtype=rows.dtype)
    return xp.stack(planes)


def unpack_bits(words):
    """Device bit-unpack: (..., W) uint32 -> (..., W*32) int32 0/1 per
    column (column c = word c>>5, bit c&31)."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = (words[..., None] >> shifts) & jnp.uint32(1)
    return bits.reshape(*words.shape[:-1],
                        words.shape[-1] * 32).astype(jnp.int32)


def code_from_planes(planes):
    """Bit-unpack + weighted recombine: (CB, ..., W) packed planes ->
    (..., W*32) int32 per-column codes (plane b contributes bit b).
    CB = 0 yields all-zero codes."""
    cb = planes.shape[0]
    if cb == 0:
        return jnp.zeros(planes.shape[1:-1] + (planes.shape[-1] * 32,),
                         dtype=jnp.int32)
    code = unpack_bits(planes[0])
    for b in range(1, cb):
        code = code | (unpack_bits(planes[b]) << b)
    return code


def code_from_planes_np(planes: np.ndarray) -> np.ndarray:
    """Host twin of code_from_planes (numpy, same layout)."""
    planes = np.asarray(planes, dtype=np.uint32)
    cb = planes.shape[0]
    out_shape = planes.shape[1:-1] + (planes.shape[-1] * 32,)
    code = np.zeros(out_shape, dtype=np.int32)
    shifts = np.arange(32, dtype=np.uint32)
    for b in range(cb):
        bits = ((planes[b][..., None] >> shifts) & 1).astype(np.int32)
        code |= bits.reshape(out_shape) << b
    return code
