"""LIKE-pattern matching.

The reference has TWO distinct matchers and we mirror both:

- ``like_match`` — the key-filter matcher used by the PQL
  Rows(like=) path (like.go:13 planLike/matchLike semantics:
  case-sensitive, ``%`` matches any run, ``_`` exactly one
  character).
- ``sql_like_match`` — the SQL scalar operator
  (sql3/planner/expression.go:2991 wildCardToRegexp: matching is
  CASE-INSENSITIVE, ``%`` -> ``.*`` and ``_`` -> ``.+`` i.e. one OR
  MORE characters — so ``'foo' LIKE '%f_'`` is true there even
  though the key matcher rejects it; defs_like.go likeTests_6).
  One deliberate deviation: the reference splices the pattern into
  the regex unescaped, so regex metacharacters misbehave there; we
  escape them.
"""

from __future__ import annotations

import re


def like_regex(pattern: str) -> re.Pattern:
    return re.compile(
        "^" + "".join(
            ".*" if ch == "%" else "." if ch == "_" else re.escape(ch)
            for ch in pattern) + "$",
        re.DOTALL)


def like_match(value: str, pattern: str) -> bool:
    return like_regex(pattern).match(value) is not None


def sql_like_regex(pattern: str) -> re.Pattern:
    return re.compile(
        "^" + "".join(
            ".*" if ch == "%" else ".+" if ch == "_"
            else re.escape(ch) for ch in pattern) + "$",
        re.DOTALL | re.IGNORECASE)


def sql_like_match(value: str, pattern: str) -> bool:
    return sql_like_regex(pattern).match(value) is not None
