"""LIKE-pattern matching shared by the PQL Rows(like=) path and the
SQL residue evaluator (like.go:13 planLike semantics: ``%`` matches
any run, ``_`` exactly one character)."""

from __future__ import annotations

import re


def like_regex(pattern: str) -> re.Pattern:
    return re.compile(
        "^" + "".join(
            ".*" if ch == "%" else "." if ch == "_" else re.escape(ch)
            for ch in pattern) + "$",
        re.DOTALL)


def like_match(value: str, pattern: str) -> bool:
    return like_regex(pattern).match(value) is not None
