"""PQL AST (shape of pql/ast.go Call/Query/Condition)."""

from __future__ import annotations

from dataclasses import dataclass, field
from decimal import Decimal
from typing import Any

# Condition ops (pql tokens): comparison ops plus BETWEEN variants.
# Between ops state bound inclusivity: "><" is [a,b] (both inclusive,
# from `field >< [a,b]`); the conditional forms `a < x < b` produce
# the partially-open variants.
OP_EQ, OP_NEQ = "==", "!="
OP_LT, OP_LTE, OP_GT, OP_GTE = "<", "<=", ">", ">="
OP_BETW = "><"            # inclusive-inclusive
OP_BTWN_LT_LT = "<x<"     # exclusive-exclusive
OP_BTWN_LTE_LT = "<=x<"
OP_BTWN_LT_LTE = "<x<="
OP_BTWN_LTE_LTE = "<=x<="  # same semantics as "><"

BETWEEN_OPS = (OP_BETW, OP_BTWN_LT_LT, OP_BTWN_LTE_LT, OP_BTWN_LT_LTE,
               OP_BTWN_LTE_LTE)


@dataclass
class Condition:
    op: str
    value: Any  # scalar, or [lo, hi] for between ops

    def __repr__(self):
        return f"Condition({self.op!r}, {self.value!r})"


@dataclass
class Call:
    name: str
    args: dict[str, Any] = field(default_factory=dict)
    children: list["Call"] = field(default_factory=list)

    def arg(self, key: str, default=None):
        return self.args.get(key, default)

    def has_condition_arg(self) -> bool:
        return any(isinstance(v, Condition) for v in self.args.values())

    def condition_field(self):
        """(field, Condition) for calls like Row(x > 5)."""
        for k, v in self.args.items():
            if isinstance(v, Condition):
                return k, v
        return None, None

    def field_arg(self):
        """The single row-spec arg (field=row) for Set/Clear/Row
        (pql.Call.FieldArg semantics)."""
        for k, v in self.args.items():
            if k.startswith("_") or isinstance(v, Condition):
                continue
            if k in ("from", "to"):
                continue
            return k, v
        return None, None

    def __repr__(self):
        parts = [repr(c) for c in self.children]
        parts += [f"{k}={v!r}" for k, v in self.args.items()]
        return f"{self.name}({', '.join(parts)})"

    def to_pql(self) -> str:
        """Serialize back to parseable PQL text (pql.Call.String
        analog) — used by the cluster layer to ship single calls to
        shard owners."""
        parts = [c.to_pql() for c in self.children]
        if "_col" in self.args:
            parts.append(_pql_value(self.args["_col"]))
        if "_field" in self.args:
            # named form: a positional field is only recognized at
            # position 0, which a child call may already occupy
            parts.append(f"field={self.args['_field']}")
        for k, v in self.args.items():
            if k in ("_col", "_field", "_timestamp"):
                continue
            if isinstance(v, Condition):
                parts.append(_pql_condition(k, v))
            else:
                parts.append(f"{k}={_pql_value(v)}")
        if "_timestamp" in self.args:
            parts.append(str(self.args["_timestamp"]))
        return f"{self.name}({', '.join(parts)})"


@dataclass
class Query:
    calls: list[Call] = field(default_factory=list)

    def __repr__(self):
        return "".join(repr(c) for c in self.calls)


def _pql_value(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        return "null"
    if isinstance(v, Call):
        # nested call args (GroupBy aggregate=, filter=, having=)
        return v.to_pql()
    if isinstance(v, str):
        return '"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"'
    if isinstance(v, (list, tuple)):
        return "[" + ",".join(_pql_value(x) for x in v) + "]"
    return str(v)


def _pql_condition(field_name: str, cond: Condition) -> str:
    if cond.op in BETWEEN_OPS:
        lo, hi = cond.value
        if cond.op in (OP_BETW, OP_BTWN_LTE_LTE):
            return f"{field_name} >< [{_pql_value(lo)},{_pql_value(hi)}]"
        left, right = cond.op.split("x")
        return (f"{_pql_value(lo)} {left} {field_name} {right} "
                f"{_pql_value(hi)}")
    return f"{field_name} {cond.op} {_pql_value(cond.value)}"


def is_between(cond: Condition) -> bool:
    return cond.op in BETWEEN_OPS


def between_bounds_inclusive(cond: Condition) -> tuple[int, int]:
    """Normalize any between-op to inclusive integer bounds [lo, hi]."""
    lo, hi = cond.value
    lo, hi = int(lo), int(hi)
    if cond.op in (OP_BTWN_LT_LT, OP_BTWN_LT_LTE):
        lo += 1
    if cond.op in (OP_BTWN_LT_LT, OP_BTWN_LTE_LT):
        hi -= 1
    return lo, hi


__all__ = [
    "Call", "Condition", "Query", "Decimal", "is_between",
    "between_bounds_inclusive",
    "OP_EQ", "OP_NEQ", "OP_LT", "OP_LTE", "OP_GT", "OP_GTE", "OP_BETW",
    "OP_BTWN_LT_LT", "OP_BTWN_LTE_LT", "OP_BTWN_LT_LTE", "OP_BTWN_LTE_LTE",
    "BETWEEN_OPS",
]
