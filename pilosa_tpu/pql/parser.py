"""Recursive-descent PQL parser.

Follows the surface of the reference grammar (pql/pql.peg): a query is
a sequence of calls; call args are nested calls, ``key=value`` pairs,
condition args (``key OP value`` with OP in < <= == != >= > ><), or
conditional triples (``5 < key < 10``).  Values: null/true/false,
decimals, quoted strings, bare words, time literals, lists, nested
calls.  Positional forms (Set/Clear column, posfield for
TopN/TopK/Rows/Min/Max/Sum/Percentile) are normalized into the
``_col``/``_field``/``_timestamp`` args the executor expects
(pql/ast.go addPosNum/addPosStr).
"""

from __future__ import annotations

import re
from decimal import Decimal

from pilosa_tpu.pql.ast import (
    OP_BETW,
    OP_BTWN_LT_LT,
    OP_BTWN_LT_LTE,
    OP_BTWN_LTE_LT,
    OP_BTWN_LTE_LTE,
    Call,
    Condition,
    Query,
)


class ParseError(Exception):
    pass


_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+)
  | (?P<timestamp>\d{4}-\d{2}-\d{2}T\d{2}:\d{2}(:\d{2}(\.\d+)?(Z|[+-]\d{2}:\d{2}))?)
  | (?P<decimal>-?\d+\.\d*|-?\.\d+|-?\d+)
  | (?P<ident>[A-Za-z_$Θ][A-Za-z0-9_\-:Θ]*)
  | (?P<dq>"(?:\\"|\\\\|\\n|\\t|[^"\\])*")
  | (?P<sq>'(?:\\'|\\\\|\\n|\\t|[^'\\])*')
  | (?P<op>><|<=|>=|==|!=|<|>|=)
  | (?P<punct>[(),\[\]])
""", re.VERBOSE)

# Calls whose first positional value is a column (pql.peg Set/Clear).
_COL_CALLS = {"Set", "Clear"}
# Calls whose first positional identifier is the field (pql.peg posfield).
_POSFIELD_CALLS = {"TopN", "TopK", "Percentile", "Rows", "Min", "Max", "Sum",
                   "Distinct", "MinRow", "MaxRow"}
# Canonical capitalizations (pql canonicalCaps).
_CANONICAL = {n.lower(): n for n in [
    "All", "Apply", "Clear", "ClearRow", "ConstRow", "Count", "Delete",
    "Difference", "Distinct", "Extract", "GroupBy", "IncludesColumn",
    "Intersect", "Limit", "Max", "Min", "MinRow", "MaxRow", "Not", "Options",
    "Percentile", "Range", "Row", "Rows", "Set", "Shift", "Sort", "Store",
    "Sum", "TopK", "TopN", "Union", "UnionRows", "Xor",
]}


class _Tokens:
    def __init__(self, text: str):
        self.text = text
        self.toks: list[tuple[str, str, int]] = []
        pos = 0
        while pos < len(text):
            m = _TOKEN_RE.match(text, pos)
            if m is None:
                raise ParseError(
                    f"unexpected character {text[pos]!r} at {pos}")
            pos = m.end()
            kind = m.lastgroup
            if kind != "ws":
                self.toks.append((kind, m.group(), m.start()))
        self.i = 0

    def peek(self, ahead: int = 0):
        j = self.i + ahead
        return self.toks[j] if j < len(self.toks) else (None, None, len(self.text))

    def next(self):
        t = self.peek()
        self.i += 1
        return t

    def expect(self, value: str):
        kind, v, pos = self.next()
        if v != value:
            raise ParseError(f"expected {value!r} at {pos}, got {v!r}")
        return v

    def at_end(self):
        return self.i >= len(self.toks)


# Parsed-query memo (prepared-statement analog): serving storms repeat
# a small vocabulary of statements, and parse cost (~0.1ms) is pure
# fixed overhead on the host fast paths.  Safe to share: nothing
# mutates a Query/Call after parse (the executor only reads args and
# attaches results to its own RowResult objects).  Bounded by wholesale
# clear — ad-hoc queries (literal ids inlined) just miss.
_PARSE_MEMO: dict[str, Query] = {}
_PARSE_MEMO_MAX = 512


def parse(text: str) -> Query:
    q = _PARSE_MEMO.get(text)
    if q is not None:
        return q
    toks = _Tokens(text)
    q = Query()
    while not toks.at_end():
        q.calls.append(_parse_call(toks))
    if len(_PARSE_MEMO) >= _PARSE_MEMO_MAX:
        _PARSE_MEMO.clear()
    _PARSE_MEMO[text] = q
    return q


def _parse_call(toks: _Tokens) -> Call:
    kind, name, pos = toks.next()
    if kind != "ident":
        raise ParseError(f"expected call name at {pos}, got {name!r}")
    name = _CANONICAL.get(name.lower(), name)
    call = Call(name)
    toks.expect("(")
    first = True
    npos = 0
    while True:
        k, v, _ = toks.peek()
        if v == ")":
            toks.next()
            break
        if not first:
            if v == ",":
                toks.next()
                k, v, _ = toks.peek()
                if v == ")":  # trailing comma
                    toks.next()
                    break
            else:
                raise ParseError(f"expected ',' or ')' in {name} args")
        first = False
        _parse_arg(toks, call, name, npos)
        npos += 1
    return call


def _is_call_start(toks: _Tokens) -> bool:
    k1, v1, _ = toks.peek()
    k2, v2, _ = toks.peek(1)
    return k1 == "ident" and v2 == "("


def _parse_arg(toks: _Tokens, call: Call, name: str, npos: int):
    # nested call
    if _is_call_start(toks):
        call.children.append(_parse_call(toks))
        return
    k1, v1, p1 = toks.peek()
    k2, v2, _ = toks.peek(1)

    # conditional triple: value < field < value
    if (k1 in ("decimal", "timestamp") and v2 in ("<", "<=")):
        lo = _scalar(k1, v1)
        toks.next()
        op1 = toks.next()[1]
        fk, fv, fp = toks.next()
        if fk != "ident":
            raise ParseError(f"expected field in conditional at {fp}")
        op2 = toks.next()[1]
        if op2 not in ("<", "<="):
            raise ParseError(f"expected < or <= in conditional, got {op2!r}")
        hk, hv, hp = toks.next()
        hi = _scalar(hk, hv)
        op = {("<", "<"): OP_BTWN_LT_LT, ("<", "<="): OP_BTWN_LT_LTE,
              ("<=", "<"): OP_BTWN_LTE_LT, ("<=", "<="): OP_BTWN_LTE_LTE}[
            (op1, op2)]
        call.args[fv] = Condition(op, [lo, hi])
        return

    # key=value / key OP value
    if k1 == "ident" and v2 in ("=", "><", "<=", ">=", "==", "!=", "<", ">"):
        toks.next()
        op = toks.next()[1]
        value = _parse_value(toks)
        key = v1
        if op == "=":
            if key == "field":
                key = "_field"
            call.args[key] = value
        else:
            call.args[key] = Condition(op, value)
        return

    # positional value
    value = _parse_value(toks)
    if name in _COL_CALLS and npos == 0:
        call.args["_col"] = value
    elif name in _POSFIELD_CALLS and npos == 0 and isinstance(value, str):
        call.args["_field"] = value
    elif name in _COL_CALLS and isinstance(value, str) and npos >= 2:
        call.args["_timestamp"] = value
    elif k1 == "timestamp":
        call.args["_timestamp"] = value
    else:
        # bare positional (e.g. Store(Row(...), f=1) handled via children;
        # ClearRow(f=1) has kv form) — keep by position for forward compat
        call.args[f"_arg{npos}"] = value


def _scalar(kind, text):
    if kind == "decimal":
        return Decimal(text) if "." in text else int(text)
    if kind == "timestamp":
        return text
    raise ParseError(f"expected scalar, got {text!r}")


_ESCAPES = {'\\"': '"', "\\'": "'", "\\\\": "\\", "\\n": "\n", "\\t": "\t"}


def _unquote(s: str) -> str:
    body = s[1:-1]
    return re.sub(r'\\.|\\', lambda m: _ESCAPES.get(m.group(), m.group()), body)


def _parse_value(toks: _Tokens):
    if _is_call_start(toks):
        return _parse_call(toks)
    kind, v, pos = toks.next()
    if v == "[":
        items = []
        while True:
            k2, v2, _ = toks.peek()
            if v2 == "]":
                toks.next()
                break
            if items:
                toks.expect(",")
            items.append(_parse_value(toks))
        return items
    if kind == "decimal":
        return Decimal(v) if "." in v else int(v)
    if kind == "timestamp":
        return v
    if kind in ("dq", "sq"):
        return _unquote(v)
    if kind == "ident":
        if v == "null":
            return None
        if v == "true":
            return True
        if v == "false":
            return False
        return v  # bare word (key or time literal fragment)
    raise ParseError(f"unexpected token {v!r} at {pos}")
