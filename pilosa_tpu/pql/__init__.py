"""PQL — the Pilosa Query Language.

Hand-written recursive-descent parser producing the same call-tree
shape as the reference's PEG parser (pql/pql.peg, pql/ast.go): a Query
of nested Calls with named args, condition args (``field > 5``,
``field >< [a, b]``, ``5 < field < 10``), positional forms for
Set/Clear/TopN/TopK/Rows/Min/Max/Sum/Percentile, lists, quoted
strings, decimals, and time literals.
"""

from pilosa_tpu.pql.ast import Call, Condition, Query
from pilosa_tpu.pql.parser import parse, ParseError

# pql.Call.IsWrite (pql/ast.go writeCallNames)
WRITE_CALLS = {"Set", "Clear", "Store", "ClearRow", "Delete"}


def is_write_query(pql: str) -> bool:
    """True when any call in the query mutates (conservative True on
    parse errors — used by authz need selection)."""
    try:
        return any(c.name in WRITE_CALLS for c in parse(pql).calls)
    except Exception:
        return True


__all__ = ["Call", "Condition", "Query", "parse", "ParseError",
           "WRITE_CALLS", "is_write_query"]
