"""HTTP transport — router + handlers over the API facade.

Reference: http_handler.go (route table :493-562, gorilla/mux) and
server.go (Server wiring holder+executor+monitors).  Routes kept:

    POST   /index/{index}/query             PQL (?profile=true)
    POST   /sql                             SQL
    GET    /schema                          full schema
    POST   /schema                          apply schema (idempotent)
    POST   /index/{index}                   create index
    DELETE /index/{index}                   delete index
    POST   /index/{index}/field/{field}     create field (JSON options)
    DELETE /index/{index}/field/{field}     delete field
    POST   /index/{index}/field/{field}/import         bits/values
    POST   /internal/translate/{index}/keys/find|create (+?field=)
    GET    /internal/translate/{index}/ids  (?field=)
    GET    /status /info /version /metrics /metrics.json
    GET    /internal/shards/max
    GET    /query-history

The server is a stdlib ThreadingHTTPServer — the transport is not the
hot path (queries run on-device); a C++ server would buy nothing here.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from pilosa_tpu.api import API, ApiError
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.obs import metrics
from pilosa_tpu.obs.logger import Logger, NopLogger


class _Httpd(ThreadingHTTPServer):
    # socketserver's default accept backlog is 5: under a client storm
    # concentrated by a node death, an overloaded-but-ALIVE node
    # starts refusing connects — which the cluster layer reads as
    # ANOTHER node dying (refused = definitive death)
    request_queue_size = 128


class Route:
    def __init__(self, method: str, pattern: str, fn,
                 admin_only: bool = False):
        self.method = method
        self.pattern = pattern  # kept for route-surface introspection
        self.re = re.compile("^" + re.sub(
            r"\{(\w+)\}", r"(?P<\1>[^/]+)", pattern) + "$")
        self.fn = fn
        self.admin_only = admin_only


class Server:
    """Wires holder + API + HTTP listener (server.go:46 analog)."""

    def __init__(self, holder: Holder | None = None, bind: str = "127.0.0.1",
                 port: int = 0, logger: Logger | None = None,
                 auth=None, api: API | None = None, config=None):
        self._owns_holder = holder is None
        self.holder = holder if holder is not None else Holder()
        self.api = api if api is not None else API(self.holder)
        self.logger = logger or NopLogger()
        # serving path (executor/serving.py): handler threads route
        # queries through the cross-query micro-batcher + versioned
        # result cache.  Defaults come from Config (env-overridable:
        # PILOSA_TPU_SERVING_BATCHING=0 disables batching,
        # PILOSA_TPU_SERVING_CACHE_MB=0 the cache).
        if config is None:
            from pilosa_tpu import config as cfgmod
            config = cfgmod.load()
        if self.api.executor.serving is None and (
                config.serving_batching or config.serving_cache_mb > 0):
            self.api.executor.enable_serving(
                window_s=config.serving_batch_window_ms / 1e3,
                max_batch=config.serving_batch_max,
                cache_bytes=config.serving_cache_mb << 20,
                batching=config.serving_batching,
                ragged=config.serving_ragged,
                admission=config.serving_admission,
                heavy_slots=config.serving_heavy_slots,
                queue_max=config.serving_queue_max,
                tenant_weights=config.serving_tenant_weights,
                default_deadline_ms=config.serving_default_deadline_ms)
        config.apply_flight_settings()
        # failure-tolerance plane: config/env-armed fault points +
        # hedge/deadline knobs for the cluster fan-out
        config.apply_fault_settings()
        # HBM residency manager ([memory]): budget ledger + paged
        # stacks + OOM backstop; the prefetcher warms predicted stack
        # pages from flight records off the serving hot path
        config.apply_memory_settings()
        # serving mesh ([cluster] mesh-devices / placement-pin):
        # per-device page placement for the mesh-sharded fused
        # program (memory/placement.py)
        config.apply_placement_settings()
        # roofline attribution ([roofline]): per-op achieved-GB/s vs a
        # measured/configured peak; the STREAM-style probe runs once
        # on a background thread so first queries never wait on it
        config.apply_roofline_settings()
        # SLO burn-rate plane ([slo]): the maintenance ticker below
        # feeds its sample ring
        config.apply_slo_settings()
        # SQL serving plane ([sql]): SELECT statements ride the fused
        # serving plane with the catalog-fed cost-based planner
        config.apply_sql_settings()
        # temporal analytics ([timeq] + [standing]): quantum-cover
        # fused plan op, rollup/write-finest lifecycle, and the
        # standing-query registry's admission knobs
        config.apply_timeq_settings()
        config.apply_standing_settings()
        # statistics catalog ([stats]): persisted flight/roofline
        # telemetry feeding the cost gates, admission classing, cache
        # eviction, and hedge derivation; persisted under the
        # holder's data dir so a restarted node plans warm
        config.apply_stats_settings(data_dir=self.holder.path)
        # incident forensics plane ([incidents] + [watchdog]):
        # anomaly-triggered black-box bundles persisted under the
        # data dir, stall watchdogs on every long-running loop, and
        # the always-on continuous profiler whose ring rides along
        # in every bundle
        config.apply_watchdog_settings()
        config.apply_incident_settings(data_dir=self.holder.path)
        # continuous correctness auditing ([audit], obs/audit.py):
        # shadow-execution sampler on the serving routes + the
        # maintenance-ticker scrubbers below
        config.apply_audit_settings()
        # disaggregated DAX tier ([dax] + [blob]): blob shard store
        # backend, lazy hydration + per-worker ledger budgets, and
        # the autoscaler's scale thresholds (dax/settings.py)
        config.apply_dax_settings()
        if (self.api.executor.serving is not None
                and config.memory_prefetch):
            self.api.executor.serving.start_prefetcher(
                interval_s=config.memory_prefetch_interval_s)
        # streaming write plane (ingest/stream.py): the batched
        # /index/{i}/ingest endpoint coalesces concurrent mutations
        # into durable windows; acks only after the WAL-synced land
        self.stream = None
        if config.ingest_stream:
            from pilosa_tpu.ingest.stream import StreamWriter
            self.stream = StreamWriter(
                self.api,
                window_s=config.ingest_window_ms / 1e3,
                max_batch=config.ingest_max_batch,
                queue_max=config.ingest_queue,
                tenant_queue_max=config.ingest_tenant_queue,
                sync=config.ingest_sync)
        # (Authenticator, Authorizer | None) — enables the chkAuthZ
        # middleware in dispatch (http_handler.go chkAuthZ)
        self.auth = auth
        self._routes: list[Route] = []
        self._register_routes()
        handler = _make_handler(self)
        self.httpd = _Httpd((bind, port), handler)
        self.port = self.httpd.server_address[1]
        self._thread: threading.Thread | None = None
        self._serving = False
        self.maintenance_interval = 60.0  # TTL sweep + flush cadence
        self._ticker_thread: threading.Thread | None = None
        self._ticker_stop = threading.Event()

    # -- lifecycle -----------------------------------------------------

    def serve_forever(self):
        self.logger.info("listening on :%d", self.port)
        self._serving = True
        self._start_tickers()
        self.httpd.serve_forever()

    def start(self):
        """Serve on a background thread (tests, embedded use)."""
        from pilosa_tpu.obs import testhook
        testhook.opened("http.Server", self, f"port={self.port}")
        self._serving = True
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True)
        self._thread.start()
        self._start_tickers()
        return self

    def _start_tickers(self):
        """Holder maintenance loop: TTL view sweep + flush (the
        reference's cache-flush ticker, holder.go:1244, and TTL view
        removal, time.go:158)."""
        if self._ticker_thread is not None:
            return
        self._ticker_thread = threading.Thread(target=self._tick_loop,
                                               daemon=True)
        self._ticker_thread.start()

    def _tick_loop(self):
        # stall watchdog: the ticker drives TTL sweeps, flushes, SLO
        # sampling, and stats persistence — a tick wedged on a dead
        # disk must be a named stall, not silently stale telemetry
        from pilosa_tpu.obs import watchdog
        watch = watchdog.register("maintenance-ticker")
        while not self._ticker_stop.wait(self.maintenance_interval):
            watch.stamp("tick")
            try:
                removed = self.holder.remove_expired_views()
                # quantum rollup ([timeq] rollup): completed fine
                # views OR-fold into their coarser parents so range
                # covers shrink as data ages
                from pilosa_tpu.models import timeq
                folded = (self.holder.rollup_views()
                          if timeq.rollup_enabled() else [])
                for _ in folded:
                    metrics.TIMEQ_ROLLUP_TOTAL.inc()
                if removed or folded:
                    if removed:
                        self.logger.info("ttl removed %d views",
                                         len(removed))
                    if folded:
                        self.logger.info("rolled up %d views",
                                         len(folded))
                    # an expired/rolled quantum view invalidates
                    # derived state: the dropped fragments' gens were
                    # bumped (models/field.py), the serving result
                    # cache is swept eagerly so no cached Row/Count
                    # keeps serving the expired window, and standing
                    # registrations re-scope their quantum cover (one
                    # declared fallback each)
                    srv = self.api.executor.serving
                    if srv is not None and srv.cache is not None:
                        srv.cache.sweep(self.holder)
                        srv.standing.on_write()
                self.holder.sync()
                # SLO sample ring: one cumulative reading per tick so
                # burn-rate windows have history between scrapes
                from pilosa_tpu.obs import slo
                slo.tick()
                # statistics catalog: fold pending flight records,
                # refresh the regression sentinel, snapshot on cadence
                from pilosa_tpu.obs import stats
                stats.tick()
                # host/runtime stats (obs/diagnostics.py): refresh the
                # dormant collector so every incident bundle carries a
                # host snapshot that PREDATES its anomaly (phone-home
                # stays off — collection is in-process only)
                from pilosa_tpu.obs import diagnostics
                diagnostics.collect()
                # correctness-audit scrubbers (obs/audit.py): sampled
                # ResultCache recomputes, standing drift checks at
                # quiesce, and — on cluster nodes — the replica
                # block-checksum scrub, each budgeted per tick
                from pilosa_tpu.obs import audit
                audit.tick(self.api.executor.serving)
            except Exception as e:
                self.logger.error("maintenance tick failed: %s", e)
            finally:
                watch.idle()

    def close(self):
        from pilosa_tpu.obs import testhook
        testhook.closed("http.Server", self)
        # persist the statistics catalog on clean shutdown — a node
        # restarted inside the snapshot interval must still plan
        # warm (no-op when persistence is off) — and DETACH the
        # store when it lives under this server's data dir: later
        # process activity must not append into a dead server's file
        # (or a deleted tmp dir in tests)
        from pilosa_tpu.obs import stats
        try:
            cat = stats.get()
            cat.save()
            # detach only when THIS server's data dir owns the store:
            # in a multi-server process the last-configured server
            # owns it, each server detaches its own on close (so no
            # appends outlive the owning dir), and detaching another
            # live server's store here would orphan its persistence —
            # nothing reattaches outside Server.__init__
            if cat.store is not None and self.holder.path and \
                    cat.store.path.startswith(
                        os.path.join(self.holder.path, "")):
                # the trailing separator makes this a DIRECTORY
                # check: /data/node1 must not claim /data/node10's
                # store and orphan a sibling server's persistence
                cat.detach_store()
        except Exception as e:
            self.logger.warn("stats snapshot on close failed: %s", e)
        if self.api.executor.serving is not None:
            self.api.executor.serving.stop_prefetcher()
            aud = getattr(self.api.executor.serving, "audit", None)
            if aud is not None:
                aud.close()
        if self.stream is not None:
            self.stream.close()
        self._ticker_stop.set()
        if self._ticker_thread:
            self._ticker_thread.join(timeout=2)
        # shutdown() blocks on an event only serve_forever() sets —
        # calling it on a never-started server would deadlock
        if self._serving:
            self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
        if self._owns_holder:
            self.holder.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- routing -------------------------------------------------------

    def _register_routes(self):
        r = self._routes.append
        r(Route("POST", "/index/{index}/query", self._post_query))
        r(Route("POST", "/sql", self._post_sql))
        r(Route("GET", "/schema", self._get_schema))
        r(Route("POST", "/schema", self._post_schema))
        r(Route("POST", "/index/{index}", self._post_index))
        r(Route("DELETE", "/index/{index}", self._delete_index))
        r(Route("POST", "/index/{index}/field/{field}", self._post_field))
        r(Route("DELETE", "/index/{index}/field/{field}",
                self._delete_field))
        r(Route("POST", "/index/{index}/field/{field}/import",
                self._post_import))
        r(Route("POST", "/index/{index}/import-columns",
                self._post_import_columns))
        r(Route("POST", "/index/{index}/ingest", self._post_ingest))
        # standing queries (executor/standing.py): register/list/drop
        # write-through maintained subscriptions
        r(Route("POST", "/index/{index}/standing",
                self._post_standing))
        r(Route("GET", "/standing", self._get_standing))
        r(Route("DELETE", "/standing/{sid}", self._delete_standing))
        r(Route("POST", "/internal/translate/{index}/keys/find",
                self._post_translate_find))
        r(Route("POST", "/internal/translate/{index}/keys/create",
                self._post_translate_create))
        r(Route("POST", "/internal/translate/{index}/ids",
                self._post_translate_ids))
        r(Route("GET", "/internal/shards/max", self._get_shards_max))
        r(Route("GET", "/internal/shards/{index}",
                lambda req: self.api.available_shards(
                    req.vars["index"])))
        r(Route("GET", "/status", lambda req: self.api.status()))
        r(Route("GET", "/info", lambda req: self.api.info()))
        r(Route("GET", "/version", lambda req: self.api.version()))
        r(Route("GET", "/query-history",
                lambda req: self.api.query_history()))
        r(Route("GET", "/metrics", self._get_metrics))
        r(Route("GET", "/metrics.json", self._get_metrics_json))
        r(Route("GET", "/login", self._get_login))
        r(Route("GET", "/debug/errors", self._get_debug_errors))
        # profiling surface (http_handler.go:493-494 pprof/fgprof):
        # wall-clock stack sampler + heap snapshot + slow-query ring
        r(Route("GET", "/debug/profile", self._get_debug_profile))
        r(Route("GET", "/debug/allocs", self._get_debug_allocs))
        r(Route("GET", "/debug/long-queries",
                lambda req: self.api.long_queries()))
        # query flight recorder (obs/flight.py): recent per-query
        # records as JSON, and as Chrome trace_event JSON loadable in
        # Perfetto / chrome://tracing
        r(Route("GET", "/debug/queries", self._get_debug_queries))
        r(Route("GET", "/debug/trace", self._get_debug_trace))
        # SLO burn-rate plane (obs/slo.py): multi-window error-budget
        # burn over the latency histogram + typed-error counters
        r(Route("GET", "/debug/slo", self._get_debug_slo))
        # statistics catalog (obs/stats.py): per-field data stats +
        # per-fingerprint runtime profiles + the regression sentinel
        r(Route("GET", "/debug/stats", self._get_debug_stats))
        # fault-injection registry (obs/faults.py): armed rules with
        # fire counts — the chaos-operator's view of what is live
        r(Route("GET", "/debug/faults", self._get_debug_faults))
        # incident forensics plane (obs/incidents.py): black-box
        # bundle listing + fetch, the watchdog registry riding along
        r(Route("GET", "/debug/incidents", self._get_debug_incidents))
        # recent log-line ring (obs/logger.py) — the tail every
        # incident bundle attaches, served live for correlation
        r(Route("GET", "/debug/logs", self._get_debug_logs))
        # standing-query registry (executor/standing.py): live
        # registrations with per-query maintenance outcome counters
        r(Route("GET", "/debug/standing", self._get_debug_standing))
        # continuous correctness auditing (obs/audit.py): recent
        # samples, mismatch quarantine ring, scrub progress
        r(Route("GET", "/debug/audit", self._get_debug_audit))
        # disaggregated DAX tier (dax/worker.py + dax/controller.py):
        # worker roster with per-shard residency, placement overlay,
        # and the autoscaler's last reconcile decision
        r(Route("GET", "/debug/dax", self._get_debug_dax))
        r(Route("GET", "/internal/diagnostics", self._get_diagnostics))
        r(Route("GET", "/internal/perf-counters",
                self._get_perf_counters))
        r(Route("POST", "/transaction", self._post_transaction))
        r(Route("POST", "/transaction/{tid}/finish",
                lambda req: self.api.finish_transaction(req.vars["tid"])))
        r(Route("GET", "/transaction/{tid}",
                lambda req: self.api.get_transaction(req.vars["tid"])))
        r(Route("GET", "/transactions",
                lambda req: self.api.txns.list()))
        r(Route("POST",
                "/index/{index}/field/{field}/import-roaring/{shard}",
                self._post_import_roaring))
        r(Route("GET",
                "/index/{index}/field/{field}/row/{row}/roaring",
                self._get_row_roaring))
        r(Route("POST", "/index/{index}/dataframe", self._post_dataframe))
        r(Route("GET", "/index/{index}/dataframe", self._get_dataframe))
        r(Route("POST", "/index/{index}/dataframe/apply",
                self._post_dataframe_apply))
        # translation sync + replica repair (holder.go:1488-1715;
        # fragment.go checksum blocks)
        r(Route("GET", "/internal/translate/{index}/partitions",
                lambda req: self.api.translate_partitions(
                    req.vars["index"])))
        r(Route("GET",
                "/internal/translate/{index}/partition/{p}/snapshot",
                lambda req: self.api.translate_partition_snapshot(
                    req.vars["index"], int(req.vars["p"]))))
        r(Route("POST",
                "/internal/translate/{index}/partition/{p}/restore",
                lambda req: self.api.translate_restore_partition(
                    req.vars["index"], int(req.vars["p"]),
                    req.json())))
        r(Route("GET",
                "/internal/translate/{index}/field/{field}/snapshot",
                lambda req: self.api.field_translate_snapshot(
                    req.vars["index"], req.vars["field"])))
        r(Route("GET", "/internal/fragment/{index}/{field}/views",
                lambda req: self.api.fragment_views(
                    req.vars["index"], req.vars["field"])))
        r(Route("GET",
                "/internal/fragment/{index}/{field}/{view}/{shard}"
                "/checksums",
                lambda req: self.api.fragment_checksums(
                    req.vars["index"], req.vars["field"],
                    req.vars["view"], int(req.vars["shard"]))))
        r(Route("GET",
                "/internal/fragment/{index}/{field}/{view}/{shard}"
                "/block/{b}",
                lambda req: self.api.fragment_block(
                    req.vars["index"], req.vars["field"],
                    req.vars["view"], int(req.vars["shard"]),
                    int(req.vars["b"]))))
        # online-resharding transfer surface (ISSUE 14): resumable
        # block push (SNAPSHOT-COPY), the copy bootstrap state, and
        # the delta-log chase feed/apply (DELTA-CHASE)
        r(Route("POST",
                "/internal/fragment/{index}/{field}/{view}/{shard}"
                "/block/{b}",
                lambda req: self.api.fragment_set_block(
                    req.vars["index"], req.vars["field"],
                    req.vars["view"], int(req.vars["shard"]),
                    int(req.vars["b"]), req.json() or {})))
        r(Route("GET",
                "/internal/fragment/{index}/{field}/{view}/{shard}"
                "/state",
                lambda req: self.api.fragment_state(
                    req.vars["index"], req.vars["field"],
                    req.vars["view"], int(req.vars["shard"]))))
        r(Route("GET",
                "/internal/fragment/{index}/{field}/{view}/{shard}"
                "/deltas",
                lambda req: self.api.fragment_deltas(
                    req.vars["index"], req.vars["field"],
                    req.vars["view"], int(req.vars["shard"]),
                    int(req.query.get("since", ["0"])[0]))))
        r(Route("POST",
                "/internal/fragment/{index}/{field}/{view}/{shard}"
                "/rows",
                lambda req: self.api.fragment_set_rows(
                    req.vars["index"], req.vars["field"],
                    req.vars["view"], int(req.vars["shard"]),
                    req.json() or {})))
        r(Route("POST",
                "/internal/translate/{index}/field/{field}/restore",
                lambda req: self.api.field_translate_restore(
                    req.vars["index"], req.vars["field"],
                    req.json() or {})))
        r(Route("GET", "/internal/backup/manifest",
                lambda req: self.api.backup_manifest()))
        r(Route("GET", "/internal/backup/file", self._get_backup_file))
        r(Route("POST", "/internal/restore/file", self._post_restore_file))
        r(Route("POST", "/internal/restore/complete",
                lambda req: self.api.restore_complete()))

    # paths served without a token when auth is enabled
    # (http_handler.go: login/metrics/version stay open)
    _OPEN_PATHS = {"/version", "/metrics", "/metrics.json", "/login"}

    def _get_debug_errors(self, req):
        """Recent captured errors (monitor.go events; /debug surface)."""
        from pilosa_tpu.obs.monitor import global_monitor
        return global_monitor.recent()

    def _get_debug_profile(self, req):
        """fgprof-style wall-clock stack sample; ?seconds=&hz= bound
        the collection (defaults 2s @ 100Hz, capped at 30s).
        ?format=collapsed drops the header comment and attaches the
        body as a download — pure folded-stack lines for flamegraph
        tooling (flamegraph.pl / speedscope / inferno).  ?ring=1
        serves the CONTINUOUS profiler's merged ring instead of
        sampling live — the profile that was already running when
        something went wrong."""
        from pilosa_tpu.obs import profiler
        collapsed = req.query.get(
            "format", [""])[0] == "collapsed"
        if req.query.get("ring", ["0"])[0] in ("1", "true"):
            c = profiler.continuous
            if c is None:
                raise ApiError("continuous profiler disabled "
                               "([incidents] profile=false)", 400)
            body = c.folded()
        else:
            seconds = min(30.0, float(
                req.query.get("seconds", ["2"])[0]))
            hz = min(1000, int(req.query.get("hz", ["100"])[0]))
            body = profiler.sample_stacks(seconds, hz,
                                          collapsed=collapsed)
        if collapsed:
            req.extra_headers["Content-Disposition"] = (
                "attachment; filename=pilosa-profile.folded")
        return RawResponse(body, "text/plain")

    def _get_debug_incidents(self, req):
        """Incident bundles (obs/incidents.py): the newest-first
        metadata listing plus the live watchdog registry, or ONE full
        bundle via ?id= (404 when unknown — never a half bundle; torn
        tmp files are invisible to both paths)."""
        from pilosa_tpu.obs import incidents
        iid = req.query.get("id", [None])[0]
        if iid is not None:
            bundle = incidents.get().fetch(iid)
            if bundle is None:
                raise ApiError(f"no such incident: {iid}", 404)
            return bundle
        limit = int(req.query.get("limit", ["50"])[0])
        return incidents.get().payload(limit)

    def _get_debug_logs(self, req):
        """Recent log lines (obs/logger.py ring), oldest first —
        ?limit=N bounds the tail (default 200)."""
        from pilosa_tpu.obs import logger
        limit = int(req.query.get("limit", ["200"])[0])
        lines = logger.ring.recent(limit)
        return {"lines": lines, "returned": len(lines),
                "kept": len(logger.ring),
                "capacity": logger.ring._ring.maxlen}

    def _get_debug_allocs(self, req):
        """tracemalloc heap snapshot (pprof allocs analog)."""
        from pilosa_tpu.obs import profiler
        top = int(req.query.get("top", ["25"])[0])
        return RawResponse(profiler.heap_snapshot(top), "text/plain")

    def _get_debug_queries(self, req):
        """Recent flight records, newest first.  Filters (ISSUE 10 —
        a 4k-record ring must stay greppable from curl):

            ?limit=N (alias ?n=)  newest N AFTER filtering
            ?route=fused|cached|direct|solo|cluster|ingest
            ?tenant=NAME          serving-path tenant attribution
            ?since_ms=EPOCH_MS    records started at/after this time
            ?audited=1|0          audit-sampled serves only (or the
                                  never-sampled remainder) — the hop
                                  from an audit-mismatch incident
                                  bundle to the query's full trace
        """
        from pilosa_tpu.obs import flight
        q = req.query
        limit = int(q.get("limit", q.get("n", ["100"]))[0])
        # scan the whole ring, filter, then truncate — "matched" is
        # the pre-truncation count so curl users see how much more a
        # bigger limit would return (a debug endpoint can afford the
        # full-ring walk)
        recs = filter_flight_records(
            flight.recorder.recent(len(flight.recorder)),
            route=q.get("route", [None])[0],
            tenant=q.get("tenant", [None])[0],
            since_ms=q.get("since_ms", [None])[0],
            audited=q.get("audited", [None])[0])
        return {"enabled": flight.recorder.enabled,
                "matched": len(recs),
                "queries": recs[:max(0, limit)]}

    def _get_debug_slo(self, req):
        """SLO burn rates (obs/slo.py): samples the typed-error
        counters + latency histogram now and evaluates every
        configured window."""
        from pilosa_tpu.obs import slo
        return slo.get().evaluate()

    def _get_debug_stats(self, req):
        """Statistics catalog (obs/stats.py): data stats per
        (index, field), runtime profiles per plan fingerprint, gate
        rates, per-node attempt summaries, and the active perf
        regressions.  Filters: ?index= ?fingerprint= ?limit=N
        (newest-N profiles)."""
        from pilosa_tpu.obs import stats
        q = req.query
        limit = q.get("limit", [None])[0]
        return stats.get().payload(
            index=q.get("index", [None])[0],
            fingerprint=q.get("fingerprint", [None])[0],
            limit=int(limit) if limit is not None else None)

    def _get_debug_trace(self, req):
        """Recent flight records as Chrome trace_event JSON — save
        the body and open it in Perfetto (ui.perfetto.dev) or
        chrome://tracing."""
        from pilosa_tpu.obs import flight
        n = int(req.query.get("n", ["100"])[0])
        return RawResponse(flight.recorder.chrome_trace_json(n),
                           "application/json")

    def _get_debug_faults(self, req):
        """Armed fault-point rules (obs/faults.py registry)."""
        from pilosa_tpu.obs import faults
        return {"faults": faults.active()}

    def _get_diagnostics(self, req):
        from pilosa_tpu import __version__
        from pilosa_tpu.obs.diagnostics import Diagnostics
        return Diagnostics(version=__version__).payload()

    def _get_perf_counters(self, req):
        from pilosa_tpu.obs.diagnostics import performance_counters
        return performance_counters.snapshot()

    def _get_login(self, req):
        if self.auth is None:
            raise ApiError("auth not enabled", 400)
        authn_, _ = self.auth
        return {"url": authn_.login_url()}

    def _check_auth(self, method: str, path: str, req,
                    admin_only: bool = False):
        """chkAuthZ middleware (http_handler.go chkAuthZ): validate the
        bearer token, then require read (GET) / write (other) on the
        route's index, or admin for /internal + schema writes."""
        req.auth_claims = {}
        if self.auth is None or path in self._OPEN_PATHS:
            return
        from pilosa_tpu.server.authn import AuthError
        authn_, authz_ = self.auth
        try:
            claims = authn_.authenticate(req.headers.get("Authorization", ""))
        except AuthError as e:
            raise ApiError(str(e), 401)
        req.auth_claims = claims
        if authz_ is None:
            return
        groups = claims.get("groups", [])
        if admin_only or path.startswith("/internal") or \
                path.startswith("/transaction") or \
                path.startswith("/debug") or (
                path == "/schema" and method != "GET"):
            # transactions included: an exclusive transaction holds the
            # whole cluster read-only, so starting/finishing one is an
            # operator action
            if not authz_.is_admin(groups):
                raise ApiError("admin required", 403)
            return
        index = req.vars.get("index")
        if index is None:
            return
        if path.endswith("/query"):
            # reads POST too: permission follows the query's calls
            from pilosa_tpu.pql import is_write_query
            body = req.json_lenient()
            pql = (body or {}).get("query") or req.text()
            need = "write" if is_write_query(pql) else "read"
        else:
            need = "read" if method == "GET" else "write"
        if not authz_.allowed(groups, index, need):
            raise ApiError(f"not authorized for {need} on {index}", 403)

    def add_route(self, method: str, pattern: str, fn,
                  admin_only: bool = True, override: bool = False):
        """Register an extra route (embedding services — DAX compute
        nodes hang /directive etc. off the same listener).  Injected
        routes default to admin-only under auth: the middleware's
        per-index rules don't know them, and cluster-internal control
        surfaces must not be reachable with a mere read token.
        override=True inserts AHEAD of the built-in surface (the DAX
        queryer front serves /sql itself)."""
        rt = Route(method, pattern, fn, admin_only=admin_only)
        if override:
            self._routes.insert(0, rt)
        else:
            self._routes.append(rt)

    def dispatch(self, method: str, path: str, req) -> tuple[int, object]:
        for rt in self._routes:
            if rt.method != method:
                continue
            m = rt.re.match(path)
            if m:
                req.vars = m.groupdict()
                try:
                    self._check_auth(method, path, req,
                                     admin_only=rt.admin_only)
                    return 200, rt.fn(req)
                except ApiError as e:
                    return e.status, {"error": str(e)}
                except Exception as e:  # keep the connection alive
                    # typed status-carrying errors (LoadShedError 503,
                    # DeadlineExceeded 504, RemoteError pass-through)
                    # keep their semantics on the wire instead of
                    # collapsing into 500 — clients distinguish
                    # "shed, retry elsewhere" from "server bug"
                    status = getattr(e, "status", None)
                    if isinstance(status, int) and 400 <= status < 600:
                        ra = getattr(e, "retry_after_s", None)
                        if ra is not None:
                            # a shed is retryable by contract — say
                            # when (one heartbeat), per RFC 9110 §10.2.3
                            req.extra_headers = {
                                "Retry-After": str(max(1, round(ra)))}
                        # typed redirect/annotation surfaces
                        # (ShardMovedError's X-Pilosa-New-Owner +
                        # moved_shards body fields): the error type
                        # itself says what to attach
                        hdrs = getattr(e, "extra_headers", None)
                        if hdrs:
                            req.extra_headers.update(hdrs)
                        extra = getattr(e, "error_fields", None)
                        if extra:
                            return status, {"error": str(e),
                                            "type": type(e).__name__,
                                            **extra}
                        if status >= 500:
                            # 5xx pass-throughs (a peer's RemoteError
                            # 500, a shed) must not go dark in
                            # monitoring even though the wire keeps
                            # the typed status
                            from pilosa_tpu.obs.monitor import (
                                capture_exception,
                            )
                            capture_exception(e, path=path,
                                              method=method)
                        return status, {"error": str(e),
                                        "type": type(e).__name__}
                    from pilosa_tpu.obs.monitor import capture_exception
                    capture_exception(e, path=path, method=method)
                    self.logger.error("http 500 on %s: %s", path, e)
                    return 500, {"error": f"internal error: {e}"}
        return 404, {"error": f"no route: {method} {path}"}

    # -- handlers ------------------------------------------------------

    def _post_query(self, req):
        body = req.json_lenient()
        remote = False
        if body is not None:
            pql = body.get("query", "")
            shards = body.get("shards")
            remote = bool(body.get("remote"))
        else:  # raw PQL body, like the reference's text/plain mode
            pql = req.text()
            shards = None
        profile = req.query.get("profile", ["false"])[0] == "true"
        trace_id = req.headers.get("X-Pilosa-Trace-Id")
        if trace_id is None:
            return self.api.query(req.vars["index"], pql, shards,
                                  profile, remote=remote,
                                  qos=_qos_from_headers(req.headers))
        # cross-node trace propagation (ISSUE 10): this node is a
        # remote leg of a cluster fan-out.  The query's flight record
        # inherits the coordinator's trace id (so the rings merge at
        # /debug/cluster/queries), the leg executes under ONE
        # recording span — attached to this handler thread via the
        # same thread-tracer machinery Profile=true uses — and the
        # serialized tree returns in the response's "trace" trailer
        # for the coordinator's per-node Perfetto lanes.
        from pilosa_tpu.obs import flight
        parent = req.headers.get("X-Pilosa-Span-Parent", "")
        node = getattr(self.api, "name", "") or "local"
        with flight.remote_leg(trace_id) as (tracer, spans):
            with tracer.span(f"rpc:{req.vars['index']}", node=node,
                             **({"parent": parent} if parent else {})):
                resp = self.api.query(
                    req.vars["index"], pql, shards, profile,
                    remote=remote, qos=_qos_from_headers(req.headers))
        if spans:
            resp["trace"] = {"node": node, "spans": spans}
        return resp

    def _post_sql(self, req):
        body = req.json_lenient()
        stmt = body.get("sql", "") if body is not None else req.text()
        auth_check = None
        if self.auth is not None and self.auth[1] is not None:
            auth_check = self.auth[1].sql_check(
                req.auth_claims.get("groups", []))
        try:
            # the same QoS headers the PQL surface honors
            # (X-Pilosa-Tenant / -Priority / -Deadline-Ms): SELECT
            # statements admit through sched.py with per-statement
            # cost classes; shed/deadline render as typed 503/504
            return self.api.sql(stmt, auth_check=auth_check,
                                qos=_qos_from_headers(req.headers))
        except PermissionError as e:
            raise ApiError(str(e), 403)

    def _standing_registry(self):
        srv = self.api.executor.serving
        if srv is None or srv.cache is None:
            raise ApiError("standing queries require the serving "
                           "result cache", 400)
        return srv.standing

    def _post_standing(self, req):
        """Register a standing query: body {"query": "<PQL>"} or
        {"sql": "SELECT COUNT(*) ..."}.  The result is maintained
        write-through from ingest deltas; polls of the same query
        text serve the advanced entry (route "standing")."""
        from pilosa_tpu.executor.standing import StandingUnsupported
        reg = self._standing_registry()
        body = req.json_lenient() or {}
        try:
            if body.get("sql"):
                return reg.register_sql(self.api.sql_engine,
                                        body["sql"])
            if not body.get("query"):
                raise ApiError(
                    "body requires \"query\" (PQL) or \"sql\"", 400)
            return reg.register(req.vars["index"], body["query"])
        except StandingUnsupported as e:
            raise ApiError(str(e), 400)

    def _get_standing(self, req):
        return {"standing": self._standing_registry().list_info()}

    def _delete_standing(self, req):
        reg = self._standing_registry()
        try:
            sid = int(req.vars["sid"])
        except ValueError:
            raise ApiError("standing id must be an integer", 400)
        if not reg.unregister(sid):
            raise ApiError(f"standing query not found: {sid}", 404)
        return {"removed": sid}

    def _get_debug_standing(self, req):
        """Standing-query registry: registrations with maintenance
        outcome counters (incremental/fallback/noop) — the operator
        view of whether subscriptions stay on the O(delta) path."""
        from pilosa_tpu.executor import standing as _standing
        reg = self._standing_registry()
        return {"enabled": _standing.enabled(),
                "standing": reg.list_info()}

    def _get_debug_audit(self, req):
        """Continuous correctness auditing (obs/audit.py): sampler
        config, per-kind/outcome counters, recent samples, the
        mismatch quarantine ring, and scrub progress."""
        from pilosa_tpu.obs import audit
        srv = self.api.executor.serving
        return audit.payload(getattr(srv, "audit", None)
                             if srv is not None else None)

    def _get_debug_dax(self, req):
        """Disaggregated-tier state: every in-process worker's
        residency (dax/worker.py) and every controller's roster +
        last reconcile decision.  A plain cluster node answers with
        empty rosters — only modules ALREADY imported are consulted,
        so the debug sweep never drags the DAX stack in."""
        import sys
        payload: dict = {"workers": [], "controllers": []}
        wmod = sys.modules.get("pilosa_tpu.dax.worker")
        if wmod is not None:
            payload["workers"] = wmod.hydrator_payloads()
        cmod = sys.modules.get("pilosa_tpu.dax.controller")
        if cmod is not None:
            payload["controllers"] = cmod.controller_payloads()
        return payload

    def _post_import_columns(self, req):
        """Binary columnar import — the wire form of
        API.import_columns for out-of-process ingesters (the
        reference's IDK clones POST binary shard payloads the same
        way, idk/ingest.go:319 -> ImportRoaringShard).  Body: an
        .npz with 'cols' plus 'bits/<field>' row-id and
        'values/<field>' value arrays."""
        import io

        import numpy as np
        try:
            z = np.load(io.BytesIO(req.raw()))
        except Exception as e:
            raise ApiError(f"malformed npz payload: {e}", 400)
        if not isinstance(z, np.lib.npyio.NpzFile):
            # a bare .npy body parses as an ndarray — still a 400
            raise ApiError("payload must be an .npz archive", 400)
        with z:
            if "cols" not in z.files:
                raise ApiError("payload missing 'cols'", 400)
            cols = z["cols"]
            bits = {k.split("/", 1)[1]: z[k] for k in z.files
                    if k.startswith("bits/")}
            values = {k.split("/", 1)[1]: z[k] for k in z.files
                      if k.startswith("values/")}
        n = self.api.import_columns(req.vars["index"], cols,
                                    bits=bits, values=values)
        return {"imported": n}

    def _post_ingest(self, req):
        """Batched streaming ingest (the write-side analog of the
        serving read batcher): every write in the body is admitted to
        the coalescing window plane and the request returns only
        after they all DURABLY landed — a 200 is an ack in the
        commit-after-land sense.  Backlog over budget → typed 503
        with Retry-After.  Body::

            {"writes": [
              {"field": f, "rows": [...], "columns": [...]},
              {"field": f, "columns": [...], "values": [...]},
              {"field": f, "rowKeys": [...], "columnKeys": [...]},
            ]}
        """
        if self.stream is None:
            raise ApiError("streaming ingest disabled "
                           "([ingest] stream=false)", 400)
        body = req.json() or {}
        writes = body.get("writes")
        if not isinstance(writes, list) or not writes:
            raise ApiError("body must carry a non-empty 'writes' "
                           "list", 400)
        index = req.vars["index"]
        muts = []
        try:
            for w in writes:
                field = w.get("field")
                if not field:
                    raise ApiError("every write needs a field", 400)
                cols = w.get("columns")
                if w.get("columnKeys") is not None:
                    cols = self.api.translate_keys(
                        index, None, w["columnKeys"], create=True)
                rows = w.get("rows")
                if w.get("rowKeys") is not None:
                    rows = self.api.translate_keys(
                        index, field, w["rowKeys"], create=True)
                try:
                    muts.append(self.stream.submit(
                        index, field, rows=rows, cols=cols,
                        values=w.get("values"),
                        timestamps=w.get("timestamps"),
                        clear=bool(w.get("clear", False)),
                        wait=False))
                except (KeyError, ValueError) as e:
                    raise ApiError(str(e), 400)
            self.stream.wait(muts, timeout=60.0)
        finally:
            # never leave un-awaited mutations: a shed mid-list must
            # still wait out the already-admitted ones (they land
            # regardless; the client retry is idempotent).  ONE
            # shared deadline across the list — a per-mutation 60 s
            # against a stalled plane would pin this worker thread
            # for 60 s x N
            deadline = time.monotonic() + 60.0
            for m in muts:
                m.event.wait(
                    timeout=max(0.0, deadline - time.monotonic()))
        return {"landed": sum(m.n for m in muts),
                "windows": len({m.window_id for m in muts})}

    def _post_import_roaring(self, req):
        """Roaring import (route shape of /import-roaring in
        http_handler.go): {"rows": {rowID: base64-roaring}, "clear"}."""
        body = req.json() or {}
        n = self.api.import_roaring(
            req.vars["index"], req.vars["field"],
            int(req.vars["shard"]), body.get("rows", {}),
            clear=bool(body.get("clear")))
        return {"imported": n}

    def _get_row_roaring(self, req):
        shard = int(req.query.get("shard", ["0"])[0])
        data = self.api.export_roaring(
            req.vars["index"], req.vars["field"], shard,
            int(req.vars["row"]))
        return RawResponse(data, "application/octet-stream")

    def _df(self, req):
        from pilosa_tpu.models.dataframe import DataframeError
        idx = self.api.holder.index(req.vars["index"])
        if idx is None:
            raise ApiError(f"index not found: {req.vars['index']}", 404)
        return idx.dataframe

    def _post_dataframe(self, req):
        """Append rows to the index dataframe (arrow.go ingest;
        http_handler.go:506 route)."""
        body = req.json() or {}
        df = self._df(req)
        try:
            df.add_rows(body.get("rows", []))
        except Exception as e:
            raise ApiError(str(e), 400)
        df.maybe_save()  # amortized; holder.sync flushes the tail
        return {"rows": df.n_rows}

    def _get_dataframe(self, req):
        df = self._df(req)
        return {"schema": df.schema(), "rows": df.n_rows}

    def _post_dataframe_apply(self, req):
        from pilosa_tpu.models.dataframe import DataframeError
        body = req.json() or {}
        df = self._df(req)
        try:
            if "aggregate" in body:
                return {"result": df.aggregate(body["aggregate"],
                                               body["column"])}
            return {"result": df.apply(body.get("expr", ""),
                                       body.get("columns"))}
        except DataframeError as e:
            raise ApiError(str(e), 400)

    def _post_transaction(self, req):
        body = req.json_lenient() or {}
        return self.api.start_transaction(
            id=body.get("id"), exclusive=bool(body.get("exclusive")),
            timeout=body.get("timeout"))

    def _get_backup_file(self, req):
        rel = req.query.get("path", [""])[0]
        return RawResponse(self.api.backup_file(rel),
                           "application/octet-stream")

    def _post_restore_file(self, req):
        rel = req.query.get("path", [""])[0]
        self.api.restore_file(rel, req._raw or b"")
        return {}

    def _get_schema(self, req):
        schema = self.api.schema()
        if self.auth is not None and self.auth[1] is not None:
            groups = req.auth_claims.get("groups", [])
            authz_ = self.auth[1]
            schema = {"indexes": [
                ix for ix in schema.get("indexes", [])
                if authz_.allowed(groups, ix["name"], "read")]}
        return schema

    def _post_schema(self, req):
        body = req.json()
        if body is None:
            raise ApiError("request body required", 400)
        self.api.apply_schema(body)
        return {}

    def _post_index(self, req):
        body = req.json() or {}
        opts = body.get("options", body)
        return self.api.create_index(
            req.vars["index"], keys=bool(opts.get("keys", False)),
            track_existence=bool(opts.get("trackExistence",
                                          opts.get("track_existence", True))))

    def _delete_index(self, req):
        self.api.delete_index(req.vars["index"])
        return {}

    def _post_field(self, req):
        body = req.json() or {}
        return self.api.create_field(
            req.vars["index"], req.vars["field"], body.get("options", body))

    def _delete_field(self, req):
        self.api.delete_field(req.vars["index"], req.vars["field"])
        return {}

    def _post_import(self, req):
        body = req.json() or {}
        kw = dict(index=req.vars["index"], field=req.vars["field"],
                  clear=bool(body.get("clear", False)))
        if "values" in body:
            n = self.api.import_values(
                cols=body.get("columns"), values=body.get("values"),
                col_keys=body.get("columnKeys"), **kw)
        else:
            n = self.api.import_bits(
                rows=body.get("rows"), cols=body.get("columns"),
                row_keys=body.get("rowKeys"),
                col_keys=body.get("columnKeys"),
                timestamps=body.get("timestamps"), **kw)
        return {"imported": n}

    def _post_translate_find(self, req):
        body = req.json() or {}
        return self.api.translate_keys(
            req.vars["index"], req.query.get("field", [None])[0],
            body.get("keys", []), create=False)

    def _post_translate_create(self, req):
        body = req.json() or {}
        return self.api.translate_keys(
            req.vars["index"], req.query.get("field", [None])[0],
            body.get("keys", []), create=True)

    def _post_translate_ids(self, req):
        body = req.json() or {}
        return self.api.translate_ids(
            req.vars["index"], req.query.get("field", [None])[0],
            body.get("ids", []))

    def _get_shards_max(self, req):
        return {"standard": self.api.shard_max()}

    def _get_metrics(self, req):
        from pilosa_tpu.obs import flight
        flight.flush_metrics()  # drain buffered phase samples first
        # exemplars are EXPLICITLY opt-in (?exemplars=1): the classic
        # 0.0.4 text parser fails the whole scrape on a mid-line '#',
        # and advertising OpenMetrics via Accept-header negotiation
        # would be worse — Prometheus sends that header by default and
        # its OpenMetrics parser rejects this exposition (no '# EOF',
        # classic counter naming), failing every stock scrape
        if req.query.get("exemplars", ["0"])[0] in ("1", "true"):
            return RawResponse(
                metrics.registry.render_text(openmetrics=True),
                "text/plain; version=0.0.4")
        return RawResponse(metrics.registry.render_text(),
                           "text/plain; version=0.0.4")

    def _get_metrics_json(self, req):
        from pilosa_tpu.obs import flight
        flight.flush_metrics()  # JSON scrapes see current data too
        return metrics.registry.render_json()


def filter_flight_records(recs: list, route=None, tenant=None,
                          since_ms=None, audited=None) -> list:
    """The /debug/queries filter predicates (route / tenant /
    since_ms / audited) — ONE implementation shared with the
    federated /debug/cluster/queries (cluster/coordinator.py) so the
    merged endpoint applies exactly what the per-node endpoint
    does."""
    if route is not None:
        recs = [r for r in recs if r.get("route") == route]
    if tenant is not None:
        recs = [r for r in recs if r.get("tenant") == tenant]
    if since_ms is not None:
        cut = float(since_ms) / 1e3
        recs = [r for r in recs if r.get("start", 0.0) >= cut]
    if audited is not None:
        want = str(audited).lower() not in ("0", "false", "")
        recs = [r for r in recs if bool(r.get("audited")) == want]
    return recs


def _qos_from_headers(headers):
    """QoS admission intent from the request headers:

        X-Pilosa-Tenant:      fair-queueing tenant (default "default")
        X-Pilosa-Priority:    "point" | "heavy" class override
        X-Pilosa-Deadline-Ms: client's total latency budget

    None when no QoS header is present (the serving layer then applies
    its configured defaults)."""
    tenant = headers.get("X-Pilosa-Tenant")
    priority = headers.get("X-Pilosa-Priority")
    deadline = headers.get("X-Pilosa-Deadline-Ms")
    if tenant is None and priority is None and deadline is None:
        return None
    from pilosa_tpu.executor.sched import QoS
    try:
        dl = float(deadline) if deadline is not None else None
    except ValueError:
        dl = None
    return QoS.make(tenant=tenant, priority=priority, deadline_ms=dl)


class RawResponse:
    def __init__(self, body: str | bytes, content_type: str):
        self.body = body
        self.content_type = content_type


HTTPServer = Server  # alias matching the reference's naming


def _make_handler(server: Server):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        # request helpers -------------------------------------------------
        def _body(self) -> bytes:
            n = int(self.headers.get("Content-Length", 0) or 0)
            return self.rfile.read(n) if n else b""

        def json(self):
            """Parse the body as a JSON object; 400 on malformed JSON
            or a non-object body, None when the body is empty."""
            raw = self._raw or b""
            if not raw:
                return None
            try:
                v = json.loads(raw)
            except json.JSONDecodeError as e:
                raise ApiError(f"malformed JSON body: {e}", 400)
            if not isinstance(v, dict):
                raise ApiError("JSON body must be an object", 400)
            return v

        def json_lenient(self):
            """For endpoints with a raw-text fallback mode (/sql and
            PQL query bodies): parsed JSON dict, or None."""
            try:
                return self.json()
            except ApiError:
                return None

        def text(self) -> str:
            return (self._raw or b"").decode("utf-8", "replace")

        def raw(self) -> bytes:
            return self._raw or b""

        # dispatch --------------------------------------------------------
        def _handle(self, method: str):
            u = urlparse(self.path)
            self.query = parse_qs(u.query)
            # always drain the body: unread bytes on a keep-alive
            # connection would be parsed as the next request line
            self._raw = self._body()
            self.extra_headers = {}  # reset across keep-alive requests
            status, result = server.dispatch(method, u.path, self)
            self._send(status, result)
            metrics.HTTP_REQUESTS.inc(
                method=method, path=u.path.split("/")[1] or "/",
                status=str(status))

        def _send(self, status: int, result):
            if isinstance(result, RawResponse):
                body = (result.body if isinstance(result.body, bytes)
                        else result.body.encode())
                ctype = result.content_type
            else:
                body = json.dumps(result).encode()
                ctype = "application/json"
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in getattr(self, "extra_headers", {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            self._handle("GET")

        def do_POST(self):
            self._handle("POST")

        def do_DELETE(self):
            self._handle("DELETE")

        def log_message(self, fmt, *args):
            server.logger.debug("http: " + fmt, *args)

    return Handler
