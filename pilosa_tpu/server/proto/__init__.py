"""Generated protobuf messages for the gRPC plane.

pilosa_tpu_pb2.py is generated from pilosa_tpu.proto by protoc
(``protoc --python_out=. pilosa_tpu.proto``) and checked in, the way
the reference checks in its generated pb/ code.
"""

from pilosa_tpu.server.proto import pilosa_tpu_pb2 as pb  # noqa: F401
