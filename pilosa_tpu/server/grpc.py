"""gRPC plane — the reference's client-facing RPC surface.

Behavioral parity with server/grpc.go: the ``proto.Pilosa`` service
(QuerySQL/QueryPQL streaming + Unary, Inspect, index CRUD,
server/grpc.go:38 GRPCHandler, :276 QuerySQLUnary, :502 QueryPQL) over
the same wire messages (proto/pilosa.proto).  Service stubs are
hand-written against grpcio's generic handler API because only message
codegen (protoc --python_out) is available; the method table mirrors
the generated one.

Result -> RowResponse mapping follows server/grpc.go ResultToRowser
(:160): Row results stream one row per column id/key, TopN streams
(row, count) pairs, ValCount/GroupCount map to typed columns.
"""

from __future__ import annotations

import datetime as dt
import time
from concurrent import futures
from decimal import Decimal as PyDecimal

import grpc

from pilosa_tpu.api import ApiError
from pilosa_tpu.executor.results import (
    DistinctValues,
    ExtractedTable,
    GroupCount,
    Pair,
    RowResult,
    SortedRow,
    ValCount,
)
from pilosa_tpu.server.proto import pb

_SERVICE = "proto.Pilosa"


from pilosa_tpu.pql import is_write_query as _pql_is_write


# ---------------------------------------------------------------------------
# result -> wire rows
# ---------------------------------------------------------------------------

def _col(value, datatype: str) -> pb.ColumnResponse:
    c = pb.ColumnResponse()
    if datatype == "string":
        c.stringVal = str(value)
    elif datatype == "uint64":
        c.uint64Val = int(value)
    elif datatype == "int64":
        c.int64Val = int(value)
    elif datatype == "bool":
        c.boolVal = bool(value)
    elif datatype == "float64":
        c.float64Val = float(value)
    elif datatype == "timestamp":
        c.timestampVal = value.isoformat() if isinstance(
            value, dt.datetime) else str(value)
    elif datatype == "decimal":
        d = PyDecimal(str(value))
        sign, digits, exp = d.as_tuple()
        unscaled = int("".join(map(str, digits))) * (-1 if sign else 1)
        if exp > 0:
            unscaled *= 10 ** exp
            exp = 0
        c.decimalVal.value = unscaled
        c.decimalVal.scale = -exp
    elif datatype == "[]uint64":
        c.uint64ArrayVal.vals.extend(int(v) for v in value)
    elif datatype == "[]string":
        c.stringArrayVal.vals.extend(str(v) for v in value)
    else:
        c.stringVal = str(value)
    return c


def _headers(pairs) -> list[pb.ColumnInfo]:
    return [pb.ColumnInfo(name=n, datatype=t) for n, t in pairs]


def result_to_rows(result):
    """Yield (headers, row_columns) for one PQL result
    (server/grpc.go ResultToRowser dispatch)."""
    if isinstance(result, RowResult):
        if result.keys is not None:
            hdrs = _headers([("_id", "string")])
            for k in result.keys:
                yield hdrs, [_col(k, "string")]
        else:
            hdrs = _headers([("_id", "uint64")])
            for c in result.columns():
                yield hdrs, [_col(int(c), "uint64")]
    elif isinstance(result, list) and (not result or
                                       isinstance(result[0], Pair)):
        # TopN/TopK pairs (grpc.go pairsToRows)
        if result and result[0].key is not None:
            hdrs = _headers([("_id", "string"), ("count", "uint64")])
            for p in result:
                yield hdrs, [_col(p.key, "string"),
                             _col(p.count, "uint64")]
        else:
            hdrs = _headers([("_id", "uint64"), ("count", "uint64")])
            for p in result:
                yield hdrs, [_col(p.id, "uint64"),
                             _col(p.count, "uint64")]
    elif isinstance(result, ValCount):
        dtype = ("float64" if isinstance(result.value, float) else
                 "timestamp" if isinstance(result.value, dt.datetime) else
                 "int64")
        hdrs = _headers([("value", dtype), ("count", "int64")])
        yield hdrs, [_col(result.value if result.value is not None else 0,
                          dtype), _col(result.count, "int64")]
    elif isinstance(result, list) and result and \
            isinstance(result[0], GroupCount):
        first = result[0]
        names = []
        for g in first.group:
            names.append((g.get("field", "?"),
                          "string" if "key" in g else "uint64"))
        hdrs = _headers(names + [("count", "uint64")] +
                        ([("agg", "int64")] if first.agg is not None else []))
        for gc in result:
            cols = []
            for g in gc.group:
                if "key" in g:
                    cols.append(_col(g["key"], "string"))
                elif "value" in g:
                    cols.append(_col(g["value"], "uint64"))
                else:
                    cols.append(_col(g.get("row_id", 0), "uint64"))
            cols.append(_col(gc.count, "uint64"))
            if gc.agg is not None:
                cols.append(_col(gc.agg, "int64"))
            yield hdrs, cols
    elif isinstance(result, DistinctValues):
        hdrs = _headers([("value", "int64")])
        for v in result.values:
            yield hdrs, [_col(v, "int64")]
    elif isinstance(result, SortedRow):
        hdrs = _headers([("_id", "uint64"), ("value", "int64")])
        for c, v in zip(result.columns, result.values):
            yield hdrs, [_col(c, "uint64"), _col(v, "int64")]
    elif isinstance(result, ExtractedTable):
        hdrs = _headers([("_id", "uint64")] +
                        [(f["name"], "[]uint64") for f in result.fields])
        for col in result.columns:
            cols = [_col(col["column"], "uint64")]
            for rows in col["rows"]:
                if isinstance(rows, (list, tuple)):
                    cols.append(_col(rows, "[]uint64"))
                else:
                    cols.append(_col([] if rows is None else [rows],
                                     "[]uint64"))
            yield hdrs, cols
    elif isinstance(result, bool):
        yield _headers([("result", "bool")]), [_col(result, "bool")]
    elif isinstance(result, int):
        yield _headers([("count", "uint64")]), [_col(result, "uint64")]
    elif result is None:
        return
    else:
        yield _headers([("result", "string")]), [_col(result, "string")]


_SQL_DTYPE = {"int": "int64", "id": "uint64", "string": "string",
              "bool": "bool", "decimal": "decimal",
              "timestamp": "timestamp", "idset": "[]uint64",
              "stringset": "[]string"}


def sql_to_rows(res):
    hdrs = _headers([(n, _SQL_DTYPE.get(t, "string"))
                     for n, t in res.schema])
    for row in res.rows:
        cols = []
        for (n, t), v in zip(res.schema, row):
            if v is None:
                cols.append(_col("", "string"))
            else:
                cols.append(_col(v, _SQL_DTYPE.get(t, "string")))
        yield hdrs, cols


# ---------------------------------------------------------------------------
# service
# ---------------------------------------------------------------------------

class GRPCHandler:
    """Method implementations (server/grpc.go:38)."""

    def __init__(self, api, sql_engine=None, auth=None):
        self.api = api
        if sql_engine is None:
            # share the API's engine (and with it the serving-enabled
            # executor + its caches): gRPC SQL must not client the
            # HBM ledger a second time (ISSUE 13 satellite)
            sql_engine = getattr(api, "sql_engine", None)
        if sql_engine is None:
            from pilosa_tpu.sql.engine import SQLEngine
            sql_engine = SQLEngine(api.holder)
        self.sql = sql_engine
        self.auth = auth  # (authenticator, authorizer) or None

    # -- helpers -------------------------------------------------------

    def _check(self, ctx, index: str | None, write: bool) -> dict:
        """authn + authz gate (http_handler chkAuthZ analog); returns
        the validated claims ({} when auth is disabled)."""
        if self.auth is None:
            return {}
        from pilosa_tpu.server.authn import AuthError
        authn, authz = self.auth
        md = dict(ctx.invocation_metadata() or ())
        token = md.get("authorization", "")
        try:
            claims = authn.authenticate(token)
        except AuthError as e:
            ctx.abort(grpc.StatusCode.UNAUTHENTICATED, str(e))
        if authz is None or index is None:
            return claims
        need = "write" if write else "read"
        if not authz.allowed(claims.get("groups", []), index, need):
            ctx.abort(grpc.StatusCode.PERMISSION_DENIED,
                      f"not authorized for {need} on {index}")
        return claims

    def _pql_results(self, request, ctx):
        """Raw executor results (api.query would JSON-serialize them;
        the wire mapping here needs the typed result objects).  Routed
        through the serving layer: concurrent RPC handler threads
        coalesce into shared device dispatches when it is enabled.

        Profile=true rides the invocation metadata (the wire message
        predates profiling): ``("profile", "true")`` returns the
        device-phase span tree — the same shape as the HTTP
        ``?profile=true`` response — as the ``profile-json`` trailing
        metadata entry."""
        self._check(ctx, request.index, write=_pql_is_write(request.pql))
        md = dict(ctx.invocation_metadata() or ())
        profile = md.get("profile", "").lower() == "true"
        # QoS admission intent rides the metadata like profile does
        # (("tenant", ...), ("priority", ...), ("deadline-ms", ...)) —
        # the gRPC twin of the X-Pilosa-* HTTP headers
        qos = None
        if any(k in md for k in ("tenant", "priority", "deadline-ms")):
            from pilosa_tpu.executor.sched import QoS
            try:
                dl = (float(md["deadline-ms"])
                      if "deadline-ms" in md else None)
            except ValueError:
                dl = None
            qos = QoS.make(tenant=md.get("tenant"),
                           priority=md.get("priority"),
                           deadline_ms=dl)
        # cross-node trace propagation (ISSUE 10): ("trace-id", ...)
        # metadata is the gRPC twin of the X-Pilosa-Trace-Id header —
        # the query's flight record inherits the caller's id and the
        # serialized span tree returns as "trace-json" trailing
        # metadata (the response-trailer form HTTP carries in-body).
        # Inlined rather than flight.remote_leg (the canonical
        # scaffold the HTTP leg uses) because ONE tracer here serves
        # both the profile-json and trace-json trailers and trailer
        # assembly must happen inside the abort-safe finally.
        trace_id = md.get("trace-id")
        tracer = prev = prev_inh = None
        if profile or trace_id is not None:
            import json as _json

            from pilosa_tpu.obs import tracing as _tr
            tracer = _tr.RecordingTracer()
            prev = _tr.push_thread_tracer(tracer)
        if trace_id is not None:
            from pilosa_tpu.obs import flight as _fl
            prev_inh = _fl.inherit_trace(trace_id)
        try:
            return self.api.executor.execute_serving(
                request.index, request.pql, qos=qos)
        except Exception as e:
            # typed QoS outcomes keep their wire semantics: a shed is
            # RESOURCE_EXHAUSTED (retryable), an expired deadline is
            # DEADLINE_EXCEEDED — not a client argument error
            status = getattr(e, "status", None)
            if status == 503:
                ctx.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
            if status == 504:
                ctx.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(e))
            ctx.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        finally:
            if trace_id is not None:
                _fl.pop_inherit(prev_inh)
            if tracer is not None:
                _tr.pop_thread_tracer(prev)
                trailers = []
                if profile:
                    trailers.append(("profile-json", _json.dumps(
                        [s.to_dict() for s in tracer.roots])))
                if trace_id is not None:
                    node = getattr(self.api, "name", "") or "local"
                    trailers.append(("trace-json", _json.dumps(
                        {"node": node,
                         "spans": [_tr.span_to_wire(s)
                                   for s in tracer.roots]})))
                try:
                    ctx.set_trailing_metadata(tuple(trailers))
                except Exception:
                    pass  # aborted context: never mask the status

    # -- PQL -----------------------------------------------------------

    def QueryPQL(self, request, ctx):
        t0 = time.perf_counter()
        for result in self._pql_results(request, ctx):
            for hdrs, cols in result_to_rows(result):
                yield pb.RowResponse(
                    headers=hdrs, columns=cols,
                    duration=int((time.perf_counter() - t0) * 1e9))
                t0 = time.perf_counter()

    def QueryPQLUnary(self, request, ctx):
        t0 = time.perf_counter()
        table = pb.TableResponse()
        for result in self._pql_results(request, ctx):
            for hdrs, cols in result_to_rows(result):
                if not table.headers:
                    table.headers.extend(hdrs)
                table.rows.append(pb.Row(columns=cols))
        table.duration = int((time.perf_counter() - t0) * 1e9)
        return table

    # -- SQL -----------------------------------------------------------

    def _sql_results(self, request, ctx):
        claims = self._check(ctx, None, write=False)
        auth_check = None
        if self.auth is not None and self.auth[1] is not None:
            # per-statement table authz (the reference checks each
            # resolved table during SQL planning)
            auth_check = self.auth[1].sql_check(claims.get("groups", []))
        try:
            return self.sql.query(request.sql, auth_check=auth_check,
                                  write_guard=self.api._check_writable)
        except PermissionError as e:
            ctx.abort(grpc.StatusCode.PERMISSION_DENIED, str(e))
        except Exception as e:
            ctx.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))

    def QuerySQL(self, request, ctx):
        t0 = time.perf_counter()
        for res in self._sql_results(request, ctx):
            for hdrs, cols in sql_to_rows(res):
                yield pb.RowResponse(
                    headers=hdrs, columns=cols,
                    duration=int((time.perf_counter() - t0) * 1e9))
                t0 = time.perf_counter()

    def QuerySQLUnary(self, request, ctx):
        t0 = time.perf_counter()
        table = pb.TableResponse()
        for res in self._sql_results(request, ctx):
            for hdrs, cols in sql_to_rows(res):
                if not table.headers:
                    table.headers.extend(hdrs)
                table.rows.append(pb.Row(columns=cols))
        table.duration = int((time.perf_counter() - t0) * 1e9)
        return table

    # -- Inspect (server/grpc.go Inspect) ------------------------------

    def Inspect(self, request, ctx):
        self._check(ctx, request.index, write=False)
        idx = self.api.holder.index(request.index)
        if idx is None:
            ctx.abort(grpc.StatusCode.NOT_FOUND,
                      f"index not found: {request.index}")
        which = request.columns.WhichOneof("type")
        if which == "ids":
            cols = list(request.columns.ids.vals)
        elif which == "keys":
            tr = idx.column_translator
            found = tr.find_keys(*request.columns.keys.vals) if tr else {}
            cols = [found[k] for k in request.columns.keys.vals
                    if k in found]
        else:
            cols = []
        limit = request.limit or len(cols)
        cols = cols[request.offset:request.offset + limit]
        fields = [f for f in idx.fields.values()
                  if not request.filterFields
                  or f.name in request.filterFields]
        hdrs = _headers([("_id", "uint64")] +
                        [(f.name, "string") for f in fields])
        for c in cols:
            out = [_col(int(c), "uint64")]
            for f in fields:
                vals = self._field_values(f, int(c))
                out.append(_col(vals, "string"))
            yield pb.RowResponse(headers=hdrs, columns=out)

    def _field_values(self, f, col: int) -> str:
        from pilosa_tpu.models.schema import FieldType
        shard, scol = divmod(col, f.width)
        if f.options.type.is_bsi:
            v = f.views.get(f.bsi_view)
            frag = v.fragment(shard) if v else None
            if frag is None or not frag.contains(0, scol):  # exists bit
                return ""
            mag = sum(1 << i for i in range(f.bit_depth)
                      if frag.contains(2 + i, scol))
            val = -mag if frag.contains(1, scol) else mag  # sign bit
            return str(f.int_to_value(val))
        from pilosa_tpu.models.view import VIEW_STANDARD
        view = f.views.get(VIEW_STANDARD)
        frag = view.fragment(shard) if view else None
        if frag is None:
            return ""
        rows = [r for r in frag.row_ids if frag.contains(r, scol)]
        if f.options.type == FieldType.BOOL:
            return str(bool(rows and rows[-1] == 1)).lower() if rows else ""
        if f.options.keys:
            return ",".join(f.row_translator.translate_ids(rows))
        return ",".join(str(r) for r in rows)

    # -- index CRUD ----------------------------------------------------

    def CreateIndex(self, request, ctx):
        self._check(ctx, request.name, write=True)
        try:
            self.api.create_index(request.name, keys=request.keys)
        except ApiError as e:
            ctx.abort(grpc.StatusCode.ALREADY_EXISTS
                      if e.status == 409 else grpc.StatusCode.INVALID_ARGUMENT,
                      str(e))
        return pb.CreateIndexResponse()

    def GetIndex(self, request, ctx):
        self._check(ctx, request.name, write=False)
        if self.api.holder.index(request.name) is None:
            ctx.abort(grpc.StatusCode.NOT_FOUND,
                      f"index not found: {request.name}")
        return pb.GetIndexResponse(index=pb.Index(name=request.name))

    def GetIndexes(self, request, ctx):
        claims = self._check(ctx, None, write=False)
        names = sorted(self.api.holder.indexes)
        if self.auth is not None and self.auth[1] is not None:
            # filter to readable indexes (grpc.go GetAuthorizedIndexList)
            authz = self.auth[1]
            groups = claims.get("groups", [])
            names = [n for n in names if authz.allowed(groups, n, "read")]
        return pb.GetIndexesResponse(indexes=[
            pb.Index(name=n) for n in names])

    def DeleteIndex(self, request, ctx):
        self._check(ctx, request.name, write=True)
        try:
            self.api.delete_index(request.name)
        except ApiError as e:
            ctx.abort(grpc.StatusCode.NOT_FOUND, str(e))
        return pb.DeleteIndexResponse()


def _method_table(handler: GRPCHandler) -> dict:
    u, s = grpc.unary_unary_rpc_method_handler, \
        grpc.unary_stream_rpc_method_handler

    def mh(kind, fn, req, resp):
        return kind(fn, request_deserializer=req.FromString,
                    response_serializer=resp.SerializeToString)

    return {
        "CreateIndex": mh(u, handler.CreateIndex,
                          pb.CreateIndexRequest, pb.CreateIndexResponse),
        "GetIndexes": mh(u, handler.GetIndexes,
                         pb.GetIndexesRequest, pb.GetIndexesResponse),
        "GetIndex": mh(u, handler.GetIndex,
                       pb.GetIndexRequest, pb.GetIndexResponse),
        "DeleteIndex": mh(u, handler.DeleteIndex,
                          pb.DeleteIndexRequest, pb.DeleteIndexResponse),
        "QuerySQL": mh(s, handler.QuerySQL,
                       pb.QuerySQLRequest, pb.RowResponse),
        "QuerySQLUnary": mh(u, handler.QuerySQLUnary,
                            pb.QuerySQLRequest, pb.TableResponse),
        "QueryPQL": mh(s, handler.QueryPQL,
                       pb.QueryPQLRequest, pb.RowResponse),
        "QueryPQLUnary": mh(u, handler.QueryPQLUnary,
                            pb.QueryPQLRequest, pb.TableResponse),
        "Inspect": mh(s, handler.Inspect,
                      pb.InspectRequest, pb.RowResponse),
    }


class GRPCServer:
    """grpcServer (server/grpc.go:618 Serve wiring)."""

    def __init__(self, api, bind: str = "127.0.0.1:0", auth=None,
                 max_workers: int = 8):
        self.handler = GRPCHandler(api, auth=auth)
        self.server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers))
        self.server.add_generic_rpc_handlers((
            grpc.method_handlers_generic_handler(
                _SERVICE, _method_table(self.handler)),))
        self.port = self.server.add_insecure_port(bind)

    @property
    def uri(self) -> str:
        return f"127.0.0.1:{self.port}"

    def start(self):
        self.server.start()
        return self

    def stop(self, grace: float = 0.5):
        self.server.stop(grace)
