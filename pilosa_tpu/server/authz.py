"""Authorization — group -> permission per index (authz/).

Parity with authz/authorization.go: a YAML policy maps IdP group ids
to a permission level per index ("read" < "write" < "admin",
authorization.go:15 Permission ordering); admin grants everything
(:44 IsAdmin), and an index-specific grant is required otherwise
(:59 GetPermissions).

Policy file shape (authorization.go's test fixtures):

    user-groups:
      "group-id-1":
        "indexname": "read"
        "other": "write"
      "group-id-2":
        "indexname": "admin"
    admin: "admin-group-id"
"""

from __future__ import annotations

_LEVELS = {"": 0, "read": 1, "write": 2, "admin": 3}


class Authorizer:
    def __init__(self, user_groups: dict | None = None,
                 admin_group: str = ""):
        self.user_groups = user_groups or {}
        self.admin_group = admin_group

    @classmethod
    def from_yaml(cls, path: str) -> "Authorizer":
        import yaml
        with open(path) as fh:
            doc = yaml.safe_load(fh) or {}
        return cls(user_groups=doc.get("user-groups", {}),
                   admin_group=doc.get("admin", ""))

    def is_admin(self, groups) -> bool:
        return bool(self.admin_group) and self.admin_group in groups

    def permission(self, groups, index: str) -> str:
        """Best permission any of the user's groups grants on index."""
        if self.is_admin(groups):
            return "admin"
        best = ""
        for g in groups:
            p = self.user_groups.get(g, {}).get(index, "")
            if _LEVELS.get(p, 0) > _LEVELS[best]:
                best = p
        return best

    def allowed(self, groups, index: str, need: str) -> bool:
        return _LEVELS[self.permission(groups, index)] >= \
            _LEVELS.get(need, 99)

    def sql_check(self, groups):
        """Per-statement (table, need) hook for SQLEngine.auth_check:
        raises PermissionError on denial.  Untargeted writes require
        admin; untargeted reads (SHOW TABLES) pass — the engine
        filters their rows via the same hook."""
        def check(table, need):
            if table is None:
                if need == "write" and not self.is_admin(groups):
                    raise PermissionError("admin required")
                return
            if not self.allowed(groups, table, need):
                raise PermissionError(
                    f"not authorized for {need} on {table}")
        return check

    def allowed_indexes(self, groups, need: str = "read") -> list[str]:
        """Indexes the user can access at `need` level (query
        filtering, authorization.go GetAuthorizedIndexList)."""
        if self.is_admin(groups):
            return ["*"]
        out = set()
        for g in groups:
            for idx, p in self.user_groups.get(g, {}).items():
                if _LEVELS.get(p, 0) >= _LEVELS.get(need, 99):
                    out.add(idx)
        return sorted(out)
