"""Authentication — JWT validation + group cache (authn/).

Behavioral parity with authn/authenticate.go: the server validates a
bearer JWT on every request (authenticate.go:93 Authenticate), reads
the user's security groups from the token claims, and caches
group lookups; the OAuth2/OIDC login dance (authenticate.go:77 Login)
is represented by the redirect-URL builder, since this build has no
egress to an IdP.

Tokens are HMAC-SHA256 (HS256) JWTs — signed with the cluster's
shared secret (the reference additionally supports RS256 via IdP
JWKS; the claim set and validation rules here are the same:
exp/nbf checks, required groups claim for authz).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
import urllib.parse


class AuthError(Exception):
    pass


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64url(s: str) -> bytes:
    pad = "=" * (-len(s) % 4)
    return base64.urlsafe_b64decode(s + pad)


def encode_jwt(claims: dict, secret: bytes) -> str:
    """Mint an HS256 JWT (test/ops tooling; fake-IdP analog of
    qa/fakeidp)."""
    header = {"alg": "HS256", "typ": "JWT"}
    h = _b64url(json.dumps(header, separators=(",", ":")).encode())
    c = _b64url(json.dumps(claims, separators=(",", ":")).encode())
    sig = hmac.new(secret, f"{h}.{c}".encode(), hashlib.sha256).digest()
    return f"{h}.{c}.{_b64url(sig)}"


def decode_jwt(token: str, secret: bytes) -> dict:
    """Validate signature + time claims; returns the claim dict."""
    try:
        h, c, s = token.split(".")
    except ValueError:
        raise AuthError("malformed token")
    try:
        header = json.loads(_unb64url(h))
    except Exception:
        raise AuthError("malformed token header")
    if header.get("alg") != "HS256":
        raise AuthError(f"unsupported alg {header.get('alg')!r}")
    want = hmac.new(secret, f"{h}.{c}".encode(), hashlib.sha256).digest()
    try:
        got_sig = _unb64url(s)
    except Exception:
        raise AuthError("malformed token signature")
    if not hmac.compare_digest(want, got_sig):
        raise AuthError("bad signature")
    try:
        claims = json.loads(_unb64url(c))
    except Exception:
        raise AuthError("malformed token claims")
    if not isinstance(claims, dict):
        raise AuthError("malformed token claims")
    _check_time_claims(claims)
    return claims


def _claim_num(claims: dict, name: str) -> float | None:
    v = claims.get(name)
    if v is None:
        return None
    try:
        return float(v)
    except (TypeError, ValueError):
        raise AuthError(f"malformed {name} claim")


def _check_time_claims(claims: dict) -> None:
    now = time.time()
    exp = _claim_num(claims, "exp")
    if exp is not None and now >= exp:
        raise AuthError("token expired")
    nbf = _claim_num(claims, "nbf")
    if nbf is not None and now < nbf:
        raise AuthError("token not yet valid")


class Authenticator:
    """authn.Auth (authenticate.go:44): validates bearer tokens and
    caches the per-token group set with a TTL (the reference's
    group-membership cache, authenticate.go:174)."""

    def __init__(self, secret: bytes, cache_ttl: float = 60.0,
                 client_id: str = "", authorize_url: str = "",
                 scopes: tuple = ("openid", "groups")):
        if isinstance(secret, str):
            secret = secret.encode()
        self.secret = secret
        self.cache_ttl = cache_ttl
        self.client_id = client_id
        self.authorize_url = authorize_url
        self.scopes = scopes
        self._cache: dict[str, tuple[float, dict]] = {}

    def authenticate(self, auth_header: str) -> dict:
        """Validate 'Bearer <jwt>' (or a bare token) -> claims."""
        if not auth_header:
            raise AuthError("missing authorization")
        token = auth_header
        if token.lower().startswith("bearer "):
            token = token[7:].strip()
        hit = self._cache.get(token)
        now = time.time()
        if hit and now - hit[0] < self.cache_ttl:
            claims = hit[1]
            _check_time_claims(claims)
            return claims
        claims = decode_jwt(token, self.secret)
        self._cache[token] = (now, claims)
        if len(self._cache) > 10000:  # bound the cache
            cutoff = now - self.cache_ttl
            self._cache = {t: v for t, v in self._cache.items()
                           if v[0] >= cutoff}
        return claims

    def login_url(self, state: str = "") -> str:
        """The OAuth2 authorize redirect the /login handler issues
        (authenticate.go:77)."""
        q = urllib.parse.urlencode({
            "response_type": "code",
            "client_id": self.client_id,
            "scope": " ".join(self.scopes),
            "state": state,
        })
        return f"{self.authorize_url}?{q}"
