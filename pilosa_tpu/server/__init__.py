"""Server — HTTP transport over the API facade (SURVEY §2.6)."""

from pilosa_tpu.server.http import HTTPServer, Server

__all__ = ["HTTPServer", "Server"]
