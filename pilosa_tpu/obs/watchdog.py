"""Stall watchdogs — progress-stamped deadlines on long-running loops.

Every long-running loop in the engine (serving batch leader, ingest
window drain, rebalance controller, maintenance ticker, cluster
heartbeat) registers a :class:`LoopWatch` and *stamps* it as it makes
progress, naming the phase it is entering.  A background monitor
thread scans the registry: an ARMED watch whose last stamp is older
than its deadline is a wedged loop — the monitor counts it
(``pilosa_watchdog_stalls_total{loop}``), grabs the stuck thread's
live stack via ``sys._current_frames``, and raises an incident
(``obs/incidents.py`` trigger ``watchdog-stall``) naming the loop and
the stuck phase.

The hot-path contract is the stamp: four attribute writes and one
``time.monotonic()`` call, no lock, no allocation — measured well
under the 8 µs budget check.sh's incident smoke gates (the same
budget class as the flight recorder's disabled path).  ``idle()``
disarms the watch while the loop is legitimately parked waiting for
work, so an empty queue never reads as a stall.

One stall fires ONCE per episode: the monitor remembers the stamp it
reported against and stays quiet until the loop stamps again (a new
episode).  Incident-side rate limiting bounds bundle volume on top.
"""

from __future__ import annotations

import os
import sys
import threading
import time

# PILOSA_TPU_WATCHDOG=0 kills the plane before config loads (same
# contract as PILOSA_TPU_FLIGHT); [watchdog] config knobs override
_enabled = os.environ.get("PILOSA_TPU_WATCHDOG", "1") != "0"
_interval_s = 1.0
_default_deadline_s = 10.0

_lock = threading.Lock()
_watches: dict[str, "LoopWatch"] = {}
_monitor: threading.Thread | None = None
_monitor_wake = threading.Event()


class LoopWatch:
    """One loop's progress stamp.

    Two usage models:

    - **single-owner loops** (ingest drain, rebalance controller,
      ticker, heartbeat): ``stamp(phase)`` / ``idle()`` — mutated
      only by the owning thread, read by the monitor.  Plain
      attributes, writes GIL-atomic; the monitor reads ``armed``
      BEFORE ``t`` (the reverse of stamp's write order, which sets
      ``t`` before ``armed``) so a stamp landing mid-snapshot can
      never pair a fresh ``armed`` with a stale ``t`` — the false
      "stalled the instant it woke up" race.
    - **overlapping dispatchers** (the serving batch leader: under
      load a full batch dispatches while another is still in
      flight): ``begin(phase)`` → token → ``end(token)``.  Tokens
      track EVERY in-flight dispatch, and staleness is judged
      against the OLDEST one — a healthy leader finishing cannot
      disarm or re-stamp away a wedged sibling.  The token lock is
      per-begin/end (per *batch*, not per query), far under the
      stamp budget's traffic.
    """

    __slots__ = ("name", "deadline_s", "phase", "t", "armed",
                 "thread_id", "stalls", "_reported_t",
                 "_tokens", "_tok_lock")

    def __init__(self, name: str, deadline_s: float):
        self.name = name
        self.deadline_s = float(deadline_s)
        self.phase = ""
        self.t = time.monotonic()
        self.armed = False
        self.thread_id = 0
        self.stalls = 0
        self._reported_t = -1.0
        self._tokens: dict[tuple, None] = {}
        self._tok_lock = threading.Lock()

    def stamp(self, phase: str) -> None:
        """Progress mark: the loop is alive and entering ``phase``.
        HOT PATH — keep to attribute writes + one monotonic read.
        ``armed`` is written LAST (see class docstring)."""
        self.phase = phase
        self.thread_id = threading.get_ident()
        self.t = time.monotonic()
        self.armed = True

    def idle(self) -> None:
        """The loop is parked waiting for work — not a stall."""
        self.armed = False

    def begin(self, phase: str) -> tuple:
        """Arm one IN-FLIGHT dispatch (overlapping-dispatcher model);
        pair with :meth:`end`.  Returns the token."""
        tok = (time.monotonic(), phase, threading.get_ident())
        with self._tok_lock:
            self._tokens[tok] = None
        return tok

    def end(self, tok: tuple) -> None:
        with self._tok_lock:
            self._tokens.pop(tok, None)

    def _oldest(self) -> tuple | None:
        """(t, phase, thread_id) of the oldest in-flight token."""
        with self._tok_lock:
            if not self._tokens:
                return None
            return min(self._tokens)

    def _observe(self) -> tuple | None:
        """Monitor-side snapshot: ``(t, phase, thread_id)`` of the
        staleness-relevant mark, or None when disarmed.  Token model
        wins when tokens are in flight; else the stamp model (armed
        read FIRST — see class docstring)."""
        oldest = self._oldest()
        if oldest is not None:
            return oldest
        if not self.armed:
            return None
        return (self.t, self.phase, self.thread_id)

    def to_dict(self) -> dict:
        obs = self._observe()
        now = time.monotonic()
        age = now - (obs[0] if obs is not None else self.t)
        return {"loop": self.name,
                "phase": obs[1] if obs is not None else self.phase,
                "armed": obs is not None,
                "deadline_s": self.deadline_s,
                "age_s": round(age, 3),
                "stalled": bool(obs is not None
                                and age > self.deadline_s),
                "stalls": self.stalls}


def register(name: str, deadline_s: float | None = None) -> LoopWatch:
    """Register (or fetch) the watch for a named loop.  Idempotent by
    name: servers are rebuilt freely in-process and the loop identity
    is the name, so re-registration returns the live watch (updating
    its deadline when one is given)."""
    with _lock:
        w = _watches.get(name)
        if w is None:
            w = _watches[name] = LoopWatch(
                name, deadline_s if deadline_s is not None
                else _default_deadline_s)
        elif deadline_s is not None:
            w.deadline_s = float(deadline_s)
    _ensure_monitor()
    return w


def deregister(name: str) -> None:
    with _lock:
        _watches.pop(name, None)


def watches() -> list[dict]:
    """Registry state (the /debug/incidents ``watchdog`` payload)."""
    with _lock:
        ws = list(_watches.values())
    return [w.to_dict() for w in sorted(ws, key=lambda w: w.name)]


def configure(enabled: bool | None = None,
              interval_s: float | None = None,
              deadline_s: float | None = None) -> None:
    """Apply the [watchdog] config knobs.  ``enabled=None`` leaves
    the PILOSA_TPU_WATCHDOG env kill-switch in charge (same contract
    as roofline/stats)."""
    global _enabled, _interval_s, _default_deadline_s
    if enabled is not None:
        _enabled = bool(enabled)
    if interval_s is not None and interval_s > 0:
        _interval_s = float(interval_s)
        _monitor_wake.set()  # re-pace the monitor promptly
    if deadline_s is not None and deadline_s > 0:
        _default_deadline_s = float(deadline_s)
    if _enabled:
        _ensure_monitor()


def enabled() -> bool:
    return _enabled


def _thread_stack(thread_id: int) -> str:
    """The live stack of one thread (best effort — it may have exited
    between the overdue check and this read)."""
    frame = sys._current_frames().get(thread_id)
    if frame is None:
        return ""
    from pilosa_tpu.obs.incidents import format_stack
    return format_stack(frame)


def scan(now: float | None = None) -> list[dict]:
    """One monitor pass over the registry; returns the stalls
    detected THIS pass (tests drive this directly for determinism —
    the background thread just calls it on a timer)."""
    if now is None:
        now = time.monotonic()
    with _lock:
        ws = list(_watches.values())
    fired = []
    for w in ws:
        obs = w._observe()
        if obs is None:
            continue
        t, phase, thread_id = obs
        if now - t <= w.deadline_s:
            continue
        if w._reported_t == t:
            continue  # this episode already reported; wait for progress
        w._reported_t = t
        w.stalls += 1
        from pilosa_tpu.obs import metrics
        metrics.WATCHDOG_STALLS.inc(loop=w.name)
        stall = {"loop": w.name, "phase": phase,
                 "overdue_s": round(now - t, 3),
                 "deadline_s": w.deadline_s,
                 "thread_id": thread_id,
                 "stack": _thread_stack(thread_id)}
        fired.append(stall)
        try:
            from pilosa_tpu.obs import incidents
            incidents.report(
                "watchdog-stall", detail=f"{w.name}:{w.phase}",
                context=stall)
        except Exception:
            pass  # the watchdog must never take the monitor down
    return fired


def _ensure_monitor() -> None:
    global _monitor
    if not _enabled or (_monitor is not None and _monitor.is_alive()):
        return
    with _lock:
        if _monitor is not None and _monitor.is_alive():
            return
        _monitor = threading.Thread(target=_monitor_loop,
                                    name="pilosa-watchdog",
                                    daemon=True)
        _monitor.start()


def _monitor_loop() -> None:
    while True:
        _monitor_wake.wait(_interval_s)
        _monitor_wake.clear()
        if _enabled:
            try:
                scan()
            except Exception:
                pass
