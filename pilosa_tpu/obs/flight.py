"""Query flight recorder — always-on per-query phase attribution.

The serving hot path (PRs 2-3) made a query's execution opaque from
the outside: it may be fused into a leader's multi-program, served
from the versioned result cache, satisfied by a patched device stack,
or trigger a jit recompile — and ``/metrics`` aggregates can't say
which happened to WHICH query.  This module keeps a bounded ring of
per-query *flight records* (trace id, route, phase durations, cache
outcomes, batch occupancy, bytes moved) cheap enough to leave on in
production, feeding:

- ``/debug/queries``  — recent records as JSON (server/http.py)
- ``/debug/trace``    — the same records exported as Chrome
  ``trace_event`` JSON, loadable in Perfetto / chrome://tracing
- ``pilosa_query_phase_seconds`` histograms with exemplar trace ids
  (obs/metrics.py)

Attribution flows through thread-local :class:`Acc` accumulators: the
serving layer pushes one per query, the deep layers (TileStackCache,
the stacked dispatch) call :func:`note_phase`/:func:`note_stack`,
which no-op in a few ns when no accumulator is active.  Work a batch
LEADER performs for a follower is accumulated into a per-request Acc
on the leader's thread and merged into the follower's record when its
event fires (executor/serving.py) — the same cross-thread shape as
``obs.tracing.TraceContext``.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager

# phases the leader stamps per fused request; also the BENCH JSON
# breakdown axes (compile/upload/execute/wait)
PHASES = ("plan_build", "compile", "execute", "demux", "cache_lookup",
          "batch", "wait", "stack_hit", "stack_patch", "stack_rebuild",
          "stack_wait")

_tls = threading.local()


class Acc:
    """Per-query phase accumulator (seconds + stack-cache outcomes).
    Plain mutable object — only ever touched by one thread at a time
    (the owning request thread, or the leader while it serves the
    request)."""

    __slots__ = ("phases", "stack", "bytes_moved", "keys", "attempts",
                 "t0", "node_spans", "ops", "pages")

    # per-record stack-key cap: a pathological query touching hundreds
    # of stacks must not bloat the ring
    _MAX_KEYS = 32
    # per-record attempt cap (cluster fan-out: one entry per per-node
    # RPC attempt incl. hedges — a 100-node fan-out must not bloat
    # the ring either)
    _MAX_ATTEMPTS = 32
    # per-record cap on per-node span-tree payloads (cluster trace
    # propagation, ISSUE 10): legs past the cap keep their timings in
    # `attempts` but drop the span detail
    _MAX_NODE_SPANS = 16

    def __init__(self):
        self.phases: dict[str, float] = {}
        self.stack: dict[str, int] = {}
        self.bytes_moved = 0
        # (key fingerprint, outcome) per NON-HIT stack access — the
        # prefetcher's prediction signal (memory/policy.py): keys
        # that keep rebuilding are keys worth warming
        self.keys: list[tuple[str, str]] = []
        # per-node RPC attempt timings from the cluster fan-out
        # (node, ms, outcome, start-offset ms) incl. hedge attempts —
        # what makes hedge delays debuggable at /debug/queries, and
        # what renders hedges as parallel spans in /debug/trace
        self.attempts: list[tuple[str, float, str, float]] = []
        # this record's perf_counter origin: attempt/node-span offsets
        # are relative to it so /debug/trace can lay legs out in time
        self.t0 = time.perf_counter()
        # per-node serialized span trees returned in RPC trailers
        # (obs.tracing.span_to_wire): [{"node", "anchor_off_us",
        # "spans"}] — the coordinator's one-timeline-with-node-lanes
        # Perfetto view
        self.node_spans: list[dict] = []
        # op-family roofline shares: op -> [bytes touched, execute s]
        # (obs/roofline.py note() feeds this per device dispatch)
        self.ops: dict[str, list] = {}
        # page-encoding mix of the stack operands this query touched
        # (encoding -> page count; memory/encode.py container kinds) —
        # how a record shows which arm served it, packed or dense
        self.pages: dict[str, int] = {}

    def add_pages(self, mix: dict):
        for k, v in mix.items():
            self.pages[k] = self.pages.get(k, 0) + int(v)

    def add_phase(self, name: str, dt: float):
        self.phases[name] = self.phases.get(name, 0.0) + dt

    def add_stack(self, outcome: str, nbytes: int, dt: float,
                  key_fp: str | None = None):
        self.stack[outcome] = self.stack.get(outcome, 0) + 1
        self.bytes_moved += int(nbytes)
        self.add_phase("stack_" + outcome, dt)
        if key_fp is not None and len(self.keys) < self._MAX_KEYS:
            self.keys.append((key_fp, outcome))

    def add_attempt(self, node: str, dt: float, outcome: str):
        if len(self.attempts) < self._MAX_ATTEMPTS:
            off = max(time.perf_counter() - self.t0 - dt, 0.0)
            self.attempts.append((node, round(dt * 1e3, 3), outcome,
                                  round(off * 1e3, 3)))

    def add_node_spans(self, node: str, spans: list,
                       anchor_perf: float):
        if spans and len(self.node_spans) < self._MAX_NODE_SPANS:
            self.node_spans.append({
                "node": node,
                "anchor_off_us": max(
                    int((anchor_perf - self.t0) * 1e6), 0),
                "spans": spans,
            })

    def add_op(self, op: str, nbytes: int, dt: float):
        st = self.ops.get(op)
        if st is None:
            st = self.ops[op] = [0, 0.0]
        st[0] += int(nbytes)
        st[1] += dt

    def merge(self, other: "Acc"):
        for k, v in other.phases.items():
            self.phases[k] = self.phases.get(k, 0.0) + v
        for k, v in other.stack.items():
            self.stack[k] = self.stack.get(k, 0) + v
        self.bytes_moved += other.bytes_moved
        room = self._MAX_KEYS - len(self.keys)
        if room > 0 and other.keys:
            self.keys.extend(other.keys[:room])
        room = self._MAX_ATTEMPTS - len(self.attempts)
        if room > 0 and other.attempts:
            self.attempts.extend(other.attempts[:room])
        room = self._MAX_NODE_SPANS - len(self.node_spans)
        if room > 0 and other.node_spans:
            self.node_spans.extend(other.node_spans[:room])
        for op, (b, s) in other.ops.items():
            st = self.ops.get(op)
            if st is None:
                self.ops[op] = [b, s]
            else:
                st[0] += b
                st[1] += s
        self.add_pages(other.pages)


def push_acc(acc: Acc):
    """Install `acc` as this thread's active accumulator; returns the
    previous one to restore via pop_acc."""
    prev = getattr(_tls, "acc", None)
    _tls.acc = acc
    return prev


def pop_acc(prev):
    _tls.acc = prev


def active_acc() -> Acc | None:
    return getattr(_tls, "acc", None)


def note_phase(name: str, dt: float):
    acc = getattr(_tls, "acc", None)
    if acc is not None:
        acc.add_phase(name, dt)


def note_route(route: str, cap: int = 32):
    """Record a nested serving-path route (fused/cached/direct) into
    the thread's ACTIVE record's ``serving_routes`` list — how a SQL
    statement record (route "sql") shows which of its inner PQL
    dispatches rode the fused plane.  No-op without an open record;
    capped so a many-call statement cannot grow a record without
    bound."""
    rec = getattr(_tls, "rec", None)
    if rec is not None:
        routes = rec.setdefault("serving_routes", [])
        if len(routes) < cap:
            routes.append(route)


def note_stack(outcome: str, nbytes: int, dt: float,
               key_fp: str | None = None):
    acc = getattr(_tls, "acc", None)
    if acc is not None:
        acc.add_stack(outcome, nbytes, dt, key_fp=key_fp)


def note_attempt(node: str, dt: float, outcome: str):
    """Record one cluster per-node RPC attempt (incl. hedges) into
    the active record's ``attempts`` field."""
    acc = getattr(_tls, "acc", None)
    if acc is not None:
        acc.add_attempt(node, dt, outcome)


def note_node_spans(node: str, spans: list, anchor_perf: float):
    """Record a remote (or local-leg) serialized span tree returned
    in an RPC trailer, anchored at the caller-clock instant the
    attempt left (cluster/coordinator.py)."""
    acc = getattr(_tls, "acc", None)
    if acc is not None:
        acc.add_node_spans(node, spans, anchor_perf)


def note_pages(mix: dict):
    """Record the page-encoding mix of one stack operand fetch
    (executor/stacked.py _assemble) into the active record."""
    acc = getattr(_tls, "acc", None)
    if acc is not None:
        acc.add_pages(mix)


def note_op(op: str, nbytes: int, dt: float):
    """Record one device dispatch's roofline share (bytes touched +
    execute seconds) by op family (obs/roofline.py calls this)."""
    acc = getattr(_tls, "acc", None)
    if acc is not None:
        acc.add_op(op, nbytes, dt)


def inherit_trace(trace_id: str | None):
    """Adopt a REMOTE caller's trace id for the next record this
    thread opens (RPC trace propagation: the X-Pilosa-Trace-Id header
    / gRPC trace-id metadata land here, so a remote leg's flight
    record joins the coordinator's under one cluster-wide id).
    Returns the previous value to restore via pop_inherit."""
    prev = getattr(_tls, "inherit", None)
    _tls.inherit = trace_id
    return prev


def pop_inherit(prev):
    _tls.inherit = prev


def current_trace_id() -> str | None:
    """The trace id of this thread's active (or inherited) flight
    record, or None — the log-correlation stamp (obs/logger.py)."""
    rec = getattr(_tls, "rec", None)
    if rec is not None:
        return rec["trace_id"]
    return getattr(_tls, "inherit", None)


@contextmanager
def remote_leg(trace_id: str, keep: int = 8):
    """The remote-leg scaffold every trace-propagating RPC surface
    shares (server/http.py, cluster/coordinator.py's local leg, the
    overhead probe): inherit the caller's trace id so this thread's
    flight record joins it, record the leg's spans on a thread-local
    tracer, and on exit serialize the roots to wire form.  Yields
    ``(tracer, spans)`` — ``spans`` fills AFTER the body exits (wire
    dicts for the response trailer); ``tracer.roots`` keeps the live
    Span objects for callers that need absolute anchors.  One
    implementation so a fix to the pop-ordering or wire shape cannot
    drift between surfaces."""
    from pilosa_tpu.obs import tracing as _tr
    spans: list[dict] = []
    prev_inh = inherit_trace(trace_id)
    tracer = _tr.RecordingTracer(keep=keep)
    prev = _tr.push_thread_tracer(tracer)
    try:
        yield tracer, spans
    finally:
        _tr.pop_thread_tracer(prev)
        pop_inherit(prev_inh)
        spans.extend(_tr.span_to_wire(s) for s in tracer.roots)


class FlightRecorder:
    """Bounded ring of finished per-query flight records."""

    def __init__(self, keep: int = 512, enabled: bool | None = None):
        if enabled is None:
            enabled = os.environ.get("PILOSA_TPU_FLIGHT", "1") != "0"
        self.enabled = enabled
        self._ring: deque[dict] = deque(maxlen=keep)
        self._ids = itertools.count(1)
        self._lock = threading.Lock()

    def configure(self, enabled: bool | None = None,
                  keep: int | None = None):
        """Apply config knobs ([flight] in config.py).  Resizing
        keeps the newest records."""
        if enabled is not None:
            self.enabled = bool(enabled)
        if keep is not None and keep != self._ring.maxlen:
            with self._lock:
                self._ring = deque(self._ring, maxlen=int(keep))

    def next_id(self) -> str:
        return f"q{next(self._ids):x}"  # itertools.count: atomic

    def record(self, rec: dict):
        # LOCK-FREE hot path: deque.append with maxlen is atomic under
        # the GIL, and a contended threading.Lock costs ~20us of GIL
        # ping-pong per acquisition — measured to dominate the whole
        # recorder at serving qps.  Readers snapshot with retry.
        self._ring.append(rec)

    def recent(self, n: int = 100) -> list[dict]:
        """Newest-first records (the /debug/queries payload)."""
        while True:
            try:
                items = list(self._ring)
                break
            except RuntimeError:
                continue  # deque mutated mid-iteration: retry
        return list(reversed(items))[: max(0, int(n))]

    def clear(self):
        self._ring.clear()

    def __len__(self) -> int:
        return len(self._ring)

    # -- Chrome trace_event export -------------------------------------

    def chrome_trace(self, n: int = 100) -> dict:
        """Recent records as the Chrome ``trace_event`` JSON object
        format (loadable in Perfetto / chrome://tracing): one complete
        ("ph": "X") event per query plus one per phase, on a per-query
        virtual thread so concurrent queries render as parallel
        tracks.  Cluster fan-out records additionally render one
        PROCESS LANE per node (``pid`` + a process_name metadata
        event): per-node RPC attempts — hedges as parallel spans —
        and the span trees each node returned in its response
        trailer, all under the query's one trace id."""
        events = []
        # pid 1 is the serving process itself; cluster legs get one
        # pid per node so Perfetto renders per-node lanes
        node_pids: dict[str, int] = {}

        def pid_for(node: str) -> int:
            p = node_pids.get(node)
            if p is None:
                p = node_pids[node] = len(node_pids) + 2
                events.append({"name": "process_name", "ph": "M",
                               "pid": p,
                               "args": {"name": f"node:{node}"}})
            return p

        for rec in self.recent(n):
            ts = rec["start"] * 1e6          # epoch microseconds
            dur = rec["duration_ms"] * 1e3
            tid = rec["trace_id"]
            args = {"index": rec.get("index", ""),
                    "query": rec.get("query", ""),
                    "route": rec.get("route", ""),
                    "batch": rec.get("batch", 1)}
            if rec.get("stack"):
                args["stack"] = rec["stack"]
            if rec.get("bytes_moved"):
                args["bytes_moved"] = rec["bytes_moved"]
            events.append({
                "name": f"query:{rec.get('route', '?')}",
                "cat": "query", "ph": "X", "pid": 1, "tid": tid,
                "ts": ts, "dur": max(dur, 1.0), "args": args,
            })
            # phases render sequentially inside the query slice; we
            # record durations (not offsets), so lay them end to end
            # in PHASES order — relative widths are what matters
            off = ts
            for name in PHASES:
                pdur = rec.get("phases", {}).get(name)
                if not pdur:
                    continue
                events.append({
                    "name": name, "cat": "phase", "ph": "X",
                    "pid": 1, "tid": tid, "ts": off,
                    "dur": max(pdur * 1e3, 0.5),
                    "args": {"ms": round(pdur, 4)},
                })
                off += pdur * 1e3
            # cluster fan-out: per-node attempt slices (true start
            # offsets — a hedge renders in parallel with the primary
            # attempt it raced) ...
            for a in rec.get("attempts", ()):
                events.append({
                    "name": f"attempt:{a.get('outcome', '?')}",
                    "cat": "attempt", "ph": "X",
                    "pid": pid_for(str(a.get("node", "?"))),
                    "tid": tid,
                    "ts": ts + a.get("t_off_ms", 0.0) * 1e3,
                    "dur": max(a.get("ms", 0.0) * 1e3, 0.5),
                    "args": {"trace_id": tid,
                             "node": a.get("node"),
                             "outcome": a.get("outcome")},
                })
            # ... and the span trees each leg returned in its
            # response trailer, re-anchored on the coordinator clock
            for ent in rec.get("node_spans", ()):
                pid = pid_for(str(ent.get("node", "?")))
                base = ts + ent.get("anchor_off_us", 0)
                stack = list(ent.get("spans", ()))
                while stack:
                    w = stack.pop()
                    ev = {"name": str(w.get("name", "span")),
                          "cat": "node", "ph": "X", "pid": pid,
                          "tid": tid,
                          "ts": base + w.get("off_us", 0),
                          "dur": max(w.get("dur_us", 0), 0.5),
                          "args": {"trace_id": tid,
                                   "node": ent.get("node")}}
                    if w.get("tags"):
                        ev["args"]["tags"] = w["tags"]
                    events.append(ev)
                    stack.extend(w.get("children", ()))
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"source": "pilosa-tpu flight recorder"}}

    def chrome_trace_json(self, n: int = 100) -> str:
        return json.dumps(self.chrome_trace(n))


# process-global recorder (the /debug surface and metrics exemplars
# read this one); config.apply_flight_settings() reconfigures it
recorder = FlightRecorder()


def begin(index: str, query) -> dict | None:
    """Open a flight record for this thread's query, or None when the
    recorder is off or a record is already active (nested execute calls
    — e.g. the serving layer's direct fallback re-entering
    Executor.execute — must not double-record)."""
    if not recorder.enabled or getattr(_tls, "rec", None) is not None:
        return None
    inherited = getattr(_tls, "inherit", None)
    rec = {
        "trace_id": inherited or recorder.next_id(),
        "index": index,
        "query": str(query)[:200],
        "start": time.time(),
        "acc": Acc(),
    }
    if inherited:
        # a remote leg of a cluster fan-out: same id as the
        # coordinator's record so /debug/cluster/queries merges them
        rec["inherited"] = True
    _tls.rec = rec
    rec["prev_acc"] = push_acc(rec["acc"])
    return rec


def commit(rec: dict | None, duration_s: float, route: str = "solo",
           batch: int = 1, error: str | None = None,
           fingerprint: str | None = None,
           extra_acc: Acc | None = None):
    """Finish and ring-buffer a record opened by begin(); exports the
    per-phase histograms with this record's trace id as exemplar."""
    if rec is None:
        return
    acc: Acc = rec.pop("acc")
    pop_acc(rec.pop("prev_acc"))
    _tls.rec = None
    if extra_acc is not None:
        acc.merge(extra_acc)
    # wait = time parked in the batcher not accounted to a device
    # phase (admission window + other requests' share of the batch).
    # Derived INTO acc.phases so it reaches the phase histogram, not
    # just the record dict.
    if "batch" in acc.phases:
        accounted = sum(v for k, v in acc.phases.items()
                        if k not in ("batch", "cache_lookup"))
        acc.add_phase("wait",
                      max(acc.phases["batch"] - accounted, 0.0))
    phases = {k: round(v * 1e3, 4) for k, v in acc.phases.items()}
    rec.update({
        "duration_ms": round(duration_s * 1e3, 4),
        "route": route,
        "batch": int(batch),
        "phases": phases,
        "stack": dict(acc.stack),
        "bytes_moved": acc.bytes_moved,
        # non-hit stack-key fingerprints feeding the prefetcher's
        # prediction scan (memory/policy.py Prefetcher.step)
        "stack_keys": list(acc.keys),
    })
    if acc.attempts:
        # per-node cluster attempt timings (hedges included) — only
        # fan-out queries carry the field, so solo records stay small.
        # t_off_ms = start offset inside the query, so /debug/trace
        # renders hedges as genuinely PARALLEL spans
        rec["attempts"] = [
            {"node": n, "ms": ms, "outcome": o, "t_off_ms": off}
            for n, ms, o, off in acc.attempts]
    if acc.node_spans:
        # per-node span trees from RPC trailers (+ the local leg) —
        # the /debug/trace node lanes
        rec["node_spans"] = list(acc.node_spans)
    if acc.pages:
        # page-encoding mix of the stack operands touched (sparse
        # device format, memory/encode.py): packed vs dense served
        rec["page_mix"] = dict(acc.pages)
    if acc.ops:
        # roofline share: bytes touched / execute time per op family,
        # with achieved GB/s (+ fraction once the peak probe landed)
        from pilosa_tpu.obs import roofline
        peak = roofline.peak_or_none()
        rl = {}
        for op, (b, s) in acc.ops.items():
            if s <= 0:
                continue
            ent = {"bytes": b, "ms": round(s * 1e3, 4),
                   "gbps": round(b / s / 1e9, 4)}
            if peak:
                ent["fraction"] = round((b / s) / peak, 5)
            rl[op] = ent
        if rl:
            rec["roofline"] = rl
    if error is not None:
        rec["error"] = error[:200]
    if fingerprint is not None:
        rec["fingerprint"] = fingerprint
    recorder.record(rec)
    _buffer_phase_samples(acc, rec["trace_id"])
    # statistics catalog (obs/stats.py): one enabled check + a
    # lock-free pending append; profile folding is amortized off the
    # hot path (same budget class as the phase-sample buffer above)
    from pilosa_tpu.obs import stats as _stats
    _stats.note_flight(rec)


# -- buffered phase-histogram export ----------------------------------------
# A contended threading.Lock costs ~20us of GIL ping-pong per
# acquisition; observing every phase of every query directly into the
# shared histogram would convoy the serving threads.  Samples append
# to a GLOBAL lock-free pending list (list.append is GIL-atomic) and
# drain in one observe_batch() every _FLUSH_N samples — amortizing the
# histogram lock ~64x.  Not per-thread: ThreadingHTTPServer spawns a
# thread per connection, and thread-local buffers would die (samples
# and all) with their threads.  /metrics rendering calls
# flush_metrics() first, so a scrape always sees current samples; the
# tiny race where a concurrent flush orphans an in-flight append loses
# at most a sample or two — acceptable for a latency histogram, never
# for the flight ring (which appends records directly).

_FLUSH_N = 64
_pending: list = []


def flush_metrics():
    """Drain the pending phase samples into the shared
    pilosa_query_phase_seconds histogram (called on /metrics render
    and by tests for determinism)."""
    global _pending
    buf, _pending = _pending, []
    if buf:
        from pilosa_tpu.obs import metrics
        metrics.PHASE_DURATION.observe_batch(buf)


def _buffer_phase_samples(acc: Acc, trace_id: str):
    pend = _pending
    for name, dt in acc.phases.items():
        pend.append((dt, {"phase": name}, trace_id))
    if len(pend) >= _FLUSH_N:
        flush_metrics()


def phase_breakdown(records: list[dict]) -> dict:
    """Aggregate records into the BENCH JSON per-phase breakdown:
    total ms by compile/upload/execute/wait (+ the rest verbatim)."""
    out: dict[str, float] = {}
    for rec in records:
        for k, v in rec.get("phases", {}).items():
            out[k] = out.get(k, 0.0) + v
    agg = {
        "compile_ms": round(out.pop("compile", 0.0), 3),
        "execute_ms": round(out.pop("execute", 0.0), 3),
        "upload_ms": round(out.pop("stack_rebuild", 0.0)
                           + out.pop("stack_patch", 0.0), 3),
        "wait_ms": round(out.pop("wait", 0.0), 3),
    }
    agg.update({k + "_ms": round(v, 3) for k, v in out.items()})
    return agg
