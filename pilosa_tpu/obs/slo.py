"""SLO burn-rate plane — multi-window error-budget math over the
metrics the engine already exports.

Two config-defined SLOs ([slo] in config.py):

- **latency**: fraction of queries answering under ``latency-ms``
  must stay >= ``latency-objective``.  Good/total derive from the
  ``pilosa_query_duration_seconds`` histogram (bucket-interpolated
  count-at-threshold — the histogram is observed on both the solo and
  serving paths).
- **availability**: fraction of requests NOT failing with a typed
  serving error must stay >= ``availability-objective``.  Bad events
  sum the typed-error counters the earlier PRs planted: 503 sheds
  (``pilosa_serving_admission_total{outcome=shed}``, cluster
  ``load_shed``, ingest backpressure), 504 deadlines
  (``outcome=expired``), and served-partial cluster results
  (``pilosa_cluster_events_total{event=partial}`` — degraded answers
  spend error budget too).

Burn rate follows the SRE-workbook convention: over each window W,
``burn = bad_fraction / (1 - objective)`` — 1.0 means spending budget
exactly at the sustainable rate, 14.4 on a 5 m window is the classic
page-now threshold.  The tracker keeps a ring of cumulative-counter
samples (the maintenance ticker feeds it; ``/debug/slo`` and
``/metrics`` renders sample on demand too) and diffs the newest
sample against the oldest one inside each window, so the cumulative
counters never need to reset.

Exported at ``/debug/slo`` (JSON payload below) and as gauges
``pilosa_slo_burn_rate{slo,window}`` /
``pilosa_slo_error_budget_remaining{slo}`` (longest window).
"""

from __future__ import annotations

import threading
import time
from collections import deque

_UNITS = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}


def parse_windows(spec: str) -> list[tuple[str, float]]:
    """'5m,1h,6h' (or bare seconds '300,3600') -> [(label, s), ...],
    sorted ascending; junk entries are dropped rather than raising —
    a typo'd window must not take the server down."""
    out = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        unit = 1.0
        if part[-1].lower() in _UNITS:
            unit = _UNITS[part[-1].lower()]
            num = part[:-1]
        else:
            num = part
        try:
            secs = float(num) * unit
        except ValueError:
            continue
        if secs > 0:
            out.append((part, secs))
    out.sort(key=lambda p: p[1])
    return out or [("5m", 300.0), ("1h", 3600.0), ("6h", 21600.0)]


class SloTracker:
    """Ring of cumulative samples + multi-window burn-rate math."""

    def __init__(self, latency_ms: float = 250.0,
                 latency_objective: float = 0.99,
                 availability_objective: float = 0.999,
                 windows: str = "5m,1h,6h"):
        self.latency_ms = float(latency_ms)
        self.latency_objective = float(latency_objective)
        self.availability_objective = float(availability_objective)
        self.windows = parse_windows(windows)
        # (t, total, lat_good, raised, degraded) cumulative readings,
        # oldest first
        self._samples: deque[tuple] = deque(maxlen=8192)
        self._lock = threading.Lock()
        self._t0 = time.time()

    # -- cumulative readings -------------------------------------------

    def _read(self) -> tuple[float, float, float, float, float]:
        """One cumulative reading of (now, total requests, requests
        under the latency threshold, RAISED typed errors, DEGRADED
        served answers).  Raised errors (sheds, expired deadlines)
        abort before the latency histogram observes, so they extend
        the request denominator; degraded answers (partial results)
        complete normally and are already inside ``total`` — keeping
        the two separate stops a partial from double-counting in the
        denominator.  Overridable test seam."""
        from pilosa_tpu.obs import metrics
        h = metrics.QUERY_DURATION
        total = float(h.count())
        good = h.count_le(self.latency_ms / 1e3)
        raised = (metrics.ADMISSION_TOTAL.total(outcome="shed")
                  + metrics.ADMISSION_TOTAL.total(outcome="expired")
                  + metrics.CLUSTER_EVENTS.value(event="load_shed")
                  + metrics.INGEST_SHED.total())
        degraded = metrics.CLUSTER_EVENTS.value(event="partial")
        return time.time(), total, good, raised, degraded

    def sample(self):
        """Record one cumulative reading (maintenance ticker +
        on-demand before every evaluation)."""
        s = self._read()
        with self._lock:
            self._samples.append(s)

    # -- burn-rate evaluation ------------------------------------------

    def _window_delta(self, now: float, secs: float):
        """Delta vs the OLDEST sample inside the window.  ``covered``
        is derived from the BASE SAMPLE'S AGE (>=90% of the window),
        not tracker uptime: ring eviction under a fast poller can
        leave only recent samples, and a burn rate computed over a
        silently shorter span must say so."""
        with self._lock:
            base = None
            for s in self._samples:
                if s[0] >= now - secs:
                    base = s
                    break
            newest = self._samples[-1] if self._samples else None
        if base is None or newest is None:
            return None, False
        covered = (now - base[0]) >= 0.9 * secs
        return tuple(n - b for n, b in zip(newest[1:], base[1:])), covered

    def evaluate(self) -> dict:
        """Sample + compute burn rates; updates the SLO gauges and
        returns the /debug/slo payload."""
        from pilosa_tpu.obs import metrics
        self.sample()
        now = time.time()
        budgets = {
            "latency": max(1.0 - self.latency_objective, 1e-9),
            "availability": max(1.0 - self.availability_objective,
                                1e-9),
        }
        slos: dict[str, dict] = {
            "latency": {"objective": self.latency_objective,
                        "threshold_ms": self.latency_ms,
                        "windows": {}},
            "availability": {"objective": self.availability_objective,
                             "windows": {}},
        }
        for label, secs in self.windows:
            delta, covered = self._window_delta(now, secs)
            if delta is None:
                continue
            d_total, d_good, d_raised, d_degraded = delta
            # latency: of the queries that completed, how many blew
            # the threshold
            lat_bad = max(d_total - d_good, 0.0)
            lat_frac = lat_bad / d_total if d_total > 0 else 0.0
            # availability: raised errors never reached the latency
            # histogram, so they extend the denominator; degraded
            # (partial) answers completed and already sit inside
            # d_total — they add only to the numerator
            d_bad = d_raised + d_degraded
            denom = d_total + d_raised
            avail_frac = d_bad / denom if denom > 0 else 0.0
            for name, frac, bad, total in (
                    ("latency", lat_frac, lat_bad, d_total),
                    ("availability", avail_frac, d_bad, denom)):
                burn = frac / budgets[name]
                slos[name]["windows"][label] = {
                    "burn_rate": round(burn, 4),
                    "bad": round(bad, 1),
                    "total": round(total, 1),
                    "window_covered": covered,
                }
                metrics.SLO_BURN_RATE.set(burn, slo=name, window=label)
        # budget remaining over the LONGEST window
        longest = self.windows[-1][0]
        for name in ("latency", "availability"):
            w = slos[name]["windows"].get(longest)
            if w is not None:
                remaining = max(0.0, 1.0 - w["burn_rate"])
                slos[name]["budget_remaining"] = round(remaining, 4)
                metrics.SLO_BUDGET_REMAINING.set(remaining, slo=name)
        payload = {"slos": slos,
                   "windows": [label for label, _ in self.windows],
                   "samples": len(self._samples),
                   "uptime_s": round(now - self._t0, 1)}
        # incident trigger (obs/incidents.py): a burn rate at/over
        # the configured threshold on a COVERED window captures one
        # rate-limited black-box bundle — detection becomes evidence
        from pilosa_tpu.obs import incidents
        incidents.note_slo(payload)
        return payload


# process-global tracker; config.apply_slo_settings() rebuilds it
tracker: SloTracker | None = None
_lock = threading.Lock()


def configure(latency_ms: float = 250.0, latency_objective: float = 0.99,
              availability_objective: float = 0.999,
              windows: str = "5m,1h,6h") -> SloTracker:
    global tracker
    with _lock:
        tracker = SloTracker(latency_ms, latency_objective,
                             availability_objective, windows)
    return tracker


def get() -> SloTracker:
    global tracker
    with _lock:
        if tracker is None:
            tracker = SloTracker()
        return tracker


def tick():
    """Maintenance-ticker hook (server/http.py): sample + refresh the
    burn-rate gauges."""
    try:
        get().evaluate()
    except Exception:
        pass  # the SLO plane must never take the ticker down
