"""Diagnostics + performance counters.

Reference: diagnostics.go:20,49 — a periodic collector of anonymized
runtime/host stats with a version check against a release endpoint
(phone-home is OFF unless a reporting URL is configured, matching the
reference's opt-out semantics under this build's zero-egress default);
performancecounters.go — named monotonic counters snapshotted for
operators; gopsutil/ — platform stats (psutil is unavailable, so the
collector reads /proc and the stdlib).
"""

from __future__ import annotations

import json
import os
import platform
import sys
import threading
import time


class Diagnostics:
    """diagnostics.Diagnostics: set/collect/flush cycle."""

    def __init__(self, version: str = "", interval: float = 3600.0,
                 send=None):
        self.version = version
        self.interval = interval
        # send(payload: dict) — None disables reporting entirely
        self._send = send
        self._info: dict = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.last_payload: dict | None = None

    def set(self, key: str, value):
        with self._lock:
            self._info[key] = value

    def platform_info(self) -> dict:
        """Host stats (gopsutil analog via stdlib + /proc)."""
        info = {
            "os": platform.system(),
            "arch": platform.machine(),
            "python": sys.version.split()[0],
            "num_cpu": os.cpu_count(),
        }
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemTotal:"):
                        info["mem_total_kb"] = int(line.split()[1])
                        break
        except OSError:
            pass
        try:
            info["load_avg"] = os.getloadavg()[0]
        except OSError:
            pass
        return info

    def payload(self) -> dict:
        with self._lock:
            return {"version": self.version, "time": time.time(),
                    **self.platform_info(), **self._info}

    def flush(self):
        self.last_payload = self.payload()
        if self._send is not None:
            try:
                self._send(self.last_payload)
            except Exception:
                pass  # diagnostics must never break the server

    def start(self):
        if self._send is None:
            return self  # reporting disabled: no ticker either
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval):
            self.flush()

    def close(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)

    @staticmethod
    def check_version(current: str, latest: str) -> str | None:
        """verchk.go semantics: a human-readable nudge when a newer
        release exists, else None."""
        def parse(v):
            return tuple(int(p) for p in
                         v.lstrip("v").split("-")[0].split("."))
        try:
            if parse(latest) > parse(current):
                return (f"version {latest} is available "
                        f"(running {current})")
        except ValueError:
            return None
        return None


class PerformanceCounters:
    """performancecounters.go: named monotonic counters + gauges with
    a consistent snapshot for operator tooling."""

    def __init__(self):
        self._counters: dict[str, int] = {}
        self._lock = threading.Lock()

    def add(self, name: str, delta: int = 1):
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + delta

    def set_gauge(self, name: str, value: int):
        with self._lock:
            self._counters[name] = int(value)

    def snapshot(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def dump_json(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)


performance_counters = PerformanceCounters()

# process-global collector: the server maintenance ticker refreshes
# it (server/http.py) so incident bundles carry a host snapshot that
# predates the anomaly — phone-home stays OFF (send=None means no
# reporting thread and no egress; only the in-process payload is kept)
collector = Diagnostics()


def collect() -> dict:
    """One collection pass (ticker hook): refresh and return the
    host/runtime payload.  Never raises — a broken /proc read must
    not take the ticker down."""
    try:
        collector.flush()
        return collector.last_payload or {}
    except Exception:
        return {}


def host_snapshot() -> dict:
    """The newest collected host payload (incident bundles attach
    this); collects on demand when the ticker has not run yet."""
    if collector.last_payload is not None:
        return collector.last_payload
    return collect()
