"""Observability — logger, metrics, tracing (SURVEY §2.9/§5).

Re-designed analogs of the reference's cross-cutting subsystems:
``logger/`` (leveled logger with nop default), ``metrics.go``
(central prometheus registry), ``tracing/tracing.go`` (global Tracer
interface, nop default, profiled per-query spans).
"""

from pilosa_tpu.obs.logger import Logger, NopLogger, StderrLogger, new_logger
from pilosa_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    registry,
)
from pilosa_tpu.obs.tracing import (
    NopTracer,
    ProfiledSpan,
    RecordingTracer,
    Span,
    TraceContext,
    Tracer,
    capture_context,
    get_tracer,
    set_tracer,
    span_into,
    start_span,
)

__all__ = [
    "Logger",
    "NopLogger",
    "StderrLogger",
    "new_logger",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "registry",
    "Tracer",
    "NopTracer",
    "RecordingTracer",
    "Span",
    "ProfiledSpan",
    "TraceContext",
    "capture_context",
    "span_into",
    "get_tracer",
    "set_tracer",
    "start_span",
]
