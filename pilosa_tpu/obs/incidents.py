"""Incident forensics — anomaly-triggered black-box bundles.

The earlier observability PRs *detect* trouble (SLO burn rates, the
perf-regression sentinel, typed sheds, watchdog stalls) but keep no
evidence: by the time an operator looks at a 3am page, the flight
ring has rotated and the stacks are gone.  This module is the
reaction — when an anomaly fires, capture ONE rate-limited,
size-bounded *incident bundle* of everything a post-mortem needs,
persisted under the data dir:

====================  ====================================================
``stacks``            every live thread's stack (``sys._current_frames``)
                      with thread names
``flight``            flight-ring snapshot (newest records)
``trace``             Chrome trace_event excerpt (Perfetto-loadable)
``metrics``           full /metrics.json dump
``stats``             statistics-catalog excerpt (profiles, regressions)
``faults``            armed fault-point rules
``config``            the server's config snapshot (secrets dropped)
``host``              host/runtime stats (obs/diagnostics.py collector)
``log_tail``          recent log lines (obs/logger.py ring, trace= stamps)
``profile``           continuous-profiler windows (folded stacks)
====================  ====================================================

Triggers wired through the stack (``report(trigger, ...)``):

- ``slo-burn``              — burn rate over threshold on a covered
  window (obs/slo.py evaluate)
- ``perf-regression``       — the statistics catalog's sentinel fires
  (obs/stats.py)
- ``watchdog-stall``        — a progress-stamped loop wedged past its
  deadline (obs/watchdog.py)
- ``device-oom``            — the OOM recovery ladder trips
  (memory/pressure.py)
- ``batch-leader-exception`` — an unhandled serving batch-leader
  error (executor/serving.py)
- ``ingest-crash``          — the streaming write plane dies
  (ingest/stream.py)

Capture runs on a dedicated daemon thread — ``report()`` is the hot
path and costs one rate-limit check + a queue append; serving never
waits on a bundle.  Rate limiting dedupes per trigger inside
``min_interval_s`` (suppressed reports are counted, not captured).
Bundles persist tmp+fsync+rename (never a half file — the
``incident-write`` fault seam proves it) with a bounded on-disk
retention, and a bounded in-memory ring serves ``/debug/incidents``
even without a data dir.
"""

from __future__ import annotations

import itertools
import json
import os
import sys
import threading
import time
import traceback
import uuid
from collections import deque

# PILOSA_TPU_INCIDENTS=0 kills the plane (env twin of
# [incidents] enabled, same contract as the stats/roofline switches)
_enabled = os.environ.get("PILOSA_TPU_INCIDENTS", "1") != "0"

# capture-section list caps BEFORE the byte bound (the bound then
# halves the biggest sections until the bundle fits)
_FLIGHT_RECORDS = 64
_TRACE_RECORDS = 32
_LOG_LINES = 200
_STATS_PROFILES = 16

TRIGGERS = ("slo-burn", "perf-regression", "watchdog-stall",
            "device-oom", "batch-leader-exception", "ingest-crash",
            "audit-mismatch", "dax-scale-out", "dax-scale-in",
            "manual")


def format_stack(frame, max_frames: int = 64) -> str:
    """One frame's stack as bounded text — the single formatting
    idiom every stack-capture surface shares (thread_dump here, the
    watchdog's stuck-thread evidence), so truncation/caps cannot
    drift between them."""
    return "".join(traceback.format_stack(frame)[-max_frames:])[-8000:]


def thread_dump(max_frames: int = 64) -> list[dict]:
    """Every live thread's stack with its name — the bundle's core
    evidence, also useful standalone."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, top in sys._current_frames().items():
        out.append({"thread_id": tid,
                    "name": names.get(tid, f"tid-{tid}"),
                    "stack": format_stack(top, max_frames)})
    out.sort(key=lambda d: d["name"])
    return out


class IncidentManager:
    """Rate-limited capture queue + bounded bundle store."""

    def __init__(self, dir: str | None = None,
                 min_interval_s: float = 60.0,
                 max_bundles: int = 32,
                 max_bundle_bytes: int = 1 << 20,
                 slo_burn_threshold: float = 8.0):
        self.dir = dir
        self.min_interval_s = float(min_interval_s)
        self.max_bundles = int(max_bundles)
        self.max_bundle_bytes = int(max_bundle_bytes)
        self.slo_burn_threshold = float(slo_burn_threshold)
        self.config_snapshot: dict | None = None
        self._ids = itertools.count(1)
        # per-process discriminator: bundle ids must stay unique
        # across a CLUSTER (the federated merge keys on them) — two
        # nodes tripping the same trigger in the same epoch second
        # with the same sequence must not collide
        self.token = uuid.uuid4().hex[:6]
        self._lock = threading.Lock()
        self._last: dict[str, float] = {}   # trigger -> last capture
        self.suppressed: dict[str, int] = {}
        # full bundles (newest last) — /debug/incidents fetch works
        # without a data dir; metadata ring is wider than the bundle
        # ring so the listing survives bundle eviction
        self._bundles: deque[dict] = deque(maxlen=8)
        self._meta: deque[dict] = deque(maxlen=64)
        self._q: deque[tuple] = deque()
        self._q_event = threading.Event()
        self._inflight = 0
        self._idle = threading.Condition(self._lock)
        self._thread: threading.Thread | None = None

    # -- hot-path entry ------------------------------------------------

    def report(self, trigger: str, detail: str = "",
               context: dict | None = None) -> bool:
        """Request a bundle for ``trigger``.  Returns False when rate
        limiting suppressed it.  Cheap by contract: one lock for the
        rate map, one queue append — capture happens on the worker."""
        now = time.monotonic()
        with self._lock:
            last = self._last.get(trigger)
            if last is not None and now - last < self.min_interval_s:
                self.suppressed[trigger] = \
                    self.suppressed.get(trigger, 0) + 1
                from pilosa_tpu.obs import metrics
                metrics.INCIDENTS_TOTAL.inc(trigger=trigger,
                                            outcome="suppressed")
                return False
            self._last[trigger] = now
            self._inflight += 1
            self._q.append((trigger, detail, context, time.time()))
        self._q_event.set()
        self._ensure_worker()
        return True

    # -- capture worker ------------------------------------------------

    def _ensure_worker(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._thread = threading.Thread(
                target=self._worker, name="pilosa-incident-capture",
                daemon=True)
            self._thread.start()

    def _worker(self) -> None:
        while True:
            self._q_event.wait(1.0)
            self._q_event.clear()
            while True:
                try:
                    item = self._q.popleft()
                except IndexError:
                    break
                try:
                    self._capture(*item)
                except Exception as e:
                    from pilosa_tpu.obs import metrics
                    from pilosa_tpu.obs.monitor import capture_exception
                    metrics.INCIDENTS_TOTAL.inc(trigger=item[0],
                                                outcome="error")
                    capture_exception(e, where="incidents.capture",
                                      trigger=item[0])
                finally:
                    with self._lock:
                        self._inflight -= 1
                        self._idle.notify_all()

    def wait_idle(self, timeout: float = 5.0) -> bool:
        """Block until every queued capture landed (tests + clean
        shutdown)."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while self._inflight > 0:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    return False
                self._idle.wait(rem)
        return True

    # -- bundle assembly -----------------------------------------------

    def _capture(self, trigger: str, detail: str,
                 context: dict | None, t: float) -> None:
        iid = f"inc-{int(t)}-{next(self._ids)}-{self.token}-{trigger}"
        bundle = {"id": iid, "time": t, "trigger": trigger,
                  "detail": str(detail)[:500]}
        if context:
            bundle["context"] = _jsonable(context)
        # every section guarded: a broken collector degrades its
        # section to an error string, never the whole bundle
        for key, fn in (
                ("stacks", thread_dump),
                ("flight", self._flight_snapshot),
                ("trace", self._trace_excerpt),
                ("metrics", self._metrics_dump),
                ("stats", self._stats_excerpt),
                ("faults", self._armed_faults),
                ("host", self._host_stats),
                ("log_tail", self._log_tail),
                ("profile", self._profile_windows)):
            try:
                bundle[key] = fn()
            except Exception as e:
                bundle[key] = {"error": f"{type(e).__name__}: {e}"}
        if self.config_snapshot is not None:
            bundle["config"] = self.config_snapshot
        nbytes = self._bound(bundle)
        bundle["bundle_bytes"] = nbytes
        meta = {"id": iid, "time": t, "trigger": trigger,
                "detail": bundle["detail"], "bytes": nbytes,
                "persisted": False}
        if self.dir:
            try:
                self._persist(iid, bundle)
                meta["persisted"] = True
            except Exception as e:
                from pilosa_tpu.obs.monitor import capture_exception
                capture_exception(e, where="incidents.persist", id=iid)
        with self._lock:
            self._bundles.append(bundle)
            self._meta.append(meta)
        from pilosa_tpu.obs import metrics
        metrics.INCIDENTS_TOTAL.inc(trigger=trigger,
                                    outcome="captured")

    @staticmethod
    def _flight_snapshot() -> list[dict]:
        from pilosa_tpu.obs import flight
        return _jsonable(flight.recorder.recent(_FLIGHT_RECORDS))

    @staticmethod
    def _trace_excerpt() -> dict:
        from pilosa_tpu.obs import flight
        return flight.recorder.chrome_trace(_TRACE_RECORDS)

    @staticmethod
    def _metrics_dump() -> dict:
        from pilosa_tpu.obs import flight, metrics
        flight.flush_metrics()
        return metrics.registry.render_json()

    @staticmethod
    def _stats_excerpt() -> dict:
        from pilosa_tpu.obs import stats
        return _jsonable(stats.get().payload(limit=_STATS_PROFILES))

    @staticmethod
    def _armed_faults() -> list[dict]:
        from pilosa_tpu.obs import faults
        return faults.active()

    @staticmethod
    def _host_stats() -> dict:
        from pilosa_tpu.obs import diagnostics
        return diagnostics.host_snapshot()

    @staticmethod
    def _log_tail() -> list[str]:
        from pilosa_tpu.obs import logger
        return logger.ring.recent(_LOG_LINES)

    @staticmethod
    def _profile_windows() -> list[dict]:
        from pilosa_tpu.obs import profiler
        return profiler.profile_windows()

    # size bound: halve the biggest list-valued sections until the
    # serialized bundle fits — a forensics bundle that OOMs the node
    # it's diagnosing would be its own incident
    _SHRINKABLE = ("trace", "flight", "log_tail", "profile", "stacks")

    def _bound(self, bundle: dict) -> int:
        nbytes = len(json.dumps(bundle, default=str))
        for _ in range(24):
            if nbytes <= self.max_bundle_bytes:
                break
            sizes = {}
            for key in self._SHRINKABLE:
                v = bundle.get(key)
                if isinstance(v, dict):  # chrome trace {traceEvents}
                    v = v.get("traceEvents")
                if isinstance(v, list) and v:
                    sizes[key] = len(json.dumps(
                        bundle[key], default=str))
            if not sizes:
                break
            key = max(sizes, key=sizes.get)
            v = bundle[key]
            if isinstance(v, dict):
                ev = v.get("traceEvents", [])
                if len(ev) <= 1:
                    bundle[key] = {"truncated": True}
                else:
                    v["traceEvents"] = ev[: len(ev) // 2]
                    v["truncated"] = True
            elif len(v) <= 1:
                bundle[key] = [{"truncated": True}] \
                    if key == "stacks" else ["<truncated>"]
            else:
                bundle[key] = v[: len(v) // 2]
            bundle["truncated"] = True
            nbytes = len(json.dumps(bundle, default=str))
        return nbytes

    # -- persistence ---------------------------------------------------

    def _persist(self, iid: str, bundle: dict) -> None:
        """tmp + fsync + rename under ``dir`` — a bundle file is
        either absent or complete.  The ``incident-write`` fault seam
        mimics a crash mid-write: half the tmp file lands, the
        'process dies', the rename never happens — the listing serves
        no half bundle (same contract as storage/stats_store.py)."""
        from pilosa_tpu.obs import faults
        os.makedirs(self.dir, exist_ok=True)
        path = os.path.join(self.dir, iid + ".json")
        tmp = path + ".tmp"
        payload = json.dumps(bundle, default=str)
        if faults.armed("incident-write"):
            with open(tmp, "w") as f:
                f.write(payload[: max(1, len(payload) // 2)])
            faults.fire("incident-write", path)
        with open(tmp, "w") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self._prune()

    def _prune(self) -> None:
        """Keep the newest ``max_bundles`` files on disk, and sweep
        torn ``.tmp`` debris (the single capture worker is the only
        writer and prune runs after its own rename, so any tmp seen
        here is a dead crash leftover)."""
        try:
            names = os.listdir(self.dir)
        except OSError:
            return
        files = sorted(f for f in names if f.startswith("inc-")
                       and f.endswith(".json"))
        doomed = files[: max(0, len(files) - self.max_bundles)]
        doomed += [f for f in names
                   if f.startswith("inc-") and f.endswith(".tmp")]
        for f in doomed:
            try:
                os.remove(os.path.join(self.dir, f))
            except OSError:
                pass

    # -- read surface --------------------------------------------------

    def list(self, limit: int = 50) -> list[dict]:
        """Newest-first bundle metadata — in-memory ring merged with
        the on-disk listing (a restarted node still serves the
        bundles its predecessor captured)."""
        with self._lock:
            out = {m["id"]: dict(m) for m in self._meta}
        if self.dir and os.path.isdir(self.dir):
            for f in os.listdir(self.dir):
                # .tmp files are torn writes — never listed
                if not f.startswith("inc-") or not f.endswith(".json"):
                    continue
                iid = f[:-5]
                if iid in out:
                    out[iid]["persisted"] = True
                    continue
                p = os.path.join(self.dir, f)
                try:
                    st = os.stat(p)
                    # id shape: inc-<ts>-<seq>-<token>-<trigger>;
                    # only the trigger may itself contain dashes
                    out[iid] = {"id": iid,
                                "time": st.st_mtime,
                                "trigger": iid.split("-", 4)[-1],
                                "detail": "",
                                "bytes": st.st_size,
                                "persisted": True}
                except OSError:
                    continue
        items = sorted(out.values(), key=lambda m: -m["time"])
        return items[: max(0, int(limit))]

    def fetch(self, iid: str) -> dict | None:
        """One full bundle by id — memory first, then disk."""
        with self._lock:
            for b in reversed(self._bundles):
                if b["id"] == iid:
                    return b
        if self.dir and "/" not in iid and os.sep not in iid:
            p = os.path.join(self.dir, iid + ".json")
            try:
                with open(p) as f:
                    return json.load(f)
            except (OSError, ValueError):
                return None
        return None

    def payload(self, limit: int = 50) -> dict:
        """The /debug/incidents listing payload (watchdog registry
        state rides along — stalls and bundles are one story)."""
        from pilosa_tpu.obs import watchdog
        return {"enabled": _enabled,
                "incidents": self.list(limit),
                "suppressed": dict(self.suppressed),
                "watchdog": watchdog.watches(),
                "dir": self.dir}

    def clear(self) -> None:
        """Test seam: forget in-memory state (disk untouched) and
        reset the rate limiter."""
        with self._lock:
            self._bundles.clear()
            self._meta.clear()
            self._last.clear()
            self.suppressed.clear()


def _jsonable(v, depth: int = 0):
    """Defensive JSON coercion for operator-supplied context dicts
    and cross-module payloads."""
    if depth > 6:
        return str(v)
    if isinstance(v, dict):
        return {str(k): _jsonable(x, depth + 1) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x, depth + 1) for x in v]
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


# ---------------------------------------------------------------------------
# process-global manager + module-level trigger entries
# ---------------------------------------------------------------------------

_manager: IncidentManager | None = None
_mgr_lock = threading.Lock()


def get() -> IncidentManager:
    global _manager
    m = _manager
    if m is not None:
        return m
    with _mgr_lock:
        if _manager is None:
            _manager = IncidentManager()
        return _manager


def swap(manager: IncidentManager | None) -> IncidentManager | None:
    """Test seam: replace the process manager, returning the prior
    one so fixtures restore exactly what they found."""
    global _manager
    with _mgr_lock:
        prev, _manager = _manager, manager
    return prev


def enabled() -> bool:
    return _enabled


def configure(enabled: bool | None = None, dir: str | None = None,
              min_interval_s: float | None = None,
              max_bundles: int | None = None,
              max_bundle_bytes: int | None = None,
              slo_burn_threshold: float | None = None,
              config_snapshot: dict | None = None) -> IncidentManager:
    """Apply the [incidents] config knobs.  ``enabled=None`` leaves
    the PILOSA_TPU_INCIDENTS env kill-switch in charge.  A dir change
    just points persistence at the new data dir (the in-memory ring
    carries over — bundles already captured stay fetchable)."""
    global _enabled
    if enabled is not None:
        _enabled = bool(enabled)
    m = get()
    if dir is not None:
        m.dir = dir or None
    if min_interval_s is not None:
        m.min_interval_s = float(min_interval_s)
    if max_bundles is not None:
        m.max_bundles = int(max_bundles)
    if max_bundle_bytes is not None:
        m.max_bundle_bytes = int(max_bundle_bytes)
    if slo_burn_threshold is not None:
        m.slo_burn_threshold = float(slo_burn_threshold)
    if config_snapshot is not None:
        m.config_snapshot = _jsonable(config_snapshot)
    return m


def report(trigger: str, detail: str = "",
           context: dict | None = None) -> bool:
    """The trigger hot path: no-op when the plane is off; otherwise
    one rate-limit check + a queue append (capture is async)."""
    if not _enabled:
        return False
    try:
        return get().report(trigger, detail, context)
    except Exception:
        return False  # forensics must never fail the caller


def note_slo(payload: dict) -> None:
    """SLO-plane hook (obs/slo.py evaluate): a burn rate at/over the
    threshold on a COVERED window is an incident — uncovered windows
    (short uptime, ring eviction) stay advisory."""
    if not _enabled:
        return
    try:
        thr = get().slo_burn_threshold
        if thr <= 0:
            return
        for name, slo in (payload.get("slos") or {}).items():
            for label, w in (slo.get("windows") or {}).items():
                if not w.get("window_covered"):
                    continue
                burn = float(w.get("burn_rate", 0.0))
                if burn >= thr:
                    report("slo-burn", detail=f"{name}:{label}",
                           context={"slo": name, "window": label,
                                    "burn_rate": burn,
                                    "threshold": thr,
                                    "bad": w.get("bad"),
                                    "total": w.get("total")})
                    return  # one bundle covers the whole evaluation
    except Exception:
        pass
