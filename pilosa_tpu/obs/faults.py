"""Fault-injection registry — named, armable failure points.

The reference proves its failure story with container-level chaos
(internal/clustertests drives pumba to pause/kill nodes); this build's
cluster is in-process, so the equivalent seam is a *registry of named
fault points* the production code consults at the exact places real
faults strike.  PR 5's ``inject_oom`` seam (memory/pressure.py) was
the prototype: one counter, one fault.  This module generalizes it —
any number of named points, armed by tests, by config
(``[faults] spec``), or by env (``PILOSA_TPU_FAULT_SPEC``), each with
an optional match substring, an activation budget, and a delay.

Fault points wired through the stack (the point name is the contract;
``detail`` is what ``match`` substring-tests against):

========================  ====================================================
``rpc-drop``              InternalClient: raise a connection error before the
                          request (detail: ``{uri}{path}``)
``rpc-delay``             InternalClient: sleep ``delay`` ms before the
                          request (same detail) — the slow-replica fault
``node-crash``            ClusterNode heartbeat loop: ``pause()`` the node
                          (detail: node id) — kill mid-traffic
``heartbeat-stall``       ClusterNode heartbeat loop: skip beats so the lease
                          expires while the node still serves (detail: node id)
``torn-write``            TranslateStore append: write half the record and
                          stop, simulating a crash mid-append (detail: path)
``device-oom``            memory/pressure.guarded dispatch (the inject_oom
                          seam, now registry-backed)
``serving-dispatch``      serving fused dispatch: fail the multi-program so
                          every rider takes the per-caller direct fallback
``dax-rpc``               DAX queryer worker fan-out (detail: worker uri)
``crash-post-append``     Fragment delta log: die right after the in-memory
                          delta entry landed (detail:
                          ``index/field/view/shard``) — the window between
                          append and any durability
``wal-torn``              IndexStorage WAL sync: commit, then truncate the
                          shard WAL mid-frame and drop the handle — a crash
                          while the commit's frames were partially on disk
                          (detail: shard file path); native recovery drops
                          the torn transaction on reopen
``crash-pre-checkpoint``  IndexStorage WAL sync: die after the WAL fsync but
                          before the checkpoint (same detail) — durable yet
                          unacked, so replay must be idempotent
``device-patch``          TileStackCache patcher (whole-entry + paged delta
                          fn): fail the in-place device patch; the cache
                          falls back to a rebuild from live rows
``crash-pre-commit``      StreamSource.commit: die after the batch landed but
                          before the consumer offsets commit (detail:
                          ``topic@group``) — the exactly-once replay window
``ingest-window-stall``   StreamWriter window loop: delay rules stall the
                          admission window (backpressure drills); error
                          rules crash the whole window pre-apply (detail:
                          comma-joined index names)
``transfer-interrupted``  Rebalance SNAPSHOT-COPY / DELTA-CHASE: the
                          transfer dies between block/row pushes (detail:
                          ``index/field/view/shard->recipient``) — proves
                          a crashed migration resumes or rolls back with
                          the donor still the one write owner
``recipient-died``        Rebalance block push: the recipient vanishes
                          mid-copy (detail: ``uri index/field/...``) —
                          same rollback contract as transfer-interrupted
``fence-crash``           RebalanceController: die after the donor fences
                          (writes blocked) but BEFORE the ownership flip
                          (detail: ``partition=N``) — rollback must lift
                          the fences so blocked writers proceed on the
                          donor, and no epoch has zero or two owners
``audit-corrupt``         Correctness-audit drill (obs/audit.py): flip one
                          bit in a served result (detail:
                          ``serve:{route}:{index}``), a stored ResultCache
                          entry (detail: ``cache:{index}``), or a maintained
                          standing result (detail: ``standing:{sid}``) —
                          the injection that PROVES the shadow/cache/
                          standing verifiers detect; armed only via the
                          test/config API like every other point
``blob-unavailable``      Blob shard store (storage/blob.py): every backend
                          op raises (detail: ``op:key``) — the tier
                          degrades to typed 503s at the worker surface,
                          never silent partial results
``blob-torn-upload``      Blob put dies after writing HALF the object and
                          BEFORE the manifest flip (detail: object key) —
                          proves a torn upload is never visible to readers
``worker-hydrate-crash``  ComputeNode hydration (dax/worker.py): die at
                          the start of a shard hydrate (detail:
                          ``addr:table/shard``) — no partial residency;
                          the next touch restarts from the manifest
``scale-event-interrupted``  Autoscaler migration (dax/controller.py):
                          die between migration phases (detail:
                          ``table/shard->addr:phase``) — an interrupted
                          scale event rolls back its fence and the next
                          reconcile resumes or completes the move
========================  ====================================================

Arming:

- tests: ``faults.inject("rpc-drop", match="10101", times=3)``;
  delay-only rules via ``delay_s`` with ``error=False`` (implied when
  a delay is given without ``error=True``).
- config/env: a spec string, rules separated by ``;``, params by
  ``,``: ``rpc-delay@10101,delay=200;node-crash@node2,times=1``.
  ``delay`` is milliseconds; ``times`` defaults to 1 (``times=0`` or
  ``times=-1`` = unlimited); a rule with a delay and no explicit
  ``error=1`` only delays.

``fire(point, detail)`` (raising/sleeping) and ``take(point, detail)``
(non-raising consume, for seams that enact the fault themselves) are
the two hot-path entries; with no rules armed for a point they cost
one dict lookup, so the points stay compiled into production paths.
"""

from __future__ import annotations

import os
import threading
import time


class InjectedFault(ConnectionError):
    """Raised by an armed error-mode fault point.  Subclasses
    ConnectionError so network-shaped injections ride the exact
    failover/retry paths a real connection failure would."""

    def __init__(self, point: str, detail: str = ""):
        super().__init__(f"injected fault {point!r}"
                         + (f" at {detail!r}" if detail else ""))
        self.point = point
        self.detail = detail


class _Rule:
    __slots__ = ("point", "match", "remaining", "delay_s", "error",
                 "source", "fired")

    def __init__(self, point: str, match: str | None, times: int,
                 delay_s: float, error: bool, source: str):
        self.point = point
        self.match = match
        self.remaining = times  # <= 0 means unlimited
        self.delay_s = delay_s
        self.error = error
        self.source = source
        self.fired = 0

    def to_dict(self) -> dict:
        return {"point": self.point, "match": self.match,
                "remaining": self.remaining, "delay_ms":
                round(self.delay_s * 1e3, 3), "error": self.error,
                "source": self.source, "fired": self.fired}


_lock = threading.Lock()
# point -> list[_Rule]; empty dict = the common no-faults fast path
_rules: dict[str, list[_Rule]] = {}
# source -> last spec string armed via configure(); an UNCHANGED spec
# re-applied (every Server/node construction re-runs config) must not
# clear-and-re-arm, or consumed budgets reset — a times=1 node-crash
# drill would re-kill the freshly rejoined node forever
_last_spec: dict[str, str] = {}


def inject(point: str, match: str | None = None, times: int = 1,
           delay_s: float = 0.0, error: bool | None = None,
           source: str = "test") -> None:
    """Arm a fault point.  ``times`` activations (<=0 unlimited);
    ``delay_s`` sleeps before acting; ``error`` None means "raise
    unless this is a delay-only rule"."""
    if error is None:
        error = delay_s <= 0
    rule = _Rule(point, match, times, delay_s, error, source)
    with _lock:
        _rules.setdefault(point, []).append(rule)


def clear(point: str | None = None, source: str | None = None) -> None:
    """Disarm rules — all of them, one point's, or one source's."""
    with _lock:
        if point is None and source is None:
            _rules.clear()
            _last_spec.clear()
            return
        if source is not None:
            _last_spec.pop(source, None)
        for p in list(_rules):
            if point is not None and p != point:
                continue
            kept = [r for r in _rules[p]
                    if source is not None and r.source != source]
            if kept:
                _rules[p] = kept
            else:
                del _rules[p]


def active() -> list[dict]:
    """Armed rules as dicts (the /debug/faults payload)."""
    with _lock:
        return [r.to_dict() for rules in _rules.values()
                for r in rules]


def _consume(point: str, detail: str) -> _Rule | None:
    """Match + consume one activation; None when nothing is armed."""
    if point not in _rules:  # lock-free fast path (GIL-atomic lookup)
        return None
    with _lock:
        rules = _rules.get(point)
        if not rules:
            return None
        for r in rules:
            if r.match is not None and r.match not in detail:
                continue
            r.fired += 1
            if r.remaining > 0:
                r.remaining -= 1
                if r.remaining == 0:
                    rules.remove(r)
                    if not rules:
                        del _rules[point]
            return r
    return None


def armed(point: str) -> bool:
    """Lock-free check whether ANY rule is armed at a point (the
    GIL-atomic dict lookup `_consume` fast-paths on).  For hot-path
    seams whose fire() detail string is itself costly to build —
    guard the construction, then fire normally."""
    return point in _rules


def take(point: str, detail: str = "") -> bool:
    """Consume an activation WITHOUT raising — for seams that enact
    the fault themselves (skip a heartbeat, tear a write, fake an
    OOM).  Applies the rule's delay; returns True when armed."""
    r = _consume(point, detail)
    if r is None:
        return False
    from pilosa_tpu.obs import metrics
    metrics.FAULTS_TOTAL.inc(point=point)
    if r.delay_s > 0:
        time.sleep(r.delay_s)
    return True


def fire(point: str, detail: str = "") -> None:
    """Consult a fault point: sleep on delay rules, raise
    InjectedFault on error rules, no-op when nothing matches."""
    r = _consume(point, detail)
    if r is None:
        return
    from pilosa_tpu.obs import metrics
    metrics.FAULTS_TOTAL.inc(point=point)
    if r.delay_s > 0:
        time.sleep(r.delay_s)
    if r.error:
        raise InjectedFault(point, detail)


def configure(spec: str, source: str = "config") -> int:
    """(Re)arm fault points from a spec string (see module docstring);
    replaces any rules previously armed from the same source, leaving
    test-armed rules alone.  An UNCHANGED spec is a no-op so repeated
    config application (one per node/server construction) preserves
    already-consumed budgets.  Returns the rule count armed."""
    spec = spec or ""
    with _lock:
        if _last_spec.get(source) == spec:
            return 0
    clear(source=source)
    n = 0
    for entry in (spec or "").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        head, *params = [p.strip() for p in entry.split(",")]
        point, _, match = head.partition("@")
        times, delay_s, error = 1, 0.0, None
        for p in params:
            k, _, v = p.partition("=")
            k = k.strip()
            v = v.strip()
            if k == "times":
                times = int(v)
            elif k == "delay":
                delay_s = float(v) / 1e3
            elif k == "error":
                error = v in ("1", "true", "yes", "on")
            else:
                raise ValueError(f"unknown fault param {k!r} in "
                                 f"{entry!r}")
        inject(point.strip(), match=match or None, times=times,
               delay_s=delay_s, error=error, source=source)
        n += 1
    with _lock:
        _last_spec[source] = spec
    return n


# env-armed faults apply as soon as any fault point is consulted —
# a spec exported before process start needs no config file
_env_spec = os.environ.get("PILOSA_TPU_FAULT_SPEC", "")
if _env_spec:
    configure(_env_spec, source="env")
