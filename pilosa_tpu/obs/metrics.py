"""Central metrics registry with prometheus text exposition.

Reference: metrics.go (one central file defining every counter/gauge/
histogram, e.g. metrics.go:86-126) exposed at ``/metrics``
(http_handler.go:495) and as JSON at ``/metrics.json``.  We keep the
same shape: a process-global ``registry`` holding named metrics with
label support, rendered in prometheus text format without any external
client library.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left

_DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def _label_key(labels: dict[str, str] | None) -> tuple:
    if not labels:
        return ()
    return tuple(sorted(labels.items()))


def _fmt_labels(key: tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + inner + "}"


class _Metric:
    kind = ""

    def __init__(self, name: str, help_: str):
        self.name = name
        self.help = help_
        self._lock = threading.Lock()

    def render(self) -> list[str]:
        raise NotImplementedError


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help_: str = ""):
        super().__init__(name, help_)
        self._vals: dict[tuple, float] = {}

    def inc(self, n: float = 1.0, **labels):
        k = _label_key(labels)
        with self._lock:
            self._vals[k] = self._vals.get(k, 0.0) + n

    def value(self, **labels) -> float:
        return self._vals.get(_label_key(labels), 0.0)

    def total(self, **labels) -> float:
        """Sum across every label set CONTAINING the given labels
        (all series when none given) — the SLO plane sums typed-error
        counters across their free labels (class, tenant, ...)."""
        sub = set(labels.items())
        with self._lock:
            return sum(v for k, v in self._vals.items()
                       if sub <= set(k))

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} counter"]
        with self._lock:
            vals = dict(self._vals)
        for k in sorted(vals):
            out.append(f"{self.name}{_fmt_labels(k)} {vals[k]:g}")
        return out


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name: str, help_: str = ""):
        super().__init__(name, help_)
        self._vals: dict[tuple, float] = {}

    def set(self, v: float, **labels):
        with self._lock:
            self._vals[_label_key(labels)] = float(v)

    def add(self, n: float = 1.0, **labels):
        k = _label_key(labels)
        with self._lock:
            self._vals[k] = self._vals.get(k, 0.0) + n

    def value(self, **labels) -> float:
        return self._vals.get(_label_key(labels), 0.0)

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} gauge"]
        with self._lock:
            vals = dict(self._vals)
        for k in sorted(vals):
            out.append(f"{self.name}{_fmt_labels(k)} {vals[k]:g}")
        return out


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help_: str = "",
                 buckets: tuple = _DEFAULT_BUCKETS,
                 quantiles: tuple = ()):
        super().__init__(name, help_)
        self.buckets = tuple(sorted(buckets))
        # bucket-interpolated quantiles rendered as gauge series
        # (`{name}_p50` etc.) so dashboards get p50/p95/p99 without a
        # scrape-side histogram_quantile()
        self.quantiles = tuple(quantiles)
        self._counts: dict[tuple, list[int]] = {}
        self._sum: dict[tuple, float] = {}
        self._n: dict[tuple, int] = {}
        # newest exemplar per (label set, bucket): (value, id, time) —
        # rendered OpenMetrics-style so a dashboard histogram links
        # back to a concrete /debug/queries trace id
        self._exemplars: dict[tuple, tuple] = {}

    def observe(self, v: float, exemplar: str | None = None, **labels):
        k = _label_key(labels)
        with self._lock:
            if k not in self._counts:
                self._counts[k] = [0] * (len(self.buckets) + 1)
            # first bucket whose upper bound (le) admits v; overflow
            # values land in the +Inf slot at index len(buckets)
            i = bisect_left(self.buckets, v)
            self._counts[k][i] += 1
            self._sum[k] = self._sum.get(k, 0.0) + v
            self._n[k] = self._n.get(k, 0) + 1
            if exemplar is not None:
                self._exemplars[(k, i)] = (v, str(exemplar), time.time())

    def observe_batch(self, items):
        """Observe several (value, labels, exemplar|None) samples
        under ONE lock acquisition.  A contended threading.Lock costs
        ~20us of GIL ping-pong per acquisition (vs ~0.3us of work), so
        hot-path producers (the flight recorder) buffer samples per
        thread and flush them here in batches."""
        now = time.time()
        with self._lock:
            for v, labels, exemplar in items:
                k = _label_key(labels)
                if k not in self._counts:
                    self._counts[k] = [0] * (len(self.buckets) + 1)
                i = bisect_left(self.buckets, v)
                self._counts[k][i] += 1
                self._sum[k] = self._sum.get(k, 0.0) + v
                self._n[k] = self._n.get(k, 0) + 1
                if exemplar is not None:
                    self._exemplars[(k, i)] = (v, str(exemplar), now)

    def exemplar(self, **labels):
        """Newest (value, trace_id) exemplar for a label set, or None."""
        k = _label_key(labels)
        with self._lock:
            best = None
            for (lk, _i), (v, eid, ts) in self._exemplars.items():
                if lk == k and (best is None or ts > best[2]):
                    best = (v, eid, ts)
        return None if best is None else (best[0], best[1])

    def count(self, **labels) -> int:
        return self._n.get(_label_key(labels), 0)

    def sum(self, **labels) -> float:
        """Cumulative observed sum for a label set — the statistics
        catalog derives measured per-byte costs from phase sums."""
        return self._sum.get(_label_key(labels), 0.0)

    def count_le(self, v: float, **labels) -> float:
        """Estimated observations <= v (linear interpolation within
        v's bucket, prometheus histogram_quantile's inverse) — the SLO
        plane's good-event count at the latency threshold.  A
        threshold at/past the last finite bound counts only the
        finite buckets: +Inf-bucket observations are indistinguishable
        from arbitrarily slow ones and must stay "bad", or a 60s
        outlier would vanish under a 10s threshold."""
        k = _label_key(labels)
        with self._lock:
            counts = list(self._counts.get(k, ()))
            n = self._n.get(k, 0)
        if not counts or n == 0:
            return 0.0
        cum, lo = 0.0, 0.0
        for i, ub in enumerate(self.buckets):
            c = counts[i]
            if v < ub:
                # v inside this bucket: linear share of its count
                frac = (v - lo) / (ub - lo) if ub > lo else 0.0
                return cum + c * max(0.0, min(1.0, frac))
            cum += c
            lo = ub
        return cum  # overflow-bucket observations stay > v

    def quantile(self, q: float, **labels) -> float:
        """Bucket-interpolated quantile estimate (prometheus
        histogram_quantile semantics: linear within the bucket; the
        +Inf bucket clamps to the largest finite bound)."""
        k = _label_key(labels)
        with self._lock:
            counts = list(self._counts.get(k, ()))
            n = self._n.get(k, 0)
        if not counts or n == 0:
            return 0.0
        target = q * n
        cum, lo = 0.0, 0.0
        for i, ub in enumerate(self.buckets):
            c = counts[i]
            if c and cum + c >= target:
                return lo + (ub - lo) * (target - cum) / c
            cum += c
            lo = ub
        return self.buckets[-1] if self.buckets else 0.0

    def render(self, openmetrics: bool = False) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        with self._lock:
            counts = {k: list(v) for k, v in self._counts.items()}
            sums = dict(self._sum)
            ns = dict(self._n)
            # snapshot under the SAME lock as the counts so an
            # exemplar never points at a bucket whose rendered count
            # predates it; rendered only under OpenMetrics — the
            # classic text-format 0.0.4 parser treats a mid-line '#'
            # as a parse error and would fail the whole scrape
            exemplars = dict(self._exemplars) if openmetrics else {}
        for k in sorted(ns):
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += counts[k][i]
                lk = k + (("le", f"{b:g}"),)
                line = f"{self.name}_bucket{_fmt_labels(lk)} {cum}"
                ex = exemplars.get((k, i))
                if ex is not None:
                    # OpenMetrics exemplar syntax: links the bucket to
                    # a flight-recorder trace id (/debug/queries)
                    line += (f' # {{trace_id="{ex[1]}"}} {ex[0]:g} '
                             f"{ex[2]:.3f}")
                out.append(line)
            lk = k + (("le", "+Inf"),)
            line = f"{self.name}_bucket{_fmt_labels(lk)} {ns[k]}"
            ex = exemplars.get((k, len(self.buckets)))
            if ex is not None:
                line += (f' # {{trace_id="{ex[1]}"}} {ex[0]:g} '
                         f"{ex[2]:.3f}")
            out.append(line)
            out.append(f"{self.name}_sum{_fmt_labels(k)} {sums[k]:g}")
            out.append(f"{self.name}_count{_fmt_labels(k)} {ns[k]}")
        for q in self.quantiles:
            qn = f"{self.name}_p{q * 100:g}"
            out.append(f"# HELP {qn} {self.help} (q={q:g} estimate)")
            out.append(f"# TYPE {qn} gauge")
            for k in sorted(ns):
                v = self.quantile(q, **dict(k))
                out.append(f"{qn}{_fmt_labels(k)} {v:g}")
        return out


class MetricsRegistry:
    def __init__(self):
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get(name, Counter, lambda: Counter(name, help_))

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get(name, Gauge, lambda: Gauge(name, help_))

    def histogram(self, name: str, help_: str = "",
                  buckets: tuple = _DEFAULT_BUCKETS,
                  quantiles: tuple = ()) -> Histogram:
        m = self._get(name, Histogram,
                      lambda: Histogram(name, help_, buckets, quantiles))
        if m.buckets != tuple(sorted(buckets)):
            raise ValueError(
                f"histogram {name} already registered with different "
                f"buckets {m.buckets}")
        if m.quantiles != tuple(quantiles):
            # same contract as buckets: a silent drop would make the
            # caller's _pNN gauge series never render
            raise ValueError(
                f"histogram {name} already registered with different "
                f"quantiles {m.quantiles}")
        return m

    def _get(self, name, cls, factory=None):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory() if factory else cls(name, "")
                self._metrics[name] = m
            assert isinstance(m, cls), f"metric {name} is {type(m)}"
            return m

    def render_text(self, openmetrics: bool = False) -> str:
        """Prometheus text exposition.  openmetrics=True additionally
        renders histogram exemplars (legal only under the
        application/openmetrics-text content type — callers negotiate
        via the Accept header)."""
        lines = []
        for name in sorted(self._metrics):
            m = self._metrics[name]
            if isinstance(m, Histogram):
                lines.extend(m.render(openmetrics=openmetrics))
            else:
                lines.extend(m.render())
        return "\n".join(lines) + "\n"

    def render_json(self) -> dict:
        out = {}
        with self._lock:
            items = sorted(self._metrics.items())
        for name, m in items:
            with m._lock:
                if isinstance(m, (Counter, Gauge)):
                    out[name] = {_fmt_labels(k) or "": v
                                 for k, v in m._vals.items()}
                elif isinstance(m, Histogram):
                    out[name] = {_fmt_labels(k) or "":
                                 {"count": m._n[k], "sum": m._sum[k]}
                                 for k in m._n}
        return out


# Process-global registry + the centrally defined metrics the engine
# uses (metrics.go analog; same naming style, pilosa_ prefix).
registry = MetricsRegistry()

QUERY_TOTAL = registry.counter(
    "pilosa_query_total", "Total PQL queries executed")
QUERY_DURATION = registry.histogram(
    "pilosa_query_duration_seconds", "PQL query latency")
SQL_TOTAL = registry.counter(
    "pilosa_sql_total", "Total SQL queries executed")
SQL_PUSHDOWN = registry.counter(
    "pilosa_sql_pushdown_total",
    "SQL planner operator decisions: op (count/sum/groupby/distinct/"
    "extract/join/...) by outcome (pushdown = rides the fused "
    "serving plane; host = solo host-side execution)")
SQL_PLAN_COST = registry.histogram(
    "pilosa_sql_plan_cost_ms",
    "SQL statement planning cost in milliseconds (parse-to-plan-op, "
    "cost-based decisions included)",
    buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0,
             50.0, 100.0))
IMPORT_TOTAL = registry.counter(
    "pilosa_import_total", "Total import requests")
IMPORTED_BITS = registry.counter(
    "pilosa_imported_bits_total", "Total bits set via imports")
HTTP_REQUESTS = registry.counter(
    "pilosa_http_request_total", "HTTP requests by route/status")
JOB_TOTAL = registry.counter(
    "pilosa_job_total", "Per-shard executor jobs run")
STACKED_QUERIES = registry.counter(
    "pilosa_stacked_queries_total",
    "Query ops routed to the stacked mesh engine vs the shard loop")
GROUPBY_KERNEL = registry.counter(
    "pilosa_groupby_kernel_total",
    "GroupBy queries served by the fused Pallas kernel path")
GROUPBY_ONEPASS = registry.counter(
    "pilosa_groupby_onepass_total",
    "GroupBy queries served by the one-pass group-code histogram")
GROUPBY_FUSED = registry.counter(
    "pilosa_groupby_fused_total",
    "One-pass GroupBy dispatches served by the fused int8 MXU "
    "single-pass kernel, by path (onepass/onepass_mesh/batched)")

# -- tile-stack maintenance (executor/stacked.py TileStackCache) --
# Outcomes: hit (fresh entry), miss (any non-hit), patch (stale entry
# delta-patched on device), rebuild (full host restack + upload),
# page_rebuild (fresh entry, evicted pages re-uploaded), wait
# (single-flight follower served by another thread's build), too_big
# (entry alone exceeds the budget — served, never retained), denied
# (ledger reservation refused under pressure — served transiently).
STACK_CACHE = registry.counter(
    "pilosa_stack_cache_total",
    "Tile-stack cache accesses by outcome (hit/miss/patch/rebuild/"
    "page_rebuild/wait/too_big/denied)")
# patched vs rebuilt bytes attribute the write-path win directly: a
# healthy patch path keeps patched ≪ rebuilt-equivalent stack bytes
STACK_MAINT_BYTES = registry.counter(
    "pilosa_stack_maintenance_bytes_total",
    "Device stack maintenance traffic by kind (patched/rebuilt)")

# -- HBM residency (memory/: budget ledger, paged stacks, OOM backstop) --
MEM_BUDGET = registry.gauge(
    "pilosa_memory_budget_bytes",
    "Device-memory budget the process ledger enforces")
MEM_RESIDENT = registry.gauge(
    "pilosa_memory_resident_bytes",
    "Ledger-accounted resident device bytes by client")
MEM_DEVICE_RESIDENT = registry.gauge(
    "pilosa_memory_device_resident_bytes",
    "Device-labeled resident bytes per serving-mesh slot (pages "
    "placed by memory/placement.py; each slot is budget/N-bounded)")
MEM_RECLAIMS = registry.counter(
    "pilosa_memory_reclaim_total",
    "Cross-client reclaim sweeps by trigger (reserve/oom/shrink)")
MEM_RECLAIMED = registry.counter(
    "pilosa_memory_reclaimed_bytes_total",
    "Bytes shed under ledger pressure by client")
MEM_DENIED = registry.counter(
    "pilosa_memory_reserve_denied_total",
    "Reservations denied (served transiently, not retained) by client")
OOM_TOTAL = registry.counter(
    "pilosa_device_oom_total",
    "Device RESOURCE_EXHAUSTED events by outcome "
    "(caught/retry_ok/host_fallback/raised)")
STACK_PAGES = registry.counter(
    "pilosa_stack_pages_total",
    "Paged stack-cache page events (build/evict/patch) by page "
    "encoding (dense/packed/run)")
PAGE_ENCODE = registry.counter(
    "pilosa_page_encode_total",
    "Page encoding decisions by from/to container kind and reason "
    "(build/drift/patch)")
PREFETCH_TOTAL = registry.counter(
    "pilosa_prefetch_total",
    "Prefetcher warm attempts by outcome "
    "(warmed/noop/skipped_pressure/error)")

# -- jit executable caches (executor/stacked.py _JIT_CACHE/_GB_KERNEL_JIT) --
# the stack cache's counters shipped in PR 3; these caches used to
# evict invisibly
JIT_CACHE = registry.counter(
    "pilosa_jit_cache_total",
    "Jit executable cache events by cache (plan/groupby_kernel) and "
    "event (insert/evict)")
JIT_CACHE_ENTRIES = registry.gauge(
    "pilosa_jit_cache_entries",
    "Jit executable cache occupancy by cache")

# -- serving path (executor/serving.py: micro-batcher + result cache) --
SERVING_LATENCY = registry.histogram(
    "pilosa_serving_latency_seconds",
    "End-to-end serving-path query latency",
    quantiles=(0.5, 0.95, 0.99))
SERVING_BATCH_SIZE = registry.histogram(
    "pilosa_serving_batch_size",
    "Concurrent queries coalesced per admission window (batch occupancy)",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128),
    quantiles=(0.5, 0.95, 0.99))
SERVING_BATCH_WAIT = registry.histogram(
    "pilosa_serving_batch_wait_seconds",
    "Admission-window wait before a batch dispatches")
SERVING_QUEUE_DEPTH = registry.gauge(
    "pilosa_serving_queue_depth",
    "Queries waiting for batch admission right now")
RESULT_CACHE = registry.counter(
    "pilosa_result_cache_total",
    "Versioned result-cache lookups by outcome (hit/miss/bypass/write)")
SERVING_BATCHED = registry.counter(
    "pilosa_serving_batched_total",
    "Serving-path queries by execution route (fused/direct/cached)")

# -- ragged dispatch + QoS admission (executor/ragged.py, sched.py) --
SERVING_DISPATCH = registry.counter(
    "pilosa_serving_dispatch_total",
    "Fused serving device dispatches by kind (ragged = one cross-"
    "index page-table program per batch; group = one multi program "
    "per (index, shards) group)")
ADMISSION_TOTAL = registry.counter(
    "pilosa_serving_admission_total",
    "Serving admission decisions by class (point/heavy) and outcome "
    "(admitted/shed/expired)")
TENANT_QUEUE_DEPTH = registry.gauge(
    "pilosa_serving_tenant_queue_depth",
    "Heavy-class queries queued per tenant in the weighted fair "
    "queue right now")

# -- streaming write plane (ingest/stream.py + ingest/kafka.py) --
INGEST_WINDOWS = registry.counter(
    "pilosa_ingest_windows_total",
    "Coalesced ingest windows by outcome (landed/failed)")
INGEST_WINDOW_OCCUPANCY = registry.histogram(
    "pilosa_ingest_window_occupancy",
    "Concurrent submits coalesced per ingest window",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096),
    quantiles=(0.5, 0.95, 0.99))
INGEST_WINDOW_MUTATIONS = registry.histogram(
    "pilosa_ingest_window_mutations",
    "Individual mutations (bits/values) coalesced per ingest window",
    buckets=(1, 8, 64, 512, 4096, 32768, 262144, 2097152),
    quantiles=(0.5, 0.95, 0.99))
INGEST_MUTATIONS = registry.counter(
    "pilosa_ingest_mutations_total",
    "Mutations durably landed through the streaming write plane")
INGEST_ACK_LATENCY = registry.histogram(
    "pilosa_ingest_ack_seconds",
    "Submit-to-durable-ack latency through the write plane",
    quantiles=(0.5, 0.95, 0.99))
INGEST_SHED = registry.counter(
    "pilosa_ingest_shed_total",
    "Write submissions shed by backpressure (typed 503) by tenant")
INGEST_REPLAYED = registry.counter(
    "pilosa_ingest_replayed_total",
    "Records re-delivered after a crash (offsets uncommitted) by topic")
INGEST_QUEUE_DEPTH = registry.gauge(
    "pilosa_ingest_queue_depth",
    "Mutations waiting for window admission right now")

# -- failure-tolerance plane (obs/faults.py, cluster/) --
CLUSTER_EVENTS = registry.counter(
    "pilosa_cluster_events_total",
    "Cluster failure-plane events "
    "(node_down/node_rejoin/failover/hedge_fired/hedge_won/"
    "load_shed/partial)")
# -- online resharding (cluster/rebalance.py) --
REBALANCE_TOTAL = registry.counter(
    "pilosa_rebalance_total",
    "Online-rebalance state-machine transitions by phase "
    "(copy/chase/fence/release/commit) and outcome "
    "(ok/error/rolled_back)")
REBALANCE_BYTES = registry.counter(
    "pilosa_rebalance_bytes_total",
    "Bytes moved by live shard migration by kind (copied = "
    "snapshot blocks, delta_replayed = chase rows, released = "
    "donor fragment bytes freed)")
HEARTBEAT_AGE = registry.gauge(
    "pilosa_cluster_heartbeat_age_seconds",
    "Seconds since each node's last heartbeat (by node)")
FAULTS_TOTAL = registry.counter(
    "pilosa_fault_injections_total",
    "Armed fault-point activations by point (obs/faults.py)")

# -- flight recorder (obs/flight.py) --
# One histogram per engine phase (labeled), with exemplar trace ids
# pointing into /debug/queries: plan_build, compile (jit trace +
# XLA compile dispatches), execute (cached-executable dispatches,
# timed through block_until_ready), stack_hit/patch/rebuild/wait
# (tile-stack cache outcomes; rebuild ~ host->device upload), demux,
# cache_lookup (result-cache snapshot walk), batch (total time in the
# micro-batcher), wait (batch minus attributed device phases).
PHASE_DURATION = registry.histogram(
    "pilosa_query_phase_seconds",
    "Per-query engine phase durations by phase (flight recorder)",
    quantiles=(0.5, 0.95, 0.99))

# -- roofline attribution (obs/roofline.py) --
# bytes-touched / execute-seconds per op family, against a measured
# (STREAM-style probe) or configured peak — ROADMAP item 3's "within
# 4x of the bandwidth bound" as a readable gauge
DEVICE_BW_GBPS = registry.gauge(
    "pilosa_device_bandwidth_gbps",
    "Achieved device memory bandwidth per op family "
    "(operand bytes / execute-phase seconds, cumulative)")
DEVICE_BW_FRACTION = registry.gauge(
    "pilosa_device_bandwidth_fraction",
    "Fraction of peak device bandwidth achieved per op family")
DEVICE_PEAK_GBPS = registry.gauge(
    "pilosa_device_peak_gbps",
    "Peak device bandwidth (PILOSA_TPU_PEAK_GBPS override or the "
    "measured STREAM-style startup probe)")

# -- statistics catalog (obs/stats.py + storage/stats_store.py) --
# persisted flight/roofline telemetry feeding the engine's cost
# decisions; the sentinel gauge carries the window/baseline ratio
# while a fingerprint regresses and 0 after recovery
STATS_FOLDS = registry.counter(
    "pilosa_stats_folds_total",
    "Flight records folded into the statistics catalog")
STATS_PROFILES = registry.gauge(
    "pilosa_stats_profiles",
    "Plan-fingerprint profiles the statistics catalog tracks")
STATS_PERSIST = registry.counter(
    "pilosa_stats_persist_total",
    "Statistics-store events "
    "(snapshot/tail/load/torn_drop/corrupt_drop)")
STATS_ADMISSION = registry.counter(
    "pilosa_stats_admission_total",
    "Cost-based admission classifications by source (profile = "
    "measured fingerprint cost; static = query-kind fallback) and "
    "class")
PERF_REGRESSION = registry.gauge(
    "pilosa_perf_regression",
    "Per-fingerprint perf-regression sentinel: current-window / "
    "baseline ratio while firing, 0 after recovery")

# -- incident forensics plane (obs/incidents.py + obs/watchdog.py) --
INCIDENTS_TOTAL = registry.counter(
    "pilosa_incidents_total",
    "Incident-bundle events by trigger (slo-burn/perf-regression/"
    "watchdog-stall/device-oom/batch-leader-exception/ingest-crash) "
    "and outcome (captured/suppressed/error)")
WATCHDOG_STALLS = registry.counter(
    "pilosa_watchdog_stalls_total",
    "Stall-watchdog detections by loop (serving-batcher/"
    "ingest-window/rebalance-controller/maintenance-ticker/"
    "heartbeat:*)")

# -- continuous correctness auditing (obs/audit.py) --
AUDIT_TOTAL = registry.counter(
    "pilosa_audit_total",
    "Correctness-audit events by verifier kind (shadow/cache/"
    "standing/replica) and outcome (sampled/match/mismatch/"
    "stale_skip/shed/unguarded/repaired/error)")

# -- SLO burn-rate plane (obs/slo.py) --
SLO_BURN_RATE = registry.gauge(
    "pilosa_slo_burn_rate",
    "Error-budget burn rate per SLO and window (1.0 = spending the "
    "budget exactly at the sustainable rate)")
SLO_BUDGET_REMAINING = registry.gauge(
    "pilosa_slo_error_budget_remaining",
    "Error-budget fraction left over the longest configured window "
    "per SLO")

# -- temporal analytics (models/timeq.py + executor/standing.py) --
# quantum-cover plan ops, rollup folds, and the standing-query
# registry's maintenance outcomes (incremental = O(delta) patch,
# fallback = declared structural re-execution, noop = no relevant
# delta)
TIMEQ_QCOVER_TOTAL = registry.counter(
    "pilosa_timeq_qcover_total",
    "Multi-view time ranges planned as quantum-cover fused ops "
    "(one single-view stack leaf per cover member)")
TIMEQ_ROLLUP_TOTAL = registry.counter(
    "pilosa_timeq_rollup_total",
    "Completed fine-quantum views OR-folded into their coarser "
    "parent views by the rollup tick")
STANDING_REGISTERED = registry.gauge(
    "pilosa_standing_registered",
    "Live standing-query registrations")
STANDING_MAINTAIN = registry.counter(
    "pilosa_standing_maintain_total",
    "Standing-query maintenance passes by outcome "
    "(incremental/fallback/noop)")
STANDING_MAINTAIN_SECONDS = registry.histogram(
    "pilosa_standing_maintain_seconds",
    "Wall seconds per standing-query maintenance pass",
    buckets=(0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5,
             1.0),
    quantiles=(0.5, 0.95, 0.99))

# -- disaggregated DAX tier (storage/blob.py + dax/worker.py +
# dax/controller.py reconcile loop) --
DAX_HYDRATIONS = registry.counter(
    "pilosa_dax_hydrations_total",
    "Worker shard hydrations by outcome (full = restored and "
    "retained under the ledger, transient = served without "
    "retention after a ledger denial, replay = resident tail "
    "replay, error = hydrate crashed and left the shard cold)")
DAX_BLOB_BYTES = registry.counter(
    "pilosa_dax_blob_bytes_total",
    "Blob shard-store transfer bytes by op (get/put/delete), "
    "manifests included")
DAX_RESIDENT_SHARDS = registry.gauge(
    "pilosa_dax_resident_shards",
    "Shards currently materialized on a worker, per worker")
DAX_COLD_SHARDS = registry.gauge(
    "pilosa_dax_cold_shards",
    "Shards assigned to a worker but not resident (hydrate on "
    "first touch), per worker")
DAX_SCALE_EVENTS = registry.counter(
    "pilosa_dax_scale_events_total",
    "Autoscaler decisions by direction (out/in) and outcome "
    "(done/partial/failed/skipped)")
