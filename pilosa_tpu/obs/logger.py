"""Leveled logger with a nop default (reference: logger/logger.go —
``Logger`` interface with Printf-style Debugf/Infof/Warnf/Errorf and a
``NopLogger``; we keep the same four levels and the nop), plus a
bounded in-memory ring of recent emitted lines — the black-box log
tail incident bundles attach (obs/incidents.py) and
``/debug/logs?limit=`` serves.  Lines keep their ``trace=`` stamps,
so a bundle's tail greps straight to its flight records."""

from __future__ import annotations

import sys
import threading
import time
from collections import deque
from typing import IO

DEBUG, INFO, WARN, ERROR = 10, 20, 30, 40
_LEVEL_NAMES = {DEBUG: "DEBUG", INFO: "INFO", WARN: "WARN", ERROR: "ERROR"}


class LogRing:
    """Bounded ring of recently emitted log lines.  The append is
    lock-free (deque with maxlen is GIL-atomic) — same budget class
    as the flight recorder's record ring."""

    def __init__(self, keep: int = 512):
        self._ring: deque[str] = deque(maxlen=keep)

    def record(self, line: str) -> None:
        self._ring.append(line)

    def recent(self, limit: int = 200) -> list[str]:
        """Newest-last lines (reads retry across a concurrent
        append, like flight.FlightRecorder.recent)."""
        while True:
            try:
                items = list(self._ring)
                break
            except RuntimeError:
                continue
        return items[-max(0, int(limit)):]

    def configure(self, keep: int) -> None:
        if keep != self._ring.maxlen:
            self._ring = deque(self._ring, maxlen=int(keep))

    def clear(self) -> None:
        self._ring.clear()

    def __len__(self) -> int:
        return len(self._ring)


# process-global ring: every Logger instance feeds it (the nop logger
# emits nothing, so it records nothing); [incidents] log-ring sizes it
ring = LogRing()


def _active_trace_id() -> str | None:
    """This thread's flight trace id (function-level import: logger
    must stay importable before/without the obs.flight module)."""
    try:
        from pilosa_tpu.obs import flight
        return flight.current_trace_id()
    except Exception:
        return None


class Logger:
    """Leveled, %-formatted logger writing one line per call."""

    def __init__(self, stream: IO[str] | None = None, level: int = INFO,
                 name: str = ""):
        self.stream = stream if stream is not None else sys.stderr
        self.level = level
        self.name = name
        self._lock = threading.Lock()

    def _log(self, level: int, fmt: str, *args):
        if level < self.level:
            return
        msg = (fmt % args) if args else fmt
        ts = time.strftime("%Y-%m-%dT%H:%M:%S")
        prefix = f"{ts} {_LEVEL_NAMES[level]:5s}"
        if self.name:
            prefix += f" [{self.name}]"
        # log/trace correlation (ISSUE 10): a line emitted while a
        # flight record (or an inherited RPC trace id) is active on
        # this thread carries that id, so logs grep straight to the
        # matching /debug/queries record and Perfetto lane
        trace = _active_trace_id()
        if trace:
            prefix += f" trace={trace}"
        line = f"{prefix} {msg}"
        ring.record(line)
        with self._lock:
            self.stream.write(line + "\n")

    def debug(self, fmt: str, *args):
        self._log(DEBUG, fmt, *args)

    def info(self, fmt: str, *args):
        self._log(INFO, fmt, *args)

    def warn(self, fmt: str, *args):
        self._log(WARN, fmt, *args)

    def error(self, fmt: str, *args):
        self._log(ERROR, fmt, *args)

    def with_prefix(self, name: str) -> "Logger":
        child = Logger(self.stream, self.level, name)
        child._lock = self._lock
        return child


class NopLogger(Logger):
    """Discards everything (logger.NopLogger analog)."""

    def __init__(self):
        super().__init__(stream=sys.stderr, level=ERROR + 1)

    def _log(self, level: int, fmt: str, *args):
        pass


def StderrLogger(level: int = INFO) -> Logger:
    return Logger(sys.stderr, level)


def new_logger(verbose: bool = False, path: str | None = None) -> Logger:
    """Build the server logger from config (server.go log-path wiring)."""
    level = DEBUG if verbose else INFO
    if path:
        return Logger(open(path, "a", buffering=1), level)
    return Logger(sys.stderr, level)
