"""Leveled logger with a nop default (reference: logger/logger.go —
``Logger`` interface with Printf-style Debugf/Infof/Warnf/Errorf and a
``NopLogger``; we keep the same four levels and the nop)."""

from __future__ import annotations

import sys
import threading
import time
from typing import IO

DEBUG, INFO, WARN, ERROR = 10, 20, 30, 40
_LEVEL_NAMES = {DEBUG: "DEBUG", INFO: "INFO", WARN: "WARN", ERROR: "ERROR"}


def _active_trace_id() -> str | None:
    """This thread's flight trace id (function-level import: logger
    must stay importable before/without the obs.flight module)."""
    try:
        from pilosa_tpu.obs import flight
        return flight.current_trace_id()
    except Exception:
        return None


class Logger:
    """Leveled, %-formatted logger writing one line per call."""

    def __init__(self, stream: IO[str] | None = None, level: int = INFO,
                 name: str = ""):
        self.stream = stream if stream is not None else sys.stderr
        self.level = level
        self.name = name
        self._lock = threading.Lock()

    def _log(self, level: int, fmt: str, *args):
        if level < self.level:
            return
        msg = (fmt % args) if args else fmt
        ts = time.strftime("%Y-%m-%dT%H:%M:%S")
        prefix = f"{ts} {_LEVEL_NAMES[level]:5s}"
        if self.name:
            prefix += f" [{self.name}]"
        # log/trace correlation (ISSUE 10): a line emitted while a
        # flight record (or an inherited RPC trace id) is active on
        # this thread carries that id, so logs grep straight to the
        # matching /debug/queries record and Perfetto lane
        trace = _active_trace_id()
        if trace:
            prefix += f" trace={trace}"
        with self._lock:
            self.stream.write(f"{prefix} {msg}\n")

    def debug(self, fmt: str, *args):
        self._log(DEBUG, fmt, *args)

    def info(self, fmt: str, *args):
        self._log(INFO, fmt, *args)

    def warn(self, fmt: str, *args):
        self._log(WARN, fmt, *args)

    def error(self, fmt: str, *args):
        self._log(ERROR, fmt, *args)

    def with_prefix(self, name: str) -> "Logger":
        child = Logger(self.stream, self.level, name)
        child._lock = self._lock
        return child


class NopLogger(Logger):
    """Discards everything (logger.NopLogger analog)."""

    def __init__(self):
        super().__init__(stream=sys.stderr, level=ERROR + 1)

    def _log(self, level: int, fmt: str, *args):
        pass


def StderrLogger(level: int = INFO) -> Logger:
    return Logger(sys.stderr, level)


def new_logger(verbose: bool = False, path: str | None = None) -> Logger:
    """Build the server logger from config (server.go log-path wiring)."""
    level = DEBUG if verbose else INFO
    if path:
        return Logger(open(path, "a", buffering=1), level)
    return Logger(sys.stderr, level)
