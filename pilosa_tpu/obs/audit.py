"""Continuous correctness auditing — the production shadow plane.

Every acceptance bar in this repo is "bit-exact", yet correctness was
only ever *asserted* in tests and bench harnesses, never *observed* on
a live serving system — and the codebase has grown many silent-
wrongness surfaces: delta-patched device stacks, sparse re-encodings,
in-program mesh combines, write-through standing results, version-
guarded result caches, replica resync.  This module keeps three
always-on (sampled, budgeted) verifiers running against production
traffic:

- **Shadow execution** — the serving layer samples a configurable
  fraction of completed reads per route (``[audit] sample-rate``,
  ``route-rates`` overrides).  A sampled serve records the query, its
  shard set, the fragment-version snapshot that PROVABLY covers the
  served answer, and a digest of the result; a bounded background
  worker re-executes it on the independent host/numpy oracle arm (a
  private ``Executor`` with ``use_stacked`` off: no serving layer, no
  ragged fusion, no fused kernels, no sparse fast paths, no result
  cache) and compares digests bit-exact.  If writes advanced past the
  snapshot — checked before AND after the shadow run — the sample is
  skipped-and-counted (``stale_skip``), never a false positive.
  Shadow admission rides the PR 8 scheduler at a dedicated
  lowest-priority ``audit`` class with its own concurrency cap, so
  audits can never steal serving slots; a full queue or busy cap
  sheds the AUDIT (counted), never the query.

- **Background scrubbers** on the maintenance ticker — a ResultCache
  audit (sampled cached entries recomputed on the oracle arm and
  compared under the entry's own snapshot guard), a standing-query
  drift audit (maintained results vs one cold execution at quiesce
  points, riding the PR 18 registry), and — on cluster nodes — a
  replica anti-entropy scrub (fragment block-checksum compare across
  live replicas; divergence is COUNTED as a detection, then repaired
  through the existing resync path, never silently healed).

- **Evidence** — every verifier outcome counts into
  ``pilosa_audit_total{kind,outcome}``; a mismatch lands in a bounded
  quarantine ring and fires a rate-limited ``audit-mismatch`` incident
  bundle (obs/incidents.py) carrying both digests, the plan
  fingerprint, and the arm/encoding/placement evidence of the live
  and shadow answers.  ``/debug/audit`` (admin-gated) exposes recent
  samples, the quarantine ring, and scrub progress; the cluster
  federates it at ``/debug/cluster/audit``.

``PILOSA_TPU_AUDIT=0`` kills the whole plane at runtime; ``[audit]``
config knobs (env twins ``PILOSA_TPU_AUDIT_*``) tune it.  The serve-
time tap's fixed cost (the not-sampled path) is gated at <= 8us by
``bench.py --audit-smoke``.
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import threading
import time
from collections import OrderedDict, deque

from pilosa_tpu.obs import faults, incidents, metrics
from pilosa_tpu.obs.monitor import capture_exception

# -- module config (the [audit] knobs; apply_audit_settings() writes
# these, PILOSA_TPU_AUDIT is the runtime kill-switch) -----------------

_ENABLED = True
_SAMPLE_RATE = 0.01
_ROUTE_RATES: dict[str, float] = {}
_QUEUE_MAX = 64
_CONCURRENCY = 1
_SCRUB_CACHE_N = 4
_SCRUB_STANDING_N = 2
_SCRUB_REPLICA_N = 2
_QUARANTINE = 32
_RECENT = 64
# bounded key->query side-table: the result cache's key carries only a
# canonical call repr (not re-parseable), so the cache scrubber can
# only recompute entries whose query it has seen served
_KEYS_MAX = 512


def configure(enabled: bool | None = None, sample_rate=None,
              route_rates=None, queue_max=None, concurrency=None,
              scrub_cache_n=None, scrub_standing_n=None,
              scrub_replica_n=None, quarantine=None) -> None:
    global _ENABLED, _SAMPLE_RATE, _ROUTE_RATES, _QUEUE_MAX, \
        _CONCURRENCY, _SCRUB_CACHE_N, _SCRUB_STANDING_N, \
        _SCRUB_REPLICA_N, _QUARANTINE
    if enabled is not None:
        _ENABLED = bool(enabled)
    if sample_rate is not None:
        _SAMPLE_RATE = max(0.0, min(1.0, float(sample_rate)))
    if route_rates is not None:
        _ROUTE_RATES = (dict(route_rates)
                        if isinstance(route_rates, dict)
                        else parse_route_rates(route_rates))
    if queue_max is not None:
        _QUEUE_MAX = max(1, int(queue_max))
    if concurrency is not None:
        _CONCURRENCY = max(1, int(concurrency))
    if scrub_cache_n is not None:
        _SCRUB_CACHE_N = max(0, int(scrub_cache_n))
    if scrub_standing_n is not None:
        _SCRUB_STANDING_N = max(0, int(scrub_standing_n))
    if scrub_replica_n is not None:
        _SCRUB_REPLICA_N = max(0, int(scrub_replica_n))
    if quarantine is not None:
        _QUARANTINE = max(1, int(quarantine))


def enabled() -> bool:
    """The audit kill-switch: the env var wins while set (a live
    operator toggle), else the configured value."""
    ev = os.environ.get("PILOSA_TPU_AUDIT")
    if ev is not None:
        return ev.lower() not in ("0", "false", "")
    return _ENABLED


def parse_route_rates(spec: str | None) -> dict[str, float]:
    """"cached=0.05,fused=0.01" -> {"cached": 0.05, ...}; malformed
    entries are ignored (an operator typo must not kill serving)."""
    out: dict[str, float] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, v = part.partition("=")
        try:
            rate = float(v)
        except ValueError:
            continue
        if name.strip():
            out[name.strip()] = max(0.0, min(1.0, rate))
    return out


# -- digests and the corruption seam ----------------------------------

def result_digest(results) -> str:
    """Short stable digest of a result list — the canonical wire
    serialization (api.serialize_result) so host/device/NumPy scalar
    type differences never alias as mismatches."""
    try:
        from pilosa_tpu import api as _api
        if isinstance(results, list):
            payload = json.dumps(
                [_api.serialize_result(r) for r in results],
                sort_keys=True, default=str)
        else:  # standing SQL results (SQLResult) and friends
            payload = repr(results)
    except Exception:
        payload = repr(results)
    return hashlib.blake2b(payload.encode(),
                           digest_size=8).hexdigest()


def corrupt_results(results):
    """The ``audit-corrupt`` drill payload: a copy of ``results`` with
    one bit flipped in the first result — the injection that PROVES
    the auditor detects (obs/faults.py table).  Never mutates the
    input (the caller decides whether the corrupt copy replaces a
    served answer, a cached entry, or a maintained result)."""
    if isinstance(results, list) and results:
        return [_flip_bit(results[0])] + list(results[1:])
    return _flip_bit(results)


def _flip_bit(r):
    from pilosa_tpu.executor.results import (
        Pair,
        RowResult,
        ValCount,
    )
    import numpy as np
    if isinstance(r, bool):
        return not r
    if isinstance(r, (int, np.integer)):
        return int(r) ^ 1
    if isinstance(r, float):
        return -r if r else 1.0
    if isinstance(r, ValCount):
        return ValCount(value=(int(r.value) ^ 1
                               if r.value is not None else 1),
                        count=r.count)
    if isinstance(r, Pair):
        return Pair(id=r.id, count=int(r.count) ^ 1, key=r.key)
    if isinstance(r, RowResult):
        out = RowResult()
        out.segments = dict(r.segments)
        out.keys = r.keys
        for shard, words in out.segments.items():
            w = np.array(words, copy=True)
            if w.size:
                w.flat[0] = int(w.flat[0]) ^ 1
                out.segments[shard] = w
                return out
        # empty row: invent one bit in shard 0
        w = np.zeros(16, dtype=np.uint64)
        w[0] = 1
        out.segments[0] = w
        return out
    if isinstance(r, list) and r:
        return [_flip_bit(r[0])] + list(r[1:])
    if isinstance(r, tuple) and r:
        return (_flip_bit(r[0]),) + tuple(r[1:])
    return 1 if r is None else r


# -- samples ----------------------------------------------------------

class _Sample:
    __slots__ = ("kind", "index", "q", "sql", "shards", "key",
                 "fields", "snapshot", "digest", "route", "fp", "rec",
                 "t")

    def __init__(self, kind, index, q, shards, key, fields, snapshot,
                 digest, route, fp=None, rec=None, sql=None):
        self.kind = kind          # shadow | cache | standing
        self.index = index
        self.q = q                # pql.ast.Query (None for SQL)
        self.sql = sql            # SQL text for standing SQL audits
        self.shards = shards
        self.key = key
        self.fields = fields
        self.snapshot = snapshot  # proven to cover ``digest``
        self.digest = digest
        self.route = route
        self.fp = fp
        self.rec = rec            # live flight record (ring dict)
        self.t = time.time()


class AuditPlane:
    """One per ServingLayer: the bounded sampler queue, the shadow
    worker(s), the scrub cursors, and the evidence rings."""

    def __init__(self, serving):
        self.serving = serving
        self._cv = threading.Condition()
        self._queue: deque[_Sample] = deque()
        self._workers: list[threading.Thread] = []
        self._inflight = 0
        self._stop = False
        self._rng = random.Random(0xA0D17)
        self.recent: deque[dict] = deque(maxlen=_RECENT)
        self.quarantine: deque[dict] = deque(maxlen=max(1, _QUARANTINE))
        self.counts: dict[tuple, int] = {}
        self._seq = 0
        self._oracle = None
        self._oracle_lock = threading.Lock()
        self._sql_oracle = None
        # serve-time key -> (index, q, shards, fields) so the cache
        # scrubber can recompute entries (bounded; see _KEYS_MAX)
        self._keys: OrderedDict[tuple, tuple] = OrderedDict()
        self._keys_lock = threading.Lock()
        self._cache_cursor = 0
        self._standing_cursor = 0
        # set by ClusterNode.open(): the replica anti-entropy scrub
        # (obs/audit.py stays cluster-agnostic; the coordinator owns
        # placement and the resync machinery)
        self.replica_scrub = None
        self.scrub_stats = {"ticks": 0, "cache_scanned": 0,
                            "standing_scanned": 0,
                            "replica_scanned": 0}

    # -- hot sampler ---------------------------------------------------

    def seed(self, seed: int) -> None:
        """Deterministic sampling for the seeded property tests."""
        self._rng = random.Random(seed)

    def maybe_sample(self, index, idx, q, shards, key, fields, snap,
                     route, results, fl) -> None:
        """The serve-time sampling decision.  The not-sampled path —
        one rate lookup + one RNG draw — is the fixed cost every
        served read pays and is gated <= 8us (bench/audit.py)."""
        rate = _ROUTE_RATES.get(route, _SAMPLE_RATE)
        if rate <= 0.0 or self._rng.random() >= rate:
            return
        if fields is None or snap is None:
            # Uncacheable read set / registry gap: no snapshot can
            # prove what state the answer reflects, so a shadow
            # comparison could false-positive — never sample these
            self._count("shadow", "unguarded")
            return
        s = _Sample("shadow", index, q, shards, key, fields, snap,
                    result_digest(results), route,
                    fp=_fp(key), rec=fl)
        with self._keys_lock:
            self._keys[key] = (index, q, shards, fields)
            self._keys.move_to_end(key)
            while len(self._keys) > _KEYS_MAX:
                self._keys.popitem(last=False)
        if fl is not None:
            # pre-commit stamp: flight.commit() update()s the same
            # dict it stores, so the flag survives into the ring and
            # /debug/queries?audited=1 can find the record
            fl["audited"] = True
        self._enqueue(s)

    def _enqueue(self, s: _Sample) -> None:
        with self._cv:
            if len(self._queue) >= _QUEUE_MAX:
                # backpressure sheds the AUDIT, never the query
                self._count(s.kind, "shed")
                if s.rec is not None:
                    s.rec["audit_outcome"] = "shed"
                return
            self._queue.append(s)
            self._ensure_workers_locked()
            self._cv.notify()
        self._count(s.kind, "sampled")

    # -- worker --------------------------------------------------------

    def _ensure_workers_locked(self) -> None:
        want = max(1, _CONCURRENCY)
        self._workers = [w for w in self._workers if w.is_alive()]
        while len(self._workers) < want:
            t = threading.Thread(target=self._run, daemon=True,
                                 name=f"audit-worker-"
                                      f"{len(self._workers)}")
            self._workers.append(t)
            t.start()

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._stop:
                    self._cv.wait(0.25)
                if self._stop and not self._queue:
                    return
                s = self._queue.popleft()
                self._inflight += 1
            try:
                self._verify(s)
            except Exception as e:
                capture_exception(e, where="audit.worker",
                                  kind=s.kind, index=s.index)
                self._finish(s, "error", None, f"{type(e).__name__}: {e}")
            finally:
                with self._cv:
                    self._inflight -= 1
                    self._cv.notify_all()

    def wait_idle(self, timeout: float = 5.0) -> bool:
        """Test/bench seam: block until every queued sample has been
        verified (or the timeout passes)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._queue or self._inflight:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    return False
                self._cv.notify_all()
                self._cv.wait(min(rem, 0.05))
        return True

    def close(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()

    # -- the shadow run ------------------------------------------------

    def _verify(self, s: _Sample) -> None:
        from pilosa_tpu.executor.serving import _shard_set, field_snapshot
        srv = self.serving
        ex = srv.executor
        idx = ex.holder.index(s.index)
        if idx is None:
            self._finish(s, "stale_skip", None, "index dropped")
            return
        sset = _shard_set(s.shards)
        if field_snapshot(idx, s.fields, sset) != s.snapshot:
            # writes advanced past the recorded snapshot before the
            # shadow could run: skipped-and-counted, by design
            self._finish(s, "stale_skip", None,
                         "writes advanced before shadow run")
            return
        # dedicated lowest-priority admission: the audit class has its
        # own concurrency cap on the serving scheduler — a busy cap
        # sheds the audit, it never waits on (or steals) serving slots
        sched = srv.sched
        slot = sched.audit_slot() if sched is not None else None
        if sched is not None and slot is None:
            self._finish(s, "shed", None, "audit slots busy")
            return
        try:
            got = self._shadow_exec(s)
        finally:
            if slot is not None:
                slot.release()
        if field_snapshot(idx, s.fields, sset) != s.snapshot:
            # a write raced the shadow run itself: the oracle answer
            # may span versions — skip, never a false positive
            self._finish(s, "stale_skip", None,
                         "writes raced the shadow run")
            return
        d = result_digest(got)
        if d == s.digest:
            self._finish(s, "match", d)
        else:
            self._mismatch(s, d, got)

    def _shadow_exec(self, s: _Sample):
        if s.sql is not None:
            return self._sql_oracle_engine().query_one(s.sql)
        return self.oracle().execute(s.index, s.q, s.shards)

    def oracle(self):
        """The independent verification arm: a private Executor with
        ``use_stacked`` off — the per-shard host/numpy reference loop.
        No serving layer, no ragged fusion, no fused kernels, no
        sparse device fast paths, no result cache."""
        with self._oracle_lock:
            if self._oracle is None:
                from pilosa_tpu.executor.executor import Executor
                o = Executor(self.serving.executor.holder)
                o.use_stacked = False
                self._oracle = o
            return self._oracle

    def _sql_oracle_engine(self):
        with self._oracle_lock:
            if self._sql_oracle is None:
                from pilosa_tpu.sql.engine import Engine
                holder = self.serving.executor.holder
                # engine over the oracle arm: its inner PQL dispatch
                # rides the same host loop, never the serving plane
                self._sql_oracle = Engine(holder, self.oracle())
            return self._sql_oracle

    # -- outcomes ------------------------------------------------------

    def _count(self, kind: str, outcome: str) -> None:
        metrics.AUDIT_TOTAL.inc(kind=kind, outcome=outcome)
        k = (kind, outcome)
        with self._cv:
            self.counts[k] = self.counts.get(k, 0) + 1

    def _finish(self, s: _Sample, outcome: str, shadow_digest,
                note: str = "") -> None:
        self._count(s.kind, outcome)
        if s.rec is not None:
            s.rec["audit_outcome"] = outcome
        ent = {"time": round(s.t, 3), "kind": s.kind,
               "outcome": outcome, "index": s.index,
               "query": _qtext(s), "route": s.route,
               "fingerprint": s.fp}
        if note:
            ent["note"] = note
        self.recent.append(ent)

    def _mismatch(self, s: _Sample, shadow_digest: str, got) -> None:
        with self._cv:
            self._seq += 1
            seq = self._seq
        ent = {
            "id": f"aud-{int(s.t)}-{seq}",
            "time": round(s.t, 3),
            "kind": s.kind,
            "index": s.index,
            "query": _qtext(s),
            "route": s.route,
            "fingerprint": s.fp,
            "shards": (sorted(s.shards)
                       if s.shards is not None else None),
            "live_digest": s.digest,
            "shadow_digest": shadow_digest,
            "live_arm": self._live_arm(s),
            "shadow_arm": {"arm": "host-loop", "use_stacked": False,
                           "serving": False, "cache": False},
        }
        self.quarantine.append(ent)
        self._finish(s, "mismatch", shadow_digest,
                     f"live {s.digest} != shadow {shadow_digest}")
        incidents.report(
            "audit-mismatch",
            detail=(f"{s.kind} audit mismatch on {s.index} "
                    f"[{s.route}]: live {s.digest} != shadow "
                    f"{shadow_digest}"),
            context=ent)

    def _live_arm(self, s: _Sample) -> dict:
        """Which arm produced the live answer: the serve route plus
        the flight record's stack/encoding/placement evidence (the
        record is the same ring dict — by verify time commit() has
        filled the device-side fields in)."""
        arm = {"route": s.route, "use_stacked": bool(
            getattr(self.serving.executor, "use_stacked", False))}
        rec = s.rec
        if isinstance(rec, dict):
            for k in ("stack", "stack_keys", "page_mix",
                      "bytes_moved", "batch", "trace_id"):
                if k in rec:
                    arm[k] = rec[k]
        try:
            eng = self.serving.executor.stacked
            mesh = getattr(eng, "mesh", None)
            if mesh is not None:
                arm["mesh_devices"] = len(getattr(mesh, "devices", [])) \
                    or getattr(mesh, "size", None)
        except Exception:
            pass
        return arm

    # -- maintenance-ticker scrubbers ----------------------------------

    def scrub(self) -> None:
        """One ticker pass: cache audit + standing drift audit +
        (cluster nodes) replica anti-entropy scrub, each budgeted by
        its [audit] scrub-*-n knob."""
        if not enabled():
            return
        self.scrub_stats["ticks"] += 1
        try:
            self._scrub_cache(_SCRUB_CACHE_N)
        except Exception as e:
            capture_exception(e, where="audit.scrub_cache")
        try:
            self._scrub_standing(_SCRUB_STANDING_N)
        except Exception as e:
            capture_exception(e, where="audit.scrub_standing")
        if self.replica_scrub is not None and _SCRUB_REPLICA_N > 0:
            try:
                self.scrub_stats["replica_scanned"] += int(
                    self.replica_scrub(_SCRUB_REPLICA_N) or 0)
            except Exception as e:
                capture_exception(e, where="audit.scrub_replica")

    def _scrub_cache(self, budget: int) -> None:
        cache = self.serving.cache
        if cache is None or budget <= 0:
            return
        with self._keys_lock:
            known = list(self._keys.items())
        if not known:
            return
        picked = 0
        n = len(known)
        for i in range(n):
            if picked >= budget:
                break
            key, (index, q, shards, fields) = known[
                (self._cache_cursor + i) % n]
            with cache._lock:
                ent = cache._entries.get(key)
            if ent is None or q is None:
                continue
            picked += 1
            # the entry's OWN snapshot is the guard: the worker
            # re-executes on the oracle and compares only if the
            # fragment versions still match what the entry recorded
            s = _Sample("cache", index, q, shards, key, ent[0],
                        ent[1], result_digest(ent[2]), "cache_scrub",
                        fp=_fp(key))
            self._enqueue(s)
        self._cache_cursor = (self._cache_cursor + picked) % max(1, n)
        self.scrub_stats["cache_scanned"] += picked

    def _scrub_standing(self, budget: int) -> None:
        reg = getattr(self.serving, "standing", None)
        if reg is None or budget <= 0:
            return
        with reg._lock:
            sqs = sorted(reg._by_id.values(), key=lambda s: s.sid)
        if not sqs:
            return
        n = len(sqs)
        picked = 0
        for i in range(n):
            if picked >= budget:
                break
            sq = sqs[(self._standing_cursor + i) % n]
            with sq.lock:
                if sq.error is not None or sq.results is None:
                    continue
                snap = sq.snapshot
                digest = result_digest(sq.results)
            picked += 1
            # drift audit at quiesce: the worker's pre/post snapshot
            # guard IS the quiesce check — a registration mid-write
            # stream skips-and-counts instead of false-positiving
            s = _Sample("standing", sq.index, sq.q, None, sq.key,
                        sq.fields, snap, digest, "standing_scrub",
                        fp=sq.fp,
                        sql=getattr(sq, "sql_text", None)
                        if sq.q is None else None)
            self._enqueue(s)
        self._standing_cursor = (self._standing_cursor + picked) \
            % max(1, n)
        self.scrub_stats["standing_scanned"] += picked

    # -- introspection -------------------------------------------------

    def queue_depth(self) -> int:
        with self._cv:
            return len(self._queue) + self._inflight

    def describe(self) -> dict:
        with self._cv:
            counts = {f"{k}:{o}": v
                      for (k, o), v in sorted(self.counts.items())}
            depth = len(self._queue) + self._inflight
        return {
            "queue_depth": depth,
            "queue_max": _QUEUE_MAX,
            "concurrency": _CONCURRENCY,
            "counts": counts,
            "recent": list(self.recent),
            "quarantine": list(self.quarantine),
            "scrub": dict(self.scrub_stats),
            "tracked_keys": len(self._keys),
        }


def _fp(key) -> str:
    return hashlib.blake2b(repr(key).encode(),
                           digest_size=8).hexdigest()


def _qtext(s: _Sample) -> str:
    if s.sql is not None:
        return s.sql
    try:
        return repr(s.q)
    except Exception:
        return "<query>"


# -- the serve-time tap (called by executor/serving.py) ---------------

def tap(plane: AuditPlane | None, index, idx, q, shards, key, fields,
        snap, route, results, fl):
    """Per-serve audit hook: corruption drill seam + sampling
    decision.  ``snap`` must be the snapshot PROVEN to cover
    ``results`` on this route (cache guard / batch post-pass / solo
    store protocol) — a hook-time snapshot could postdate a racing
    write and turn the shadow comparison into a false positive.
    Returns the results to serve (a corrupted COPY while the
    ``audit-corrupt`` drill is armed; the underlying entry is never
    touched on the serve seam)."""
    if plane is None or not enabled():
        return results
    if faults.armed("audit-corrupt") and faults.take(
            "audit-corrupt", f"serve:{route}:{index}"):
        results = corrupt_results(results)
    plane.maybe_sample(index, idx, q, shards, key, fields, snap,
                       route, results, fl)
    return results


def tick(serving) -> None:
    """Maintenance-ticker entry point (server/http.py _tick_loop)."""
    plane = getattr(serving, "audit", None)
    if plane is not None:
        plane.scrub()


def payload(plane: AuditPlane | None) -> dict:
    """The /debug/audit payload."""
    out = {
        "enabled": enabled(),
        "sample_rate": _SAMPLE_RATE,
        "route_rates": dict(_ROUTE_RATES),
        "scrub_budgets": {"cache": _SCRUB_CACHE_N,
                          "standing": _SCRUB_STANDING_N,
                          "replica": _SCRUB_REPLICA_N},
        "active": plane is not None,
    }
    if plane is not None:
        out.update(plane.describe())
    else:
        out.update({"queue_depth": 0, "counts": {}, "recent": [],
                    "quarantine": [], "scrub": {}})
    return out
