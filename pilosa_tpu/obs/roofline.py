"""Roofline attribution — joins bytes touched with device time.

ROADMAP item 3's acceptance ("net device time within 4x of the
bandwidth bound implied by bytes touched" — the Buddy-RAM framing,
PAPERS.md arxiv 1611.09988: bulk bitwise ops should be limited by raw
memory bandwidth) is unverifiable from one-off bench claims; it needs
a live join of bytes-touched with device time.  This module is that
join: ``stacked.timed_dispatch`` already knows both (operand leaf
bytes, execute-phase seconds through ``block_until_ready``) and calls
:func:`note` per cached-executable dispatch, which folds the sample
into per-op-family achieved bandwidth:

- ``pilosa_device_bandwidth_gbps{op}``      achieved GB/s (cumulative
  bytes / cumulative execute seconds — compile dispatches excluded,
  their wall time is trace+XLA, not memory traffic)
- ``pilosa_device_bandwidth_fraction{op}``  achieved / peak

Peak comes from ``PILOSA_TPU_PEAK_GBPS`` (device spec) or a measured
STREAM-style probe (:func:`ensure_peak`) run once at server startup —
on CPU fallback the probe measures host memory bandwidth, so the
fraction stays meaningful (if humble) off-TPU.  Per-query shares land
in each flight record's ``roofline`` field (obs/flight.py), and the
bench cells emit windowed snapshots (bench/headline.py, serving.py).

Always-on budget: :func:`note` is one dict update + two gauge sets on
a path that just paid a device dispatch; the disabled path is a
single module-global check (gated with the tracing-overhead smoke in
check.sh).
"""

from __future__ import annotations

import os
import threading

from pilosa_tpu.obs import flight, metrics

_lock = threading.Lock()         # guards _stats
_probe_lock = threading.Lock()   # serializes the peak probe/spawn
# op -> [bytes, seconds, dispatches]; cumulative since process start
_stats: dict[str, list] = {}
_peak_bytes_per_s: float | None = None
_enabled: bool | None = None  # None -> resolve from env on first ask
_probe_thread: threading.Thread | None = None


def enabled() -> bool:
    if _enabled is not None:
        return _enabled
    return os.environ.get("PILOSA_TPU_ROOFLINE", "1") != "0"


def configure(enabled: bool | None = None,
              peak_gbps: float | None = None):
    """Apply the [roofline] config knobs (config.py).  ``peak_gbps``
    overrides the measured probe (device-spec peak); 0/None keeps the
    probe."""
    global _enabled, _peak_bytes_per_s
    if enabled is not None:
        _enabled = bool(enabled)
    if peak_gbps:
        set_peak(float(peak_gbps) * 1e9)


def set_peak(bytes_per_s: float):
    global _peak_bytes_per_s
    _peak_bytes_per_s = float(bytes_per_s)
    metrics.DEVICE_PEAK_GBPS.set(_peak_bytes_per_s / 1e9)
    _refresh_fractions()


def peak_or_none() -> float | None:
    """The known peak (bytes/s) WITHOUT triggering a probe — hot-path
    callers (flight.commit) must never block on measurement."""
    return _peak_bytes_per_s


def measure_peak(size_mb: int = 16, reps: int = 3) -> float:
    """STREAM-style copy probe on the default backend: time
    ``y = x ^ 1`` over a ``size_mb`` uint32 array (reads + writes =
    2x bytes), best of ``reps`` after one warm run.  Returns bytes/s.
    On TPU this measures HBM stream bandwidth; on the CPU fallback,
    host memory bandwidth — both are the honest denominator for the
    fraction gauge on that backend."""
    import time as _time

    import jax
    import jax.numpy as jnp
    n = (size_mb << 20) // 4
    x = jnp.zeros((n,), dtype=jnp.uint32)
    f = jax.jit(lambda a: a ^ jnp.uint32(1))
    jax.block_until_ready(f(x))  # compile + warm
    best = float("inf")
    for _ in range(reps):
        t0 = _time.perf_counter()
        jax.block_until_ready(f(x))
        best = min(best, _time.perf_counter() - t0)
    return 2 * x.nbytes / max(best, 1e-9)


def ensure_peak(block: bool = True) -> float | None:
    """Resolve the peak: env override first, else the measured probe.
    ``block=False`` runs the probe on a background daemon thread (the
    server-startup path — first queries must not wait ~50 ms on a
    bandwidth probe) and returns None until it lands."""
    global _peak_bytes_per_s, _probe_thread
    if _peak_bytes_per_s is not None:
        return _peak_bytes_per_s
    env = os.environ.get("PILOSA_TPU_PEAK_GBPS")
    if env:
        try:
            set_peak(float(env) * 1e9)
            return _peak_bytes_per_s
        except ValueError:
            pass
    if not block:
        with _probe_lock:
            if _probe_thread is None or not _probe_thread.is_alive():
                _probe_thread = threading.Thread(
                    target=lambda: ensure_peak(block=True), daemon=True)
                _probe_thread.start()
        return None
    with _probe_lock:
        if _peak_bytes_per_s is None:
            try:
                set_peak(measure_peak())
            except Exception:
                return None  # no usable backend: fractions stay unset
    return _peak_bytes_per_s


def note(op: str, nbytes: int, seconds: float, device=None):
    """Fold one cached-executable dispatch into the per-op bandwidth
    attribution (and the active flight record's roofline share).

    ``device`` (a serving-mesh slot index, memory/placement.py)
    attributes a PER-DEVICE share of a mesh dispatch: the sample
    accumulates under the ``"{op}/dev{device}"`` stats key — its own
    snapshot/window row, the per-chip bench occupancy truth — and
    sets the gauges with a ``device="d{device}"`` label.  Device
    samples never touch the flight record (the caller notes the
    aggregate separately; double-counting the per-device split would
    inflate every rider's roofline share)."""
    if not enabled() or seconds <= 0 or nbytes <= 0:
        return
    key = op if device is None else f"{op}/dev{device}"
    with _lock:
        st = _stats.get(key)
        if st is None:
            st = _stats[key] = [0, 0.0, 0]
        st[0] += int(nbytes)
        st[1] += seconds
        st[2] += 1
        b, s = st[0], st[1]
    gbps = b / s / 1e9
    labels = {"op": op}
    if device is not None:
        labels["device"] = f"d{device}"
    metrics.DEVICE_BW_GBPS.set(gbps, **labels)
    peak = _peak_bytes_per_s
    if peak:
        metrics.DEVICE_BW_FRACTION.set((b / s) / peak, **labels)
    if device is None:
        flight.note_op(op, nbytes, seconds)


def _split_key(key: str) -> dict:
    """Stats key -> gauge labels ("ragged/dev3" -> op + device)."""
    if "/dev" in key:
        op, _, d = key.rpartition("/dev")
        return {"op": op, "device": f"d{d}"}
    return {"op": key}


def _refresh_fractions():
    """Re-derive the fraction gauges after the peak lands (the
    background probe may finish after dispatches already noted)."""
    peak = _peak_bytes_per_s
    if not peak:
        return
    with _lock:
        items = [(key, st[0], st[1]) for key, st in _stats.items()]
    for key, b, s in items:
        if s > 0:
            metrics.DEVICE_BW_FRACTION.set((b / s) / peak,
                                           **_split_key(key))


def snapshot() -> dict:
    """Cumulative per-op attribution for bench cells and /debug use:
    ``{"peak_gbps": ..., "ops": {op: {bytes, seconds, dispatches,
    gbps, fraction?}}}``.  Pure read — never triggers a probe."""
    peak = _peak_bytes_per_s
    with _lock:
        items = {op: list(st) for op, st in _stats.items()}
    ops = {}
    for op, (b, s, n) in items.items():
        ent = {"bytes": b, "seconds": round(s, 6), "dispatches": n}
        if s > 0:
            ent["gbps"] = round(b / s / 1e9, 4)
            if peak:
                ent["fraction"] = round((b / s) / peak, 5)
        ops[op] = ent
    out = {"ops": ops}
    if peak:
        out["peak_gbps"] = round(peak / 1e9, 3)
    return out


def window(before: dict, after: dict) -> dict:
    """Delta between two :func:`snapshot` calls — the per-bench-cell
    achieved-GB/s + fraction-of-peak emission."""
    peak_gbps = after.get("peak_gbps")
    ops = {}
    for op, a in after.get("ops", {}).items():
        b0 = before.get("ops", {}).get(op, {})
        db = a["bytes"] - b0.get("bytes", 0)
        ds = a["seconds"] - b0.get("seconds", 0.0)
        dn = a["dispatches"] - b0.get("dispatches", 0)
        if dn <= 0 or ds <= 0:
            continue
        ent = {"bytes": db, "seconds": round(ds, 6), "dispatches": dn,
               "gbps": round(db / ds / 1e9, 4)}
        if peak_gbps:
            ent["fraction"] = round((db / ds / 1e9) / peak_gbps, 5)
        ops[op] = ent
    out = {"ops": ops}
    if peak_gbps:
        out["peak_gbps"] = peak_gbps
    return out


def reset_stats():
    """Test/bench seam: forget cumulative attribution (gauges keep
    their last values until the next note)."""
    with _lock:
        _stats.clear()


def swap_state(enabled=None, peak_bytes_per_s=None):
    """Test/bench seam: set (or with None-able values, CLEAR) the
    module enable flag and peak, returning the prior pair so a probe
    can restore exactly what it found — including 'unset'."""
    global _enabled, _peak_bytes_per_s
    prev = (_enabled, _peak_bytes_per_s)
    _enabled = enabled
    _peak_bytes_per_s = peak_bytes_per_s
    if peak_bytes_per_s:
        metrics.DEVICE_PEAK_GBPS.set(peak_bytes_per_s / 1e9)
    return prev
