"""Error monitor — Sentry-style capture hook.

Reference: monitor/monitor.go:26 — an error/panic reporter with a
global nop default; the server calls CaptureException at recover
points.  Here the sink is pluggable (a real Sentry SDK drops in as
``sink``); the default in-memory ring is what tests and the /debug
surface read.
"""

from __future__ import annotations

import threading
import time
import traceback


class Monitor:
    def __init__(self, sink=None, keep: int = 100):
        self.sink = sink          # callable(event: dict) or None
        self.keep = keep
        self.events: list[dict] = []
        self._lock = threading.Lock()
        self.enabled = True

    def capture_exception(self, exc: BaseException, **context):
        if not self.enabled:
            return
        event = {
            "time": time.time(),
            "type": type(exc).__name__,
            "message": str(exc),
            "traceback": "".join(traceback.format_exception(
                type(exc), exc, exc.__traceback__))[-4000:],
            **context,
        }
        with self._lock:
            self.events.append(event)
            if len(self.events) > self.keep:
                self.events.pop(0)
        if self.sink is not None:
            try:
                self.sink(event)
            except Exception:
                pass  # the monitor must never take the server down

    def capture_message(self, msg: str, **context):
        with self._lock:
            self.events.append({"time": time.time(), "type": "message",
                                "message": msg, **context})
            if len(self.events) > self.keep:
                self.events.pop(0)

    def recent(self) -> list[dict]:
        with self._lock:
            return list(self.events)


# global monitor with nop-ish default (monitor.go global pattern)
global_monitor = Monitor()


def capture_exception(exc: BaseException, **context):
    global_monitor.capture_exception(exc, **context)
