"""Statistics catalog — persisted flight/roofline telemetry that
drives the engine's cost decisions.

ROADMAP item 3 names the goal: "the observability plane becomes the
optimizer's statistics catalog".  Before this module every
flight/roofline signal died with the process and every engine
decision ran on static heuristics.  The catalog keeps two planes:

- **Data stats** — per-(index, field) row cardinality, per-shard bit
  counts (shard skew), BSI value summaries harvested for free from
  the single-pass ``bsi_value_hist`` byproduct.  Maintained
  incrementally from the ingest path (api.import_bits/import_values)
  and persisted through a tail log of ingest events.

- **Runtime stats** — per-plan-fingerprint profiles (EWMA of
  duration, execute-phase device time, bytes streamed, batch
  occupancy, cache-hit rate) folded in from finished flight records
  OFF the hot path (lock-free pending append, batch fold), plus
  per-node cluster attempt latencies and measured cost-gate rates.

Consumers (the catalog is load-bearing, not decorative):

- ``executor/stacked.py`` — the one-pass-vs-per-combo GroupBy gate
  scales its unit model by measured seconds-per-unit for each arm
  (:func:`gate_rates`), and the patch-vs-rebuild dirty-fraction
  threshold becomes the measured break-even
  (:func:`patch_break_even_frac`) instead of a constant.
- ``executor/sched.py`` — admission classifies by estimated cost
  (:func:`est_cost_ms` from the fingerprint profile) with the
  query-kind walk as the cold-start fallback.
- ``executor/serving.py`` — ResultCache eviction prefers keeping
  high-recompute-cost entries.
- ``cluster/coordinator.py`` — hedge-delay derivation reads the
  persisted per-node attempt distributions, so hedging is calibrated
  from the first post-restart query.

A **regression sentinel** compares each fingerprint's fast window
EWMA against its frozen baseline and exports
``pilosa_perf_regression{fingerprint,metric}`` (the ratio while
firing, 0 after recovery).

Kill-switch: ``PILOSA_TPU_STATS=0`` (or ``[stats] enabled=false``)
disables the whole plane — every consumer falls back to its static
heuristic, bit-exact by construction (stats only steer plan/schedule
choices, never results).  Persistence: ``storage/stats_store.py``
(tmp+rename snapshot + torn-tail-dropping JSONL tail).
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque

from pilosa_tpu.obs import metrics

# fold the pending flight records every N appends (amortizes the
# catalog lock the same way flight.py amortizes the histogram lock)
_FOLD_N = 32
# bounded tables: profiles LRU-evict past this, per-node attempt
# rings and ingest row sets are capped below
_MAX_PROFILES = 512
_MAX_NODE_SAMPLES = 256
_MAX_CLUSTER_DURS = 512
_ROWS_CAP = 8192

_enabled: bool | None = None  # None -> resolve from env on each ask


def enabled() -> bool:
    if _enabled is not None:
        return _enabled
    return os.environ.get("PILOSA_TPU_STATS", "1") != "0"


def _ewma(prev: float | None, v: float, alpha: float) -> float:
    if prev is None:
        return v
    return prev + alpha * (v - prev)


class FieldStats:
    """Data-plane stats for one (index, field)."""

    __slots__ = ("rows", "rows_capped", "shard_bits", "vmin", "vmax",
                 "vcount", "vhist", "encodings")

    def __init__(self):
        self.rows: set[int] = set()
        self.rows_capped = False
        self.shard_bits: dict[int, int] = {}
        self.vmin: float | None = None
        self.vmax: float | None = None
        self.vcount = 0
        self.vhist: dict | None = None
        # device-format decisions for this field's pages (kind ->
        # count; memory/encode.py): the /debug/stats per-field
        # encoding breakdown.  Process-lifetime tallies — not
        # persisted through ingest events, only snapshots.
        self.encodings: dict[str, int] = {}

    def note(self, rows, shard_bits: dict, vmin=None, vmax=None,
             vcount: int = 0):
        for r in rows:
            if len(self.rows) >= _ROWS_CAP:
                self.rows_capped = True
                break
            self.rows.add(int(r))
        for s, n in shard_bits.items():
            s = int(s)
            self.shard_bits[s] = self.shard_bits.get(s, 0) + int(n)
        if vmin is not None:
            self.vmin = vmin if self.vmin is None else min(self.vmin,
                                                           vmin)
        if vmax is not None:
            self.vmax = vmax if self.vmax is None else max(self.vmax,
                                                           vmax)
        self.vcount += int(vcount)

    def skew(self) -> float | None:
        """max-shard / mean-shard bit-count ratio (1.0 = perfectly
        even) — the shard-skew input to cost estimation."""
        if not self.shard_bits:
            return None
        vals = list(self.shard_bits.values())
        mean = sum(vals) / len(vals)
        return round(max(vals) / mean, 4) if mean > 0 else None

    def payload(self) -> dict:
        out = {"rows": len(self.rows), "rows_capped": self.rows_capped,
               "shards": len(self.shard_bits),
               "bits": sum(self.shard_bits.values())}
        skew = self.skew()
        if skew is not None:
            out["shard_skew"] = skew
        if self.vcount:
            out["values"] = {"count": self.vcount, "min": self.vmin,
                             "max": self.vmax}
        if self.vhist is not None:
            out["value_hist"] = dict(self.vhist)
        if self.encodings:
            out["encodings"] = dict(self.encodings)
        return out

    def to_state(self) -> dict:
        return {"rows": sorted(self.rows),
                "rows_capped": self.rows_capped,
                "shard_bits": {str(k): v
                               for k, v in self.shard_bits.items()},
                "vmin": self.vmin, "vmax": self.vmax,
                "vcount": self.vcount, "vhist": self.vhist,
                "encodings": dict(self.encodings)}

    @classmethod
    def from_state(cls, st: dict) -> "FieldStats":
        fs = cls()
        fs.rows = {int(r) for r in st.get("rows", ())}
        fs.rows_capped = bool(st.get("rows_capped"))
        fs.shard_bits = {int(k): int(v)
                         for k, v in st.get("shard_bits", {}).items()}
        fs.vmin = st.get("vmin")
        fs.vmax = st.get("vmax")
        fs.vcount = int(st.get("vcount", 0))
        fs.vhist = st.get("vhist")
        fs.encodings = {str(k): int(v)
                        for k, v in (st.get("encodings") or {}).items()}
        return fs


class FingerprintProfile:
    """Runtime-plane profile for one plan fingerprint.  ``ms`` is the
    steady cost estimate (mid EWMA); ``fast_ms`` / ``base_ms`` are
    the sentinel pair — the baseline FREEZES while a regression fires
    so the fault can't be absorbed into it."""

    __slots__ = ("n", "ms", "exec_ms", "recompute_ms", "bytes",
                 "batch", "hits", "total", "fast_ms", "base_ms",
                 "firing")

    def __init__(self):
        self.n = 0
        self.ms: float | None = None
        self.exec_ms: float | None = None
        # EWMA over NON-cached serves only: what this plan costs to
        # actually COMPUTE.  `ms` (all serves, cache hits included)
        # is the admission signal — serving a cached entry costs the
        # engine nothing, so it may ride the point lane; recompute_ms
        # is the cache-eviction signal — the cache's own hits must
        # not talk it into evicting its most valuable entries.
        self.recompute_ms: float | None = None
        self.bytes: float | None = None
        self.batch: float | None = None
        self.hits = 0
        self.total = 0
        self.fast_ms: float | None = None
        self.base_ms: float | None = None
        self.firing = False

    def fold(self, rec: dict, ratio: float = 3.0,
             min_samples: int = 6):
        d = float(rec.get("duration_ms", 0.0))
        phases = rec.get("phases", {}) or {}
        self.n += 1
        self.total += 1
        if rec.get("route") == "cached" or rec.get("cached"):
            # rec["cached"]: a statement-cache-served SQL record —
            # route stays "sql" for /debug/queries, but the serve
            # cost the engine paid is a cache hit's
            self.hits += 1
        else:
            self.recompute_ms = _ewma(self.recompute_ms, d, 0.2)
        self.ms = _ewma(self.ms, d, 0.2)
        self.exec_ms = _ewma(
            self.exec_ms,
            float(phases.get("execute", 0.0))
            + float(phases.get("compile", 0.0)), 0.2)
        self.bytes = _ewma(self.bytes,
                           float(rec.get("bytes_moved", 0)), 0.2)
        self.batch = _ewma(self.batch, float(rec.get("batch", 1)), 0.2)
        self.fast_ms = _ewma(self.fast_ms, d, 0.5)
        # sentinel detection PER RECORD, before the baseline updates:
        # batch-folded slow samples must not drip into the baseline
        # faster than the comparison runs, or a sustained slowdown
        # could be absorbed without ever crossing the ratio
        if self.base_ms is not None and self.base_ms >= 0.01 \
                and self.n >= min_samples:
            self.firing = (self.fast_ms / self.base_ms) >= ratio
        # baseline skips the first samples (cold compile / cold cache
        # would seed it 100x high and the sentinel could never fire)
        # and FREEZES while a regression fires (the fault must not be
        # absorbed into the baseline it is measured against)
        if not self.firing and self.n > 3:
            self.base_ms = _ewma(self.base_ms, d, 0.05)

    def payload(self) -> dict:
        out = {"n": self.n,
               "ms": round(self.ms or 0.0, 4),
               "execute_ms": round(self.exec_ms or 0.0, 4),
               "bytes": int(self.bytes or 0),
               "batch": round(self.batch or 1.0, 2),
               "cache_hit_rate": round(self.hits / self.total, 4)
               if self.total else 0.0}
        if self.base_ms is not None:
            out["baseline_ms"] = round(self.base_ms, 4)
            out["window_ms"] = round(self.fast_ms or 0.0, 4)
        if self.firing:
            out["regressing"] = True
        return out

    def to_state(self) -> dict:
        return {"n": self.n, "ms": self.ms, "exec_ms": self.exec_ms,
                "recompute_ms": self.recompute_ms,
                "bytes": self.bytes, "batch": self.batch,
                "hits": self.hits, "total": self.total,
                "fast_ms": self.fast_ms, "base_ms": self.base_ms}

    @classmethod
    def from_state(cls, st: dict) -> "FingerprintProfile":
        p = cls()
        p.n = int(st.get("n", 0))
        p.ms = st.get("ms")
        p.exec_ms = st.get("exec_ms")
        p.recompute_ms = st.get("recompute_ms")
        p.bytes = st.get("bytes")
        p.batch = st.get("batch")
        p.hits = int(st.get("hits", 0))
        p.total = int(st.get("total", 0))
        p.fast_ms = st.get("fast_ms")
        p.base_ms = st.get("base_ms")
        return p


class StatsCatalog:
    """The process statistics catalog: data + runtime planes, the
    regression sentinel, and the persistence glue."""

    def __init__(self, path: str | None = None,
                 heavy_cost_ms: float = 5.0,
                 regression_ratio: float = 3.0,
                 regression_min_samples: int = 6,
                 snapshot_interval_s: float = 60.0):
        self.heavy_cost_ms = float(heavy_cost_ms)
        self.regression_ratio = float(regression_ratio)
        self.regression_min_samples = int(regression_min_samples)
        self.snapshot_interval_s = float(snapshot_interval_s)
        self._lock = threading.Lock()
        # serializes (apply event + tail append) against (state
        # capture + snapshot): without it an ingest event landing
        # between the two halves of a save could be stamped as
        # folded-into-the-snapshot and truncated while the snapshot
        # predates it — lost from persistence
        self._persist_mu = threading.Lock()
        self._fields: dict[tuple[str, str], FieldStats] = {}
        self._profiles: OrderedDict[str, FingerprintProfile] = \
            OrderedDict()
        self._node_ms: dict[str, deque] = {}
        self._cluster_durs: deque = deque(maxlen=_MAX_CLUSTER_DURS)
        # gate-arm rates: op -> (EWMA sec-per-unit, samples, t_mono)
        self._gate_rates: dict[str, tuple[float, int, float]] = {}
        # lock-free pending list (list.append is GIL-atomic): flight
        # records queue here and fold in batches off the hot path
        self._pending: list[dict] = []
        self._patch_memo: tuple[float, float | None] | None = None
        self._last_save = time.monotonic()
        self.store = None
        self.store_path: str | None = None  # survives detach_store
        self.loaded_from_disk = False
        if path:
            self._open_store(path)

    # -- persistence ---------------------------------------------------

    def _open_store(self, path: str):
        from pilosa_tpu.storage.stats_store import StatsStore
        self.store = StatsStore(path)
        self.store_path = path
        state, events, torn = self.store.load()
        if state is not None:
            self._load_state(state)
            self.loaded_from_disk = True
        for ev in events:
            self._apply_event(ev)
            self.loaded_from_disk = True
        if torn or self.store.tail_over_threshold():
            # recompact immediately: a torn tail must not be appended
            # after, and an over-threshold tail means the last run
            # died between threshold and compaction
            self.store.compact(self._state())

    def _state(self) -> dict:
        with self._lock:
            return {
                "v": 1,
                "fields": {f"{i}\x00{f}": fs.to_state()
                           for (i, f), fs in self._fields.items()},
                "profiles": {fp: p.to_state()
                             for fp, p in self._profiles.items()},
                "nodes": {n: [round(v, 3) for v in dq]
                          for n, dq in self._node_ms.items()},
                "cluster_durs": [round(v, 3)
                                 for v in self._cluster_durs],
                "gates": {op: [r, n]
                          for op, (r, n, _t)
                          in self._gate_rates.items()},
            }

    def _load_state(self, st: dict):
        with self._lock:
            for key, fst in st.get("fields", {}).items():
                i, _, f = key.partition("\x00")
                self._fields[(i, f)] = FieldStats.from_state(fst)
            for fp, pst in st.get("profiles", {}).items():
                self._profiles[fp] = FingerprintProfile.from_state(pst)
            for n, lst in st.get("nodes", {}).items():
                self._node_ms[n] = deque(
                    (float(v) for v in lst), maxlen=_MAX_NODE_SAMPLES)
            # REPLACE, don't extend: a same-path reopen after a
            # detach would otherwise duplicate every persisted
            # duration on top of the in-memory copy
            self._cluster_durs.clear()
            self._cluster_durs.extend(
                float(v) for v in st.get("cluster_durs", ()))
            now = time.monotonic()
            for op, (r, n) in st.get("gates", {}).items():
                # ages don't persist: loaded rates count as fresh so
                # post-restart gate decisions equal pre-restart ones,
                # then age out normally if the arm never runs again
                self._gate_rates[op] = (float(r), int(n), now)

    def save(self):
        """Snapshot the full catalog state (tmp+rename; the
        ``stats-snapshot`` fault seam crashes mid-write without ever
        exposing a half-written file).  The persist mutex makes
        (state capture, watermark stamp) atomic against concurrent
        ingest notes."""
        if self.store is None:
            return
        self.fold()
        with self._persist_mu:
            self.store.compact(self._state())
        self._last_save = time.monotonic()

    def maybe_save(self):
        if self.store is None:
            return
        if (time.monotonic() - self._last_save
                >= self.snapshot_interval_s
                or self.store.tail_over_threshold()):
            self.save()

    def detach_store(self):
        """Close and drop the persistence store (the owning server
        is shutting down): later notes stay in memory instead of
        appending to a dead server's file — or a deleted data dir."""
        with self._persist_mu:
            if self.store is not None:
                self.store.close()
                self.store = None
                self.loaded_from_disk = False

    def close(self):
        if self.store is not None:
            self.store.close()

    # -- data plane (ingest path) --------------------------------------

    def note_ingest(self, index: str, field: str, rows=None,
                    cols=None, values=None, width: int = 1 << 20):
        """Fold one import call into the field's data stats and
        append the event to the persistence tail.  Called from
        api.import_bits/import_values after the write landed."""
        import numpy as np
        ev: dict = {"t": "ingest", "i": index, "f": field}
        if rows is not None and len(rows):
            # vectorized: a bulk import passes millions of entries and
            # this sits on the ingest path — no Python per-bit loops
            uniq = np.unique(np.asarray(rows).astype(np.int64))
            ev["rows"] = [int(r) for r in uniq[:_ROWS_CAP]]
        if cols is not None and len(cols):
            sh, cnt = np.unique(
                np.asarray(cols).astype(np.int64) // width,
                return_counts=True)
            ev["sb"] = {str(int(s)): int(c)
                        for s, c in zip(sh, cnt)}
        if values is not None and len(values):
            va = np.asarray(values)
            if va.dtype.kind in "iu":
                ev["vmin"], ev["vmax"] = int(va.min()), int(va.max())
                ev["vn"] = int(va.size)
            elif va.dtype.kind == "f":
                ev["vmin"] = float(va.min())
                ev["vmax"] = float(va.max())
                ev["vn"] = int(va.size)
        with self._persist_mu:
            self._apply_event(ev)
            if self.store is not None:
                self.store.append(ev)

    def _apply_event(self, ev: dict):
        if ev.get("t") != "ingest":
            return
        key = (str(ev.get("i", "")), str(ev.get("f", "")))
        with self._lock:
            fs = self._fields.get(key)
            if fs is None:
                fs = self._fields[key] = FieldStats()
            fs.note(ev.get("rows", ()), ev.get("sb", {}),
                    vmin=ev.get("vmin"), vmax=ev.get("vmax"),
                    vcount=ev.get("vn", 0))

    def note_value_hist(self, index: str, field: str, pos, neg):
        """Harvest the single-pass ``bsi_value_hist`` byproduct: a
        per-value histogram just computed on the query path becomes
        the field's value-distribution summary for free."""
        import numpy as np
        pos = np.asarray(pos)
        neg = np.asarray(neg)
        pnz = np.flatnonzero(pos)
        nnz = np.flatnonzero(neg)
        summary = {
            "depth": int(pos.shape[0]).bit_length() - 1,
            "count": int(pos.sum() + neg.sum()),
            "distinct": int(len(pnz) + len(nnz)),
        }
        if len(pnz) or len(nnz):
            summary["min"] = (-int(nnz.max()) if len(nnz)
                              else int(pnz.min()))
            summary["max"] = (int(pnz.max()) if len(pnz)
                              else -int(nnz.min()))
        key = (index, field)
        with self._lock:
            fs = self._fields.get(key)
            if fs is None:
                fs = self._fields[key] = FieldStats()
            fs.vhist = summary

    def note_page_encoding(self, index: str, field: str, kind: str):
        """Tally one device-format decision for a field's pages
        (executor/stacked.py _commit_page) — the /debug/stats
        per-field encoding breakdown."""
        key = (index, field)
        with self._lock:
            fs = self._fields.get(key)
            if fs is None:
                fs = self._fields[key] = FieldStats()
            fs.encodings[kind] = fs.encodings.get(kind, 0) + 1

    def field_density(self, index: str, field: str,
                      width_bits: int) -> float | None:
        """Estimated set-bit density of one (row, shard) slab of the
        field — the encoder's skip-the-scan hint for clearly-dense
        fields (memory/encode.py).  None when the catalog can't say
        (no ingest stats, or the row set hit its cap — a capped set
        would overestimate density and wrongly pin sparse fields
        dense)."""
        with self._lock:
            fs = self._fields.get((index, field))
            if (fs is None or fs.rows_capped or not fs.rows
                    or not fs.shard_bits or width_bits <= 0):
                return None
            total = sum(fs.shard_bits.values())
            slots = len(fs.rows) * len(fs.shard_bits) * width_bits
        return total / slots if slots > 0 else None

    def field_stats(self, index: str, field: str) -> dict | None:
        with self._lock:
            fs = self._fields.get((index, field))
            return fs.payload() if fs is not None else None

    def est_index_rows(self, index: str) -> float | None:
        """Estimated record count of one index for the SQL cost
        planner (sql/costplan.py): the existence field's bit count
        when the ingest path noted it (authoritative — one bit per
        live record), else the widest field's bit count as a lower
        bound.  None when the catalog holds nothing for the index
        (the planner then keeps its static decision)."""
        # EXISTENCE_FIELD's literal name, not the models import: the
        # obs plane must not import the model layer at call time
        exists_key = (index, "_exists")
        with self._lock:
            fs = self._fields.get(exists_key)
            if fs is not None and fs.shard_bits:
                return float(sum(fs.shard_bits.values()))
            best = None
            for (i, _f), st in self._fields.items():
                if i != index or not st.shard_bits:
                    continue
                n = sum(st.shard_bits.values())
                if best is None or n > best:
                    best = n
            return float(best) if best is not None else None

    # -- runtime plane (flight fold) -----------------------------------

    def note_flight(self, rec: dict):
        """Queue one finished flight record for folding (lock-free
        append; amortized batch fold)."""
        pend = self._pending
        pend.append(rec)
        if len(pend) >= _FOLD_N:
            self.fold()

    def fold(self):
        """Drain the pending records into the profiles / node tables
        and run the sentinel over the touched fingerprints.  The
        pending swap happens under the catalog lock: fold() is
        reachable concurrently (query threads at _FOLD_N, the
        maintenance ticker, /debug/stats), and an unlocked two-target
        swap would let two folders drain the SAME buffer — every
        record double-folded.  note_flight's append stays lock-free;
        an append that captured the list mid-swap can lose that one
        record, the same accepted race as flight.py's sample buffer."""
        with self._lock:
            buf, self._pending = self._pending, []
        if not buf:
            return
        touched: list[str] = []
        evicted_firing: list[str] = []
        with self._lock:
            for rec in buf:
                fp = rec.get("fingerprint")
                if fp is not None and rec.get("error") is None:
                    p = self._profiles.get(fp)
                    if p is None:
                        p = self._profiles[fp] = FingerprintProfile()
                        while len(self._profiles) > _MAX_PROFILES:
                            ofp, op = self._profiles.popitem(
                                last=False)
                            if op.firing:
                                # the gauge would otherwise stay at
                                # its last nonzero ratio forever —
                                # _sentinel can't clear a profile
                                # that no longer exists
                                evicted_firing.append(ofp)
                    else:
                        self._profiles.move_to_end(fp)
                    p.fold(rec, ratio=self.regression_ratio,
                           min_samples=self.regression_min_samples)
                    touched.append(fp)
                if rec.get("route") == "cluster" and \
                        rec.get("error") is None:
                    self._cluster_durs.append(
                        float(rec.get("duration_ms", 0.0)))
                    for a in rec.get("attempts", ()):
                        if not str(a.get("outcome", "")).endswith("ok"):
                            continue
                        node = str(a.get("node", ""))
                        dq = self._node_ms.get(node)
                        if dq is None:
                            dq = self._node_ms[node] = deque(
                                maxlen=_MAX_NODE_SAMPLES)
                        dq.append(float(a.get("ms", 0.0)))
            n_profiles = len(self._profiles)
        metrics.STATS_FOLDS.inc(len(buf))
        metrics.STATS_PROFILES.set(n_profiles)
        for fp in evicted_firing:
            metrics.PERF_REGRESSION.set(0.0, fingerprint=fp,
                                        metric="duration_ms")
        for fp in set(touched):
            self._sentinel(fp)

    # -- regression sentinel -------------------------------------------

    def _sentinel(self, fp: str):
        """Export one fingerprint's sentinel state (detection ran
        per-record inside FingerprintProfile.fold) as
        ``pilosa_perf_regression{fingerprint,metric}``: the ratio
        while firing, an explicit 0 once it recovers — a gauge
        series exists only for fingerprints that have ever fired, so
        label cardinality tracks incidents, not traffic."""
        with self._lock:
            p = self._profiles.get(fp)
            if p is None:
                return
            base, fast, firing = p.base_ms, p.fast_ms, p.firing
        if firing and base:
            metrics.PERF_REGRESSION.set(round(fast / base, 3),
                                        fingerprint=fp,
                                        metric="duration_ms")
            # incident trigger (obs/incidents.py): the sentinel firing
            # captures one rate-limited bundle carrying the flight
            # records / stacks / profile of the regressing window —
            # repeated exports dedupe inside the rate-limit window
            from pilosa_tpu.obs import incidents
            incidents.report(
                "perf-regression", detail=fp,
                context={"fingerprint": fp,
                         "baseline_ms": round(base, 4),
                         "window_ms": round(fast or 0.0, 4),
                         "ratio": round((fast or 0.0) / base, 3)})
        elif metrics.PERF_REGRESSION.value(fingerprint=fp,
                                           metric="duration_ms"):
            metrics.PERF_REGRESSION.set(0.0, fingerprint=fp,
                                        metric="duration_ms")

    def regressions(self) -> list[dict]:
        self.fold()
        out = []
        with self._lock:
            items = list(self._profiles.items())
        for fp, p in items:
            if p.firing and p.base_ms:
                out.append({"fingerprint": fp, "metric": "duration_ms",
                            "baseline_ms": round(p.base_ms, 4),
                            "window_ms": round(p.fast_ms or 0.0, 4),
                            "ratio": round((p.fast_ms or 0.0)
                                           / p.base_ms, 3)})
        return out

    # -- consumers -----------------------------------------------------

    def profile(self, fingerprint: str) -> FingerprintProfile | None:
        with self._lock:
            return self._profiles.get(fingerprint)

    def est_cost_ms(self, fingerprint: str) -> float | None:
        """Estimated SERVE cost for a plan fingerprint (cache hits
        included — the admission signal: a reliably cache-served
        query costs the engine nothing and may ride the point lane;
        after an invalidation the estimate re-adapts within a few
        direct serves), or None below the confidence floor."""
        with self._lock:
            p = self._profiles.get(fingerprint)
            if p is None or p.n < 3 or p.ms is None:
                return None
            return p.ms

    def est_recompute_ms(self, fingerprint: str) -> float | None:
        """Estimated RECOMPUTE cost (non-cached serves only) — the
        cache-eviction signal: the cache's own sub-ms hits must not
        talk the estimate down for exactly the entries most worth
        keeping."""
        with self._lock:
            p = self._profiles.get(fingerprint)
            if p is None:
                return None
            return p.recompute_ms

    # a gate arm unsampled this long falls back to the static unit
    # model, letting the model-preferred arm run (and re-calibrate):
    # the anti-latch for "the losing arm never gets new samples"
    _GATE_STALE_S = 600.0

    def note_gate(self, op: str, units: float, seconds: float):
        """Fold one measured cost-gate arm execution (e.g.
        ``groupby_onepass``): EWMA of seconds-per-unit against the
        gate's own unit model, so the gate compares measured rates
        instead of assuming 1:1.  A sample >10x the current rate
        (a recompile riding the wall time, a GC pause) folds with a
        much smaller alpha — one outlier must not flip the gate onto
        the slower arm and latch there."""
        if units <= 0 or seconds <= 0:
            return
        sample = seconds / units
        with self._lock:
            rate, n, _t = self._gate_rates.get(op, (None, 0, 0.0))
            alpha = 0.3
            if rate is not None and sample > 10.0 * rate:
                alpha = 0.05
            self._gate_rates[op] = (_ewma(rate, sample, alpha),
                                    n + 1, time.monotonic())

    def gate_rates(self, op_a: str, op_b: str,
                   min_samples: int = 3) -> tuple[float, float]:
        """Measured seconds-per-unit for two gate arms, or (1.0, 1.0)
        — the static-model fallback — until BOTH arms have enough
        FRESH samples (an arm the gate stopped choosing ages out, so
        a wrong rate cannot latch forever)."""
        with self._lock:
            ra = self._gate_rates.get(op_a)
            rb = self._gate_rates.get(op_b)
        now = time.monotonic()
        for r in (ra, rb):
            if r is None or r[1] < min_samples or not r[0] \
                    or now - r[2] > self._GATE_STALE_S:
                return 1.0, 1.0
        return ra[0], rb[0]

    def patch_break_even_frac(self) -> float | None:
        """Measured patch-vs-rebuild break-even dirty fraction from
        the maintenance counters (bytes patched/rebuilt vs the
        stack_patch/stack_rebuild phase time): patching wins while
        dirty_bytes * cost_per_patched_byte < total_bytes *
        cost_per_rebuilt_byte, i.e. frac* = c_rebuild / c_patch.
        None (→ static threshold) until both arms have real volume.
        Memoized 1 s — this sits on the write path."""
        now = time.monotonic()
        memo = self._patch_memo
        if memo is not None and now - memo[0] < 1.0:
            return memo[1]
        from pilosa_tpu.obs import flight
        flight.flush_metrics()
        patched_b = metrics.STACK_MAINT_BYTES.value(kind="patched")
        rebuilt_b = metrics.STACK_MAINT_BYTES.value(kind="rebuilt")
        patch_s = metrics.PHASE_DURATION.sum(phase="stack_patch")
        reb_s = metrics.PHASE_DURATION.sum(phase="stack_rebuild")
        out = None
        if patched_b >= (1 << 18) and rebuilt_b >= (1 << 18) \
                and patch_s > 1e-3 and reb_s > 1e-3:
            c_patch = patch_s / patched_b
            c_rebuild = reb_s / rebuilt_b
            out = min(max(c_rebuild / c_patch, 0.05), 0.95)
        self._patch_memo = (now, out)
        return out

    def hedge_samples(self, min_records: int = 32):
        """Per-node attempt samples + cluster durations for the
        hedge-delay derivation, or None when the catalog holds too
        few to beat the in-memory flight ring."""
        self.fold()
        with self._lock:
            by_node = {n: list(dq) for n, dq in self._node_ms.items()
                       if dq}
            durs = list(self._cluster_durs)
        atts = sum(len(v) for v in by_node.values())
        if atts < min_records and len(durs) < min_records:
            return None
        return by_node, durs

    # -- introspection (/debug/stats) ----------------------------------

    def payload(self, index: str | None = None,
                fingerprint: str | None = None,
                limit: int | None = None) -> dict:
        self.fold()
        with self._lock:
            fields = {f"{i}/{f}": fs.payload()
                      for (i, f), fs in sorted(self._fields.items())
                      if index is None or i == index}
            profs = [(fp, p.payload())
                     for fp, p in reversed(self._profiles.items())
                     if fingerprint is None or fp == fingerprint]
            nodes = {n: {"n": len(dq),
                         "p50_ms": round(sorted(dq)[len(dq) // 2], 3)}
                     for n, dq in sorted(self._node_ms.items()) if dq}
            gates = {op: {"sec_per_unit": r, "n": n}
                     for op, (r, n, _t)
                     in sorted(self._gate_rates.items())}
        if limit is not None:
            profs = profs[: max(0, int(limit))]
        out = {
            "enabled": enabled(),
            "data": fields,
            "runtime": dict(profs),
            "nodes": nodes,
            "gates": gates,
            "regressions": self.regressions(),
            "knobs": {
                "heavy_cost_ms": self.heavy_cost_ms,
                "regression_ratio": self.regression_ratio,
                "regression_min_samples": self.regression_min_samples,
            },
        }
        if self.store is not None:
            out["store"] = {"path": self.store.path,
                            "loaded": self.loaded_from_disk,
                            "tail_records": self.store.tail_records}
        return out

    def clear(self):
        """Test seam: forget everything in memory (disk untouched)."""
        with self._lock:
            self._fields.clear()
            self._profiles.clear()
            self._node_ms.clear()
            self._cluster_durs.clear()
            self._gate_rates.clear()
            self._pending = []
            self._patch_memo = None
            self.loaded_from_disk = False


# ---------------------------------------------------------------------------
# process-global catalog + module-level hot-path entries
# ---------------------------------------------------------------------------

_catalog: StatsCatalog | None = None
_cat_lock = threading.Lock()


def get() -> StatsCatalog:
    # double-checked fast path: get() sits on the per-query hot path
    # (note_flight, est_cost_ms, gate_rates) — steady state must not
    # contend on the creation mutex (the global read is GIL-atomic)
    global _catalog
    cat = _catalog
    if cat is not None:
        return cat
    with _cat_lock:
        if _catalog is None:
            _catalog = StatsCatalog()
        return _catalog


def configure(enabled: bool | None = None, path: str | None = None,
              heavy_cost_ms: float | None = None,
              regression_ratio: float | None = None,
              regression_min_samples: int | None = None,
              snapshot_interval_s: float | None = None) -> StatsCatalog:
    """Apply the [stats] config knobs.  ``enabled=None`` leaves the
    env kill-switch (PILOSA_TPU_STATS) in charge.  A path CHANGE
    reopens the store (loading its persisted state); the in-memory
    planes are preserved across reconfigures."""
    global _enabled, _catalog
    _enabled = enabled
    cat = get()
    if heavy_cost_ms is not None:
        cat.heavy_cost_ms = float(heavy_cost_ms)
    if regression_ratio is not None:
        cat.regression_ratio = float(regression_ratio)
    if regression_min_samples is not None:
        cat.regression_min_samples = int(regression_min_samples)
    if snapshot_interval_s is not None:
        cat.snapshot_interval_s = float(snapshot_interval_s)
    if path is not None:
        if cat.store_path != path:
            # a DIFFERENT data dir: the catalog follows the store —
            # carrying another dir's in-memory state forward would
            # write one holder's stats into another's file
            if cat.store is not None:
                cat.store.close()
                cat.store = None
            cat.clear()
            cat._open_store(path)
        elif cat.store is None:
            # same path, store detached (owning server closed):
            # reattach and reload the snapshot we saved then
            cat._open_store(path)
    return cat


def swap(catalog: StatsCatalog | None) -> StatsCatalog | None:
    """Test seam: replace the process catalog, returning the prior
    one so fixtures can restore exactly what they found."""
    global _catalog
    with _cat_lock:
        prev, _catalog = _catalog, catalog
    return prev


def note_flight(rec: dict):
    """Hot-path entry (flight.commit): one enabled check + one
    lock-free list append; folding is amortized."""
    if not enabled():
        return
    get().note_flight(rec)


def note_ingest(index: str, field: str, rows=None, cols=None,
                values=None, width: int = 1 << 20):
    if not enabled():
        return
    try:
        get().note_ingest(index, field, rows=rows, cols=cols,
                          values=values, width=width)
    except Exception:
        pass  # stats must never fail a write


def note_value_hist(index: str, field: str, pos, neg):
    if not enabled():
        return
    try:
        get().note_value_hist(index, field, pos, neg)
    except Exception:
        pass


def note_page_encoding(index: str, field: str, kind: str):
    if not enabled():
        return
    try:
        get().note_page_encoding(index, field, kind)
    except Exception:
        pass  # stats must never fail a page build


def field_density(index: str, field: str,
                  width_bits: int) -> float | None:
    if not enabled():
        return None
    try:
        return get().field_density(index, field, width_bits)
    except Exception:
        return None


def note_gate(op: str, units: float, seconds: float):
    if not enabled():
        return
    get().note_gate(op, units, seconds)


def gate_rates(op_a: str, op_b: str) -> tuple[float, float]:
    if not enabled():
        return 1.0, 1.0
    return get().gate_rates(op_a, op_b)


def patch_break_even_frac() -> float | None:
    if not enabled():
        return None
    return get().patch_break_even_frac()


def est_cost_ms(fingerprint: str) -> float | None:
    if not enabled():
        return None
    return get().est_cost_ms(fingerprint)


def est_recompute_ms(fingerprint: str) -> float | None:
    if not enabled():
        return None
    return get().est_recompute_ms(fingerprint)


def heavy_cost_ms() -> float:
    return get().heavy_cost_ms


def hedge_samples(min_records: int = 32):
    if not enabled():
        return None
    return get().hedge_samples(min_records=min_records)


def tick():
    """Maintenance-ticker hook (server/http.py): fold pending
    records, refresh the sentinel, persist on the snapshot cadence."""
    try:
        cat = get()
        cat.fold()
        cat.maybe_save()
    except Exception:
        pass  # the stats plane must never take the ticker down
