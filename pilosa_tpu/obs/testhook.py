"""Resource leak auditor — the testhook/ analog (testhook/hook.go,
registry.go, auditor.go: opened/closed resource tracking consulted by
tests, e.g. executor.go:144).

Opt-in via ``PILOSA_TPU_TESTHOOK=1`` (the reference gates its hooks
behind build tags the same way): when disabled, ``opened``/``closed``
are no-ops costing one attribute read.  When enabled, every tracked
resource kind keeps a live table of (id, description, stack-summary);
``audit()`` returns what is still open, and the test suite's session
teardown asserts it is empty.

Tracked kinds (wired at the resource's open/close sites):
``rbf.DB``, ``http.Server``, ``spill.SpillSet``.
"""

from __future__ import annotations

import os
import threading
import traceback

ENABLED = os.environ.get("PILOSA_TPU_TESTHOOK") == "1"

_lock = threading.Lock()
# kind -> id(obj) -> (obj, description, opening stack summary).  The
# object itself is kept (strong ref) so a leaked resource cannot be
# garbage-collected and have its id() reused by a later open —
# which would overwrite the leaked entry and mask the leak.
_live: dict[str, dict[int, tuple[object, str, str]]] = {}


def opened(kind: str, obj, description: str = "") -> None:
    if not ENABLED:
        return
    # innermost few non-testhook frames: enough to find the leak site
    stack = "".join(traceback.format_stack(limit=6)[:-1])
    with _lock:
        _live.setdefault(kind, {})[id(obj)] = (
            obj, description or repr(obj), stack)


def closed(kind: str, obj) -> None:
    if not ENABLED:
        return
    with _lock:
        _live.get(kind, {}).pop(id(obj), None)


def audit() -> dict[str, list[str]]:
    """kind -> descriptions of still-open resources."""
    with _lock:
        return {k: [d for _o, d, _s in v.values()]
                for k, v in _live.items() if v}


def audit_stacks() -> dict[str, list[str]]:
    """kind -> opening stacks of still-open resources (diagnosis)."""
    with _lock:
        return {k: [s for _o, _d, s in v.values()]
                for k, v in _live.items() if v}


def reset() -> None:
    with _lock:
        _live.clear()
