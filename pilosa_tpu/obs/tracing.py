"""Tracing — global Tracer with nop default + profiled query spans.

Reference: tracing/tracing.go:12 (global ``Tracer`` interface, nop
default, opentracing adapter) and the profiled-span machinery
(tracing/tracing.go:22-50) that returns a span tree with timings when
``QueryRequest.Profile=true`` (handler.go:40).  Spans are threaded
through the engine the same way (``start_span`` at every layer).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager


class Span:
    """One timed operation; children nest via the active-span stack."""

    __slots__ = ("name", "tags", "start", "end", "children")

    def __init__(self, name: str):
        self.name = name
        self.tags: dict = {}
        self.start = time.perf_counter()
        self.end: float | None = None
        self.children: list[Span] = []

    def set_tag(self, key: str, value):
        self.tags[key] = value

    def finish(self):
        if self.end is None:
            self.end = time.perf_counter()

    def copy(self) -> "Span":
        """Deep copy of the finished subtree — a shared span (one
        fused device dispatch serving N queries) is attached to every
        requester's tree as its OWN copy, so no two trees alias."""
        s = Span.__new__(Span)
        s.name = self.name
        s.tags = dict(self.tags)
        s.start = self.start
        s.end = self.end
        s.children = [c.copy() for c in self.children]
        return s

    @property
    def duration(self) -> float:
        return (self.end if self.end is not None
                else time.perf_counter()) - self.start

    def to_dict(self) -> dict:
        d = {"name": self.name, "duration_us": int(self.duration * 1e6)}
        if self.tags:
            d["tags"] = dict(self.tags)
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d


ProfiledSpan = Span  # profiled spans are plain spans kept in a tree


class Tracer:
    """Records a span tree per thread.  Subclass or use as-is."""

    def __init__(self):
        self._tls = threading.local()

    def _stack(self) -> list[Span]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
        return st

    @contextmanager
    def span(self, name: str, **tags):
        s = Span(name)
        s.tags.update(tags)
        st = self._stack()
        if st:
            st[-1].children.append(s)
        st.append(s)
        try:
            yield s
        finally:
            s.finish()
            st.pop()
            self.on_finish(s, root=not st)

    def on_finish(self, span: Span, root: bool):
        """Hook for exporters (opentracing adapter analog)."""


class NopTracer(Tracer):
    @contextmanager
    def span(self, name: str, **tags):
        # a FRESH nop span per call: a single shared mutable instance
        # would let any caller that appends children or pokes
        # start/end corrupt every other caller's span (and leak the
        # child list forever) — pinned by test_nop_span_not_shared
        yield _NopSpan()


class _NopSpan(Span):
    """Inert span: mutators are no-ops, duration is frozen at 0."""

    __slots__ = ()

    def __init__(self):
        super().__init__("nop")
        self.end = self.start

    def set_tag(self, key: str, value):
        pass

    def finish(self):
        pass

_global = NopTracer()
_tls = threading.local()


def set_tracer(t: Tracer):
    global _global
    _global = t


def get_tracer() -> Tracer:
    """The active tracer: a per-thread override (profiled queries)
    wins over the process-global tracer."""
    t = getattr(_tls, "tracer", None)
    return t if t is not None else _global


def push_thread_tracer(t: Tracer) -> Tracer | None:
    """Install a tracer for THIS thread only (Profile=true queries on
    a threaded server must not race the process-global tracer).
    Returns the previous thread-local tracer to restore."""
    prev = getattr(_tls, "tracer", None)
    _tls.tracer = t
    return prev


def pop_thread_tracer(prev: Tracer | None):
    _tls.tracer = prev


def start_span(name: str, **tags):
    """StartSpanFromContext analog — context is the thread."""
    return get_tracer().span(name, **tags)


class RecordingTracer(Tracer):
    """Keeps finished root spans; used for Profile=true queries and
    the query-history ring (http_handler.go:540)."""

    def __init__(self, keep: int = 100):
        super().__init__()
        self.roots: list[Span] = []
        self.keep = keep
        self._lock = threading.Lock()

    def on_finish(self, span: Span, root: bool):
        if root:
            with self._lock:
                self.roots.append(span)
                if len(self.roots) > self.keep:
                    self.roots.pop(0)


# ---------------------------------------------------------------------------
# cross-thread trace-context propagation
# ---------------------------------------------------------------------------
# The serving batcher executes a follower's query on the LEADER's
# thread (executor/serving.py); thread-local tracing would silently
# drop every device phase of a fused Profile=true query.  A follower
# captures a TraceContext (its tracer + innermost open span), carries
# it into the leader, and the leader records spans INTO that context
# from its own thread — the follower's span tree then includes the
# leader-executed compile/upload/execute phases.

_ATTACH_LOCK = threading.Lock()


class TraceContext:
    """Handle to another thread's (tracer, parent span)."""

    __slots__ = ("tracer", "parent")

    def __init__(self, tracer: Tracer, parent: Span | None):
        self.tracer = tracer
        self.parent = parent

    def attach(self, span: Span):
        """Graft a FINISHED span (tree) under the captured parent.
        Safe from any thread: appends are serialized by a module lock
        (the owning thread only ever appends too, never removes)."""
        if self.parent is not None:
            with _ATTACH_LOCK:
                self.parent.children.append(span)
        else:
            self.tracer.on_finish(span, root=True)


def capture_context() -> TraceContext | None:
    """This thread's active trace context, or None when nothing
    records (the common untraced case — callers skip all cross-thread
    span work on None, keeping the disabled path overhead-free)."""
    t = get_tracer()
    if isinstance(t, NopTracer):
        return None
    st = t._stack()
    return TraceContext(t, st[-1] if st else None)


class _AttachTracer(Tracer):
    """Thread-local tracer whose finished roots graft into a captured
    TraceContext — spans opened via start_span() on the borrowed
    thread (stack uploads, jit dispatch) land in the right tree."""

    def __init__(self, ctx: TraceContext):
        super().__init__()
        self.ctx = ctx

    def on_finish(self, span: Span, root: bool):
        if root:
            self.ctx.attach(span)


_NOP_TRACER = NopTracer()


# ---------------------------------------------------------------------------
# cross-NODE span serialization (ISSUE 10)
# ---------------------------------------------------------------------------
# Span.start is time.perf_counter() — a node-local monotonic clock
# that means nothing on another host.  A span tree crosses an RPC as
# OFFSETS relative to its own root's start; the receiving coordinator
# re-anchors the tree at the moment it observed the attempt leave
# (caller clock), which is the honest alignment available without
# cross-host clock sync (skew shows as at most the connect latency).

def span_to_wire(span: Span, base: float | None = None) -> dict:
    """Serialize a finished span tree for an RPC trailer.  Every
    ``off_us`` in the tree is relative to the SAME base (the root
    span's start by default), so the receiver shifts the whole tree
    with one anchor."""
    if base is None:
        base = span.start
    d = {"name": span.name,
         "off_us": int((span.start - base) * 1e6),
         "dur_us": int(span.duration * 1e6)}
    if span.tags:
        d["tags"] = dict(span.tags)
    if span.children:
        d["children"] = [span_to_wire(c, base) for c in span.children]
    return d


def span_from_wire(d: dict, anchor: float) -> Span:
    """Rebuild a Span tree from its wire form, anchored at ``anchor``
    (this process's perf_counter timeline) — lets a remote tree graft
    into a local tracer via TraceContext.attach."""
    s = Span.__new__(Span)
    s.name = str(d.get("name", "remote"))
    s.tags = dict(d.get("tags", {}))
    s.start = anchor + d.get("off_us", 0) / 1e6
    s.end = s.start + d.get("dur_us", 0) / 1e6
    s.children = [span_from_wire(c, anchor)
                  for c in d.get("children", ())]
    return s


@contextmanager
def span_into(ctx: TraceContext | None, name: str, **tags):
    """Open a span on THIS thread that records (with everything
    start_span() nests inside it) into `ctx`'s tree.  With ctx=None
    the body is SILENCED, not left on the thread's own tracer: a
    traced batch leader serving an untraced follower must not adopt
    the follower's inner spans (stack fetches etc.) into its own
    profile tree."""
    if ctx is None:
        prev = push_thread_tracer(_NOP_TRACER)
        try:
            yield _NopSpan()
        finally:
            pop_thread_tracer(prev)
        return
    t = _AttachTracer(ctx)
    prev = push_thread_tracer(t)
    try:
        with t.span(name, **tags) as s:
            yield s
    finally:
        pop_thread_tracer(prev)
